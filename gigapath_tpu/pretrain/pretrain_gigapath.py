"""Two-stage pretraining driver (replication additions).

Parity with reference ``docker/workspace/prov-gigapath/pretrain_gigapath.py``:

- **Stage 1 — simplified-MAE tile pretrain** (``MaskedAutoencoder:48``,
  ``pretrain_tile_encoder:120``): random pixel-token zero-masking (ratio
  0.75), the ViT tile encoder, an MLP decoder reconstructing the full
  224x224x3 image, MSE over *all* pixels (the reference computes the loss on
  everything despite its masked-region comment); AdamW + cosine; best +
  periodic checkpoints.
- **Stage 2 — contrastive slide pretrain** (``pretrain_slide_encoder:206``):
  frozen tile encoder feature extraction per slide, a mean-pool MLP
  ``SimpleSlideEncoder`` stand-in (``:226-250``), InfoNCE at temperature
  0.07 with self-similarity logits (``contrastive_loss:264``), one optimizer
  step per epoch over the stacked slide features.
- Orchestration with resume-if-processed slide preprocessing
  (``main:506``, skip at ``:487-490``).

TPU deltas: the per-sample Python masking loop becomes a vectorized
``jax.random.permutation`` over pixel tokens; fp16 autocast becomes bf16;
checkpoints are orbax state dicts.
"""

from __future__ import annotations

import glob
import os
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from gigapath_tpu.models.tile_encoder import VisionTransformer
from gigapath_tpu.obs import (
    CompileWatchdog,
    Heartbeat,
    console,
    get_ledger,
    get_metrics,
    get_run_log,
    span,
)
from gigapath_tpu.obs.runlog import fail_run
from gigapath_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint


def random_masking(rng: jax.Array, imgs: jnp.ndarray, mask_ratio: float) -> jnp.ndarray:
    """Zero a random ``mask_ratio`` of pixel positions per image
    ([B, H, W, C]); vectorized counterpart of the reference's per-sample
    token loop (``pretrain_gigapath.py:66-91``)."""
    B, H, W, C = imgs.shape
    L = H * W
    len_keep = int(L * (1 - mask_ratio))
    noise = jax.random.uniform(rng, (B, L))
    # rank of each position in the random shuffle; the len_keep lowest-noise
    # positions are kept — scatter-free formulation of the reference's
    # ids_shuffle / ids_keep dance
    ranks = jnp.argsort(jnp.argsort(noise, axis=1), axis=1)
    keep = ranks < len_keep
    return imgs * keep.reshape(B, H, W, 1).astype(imgs.dtype)


class MaskedAutoencoder(nn.Module):
    """Simplified MAE: encoder + MLP pixel decoder (reference ``:48-107``)."""

    encoder: VisionTransformer
    decoder_dim: int = 512
    mask_ratio: float = 0.75

    @nn.compact
    def __call__(self, imgs: jnp.ndarray, rng: Optional[jax.Array] = None):
        masked = imgs if rng is None else random_masking(rng, imgs, self.mask_ratio)
        latent = self.encoder(masked)
        h = nn.Dense(self.decoder_dim, name="dec1")(latent)
        h = nn.gelu(h)
        h = nn.Dense(self.decoder_dim, name="dec2")(h)
        h = nn.gelu(h)
        size = self.encoder.img_size
        pred = nn.Dense(3 * size * size, name="dec3")(h)
        pred = pred.reshape(pred.shape[0], size, size, 3)
        loss = jnp.mean((pred.astype(jnp.float32) - imgs.astype(jnp.float32)) ** 2)
        return loss, pred


def _load_tile_batch(paths: Sequence[str], img_size: int) -> np.ndarray:
    from PIL import Image

    from gigapath_tpu.data.transforms import preprocess_tile

    return np.stack(
        [preprocess_tile(Image.open(p), crop_size=img_size) for p in paths]
    )


def collect_image_paths(data_dir: str, extensions=(".png", ".jpg", ".jpeg")) -> List[str]:
    image_paths: List[str] = []
    for ext in extensions:
        image_paths.extend(
            glob.glob(os.path.join(data_dir, f"**/*{ext}"), recursive=True)
        )
    return sorted(image_paths)


def pretrain_tile_encoder(
    image_paths: Sequence[str],
    output_dir: str,
    *,
    encoder: Optional[VisionTransformer] = None,
    batch_size: int = 64,
    num_epochs: int = 100,
    learning_rate: float = 1e-4,
    mask_ratio: float = 0.75,
    checkpoint_every: int = 10,
    seed: int = 0,
) -> str:
    """Stage 1 (reference ``pretrain_tile_encoder:120-204``): returns the
    best-checkpoint path."""
    os.makedirs(output_dir, exist_ok=True)
    encoder = encoder or VisionTransformer(dtype=jnp.bfloat16)
    mae = MaskedAutoencoder(encoder=encoder, mask_ratio=mask_ratio)

    rng = jax.random.PRNGKey(seed)
    init_imgs = jnp.zeros((1, encoder.img_size, encoder.img_size, 3), jnp.float32)
    params = mae.init(rng, init_imgs)["params"]

    steps_per_epoch = max(len(image_paths) // batch_size, 1)
    tx = optax.adamw(
        optax.cosine_decay_schedule(learning_rate, num_epochs * steps_per_epoch)
    )
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, imgs, rng):
        (loss, _), grads = jax.value_and_grad(
            lambda p: mae.apply({"params": p}, imgs, rng), has_aux=True
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    runlog = get_run_log(
        "pretrain_tile", out_dir=output_dir,
        config={"batch_size": batch_size, "num_epochs": num_epochs,
                "learning_rate": learning_rate, "mask_ratio": mask_ratio,
                "n_images": len(image_paths), "seed": seed},
    )
    ledger = get_ledger(runlog)
    watchdog = CompileWatchdog("pretrain_tile.step", runlog, ledger=ledger)
    instrumented_step = watchdog.wrap(step)
    # typed metrics (obs/metrics.py): synced step-wall histogram; the
    # final snapshot flushes inside run_end via the registry's closer
    metrics = get_metrics(runlog)
    step_walls = metrics.histogram("pretrain_tile.step_wall_s")
    order_rng = np.random.default_rng(seed)
    best_loss = float("inf")
    best_path = os.path.join(output_dir, "best_tile_encoder")
    try:
        with Heartbeat(runlog, name="pretrain_tile") as heartbeat:
            global_step = 0
            for epoch in range(num_epochs):
                order = order_rng.permutation(len(image_paths))
                epoch_loss, n_steps = 0.0, 0
                t_epoch = time.time()
                for start in range(0, steps_per_epoch * batch_size, batch_size):
                    idx = order[start : start + batch_size]
                    if len(idx) == 0:
                        break
                    imgs = jnp.asarray(
                        _load_tile_batch([image_paths[i] for i in idx], encoder.img_size)
                    )
                    rng, mask_rng = jax.random.split(rng)
                    # fenced span (GL008): honest per-step device timing
                    with span("step", runlog, fence=True) as sp:
                        params, opt_state, loss = instrumented_step(
                            params, opt_state, imgs, mask_rng
                        )
                        sp.fence(loss)
                    loss = float(loss)  # host sync (tiny batches)
                    epoch_loss += loss
                    n_steps += 1
                    runlog.step(
                        global_step, wall_s=sp.dur_s,
                        synced=True, epoch=epoch, loss=loss,
                    )
                    if sp.dur_s is not None:
                        step_walls.observe(sp.dur_s)
                    metrics.maybe_flush()
                    heartbeat.beat(global_step)
                    global_step += 1
                epoch_loss /= max(n_steps, 1)
                epoch_sec = time.time() - t_epoch
                runlog.echo(
                    "Epoch: {}, Loss: {:.6f}, Epoch time: {:.1f}s "
                    "({:.3f} sec/it)".format(
                        epoch, epoch_loss, epoch_sec, epoch_sec / max(n_steps, 1)
                    ),
                    step=max(global_step - 1, 0),
                )
                if epoch_loss < best_loss:
                    best_loss = epoch_loss
                    save_checkpoint(
                        best_path,
                        {"params": jax.device_get(params), "epoch": np.asarray(epoch), "loss": np.asarray(epoch_loss)},
                    )
                if (epoch + 1) % checkpoint_every == 0:
                    save_checkpoint(
                        os.path.join(output_dir, f"tile_encoder_epoch_{epoch + 1}"),
                        {"params": jax.device_get(params), "epoch": np.asarray(epoch)},
                    )
    except Exception as e:
        fail_run(
            runlog, "pretrain_tile_encoder", e,
            emergency=lambda: (
                save_checkpoint(
                    os.path.join(output_dir, "emergency_tile_encoder"),
                    {"params": jax.device_get(params)},
                )
                or os.path.join(output_dir, "emergency_tile_encoder")
            ),
        )
        raise
    runlog.echo(f"Pretraining done. Best loss: {best_loss:.6f}")
    runlog.run_end(
        status="ok", best_loss=best_loss,
        compile_seconds_total=watchdog.compile_seconds_total(),
        ledger_path=ledger.path,
    )
    return best_path


class SimpleSlideEncoder(nn.Module):
    """Mean-pool MLP slide-encoder stand-in (reference ``:226-250``)."""

    in_dim: int = 1536
    hidden_dim: int = 768
    out_dim: int = 768

    @nn.compact
    def __call__(self, x: jnp.ndarray, coords=None) -> jnp.ndarray:
        x = x.mean(axis=1)
        # ONE norm applied at both sites, params tied — the reference
        # declares a single self.norm and calls it twice
        # (pretrain_gigapath.py:237,243-246)
        norm = nn.LayerNorm(name="norm")
        x = norm(nn.gelu(nn.Dense(self.hidden_dim, name="fc1")(x)))
        x = norm(nn.gelu(nn.Dense(self.hidden_dim, name="fc2")(x)))
        return nn.Dense(self.out_dim, name="fc3")(x)


def contrastive_loss(features: jnp.ndarray, temperature: float = 0.07) -> jnp.ndarray:
    """InfoNCE on the self-similarity matrix (reference
    ``contrastive_loss:264-287``)."""
    if features.shape[0] <= 1:
        return jnp.float32(0.1)
    features = features / jnp.clip(
        jnp.linalg.norm(features, axis=1, keepdims=True), 1e-8
    )
    sim = features @ features.T
    labels = jnp.arange(features.shape[0])
    return optax.softmax_cross_entropy_with_integer_labels(
        sim / temperature, labels
    ).mean()


def extract_slide_features(
    tile_encoder, tile_params, slide_dirs: Sequence[str], batch_size: int = 64
) -> List[np.ndarray]:
    """Frozen tile-encoder features per slide directory
    (reference ``:329-352``)."""
    encode = jax.jit(lambda p, x: tile_encoder.apply({"params": p}, x))
    all_feats = []
    for slide_dir in slide_dirs:
        image_paths = collect_image_paths(slide_dir)
        if not image_paths:
            continue
        feats = []
        for start in range(0, len(image_paths), batch_size):
            imgs = _load_tile_batch(
                image_paths[start : start + batch_size], tile_encoder.img_size
            )
            feats.append(np.asarray(encode(tile_params, jnp.asarray(imgs)), np.float32))
        all_feats.append(np.concatenate(feats))
    return all_feats


def pretrain_slide_encoder(
    tile_encoder,
    tile_params,
    image_dirs: Sequence[str],
    output_dir: str,
    *,
    num_epochs: int = 50,
    learning_rate: float = 1e-4,
    max_tiles: int = 256,
    seed: int = 0,
) -> str:
    """Stage 2 (reference ``pretrain_slide_encoder:206-451``): contrastive
    training of the slide encoder over frozen tile features; one optimizer
    step per epoch, matching the reference (``:405-420``)."""
    os.makedirs(output_dir, exist_ok=True)
    slide_feats = extract_slide_features(tile_encoder, tile_params, image_dirs)
    if not slide_feats:
        raise ValueError("no slides with tiles found")
    n = min(min(f.shape[0] for f in slide_feats), max_tiles)
    batch = jnp.asarray(np.stack([f[:n] for f in slide_feats]))  # [S, n, D]

    model = SimpleSlideEncoder(in_dim=batch.shape[-1])
    params = model.init(jax.random.PRNGKey(seed), batch)["params"]
    tx = optax.adamw(optax.cosine_decay_schedule(learning_rate, num_epochs))
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            return contrastive_loss(model.apply({"params": p}, batch))

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    runlog = get_run_log(
        "pretrain_slide", out_dir=output_dir,
        config={"num_epochs": num_epochs, "learning_rate": learning_rate,
                "max_tiles": max_tiles, "n_slides": int(batch.shape[0]),
                "seed": seed},
    )
    ledger = get_ledger(runlog)
    watchdog = CompileWatchdog("pretrain_slide.step", runlog, ledger=ledger)
    instrumented_step = watchdog.wrap(step)
    metrics = get_metrics(runlog)
    step_walls = metrics.histogram("pretrain_slide.step_wall_s")
    best_loss = float("inf")
    best_path = os.path.join(output_dir, "best_slide_encoder")
    try:
        with Heartbeat(runlog, name="pretrain_slide") as heartbeat:
            for epoch in range(num_epochs):
                # fenced span (GL008): honest per-epoch-step device timing
                with span("step", runlog, fence=True) as sp:
                    params, opt_state, loss = instrumented_step(params, opt_state)
                    sp.fence(loss)
                loss = float(loss)
                runlog.step(
                    epoch, wall_s=sp.dur_s, synced=True,
                    loss=loss,
                )
                if sp.dur_s is not None:
                    step_walls.observe(sp.dur_s)
                metrics.maybe_flush()
                heartbeat.beat(epoch)
                runlog.echo(
                    f"Epoch: {epoch}, Contrastive loss: {loss:.6f}", step=epoch
                )
                if loss < best_loss:
                    best_loss = loss
                    save_checkpoint(
                        best_path, {"params": jax.device_get(params), "loss": np.asarray(loss)}
                    )
    except Exception as e:
        fail_run(
            runlog, "pretrain_slide_encoder", e,
            emergency=lambda: (
                save_checkpoint(
                    os.path.join(output_dir, "emergency_slide_encoder"),
                    {"params": jax.device_get(params)},
                )
                or os.path.join(output_dir, "emergency_slide_encoder")
            ),
        )
        raise
    runlog.echo(f"Slide pretraining done. Best loss: {best_loss:.6f}")
    runlog.run_end(
        status="ok", best_loss=best_loss,
        compile_seconds_total=watchdog.compile_seconds_total(),
        ledger_path=ledger.path,
    )
    return best_path


def preprocess_slides(
    slide_files: Sequence[str], output_dir: str, tile_size: int = 256
) -> List[str]:
    """Tile raw slides, skipping already-processed ones
    (reference ``preprocess_slides:476-504``)."""
    from gigapath_tpu.pipeline import tile_one_slide

    slide_dirs = []
    for slide_file in slide_files:
        slide_id = os.path.basename(slide_file)
        out = os.path.join(output_dir, "output", slide_id)
        if os.path.isdir(out) and glob.glob(os.path.join(out, "*.png")):
            console(f"Skipping {slide_id} - already processed")
        else:
            tile_one_slide(slide_file, output_dir, level=0, tile_size=tile_size)
        slide_dirs.append(out)
    return slide_dirs


def main(
    slide_files: Sequence[str],
    output_dir: str,
    *,
    encoder: Optional[VisionTransformer] = None,
    tile_size: int = 256,
    tile_epochs: int = 100,
    slide_epochs: int = 50,
    batch_size: int = 64,
):
    """Full two-stage orchestration (reference ``main:506-537``)."""
    slide_dirs = preprocess_slides(slide_files, output_dir, tile_size)
    image_paths = [p for d in slide_dirs for p in collect_image_paths(d)]
    encoder = encoder or VisionTransformer(dtype=jnp.bfloat16)
    best_tile = pretrain_tile_encoder(
        image_paths,
        os.path.join(output_dir, "tile_pretrain"),
        encoder=encoder,
        batch_size=batch_size,
        num_epochs=tile_epochs,
    )
    tile_state = restore_checkpoint(best_tile)
    tile_params = tile_state["params"]["encoder"]
    best_slide = pretrain_slide_encoder(
        encoder,
        tile_params,
        slide_dirs,
        os.path.join(output_dir, "slide_pretrain"),
        num_epochs=slide_epochs,
    )
    return best_tile, best_slide
