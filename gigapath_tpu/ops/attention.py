"""Multi-head attention with log-sum-exp output.

TPU-native counterpart of reference ``torchscale/component/multihead_attention.py``
and ``torchscale/component/flash_attention.py``. The reference needs two CUDA
kernel stacks (flash-attn, xformers CUTLASS) because its LSE output is required
by dilated attention's branch recombination (``dilated_attention.py:119-128``).
Here the op is a single function: a pure-jnp softmax attention that always
returns ``(out, lse)``, which XLA fuses well at the segment sizes dilated
attention produces, plus an opt-in Pallas flash kernel
(:mod:`gigapath_tpu.ops.flash_attention`) for long dense segments.

Shapes follow the flash-attn convention the reference uses at the kernel
boundary: q/k/v are ``[B, L, H, D]``, lse is ``[B, H, L]``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

# Large-but-finite mask value: keeps fully-masked rows NaN-free (exp(-1e8)=0,
# lse=-1e8 instead of -inf) which the dilated-branch recombination relies on.
NEG_INF = -1e8


def attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: Optional[float] = None,
    bias: Optional[jnp.ndarray] = None,
    key_padding_mask: Optional[jnp.ndarray] = None,
    kv_valid_len=None,
    is_causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Softmax attention returning ``(out [B,Lq,H,D], lse [B,H,Lq])``.

    Softmax statistics are accumulated in fp32 regardless of input dtype
    (bf16-safe); the output is cast back to the input dtype.

    - ``bias``: additive logits bias broadcastable to ``[B, H, Lq, Lk]``
      (T5 relative-position bias or a pre-built attn_mask).
    - ``key_padding_mask``: ``[B, Lk]`` bool, True = padding.
    - ``kv_valid_len``: static [B, H] per-(batch, head) valid key counts
      (keys at index >= count are masked) — same contract as the Pallas
      kernel's ragged masking.
    - ``is_causal``: lower-triangular mask (query i attends keys <= i).
    """
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if scale is None:
        scale = D**-0.5

    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ).astype(jnp.float32) * scale

    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    kv_mask = None
    if kv_valid_len is not None:
        # accepts trace-time constants (numpy/tuple) or traced int arrays
        # (dynamic suffix-pad masking)
        lens = jnp.asarray(kv_valid_len, jnp.int32).reshape(B, H)[:, :, None, None]
        kv_mask = jnp.arange(Lk)[None, None, None, :] >= lens
        logits = jnp.where(kv_mask, NEG_INF, logits)
    pad_mask = None
    if key_padding_mask is not None:
        pad_mask = key_padding_mask[:, None, None, :]
        logits = jnp.where(pad_mask, NEG_INF, logits)
    if is_causal:
        qi = jnp.arange(Lq)[:, None] + (Lk - Lq)  # align ends when Lq != Lk
        ki = jnp.arange(Lk)[None, :]
        logits = jnp.where(ki > qi, NEG_INF, logits)

    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B, H, Lq]
    probs = jnp.exp(logits - lse[..., None])
    if kv_mask is not None:
        # rows with zero valid keys yield out=0, not a mean over masked slots
        # (matches the Pallas kernel's explicit zeroing)
        probs = jnp.where(kv_mask, 0.0, probs)
    if pad_mask is not None:
        # same zeroing for key_padding_mask: a fully-padded row otherwise
        # degenerates to uniform probs (mean of V) instead of zeros
        probs = jnp.where(pad_mask, 0.0, probs)

    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)

    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype), lse


class MultiheadAttention(nn.Module):
    """Self/cross attention block with q/k/v/out projections.

    Parity with reference ``multihead_attention.py:20-171``: optional xPos
    rotary position, optional sub-LayerNorm on the attention output
    (``subln``), an inner attention op returning ``(out, lse)``, and
    Multiway (BEiT-3) two-branch projections/inner-LN when ``multiway`` is
    set — the split index is passed per call as ``multiway_split_position``,
    mirroring the reference's ``MultiwayWrapper``-wrapped projections.
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    self_attention: bool = True
    encoder_decoder_attention: bool = False
    subln: bool = False
    layernorm_eps: float = 1e-5
    xpos_rel_pos: bool = False
    xpos_scale_base: int = 512
    multiway: bool = False
    dtype: Any = None

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def _attend(
        self,
        q: jnp.ndarray,
        k: jnp.ndarray,
        v: jnp.ndarray,
        *,
        key_padding_mask=None,
        attn_mask=None,
        rel_pos=None,
        is_causal: bool = False,
        deterministic: bool = True,
        offset: int = 0,
    ) -> jnp.ndarray:
        """Inner attention on [B, L, H, D] tensors -> [B, Lq, H*D].

        Subclasses (DilatedAttention) override this to restructure the
        sequence around the core op. ``offset`` is the decode position of
        the first query row — only produced by subclasses that opt into
        positional cache handling (see ``_cached_attend_inputs``).
        """
        assert offset == 0, "base attention consumes the cache via its bias"
        bias = None
        if attn_mask is not None:
            bias = attn_mask
        if rel_pos is not None:
            rel = rel_pos.reshape(q.shape[0], self.num_heads, q.shape[1], k.shape[1])
            bias = rel if bias is None else bias + rel
        rng = None
        if self.dropout > 0.0 and not deterministic:
            rng = self.make_rng("dropout")
        out, _ = attention_with_lse(
            q,
            k,
            v,
            bias=bias,
            key_padding_mask=key_padding_mask,
            is_causal=is_causal,
            dropout_rate=0.0 if deterministic else self.dropout,
            dropout_rng=rng,
        )
        return out.reshape(out.shape[0], out.shape[1], self.embed_dim)

    def _cached_attend_inputs(self, k, v, cur, Lq, attn_mask, is_causal):
        """Turn the updated KV cache into inputs for ``_attend``.

        Returns ``(k, v, attn_mask, is_causal, offset)``. The base class
        attends the whole static cache buffer with future rows masked by a
        per-query bias: query row i (absolute position cur+i) may attend
        keys <= cur+i — correct for single-token steps AND multi-token
        chunked prefill. DilatedAttention overrides this with positional
        (offset-based) handling, because its segment structure needs real
        positions rather than a dense mask.
        """
        max_len = k.shape[1]
        qi = jnp.arange(Lq)[:, None]
        ki = jnp.arange(max_len)[None, :]
        cache_bias = jnp.where(ki <= (cur + qi), 0.0, NEG_INF)[None, None]
        attn_mask = cache_bias if attn_mask is None else attn_mask + cache_bias
        return k, v, attn_mask, False, 0  # the cache bias supersedes the triangle

    @nn.compact
    def __call__(
        self,
        query: jnp.ndarray,
        key: jnp.ndarray,
        value: jnp.ndarray,
        *,
        key_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        rel_pos: Optional[jnp.ndarray] = None,
        is_causal: bool = False,
        decode: bool = False,
        multiway_split_position: int = -1,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        assert self.self_attention ^ self.encoder_decoder_attention
        B, Lq, _ = query.shape
        H, Dh = self.num_heads, self.head_dim

        from gigapath_tpu.ops.multiway import maybe_multiway

        def proj(name: str, x: jnp.ndarray) -> jnp.ndarray:
            make = lambda name: nn.Dense(  # noqa: E731
                self.embed_dim,
                use_bias=True,
                dtype=self.dtype,
                kernel_init=nn.initializers.xavier_uniform(),
                name=name,
            )
            return maybe_multiway(self.multiway, make, name)(
                x, split_position=multiway_split_position
            )

        q = proj("q_proj", query).reshape(B, Lq, H, Dh)
        k = proj("k_proj", key).reshape(B, key.shape[1], H, Dh)
        v = proj("v_proj", value).reshape(B, value.shape[1], H, Dh)

        if self.xpos_rel_pos and self.self_attention:
            from gigapath_tpu.ops.xpos import apply_xpos

            assert not decode, "xPos + incremental decode not supported"
            k = apply_xpos(k, scale_base=self.xpos_scale_base, downscale=True)
            q = apply_xpos(q, scale_base=self.xpos_scale_base, downscale=False)

        decode_offset = 0
        if decode and self.self_attention:
            # flax-style KV cache: the incremental-state counterpart of the
            # reference (multihead_attention.py:129-144 stores prev_key/
            # prev_value dicts). Cache shape is fixed by the first (init)
            # call; subsequent calls write the new rows at cache_index and
            # attend the buffer through the subclass-selected mechanism.
            is_initialized = self.has_variable("cache", "cached_key")
            cached_key = self.variable("cache", "cached_key", jnp.zeros, k.shape, k.dtype)
            cached_value = self.variable("cache", "cached_value", jnp.zeros, v.shape, v.dtype)
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.array(0, jnp.int32)
            )
            if is_initialized:
                cur = cache_index.value
                k = jax.lax.dynamic_update_slice(cached_key.value, k, (0, cur, 0, 0))
                v = jax.lax.dynamic_update_slice(cached_value.value, v, (0, cur, 0, 0))
                cached_key.value, cached_value.value = k, v
                cache_index.value = cur + Lq
                k, v, attn_mask, is_causal, decode_offset = (
                    self._cached_attend_inputs(k, v, cur, Lq, attn_mask, is_causal)
                )

        attn = self._attend(
            q,
            k,
            v,
            key_padding_mask=key_padding_mask,
            attn_mask=attn_mask,
            rel_pos=rel_pos,
            is_causal=is_causal,
            deterministic=deterministic,
            offset=decode_offset,
        )

        if self.subln and self.self_attention:
            from gigapath_tpu.ops.multiway import multiway_layernorm

            attn = multiway_layernorm(
                self.multiway,
                "inner_attn_ln",
                epsilon=self.layernorm_eps,
                dtype=self.dtype,
            )(attn, split_position=multiway_split_position)

        return proj("out_proj", attn)
