"""Fused dilated-attention branch kernel (phase-major layout), fwd + bwd.

Second-generation Pallas path for LongNet dilated attention (the reference's
``torchscale/component/dilated_attention.py`` branch loop). The first
generation ran a segment-grid flash kernel on a head-major ``[B, H, S, M, D]``
layout; profiling showed the kernel itself was fine but the XLA glue around it
(BLHD<->BHLD relayouts with a 48-wide minor dim, per-branch dilation
selects/scatters, and the mega-fusions XLA built across them) cost more than
the attention math. This kernel removes that glue by construction:

- Activations stay ``[B, L, E]`` (E = H*Dh, 128-lane aligned) end to end.
  Per branch, dense tensors are packed into a DIAGONAL-ONLY phase-major
  layout ``[B, S, r, H/r, Mp, Dh]`` holding just the (phase == band) data
  — 1/r of the dense volume — by small Pallas copy kernels (static-phase
  strided row extraction + static lane slices, measured 3.5x faster than
  the round-3 XLA 7-D transpose whose 48-minor reshape re-tiled at
  T(2,128) and materialized all r^2 (phase, band) blocks).
- A dilated branch with ratio ``r`` makes head band ``p`` (heads
  ``p*H/r .. (p+1)*H/r - 1``) attend exactly the tokens of phase ``p``
  (positions ``s*g + p + r*j``, ``dense_to_sparse`` in the reference) —
  the packed layout's index maps deliver that directly: dilation costs
  nothing inside the attention kernel.
- One head per grid cell — grid ``(B, S, r, nq, hb, nk)`` with ``[block,
  Dh]`` blocks whose lane range the head grid index picks via the packed
  array's head dim. (Unrolling a band's heads over lane slices of a single
  ``[block, E/r]`` tile was ~1.6x slower: Mosaic lane shuffles.)
- The unpack kernel writes off-band lanes of the dense result as exact
  zeros — the branch's cover pattern — so no separate cover-mask select
  exists anywhere, and the cross-branch fusion gives uncovered slots
  weight 0 through the NEG_INF lse. Gradients at those slots are genuinely
  zero, which the same zero-fill provides in the backward.
- The log-sum-exp per (token, head) — required by the cross-branch fusion
  (reference ``dilated_attention.py:119-128``) — is emitted compactly as
  ``[B, S, r, M, LANES]`` with one lane per band head.

Same numerics as ``pallas_flash.py``: fp32 online softmax (base-2 in the
forward: log2(e) folds into the q scale so the hot loop runs ``exp2``),
running max floored at ``M_FLOOR`` so masked/padded slots underflow to
exactly 0 and fully-masked rows produce out=0 / lse ~ -7e19, ragged tails
masked from an SMEM table of per-(segment, phase) valid counts with
fully-masked key blocks skipped.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gigapath_tpu.ops.pallas_flash import (  # shared kernel numerics
    LANES,
    LN2,
    LOG2E,
    M_FLOOR,
    NEG_INF,
    round_up as _round_up,
)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, kvlen_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, *, scale, causal,
                block_q, block_k):
    # grid (B, S, r, nq, hb, nk): one head-band slice per cell — blocks are
    # [block, Dh] lane slices picked by the head index in the BlockSpecs, so
    # the body never slices lanes (Mosaic lane shuffles measured ~1.6x the
    # whole kernel cost when heads were unrolled over an [block, W] tile)
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    i, t, j = pl.program_id(3), pl.program_id(4), pl.program_id(5)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, M_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _online_step(masked: bool):
        # log2(e) folded into the scale: exp2 instead of exp in the hot loop
        qh = (q_ref[0, 0, 0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(
            q_ref.dtype
        )  # [bq, Dh]
        s_ = jax.lax.dot_general(
            qh, k_ref[0, 0, 0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk], in log2 units
        if masked:
            # select, not additive bias, masking BEFORE the running max
            # (same rationale as pallas_flash._fwd_kernel: masked slots can
            # hold real activations after residual layers)
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
                < kvlen_ref[b, s, p]
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            s_ = jnp.where(cols > rows, NEG_INF, s_)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1, keepdims=True))
        pp = jnp.exp2(s_ - m_new)
        if pl.num_programs(5) == 1:
            # single k block: no online carry — skip the acc rescale and
            # write the stats once
            l_new = jnp.sum(pp, axis=-1, keepdims=True)
            acc_ref[:] = jax.lax.dot_general(
                pp.astype(v_ref.dtype), v_ref[0, 0, 0, 0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            alpha = jnp.exp2(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(pp, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                pp.astype(v_ref.dtype), v_ref[0, 0, 0, 0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        # single-lane stats stores (a broadcast-to-128-lane store writes
        # 128x the bytes per step)
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    # full key blocks skip the col-mask VPU pass entirely; only the block
    # straddling the valid-key boundary pays for masking
    @pl.when((j + 1) * block_k <= kvlen_ref[b, s, p])
    def _compute_full():
        _online_step(masked=False)

    @pl.when(
        (j * block_k < kvlen_ref[b, s, p])
        & ((j + 1) * block_k > kvlen_ref[b, s, p])
    )
    def _compute_partial():
        _online_step(masked=True)

    @pl.when(j == pl.num_programs(5) - 1)
    def _finalize():
        safe_l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0, 0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # natural-log lse from the base-2 stats, written into lane t of the
        # shared [bq, LANES] block. The block persists in VMEM across the
        # (t, j) iterations of one i, so each head deposits its lane; lanes
        # beyond the band's heads keep the t=0 fill (sliced off outside).
        val = (m_ref[:, :1] + jnp.log2(safe_l)) * LN2  # [bq, 1]
        lane = jax.lax.broadcasted_iota(jnp.int32, (block_q, LANES), 1)

        @pl.when(t == 0)
        def _first_head():
            lse_ref[0, 0, 0] = jnp.where(lane == 0, val, NEG_INF)

        @pl.when(t > 0)
        def _later_head():
            lse_ref[0, 0, 0] = jnp.where(lane == t, val, lse_ref[0, 0, 0])


def _fwd_kernel_pipe(q_ref, k_ref, v_ref, kvlen_ref, o_ref, lse_ref,
                     m_ref, l_ref, acc_ref, s_bufs, *, scale,
                     block_q, block_k, hb, nk):
    """Software-pipelined forward: grid (B, S, r, nq, hb*nk + 1).

    The serial kernel's body is a strict MXU -> VPU -> MXU dependence
    chain (QK^T, softmax, PV), so the VPU softmax serializes behind the
    MXU and cells measure ~1.7-1.9x over the Dh=48 shape bound
    (PERFORMANCE.md round-4 decomposition). This variant restructures the
    chain across grid steps: step n computes cell n's logits (MXU, into a
    parity scratch) and consumes cell n-1's logits (VPU softmax + PV) —
    every body opens with a big MXU matmul that is data-independent of
    the VPU chain that follows, which is the opportunity the serial body
    never gives the Mosaic scheduler. Cells are the flattened (head,
    k-block) steps of one q block; v/out index maps lag one step. The
    round-3 in-cell k-split (memory: rejected, 2.83->3.05 ms) differs
    materially: its two softmax chains shared the running (m, l) carry,
    so the "independent" matmul was bracketed by dependent VPU work.

    Non-causal only (the fused path's production use); the serial kernel
    remains for causal and as the default until the on-chip A/B decides.
    """
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n = pl.program_id(4)
    total = hb * nk
    kv = kvlen_ref[b, s, p]
    j_p = jax.lax.rem(n, nk)
    t_c = jax.lax.div(n - 1, nk)
    j_c = jax.lax.rem(n - 1, nk)

    # ---- produce: cell n's logits into the parity scratch (MXU) ----
    @pl.when((n < total) & (j_p * block_k < kv))
    def _produce():
        qh = (q_ref[0, 0, 0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(
            q_ref.dtype
        )
        s_bufs[jax.lax.rem(n, 2)] = jax.lax.dot_general(
            qh, k_ref[0, 0, 0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # ---- consume: cell n-1's logits (VPU softmax + PV matmul) ----
    @pl.when((n >= 1) & (j_c == 0))
    def _init():
        m_ref[:] = jnp.full_like(m_ref, M_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _consume(masked: bool):
        s_ = s_bufs[jax.lax.rem(n - 1, 2)]
        if masked:
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
                + j_c * block_k
                < kv
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1, keepdims=True))
        pp = jnp.exp2(s_ - m_new)
        if nk == 1:
            # single k block per head: no online carry (see _fwd_kernel)
            l_new = jnp.sum(pp, axis=-1, keepdims=True)
            acc_ref[:] = jax.lax.dot_general(
                pp.astype(v_ref.dtype), v_ref[0, 0, 0, 0],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            )
        else:
            alpha = jnp.exp2(m_prev - m_new)
            l_new = l_ref[:, :1] * alpha + jnp.sum(pp, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                pp.astype(v_ref.dtype), v_ref[0, 0, 0, 0],
                (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            )
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when((n >= 1) & ((j_c + 1) * block_k <= kv))
    def _consume_full():
        _consume(masked=False)

    @pl.when((n >= 1) & (j_c * block_k < kv) & ((j_c + 1) * block_k > kv))
    def _consume_partial():
        _consume(masked=True)

    @pl.when((n >= 1) & (j_c == nk - 1))
    def _finalize():
        safe_l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0, 0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        val = (m_ref[:, :1] + jnp.log2(safe_l)) * LN2
        lane = jax.lax.broadcasted_iota(jnp.int32, (block_q, LANES), 1)

        @pl.when(t_c == 0)
        def _first_head():
            lse_ref[0, 0, 0] = jnp.where(lane == 0, val, NEG_INF)

        @pl.when(t_c > 0)
        def _later_head():
            lse_ref[0, 0, 0] = jnp.where(lane == t_c, val, lse_ref[0, 0, 0])


def _fwd_impl_pipe(q6, k6, v6, kvlen, scale, heads, head_dim,
                   block_q, block_k, interpret):
    """Pipelined forward dispatch: same contract as _fwd_impl (non-causal).

    block_k may differ from block_q (a shallower k block deepens the
    pipeline); the k/v packed arrays are zero-padded to a block_k multiple
    — padded blocks are skipped by the kvlen guards."""
    B, S, r, hb, M, Dh = q6.shape
    Mk = k6.shape[4]
    assert hb == heads and Dh == head_dim, (hb, heads, Dh, head_dim)
    Mkp = _round_up(Mk, block_k)
    if Mkp != Mk:
        pad = ((0, 0), (0, 0), (0, 0), (0, 0), (0, Mkp - Mk), (0, 0))
        k6 = jnp.pad(k6, pad)
        v6 = jnp.pad(v6, pad)
    nq, nk = M // block_q, Mkp // block_k
    total = hb * nk

    def t_p(n):
        return jnp.minimum(n // nk, hb - 1)

    def cell_c(n):
        tc = jnp.clip((n - 1) // nk, 0, hb - 1)
        jc = jnp.clip(n - 1 - tc * nk, 0, nk - 1)
        return tc, jc

    spec_q = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, i, n: (b, s, p, t_p(n), i, 0),
        memory_space=pltpu.VMEM,
    )
    spec_k = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        # j clamped: at the drain step (n == hb*nk) no produce executes but
        # the index must still name a real block
        lambda b, s, p, i, n: (
            b, s, p, t_p(n), jnp.minimum(n - t_p(n) * nk, nk - 1), 0,
        ),
        memory_space=pltpu.VMEM,
    )
    def v_map(b, s, p, i, n):
        tc, jc = cell_c(n)
        return (b, s, p, tc, jc, 0)

    spec_v = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim), v_map, memory_space=pltpu.VMEM,
    )

    def o_map(b, s, p, i, n):
        tc, _ = cell_c(n)
        return (b, s, p, tc, i, 0)

    spec_o = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim), o_map, memory_space=pltpu.VMEM,
    )
    lse_spec = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), lambda b, s, p, i, n: (b, s, p, i, 0),
        memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _fwd_kernel_pipe, scale=scale,
        block_q=block_q, block_k=block_k, hb=hb, nk=nk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, S, r, nq, total + 1),
        in_specs=[spec_q, spec_k, spec_v, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec_o, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q6.shape, q6.dtype),
            jax.ShapeDtypeStruct((B, S, r, M, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((2, block_q, block_k), jnp.float32),
        ],
        interpret=interpret,
    )(q6, k6, v6, kvlen)
    return out, lse


def _fwd_impl(q6, k6, v6, kvlen, causal, scale, heads, head_dim,
              block_q, block_k, interpret):
    B, S, r, hb, M, Dh = q6.shape
    Mk = k6.shape[4]
    nq, nk = M // block_q, Mk // block_k
    assert hb == heads and Dh == head_dim, (hb, heads, Dh, head_dim)

    spec_q = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, i, t, j: (b, s, p, t, i, 0),
        memory_space=pltpu.VMEM,
    )
    spec_k = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        lambda b, s, p, i, t, j: (b, s, p, t, j, 0),
        memory_space=pltpu.VMEM,
    )
    lse_spec = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), lambda b, s, p, i, t, j: (b, s, p, i, 0),
        memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, S, r, nq, heads, nk),
        in_specs=[spec_q, spec_k, spec_k, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec_q, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q6.shape, q6.dtype),
            jax.ShapeDtypeStruct((B, S, r, M, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q6, k6, v6, kvlen)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _lane(vec_block, t, block_q):
    """Extract lane ``t`` (a traced grid index) of a [bq, LANES] block as
    [bq, 1]: mask-and-rowsum, no dynamic lane slicing."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (block_q, LANES), 1)
    return jnp.sum(jnp.where(lane == t, vec_block, 0.0), axis=1, keepdims=True)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kvlen_ref,
               dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    i, t, j = pl.program_id(3), pl.program_id(4), pl.program_id(5)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _compute(masked: bool):
        qh = q_ref[0, 0, 0, 0]
        kh = k_ref[0, 0, 0, 0]
        # base-2 recompute (exp2 = one fewer VPU pass per logit than exp);
        # the natural-log lse rescales on its [bq, 1] column, not per logit
        s_ = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (scale * LOG2E)
        if masked:
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
                < kvlen_ref[b, s, p]
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        pp = jnp.exp2(s_ - _lane(lse_ref[0, 0, 0], t, block_q) * LOG2E)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            pp = jnp.where(cols > rows, 0.0, pp)
        dp = jax.lax.dot_general(
            do_ref[0, 0, 0, 0].astype(jnp.float32),
            v_ref[0, 0, 0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = pp * (dp - _lane(delta_ref[0, 0, 0], t, block_q))
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kh.dtype), kh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    # full key blocks skip the col-mask pass (see _fwd_kernel)
    @pl.when((j + 1) * block_k <= kvlen_ref[b, s, p])
    def _compute_full():
        _compute(masked=False)

    @pl.when(
        (j * block_k < kvlen_ref[b, s, p])
        & ((j + 1) * block_k > kvlen_ref[b, s, p])
    )
    def _compute_partial():
        _compute(masked=True)

    @pl.when(j == pl.num_programs(5) - 1)
    def _finalize():
        dq_ref[0, 0, 0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kvlen_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block_q, block_k):
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    j, t, i = pl.program_id(3), pl.program_id(4), pl.program_id(5)  # grid: (B, S, r, nk, hb, nq)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute(masked: bool):
        qh = q_ref[0, 0, 0, 0]
        kh = k_ref[0, 0, 0, 0]
        s_ = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (scale * LOG2E)  # base-2 units (see _dq_kernel)
        if masked:
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
                < kvlen_ref[b, s, p]
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        pp = jnp.exp2(s_ - _lane(lse_ref[0, 0, 0], t, block_q) * LOG2E)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            pp = jnp.where(cols > rows, 0.0, pp)
        do_h = do_ref[0, 0, 0, 0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            pp, do_h, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_h, v_ref[0, 0, 0, 0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = pp * (dp - _lane(delta_ref[0, 0, 0], t, block_q))
        dk_acc[:] += jax.lax.dot_general(
            ds, qh.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when((j + 1) * block_k <= kvlen_ref[b, s, p])
    def _compute_full():
        _compute(masked=False)

    @pl.when(
        (j * block_k < kvlen_ref[b, s, p])
        & ((j + 1) * block_k > kvlen_ref[b, s, p])
    )
    def _compute_partial():
        _compute(masked=True)

    @pl.when(i == pl.num_programs(5) - 1)
    def _finalize():
        dk_ref[0, 0, 0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, 0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _dq_kernel_pipe(q_ref, k_ref, v_ref, kc_ref, do_ref, lse_ref, delta_ref,
                    kvlen_ref, dq_ref, dq_acc, s_bufs, dp_bufs, *, scale,
                    block_q, block_k, hb, nk):
    """Software-pipelined dQ: grid (B, S, r, nq, hb*nk + 1).

    Step n computes BOTH of cell n's matmuls that feed the VPU chain —
    s_n = (q*scale)@k_n^T and dp_n = do@v_n^T — into parity scratches,
    then consumes cell n-1: p = exp2(s - lse), ds = p*(dp - delta) (VPU)
    and dq_acc += ds@k (MXU, via the LAGGED second k input kc_ref). Same
    restructuring rationale as _fwd_kernel_pipe. Non-causal only."""
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    n = pl.program_id(4)
    total = hb * nk
    kv = kvlen_ref[b, s, p]
    j_p = jax.lax.rem(n, nk)
    t_c = jax.lax.div(n - 1, nk)
    j_c = jax.lax.rem(n - 1, nk)

    @pl.when((n < total) & (j_p * block_k < kv))
    def _produce():
        qh = (q_ref[0, 0, 0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(
            q_ref.dtype
        )
        par = jax.lax.rem(n, 2)
        s_bufs[par] = jax.lax.dot_general(
            qh, k_ref[0, 0, 0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_bufs[par] = jax.lax.dot_general(
            do_ref[0, 0, 0, 0].astype(jnp.float32),
            v_ref[0, 0, 0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when((n >= 1) & (j_c == 0))
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _consume(masked: bool):
        par = jax.lax.rem(n - 1, 2)
        s_ = s_bufs[par]
        if masked:
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
                + j_c * block_k
                < kv
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        pp = jnp.exp2(s_ - _lane(lse_ref[0, 0, 0], t_c, block_q) * LOG2E)
        ds = pp * (dp_bufs[par] - _lane(delta_ref[0, 0, 0], t_c, block_q))
        kh = kc_ref[0, 0, 0, 0]
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(kh.dtype), kh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when((n >= 1) & ((j_c + 1) * block_k <= kv))
    def _consume_full():
        _consume(masked=False)

    @pl.when((n >= 1) & (j_c * block_k < kv) & ((j_c + 1) * block_k > kv))
    def _consume_partial():
        _consume(masked=True)

    @pl.when((n >= 1) & (j_c == nk - 1))
    def _finalize():
        dq_ref[0, 0, 0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel_pipe(q_ref, k_ref, v_ref, qc_ref, doc_ref, do_ref, lse_ref,
                     delta_ref, kvlen_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                     s_bufs, dp_bufs, *, scale, block_q, block_k, hb, nq):
    """Software-pipelined dK/dV: grid (B, S, r, nk, hb*nq + 1).

    Per k block j, the flattened (head, q-block) steps pipeline: step n
    produces s_n = (q*scale)@k^T and dp_n = do@v^T (MXU), consumes cell
    n-1's p/ds (VPU) + the dv/dk accumulation matmuls against the LAGGED
    q/do inputs (qc_ref/doc_ref). lse/delta index maps lag too. Non-causal
    only."""
    b, s, p = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    j = pl.program_id(3)
    n = pl.program_id(4)
    total = hb * nq
    kv = kvlen_ref[b, s, p]
    t_c = jax.lax.div(n - 1, nq)
    i_c = jax.lax.rem(n - 1, nq)

    @pl.when((n < total) & (j * block_k < kv))
    def _produce():
        qh = (q_ref[0, 0, 0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(
            q_ref.dtype
        )
        par = jax.lax.rem(n, 2)
        s_bufs[par] = jax.lax.dot_general(
            qh, k_ref[0, 0, 0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_bufs[par] = jax.lax.dot_general(
            do_ref[0, 0, 0, 0].astype(jnp.float32),
            v_ref[0, 0, 0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )

    @pl.when((n >= 1) & (i_c == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _consume(masked: bool):
        par = jax.lax.rem(n - 1, 2)
        s_ = s_bufs[par]
        if masked:
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
                + j * block_k
                < kv
            )
            s_ = jnp.where(col_ok, s_, NEG_INF)
        pp = jnp.exp2(s_ - _lane(lse_ref[0, 0, 0], t_c, block_q) * LOG2E)
        do_h = doc_ref[0, 0, 0, 0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            pp, do_h, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = pp * (dp_bufs[par] - _lane(delta_ref[0, 0, 0], t_c, block_q))
        dk_acc[:] += jax.lax.dot_general(
            ds, qc_ref[0, 0, 0, 0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when((n >= 1) & ((j + 1) * block_k <= kv))
    def _consume_full():
        _consume(masked=False)

    @pl.when((n >= 1) & (j * block_k < kv) & ((j + 1) * block_k > kv))
    def _consume_partial():
        _consume(masked=True)

    @pl.when((n >= 1) & (i_c == nq - 1))
    def _finalize():
        dk_ref[0, 0, 0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, 0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _pipe_bwd_block_k(block_q: int, override: Optional[int]) -> int:
    """k block for the pipelined backward: the parity scratches double the
    live fp32 logits tiles (~6 at peak: s2, dp2, pp, ds), so cap
    bq*bk <= 512k elements (~12 MB across 6 tiles). ``override`` comes
    from the PipelineFlags snapshot (GIGAPATH_PIPE_BWD_BLOCK_K), read
    once at dispatch — never from the environment here, where the value
    would be baked into the jit cache invisibly (gigalint GL001)."""
    if override:
        return max(LANES, min(override, block_q))
    bk = 512
    while bk > LANES and block_q * bk > 512 * 1024:
        bk //= 2
    return min(bk, block_q)


def _bwd_impl_pipe(q6, k6, v6, do6, lse, delta, kvlen, scale,
                   heads, head_dim, block_q, block_k, interpret):
    """Pipelined backward dispatch: same contract as _bwd_impl (non-causal).
    k/v padded to a block_k multiple; padded blocks skipped by kvlen."""
    B, S, r, hb, M, Dh = q6.shape
    Mk = k6.shape[4]
    Mkp = _round_up(Mk, block_k)
    if Mkp != Mk:
        pad = ((0, 0), (0, 0), (0, 0), (0, 0), (0, Mkp - Mk), (0, 0))
        k6p = jnp.pad(k6, pad)
        v6p = jnp.pad(v6, pad)
    else:
        k6p, v6p = k6, v6
    nq, nk = M // block_q, Mkp // block_k
    total_q = hb * nk

    def t_p(n):
        return jnp.minimum(n // nk, hb - 1)

    def cell_c(n, inner):
        tc = jnp.clip((n - 1) // inner, 0, hb - 1)
        jc = jnp.clip(n - 1 - tc * inner, 0, inner - 1)
        return tc, jc

    # ---- dQ: grid (B, S, r, nq, hb*nk + 1) ----
    spec_q = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, i, n: (b, s, p, t_p(n), i, 0),
        memory_space=pltpu.VMEM,
    )
    spec_k_prod = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        lambda b, s, p, i, n: (
            b, s, p, t_p(n), jnp.minimum(n - t_p(n) * nk, nk - 1), 0,
        ),
        memory_space=pltpu.VMEM,
    )

    def kc_map(b, s, p, i, n):
        tc, jc = cell_c(n, nk)
        return (b, s, p, tc, jc, 0)

    spec_k_cons = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim), kc_map, memory_space=pltpu.VMEM,
    )

    def dq_map(b, s, p, i, n):
        tc, _ = cell_c(n, nk)
        return (b, s, p, tc, i, 0)

    spec_dq = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim), dq_map, memory_space=pltpu.VMEM,
    )
    vec_spec = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), lambda b, s, p, i, n: (b, s, p, i, 0),
        memory_space=pltpu.VMEM,
    )
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel_pipe, scale=scale,
            block_q=block_q, block_k=block_k, hb=hb, nk=nk,
        ),
        grid=(B, S, r, nq, total_q + 1),
        in_specs=[spec_q, spec_k_prod, spec_k_prod, spec_k_cons, spec_q,
                  vec_spec, vec_spec, smem],
        out_specs=[spec_dq],
        out_shape=[jax.ShapeDtypeStruct(q6.shape, q6.dtype)],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((2, block_q, block_k), jnp.float32),
            pltpu.VMEM((2, block_q, block_k), jnp.float32),
        ],
        interpret=interpret,
    )(q6, k6p, v6p, k6p, do6, lse, delta, kvlen)[0]

    # ---- dK/dV: grid (B, S, r, nk, hb*nq + 1) ----
    total_kv = hb * nq

    def t_p_kv(n):
        return jnp.minimum(n // nq, hb - 1)

    spec_q_prod = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, j, n: (
            b, s, p, t_p_kv(n), jnp.minimum(n - t_p_kv(n) * nq, nq - 1), 0,
        ),
        memory_space=pltpu.VMEM,
    )

    def qc_map(b, s, p, j, n):
        tc, ic = cell_c(n, nq)
        return (b, s, p, tc, ic, 0)

    spec_q_cons = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim), qc_map, memory_space=pltpu.VMEM,
    )
    spec_k_kv = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        lambda b, s, p, j, n: (b, s, p, t_p_kv(n), j, 0),
        memory_space=pltpu.VMEM,
    )

    def dk_map(b, s, p, j, n):
        tc, _ = cell_c(n, nq)
        return (b, s, p, tc, j, 0)

    spec_dk = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim), dk_map, memory_space=pltpu.VMEM,
    )

    def vec_c_map(b, s, p, j, n):
        _, ic = cell_c(n, nq)
        return (b, s, p, ic, 0)

    vec_spec_c = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), vec_c_map, memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel_pipe, scale=scale,
            block_q=block_q, block_k=block_k, hb=hb, nq=nq,
        ),
        grid=(B, S, r, nk, total_kv + 1),
        in_specs=[spec_q_prod, spec_k_kv, spec_k_kv, spec_q_cons, spec_q_cons,
                  spec_q_prod, vec_spec_c, vec_spec_c, smem],
        out_specs=[spec_dk, spec_dk],
        out_shape=[
            jax.ShapeDtypeStruct(k6p.shape, k6.dtype),
            jax.ShapeDtypeStruct(v6p.shape, v6.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((2, block_q, block_k), jnp.float32),
            pltpu.VMEM((2, block_q, block_k), jnp.float32),
        ],
        interpret=interpret,
    )(q6, k6p, v6p, q6, do6, do6, lse, delta, kvlen)
    if Mkp != Mk:
        dk = dk[:, :, :, :, :Mk]
        dv = dv[:, :, :, :, :Mk]
    return dq, dk, dv


class PipelineFlags(NamedTuple):
    """One trace-stable snapshot of the kernel-dispatch env flags.

    Read ONCE per public ``dilated_branch_attention`` call (host side, at
    dispatch) and threaded through the custom_vjp as a static argument, so
    the forward and backward of one call can never observe different flag
    values, and no traced code reads the environment (gigalint GL001).
    Toggling a flag still only affects future traces — the jit cache keys
    on the traced program, not the environment; see the README flag table
    for the fresh-function-identity workaround.
    """

    pipelined_fwd: bool = False
    pipelined_bwd: bool = False
    pipe_block_k: Optional[int] = None  # None: VMEM-budget auto choice
    pipe_bwd_block_k: Optional[int] = None
    pack_direct: bool = False
    stream_fusion: bool = False
    # ring-scheduled K/V exchange for gathered sequence-parallel branches
    # (ops/dilated_attention.py): per-shard memory O(local chunk) instead
    # of O(full segment), ppermute overlapped with partial attention
    ring_attn: bool = False
    # streaming chunked prefill (ops/streaming_prefill.py): drivers that
    # hold a snapshot route slide forwards through the chunk-fold path
    # (dist consumer, inference --stream default) instead of
    # assemble-then-encode; the dense path stays the fallback/oracle
    chunked_prefill: bool = False
    # quantized tile-encoder tier (gigapath_tpu/quant/): '' = off (the
    # f32/bf16 fallback and parity oracle), 'int8' / 'fp8_e4m3' =
    # quantized Dense kernels, '+attn' rider = int8 attention logits
    # too. Drivers holding a snapshot pass this into the tile-encoder
    # factory; the quant ops themselves never read the environment
    quant_tile: str = ""
    # Pallas tier for the quantized matmul/attention kernels (the jnp
    # reference formulation is the default tier)
    quant_pallas: bool = False
    # online dense branch fold (GIGAPATH_STREAMING_FUSION — the
    # memory-motivated near-namesake of stream_fusion above): fold
    # dilated branches into running (acc, m, l) instead of stacking all
    # branch outputs. Lives in the snapshot since the plan refactor so
    # the dispatcher reads it from ONE resolved carrier, never the
    # environment (gigalint GL017)
    streaming_fusion: bool = False
    # per-branch-class plan entries from a blessed ExecutionPlan
    # (gigapath_tpu/plan/): (segment_length, ratio, variant, block) —
    # variant "" inherits the global pipelined flags, "serial"/
    # "pipelined" pin the branch's forward kernel family, block (a
    # 128-multiple in [128, 1024]; 0 = auto) overrides the phase-major
    # q/k block of _branch_geometry. Never set from the environment:
    # only resolve_plan fills it, so an empty tuple keeps dispatch
    # byte-identical to the flag-only behavior
    branch_plans: Tuple[Tuple[int, int, str, int], ...] = ()
    # Pallas tier for the streaming-fold pair partial
    # (ops/pallas_streaming.py): in-kernel iota masks instead of the jnp
    # oracle's dense [H, cq, ck] mask tensors. False keeps the fold
    # byte-identical to the jnp path (the parity oracle)
    fold_pallas: bool = False
    # global fold block overrides (None: DEFAULT_FOLD_BLOCK auto choice)
    fold_block_q: Optional[int] = None
    fold_block_k: Optional[int] = None
    # per-fold-branch-class plan entries: (segment_length, ratio,
    # block_q, block_k), 0 = auto. Plan-only data like branch_plans:
    # only resolve_plan fills it
    fold_branches: Tuple[Tuple[int, int, int, int], ...] = ()


# field -> environment twin: the one mapping the plan resolver
# (gigapath_tpu/plan/executionplan.py) uses to decide which fields the
# environment has pinned (env wins) and which a blessed plan may fill.
# branch_plans has no env twin on purpose — per-branch entries are
# plan-only data.
FLAG_ENV = {
    "pipelined_fwd": "GIGAPATH_PIPELINED_ATTN",
    "pipelined_bwd": "GIGAPATH_PIPELINED_BWD",
    "pipe_block_k": "GIGAPATH_PIPE_BLOCK_K",
    "pipe_bwd_block_k": "GIGAPATH_PIPE_BWD_BLOCK_K",
    "pack_direct": "GIGAPATH_PACK_DIRECT",
    "stream_fusion": "GIGAPATH_STREAM_FUSION",
    "streaming_fusion": "GIGAPATH_STREAMING_FUSION",
    "ring_attn": "GIGAPATH_RING_ATTN",
    "chunked_prefill": "GIGAPATH_CHUNKED_PREFILL",
    "quant_tile": "GIGAPATH_QUANT_TILE",
    "quant_pallas": "GIGAPATH_QUANT_PALLAS",
    "fold_pallas": "GIGAPATH_FOLD_PALLAS",
    "fold_block_q": "GIGAPATH_FOLD_BLOCK_Q",
    "fold_block_k": "GIGAPATH_FOLD_BLOCK_K",
}


def snapshot_flags() -> PipelineFlags:
    """Read GIGAPATH_PIPELINED_ATTN/_BWD, GIGAPATH_PIPE(_BWD)_BLOCK_K,
    GIGAPATH_PACK_DIRECT, GIGAPATH_STREAM_FUSION,
    GIGAPATH_STREAMING_FUSION, GIGAPATH_RING_ATTN,
    GIGAPATH_CHUNKED_PREFILL, GIGAPATH_QUANT_TILE,
    GIGAPATH_QUANT_PALLAS, GIGAPATH_FOLD_PALLAS and
    GIGAPATH_FOLD_BLOCK_Q/_K from the environment, once."""
    import os

    from gigapath_tpu.ops.common import env_flag
    from gigapath_tpu.quant.qtensor import normalize_mode

    def _int(name: str) -> Optional[int]:
        raw = os.environ.get(name, "").strip()
        return int(raw) if raw else None

    def _str(name: str) -> str:
        return os.environ.get(name, "").strip()

    return PipelineFlags(
        pipelined_fwd=env_flag("GIGAPATH_PIPELINED_ATTN"),
        pipelined_bwd=env_flag("GIGAPATH_PIPELINED_BWD"),
        pipe_block_k=_int("GIGAPATH_PIPE_BLOCK_K"),
        pipe_bwd_block_k=_int("GIGAPATH_PIPE_BWD_BLOCK_K"),
        pack_direct=env_flag("GIGAPATH_PACK_DIRECT"),
        stream_fusion=env_flag("GIGAPATH_STREAM_FUSION"),
        ring_attn=env_flag("GIGAPATH_RING_ATTN"),
        chunked_prefill=env_flag("GIGAPATH_CHUNKED_PREFILL"),
        quant_tile=normalize_mode(_str("GIGAPATH_QUANT_TILE")),
        quant_pallas=env_flag("GIGAPATH_QUANT_PALLAS"),
        streaming_fusion=env_flag("GIGAPATH_STREAMING_FUSION"),
        fold_pallas=env_flag("GIGAPATH_FOLD_PALLAS"),
        fold_block_q=_int("GIGAPATH_FOLD_BLOCK_Q"),
        fold_block_k=_int("GIGAPATH_FOLD_BLOCK_K"),
    )


def _branch_plan_entry(flags, sl: int, r: int):
    """The (sl, r, variant, block) plan entry for one branch class, or
    None — matched on the branch's OWN (segment_length, ratio), so one
    geometry's plan covers every branch of the schedule."""
    if flags is None:
        return None
    for entry in getattr(flags, "branch_plans", ()) or ():
        if int(entry[0]) == int(sl) and int(entry[1]) == int(r):
            return entry
    return None


def _plan_block(flags, sl: int, r: int) -> int:
    """Blessed block override for one branch class (0 = auto)."""
    entry = _branch_plan_entry(flags, sl, r)
    return int(entry[3]) if entry is not None else 0


def _plan_variant(flags, sl: int, r: int) -> str:
    """Blessed kernel-family variant for one branch class ("" = the
    global pipelined flags stand)."""
    entry = _branch_plan_entry(flags, sl, r)
    return str(entry[2]) if entry is not None else ""


def _branch_pipelined(flags, sl: int, r: int) -> Tuple[bool, bool]:
    """(forward pipelined?, backward pipelined?) for one branch. The
    per-branch plan variant refines the FORWARD kernel family only
    ("serial"/"pipelined" pin it; "" inherits the global flag); the
    backward always rides the global ``pipelined_bwd`` field — which
    keeps the env-precedence contract intact: an explicitly set
    GIGAPATH_PIPELINED_BWD survives resolution in that field, and a
    per-branch variant can never override it. Plans that want a serial
    backward set the global ``pipelined_bwd: false`` opinion, which the
    env flag correctly beats."""
    variant = _plan_variant(flags, sl, r)
    if variant == "serial":
        return False, bool(flags.pipelined_bwd)
    if variant == "pipelined":
        return True, bool(flags.pipelined_bwd)
    return bool(flags.pipelined_fwd), bool(flags.pipelined_bwd)


def _bwd_impl(q6, k6, v6, do6, lse, delta, kvlen, causal, scale,
              heads, head_dim, block_q, block_k, interpret):
    B, S, r, hb, M, Dh = q6.shape
    Mk = k6.shape[4]
    nq, nk = M // block_q, Mk // block_k

    spec_q = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, i, t, j: (b, s, p, t, i, 0),
        memory_space=pltpu.VMEM,
    )
    spec_k = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        lambda b, s, p, i, t, j: (b, s, p, t, j, 0),
        memory_space=pltpu.VMEM,
    )
    vec_spec = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), lambda b, s, p, i, t, j: (b, s, p, i, 0),
        memory_space=pltpu.VMEM,
    )
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, S, r, nq, heads, nk),
        in_specs=[spec_q, spec_k, spec_k, spec_q, vec_spec, vec_spec, smem],
        out_specs=[spec_q],
        out_shape=[jax.ShapeDtypeStruct(q6.shape, q6.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(q6, k6, v6, do6, lse, delta, kvlen)[0]

    # grid (B, S, r, nk, hb, nq): index maps see (b, s, p, j, t, i)
    spec_q_kv = pl.BlockSpec(
        (1, 1, 1, 1, block_q, head_dim),
        lambda b, s, p, j, t, i: (b, s, p, t, i, 0),
        memory_space=pltpu.VMEM,
    )
    spec_k_kv = pl.BlockSpec(
        (1, 1, 1, 1, block_k, head_dim),
        lambda b, s, p, j, t, i: (b, s, p, t, j, 0),
        memory_space=pltpu.VMEM,
    )
    vec_spec_kv = pl.BlockSpec(
        (1, 1, 1, block_q, LANES), lambda b, s, p, j, t, i: (b, s, p, i, 0),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, S, r, nk, heads, nq),
        in_specs=[spec_q_kv, spec_k_kv, spec_k_kv, spec_q_kv,
                  vec_spec_kv, vec_spec_kv, smem],
        out_specs=[spec_k_kv, spec_k_kv],
        out_shape=[
            jax.ShapeDtypeStruct(k6.shape, k6.dtype),
            jax.ShapeDtypeStruct(v6.shape, v6.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(q6, k6, v6, do6, lse, delta, kvlen)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# phase-major packing + the differentiable branch op
# ---------------------------------------------------------------------------


def _branch_geometry(L: int, E: int, sl: int, r: int,
                     block_override: int = 0) -> Tuple[int, int, int, int, int, int]:
    """(g, S, gp, m, Mp, block): segment length/count, r-padded segment,
    sparse length, block-padded sparse length, block size.

    Block choice: one block when the whole sparse segment fits the VMEM
    budget; otherwise the candidate (multiple of 128) minimizing q-row
    padding — padded key blocks are skipped by the kernel, padded q rows are
    not. The cap keeps q/k/v/out double-buffered blocks plus the fp32 logits
    tile inside VMEM (W = E/r lanes per block row).

    ``block_override`` (a blessed ExecutionPlan's per-branch block): a
    legal value — 128-multiple in [LANES, 1024] — replaces the auto
    choice; anything else is ignored so a stale registry can change
    performance but never legality. Callers that hold a flags snapshot
    use :func:`_plan_geometry`, which keeps the forward, backward and
    epilogue planner on ONE consistent Mp per branch."""
    g = min(sl, L)
    S = _round_up(L, g) // g
    gp = _round_up(g, r)
    m = gp // r
    # per-cell VMEM is dominated by the [bq, bk] fp32 logits/probs tiles
    # (blocks themselves are [b, Dh], tiny): 1024^2 blocks fit and are
    # ~2x faster than 512 on the LongNet shapes (fewer K/V restreams);
    # candidates below trade q-row padding against cell count
    cap = 1024
    single = _round_up(m, LANES)
    if block_override and block_override % LANES == 0 \
            and LANES <= block_override <= cap:
        block = block_override
    elif single <= cap:
        block = single
    else:
        block = min(
            (512, 640, 768, 896, 1024),
            key=lambda b: (_round_up(m, b), -b),
        )
    Mp = _round_up(m, block)
    return g, S, gp, m, Mp, block


def _plan_geometry(L: int, E: int, sl: int, r: int, flags):
    """:func:`_branch_geometry` with the branch's blessed block override
    applied — the one geometry call every flags-holding site uses."""
    return _branch_geometry(L, E, sl, r, _plan_block(flags, sl, r))


def _pack_bt(Mp: int, r: int, E: int, itemsize: int) -> int:
    """Row-block size for the pack/unpack copy kernels: each cell holds a
    [bt, r*E] dense row-block in VMEM, so bt*r*E*itemsize must stay well
    under the budget with double buffering (itemsize matters: the public
    op is dtype-generic, and fp32 doubles the footprint). Mp is always a
    multiple of 128 (block sizes are), so every candidate divides it.

    bt is a SUBLANE block dim (lanes are r*E, always full-width), so it may
    legally shrink below 128 down to the 8-row fp32 tile — which is what
    enforces the budget when r*E*itemsize is large: at the flagship r=16
    branch in fp32, bt=128 would be ~6.3 MB in + 6.3 MB out (~25 MB
    double-buffered, over the ~16 MB scoped-VMEM ceiling — the BENCH_r03
    OOM class); bt=64 lands back inside the budget. A lane split is NOT
    available here: the per-phase window is W = E/r lanes (48 at the
    flagship), and Mosaic only allows lane blocks that are 128-multiples
    or the whole dim."""
    bt = 512
    while bt > 8 and bt * r * E * itemsize > 4 * 2 ** 20:
        bt //= 2
    while Mp % bt:
        bt //= 2
    if bt * r * E * itemsize > 8 * 2 ** 20:
        raise ValueError(
            f"pack/unpack row block [bt={bt}, r*E={r * E}] at itemsize "
            f"{itemsize} exceeds the VMEM copy budget even at the minimum "
            f"block height; use a narrower model width, smaller dilation "
            f"ratio, or a 2-byte dtype"
        )
    return bt


def _band_lanes(r, hb, Dh, E):
    """(phase, head, lane_start) of the diagonal band layout in a
    [bt, r*E] dense row-block: token ``j*r + p`` of a segment is row j,
    lanes ``[p*E, (p+1)*E)``, and band p's heads sit at sublanes
    ``p*W + t*Dh`` within the token (W = hb*Dh) — so phase/head extraction
    is pure static LANE slicing. The ONE place the layout math lives:
    both pack kernels extract with it and both unpack kernels rebuild
    with it (the padded-view and direct variants must never diverge)."""
    W = hb * Dh
    for p in range(r):
        base = p * E + p * W
        for t in range(hb):
            yield p, t, base + t * Dh


def _extract_bands(x, o_ref, r, hb, Dh):
    """[bt, r*E] dense row-block -> packed [.., p, t] blocks of o_ref.
    (The earlier per-phase variant extracted rows ``phase::r``, a stride-r
    sublane gather that measured ~5x over the bandwidth floor at r=2, and
    re-read the dense block once per phase on top.)"""
    E = x.shape[-1] // r
    for p, t, lane in _band_lanes(r, hb, Dh, E):
        o_ref[0, 0, p, t] = x[:, lane : lane + Dh]


def _assemble_bands(x_ref, r, hb, Dh, E, bt, dtype):
    """Packed [.., p, t] blocks -> one dense [bt, r*E] row-block, band
    lanes filled, every other lane exactly 0 (the branch's cover pattern,
    so no separate cover-mask select is needed)."""
    pieces = []
    cursor = 0
    for p, t, lane in _band_lanes(r, hb, Dh, E):
        if lane > cursor:
            pieces.append(jnp.zeros((bt, lane - cursor), dtype))
        pieces.append(x_ref[0, 0, p, t].astype(dtype))
        cursor = lane + Dh
    if r * E > cursor:
        pieces.append(jnp.zeros((bt, r * E - cursor), dtype))
    return jnp.concatenate(pieces, axis=-1)


def _pack_kernel(x_ref, o_ref, *, r, hb, Dh, bt):
    """One dense row-block [bt, r*E] of the [B, S, Mp, r*E] padded view ->
    ALL phases' [r, hb, bt, Dh] packed blocks (see _band_lanes)."""
    _extract_bands(x_ref[0, 0], o_ref, r, hb, Dh)


def _unpack_kernel(x_ref, o_ref, *, r, hb, Dh, bt):
    """All phases' [r, hb, bt, Dh] packed blocks -> one dense row-block
    [bt, r*E] of the padded view."""
    E = o_ref.shape[-1] // r
    o_ref[0, 0] = _assemble_bands(x_ref, r, hb, Dh, E, bt, o_ref.dtype)


def _pack_kernel_direct(x_ref, o_ref, *, r, hb, Dh, bt, L):
    """Dense [bt*r, E] row-block read STRAIGHT off the [B, L, E] activation
    -> all phases' [r, hb, bt, Dh] packed blocks, merging the XLA
    pad+reshape re-tile pass (~40-53 us/tensor HBM round-trip, round-4
    decomposition) into the copy kernel: the (bt*r, E) -> (bt, r*E)
    re-tile happens in VMEM. Tail rows >= L are zeroed by LOGICAL row
    index before the reshape — correct no matter what the clamped OOB
    block DMA delivered (garbage may be non-finite, and packed K/V MUST
    be exact zeros at padded slots or p=0 x NaN poisons the PV matmul);
    full blocks skip the select. Single-segment branches only: with
    S > 1 the per-segment padding makes dense row offsets
    non-block-aligned."""
    i = pl.program_id(1)

    def emit(x):
        _extract_bands(x.reshape(bt, r * x.shape[-1]), o_ref, r, hb, Dh)

    @pl.when((i + 1) * bt * r <= L)
    def _full():
        emit(x_ref[0])

    @pl.when((i + 1) * bt * r > L)
    def _partial():
        rows = jax.lax.broadcasted_iota(jnp.int32, (bt * r, 1), 0) + i * bt * r
        emit(jnp.where(rows < L, x_ref[0], 0))


def _unpack_kernel_direct(x_ref, o_ref, *, r, hb, Dh, bt):
    """Packed [r, hb, bt, Dh] blocks -> a dense [bt*r, E] row-block written
    straight into the [B, L, E] output. The straddling tail block's OOB
    rows are truncated by the block DMA; blocks that would START past L
    are excluded from the grid by the caller (clamping would otherwise
    slide them backward over valid rows). Off-band lanes exact 0, as in
    _unpack_kernel."""
    E = o_ref.shape[-1]
    o_ref[0] = _assemble_bands(
        x_ref, r, hb, Dh, E, bt, o_ref.dtype
    ).reshape(bt * r, E)


def _pad_segments(x: jnp.ndarray, g: int, S: int, gp2: int) -> jnp.ndarray:
    """[B, L, E] -> [B, S, gp2, E] (zero pads on the clean E-lane layout)."""
    B, L, E = x.shape
    if S * g != L:
        x = jnp.pad(x, ((0, 0), (0, S * g - L), (0, 0)))
    x = x.reshape(B, S, g, E)
    if gp2 != g:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, gp2 - g), (0, 0)))
    return x


def _pack_phases(x: jnp.ndarray, g: int, S: int, r: int, Mp: int, H: int,
                 interpret: bool, pack_direct: bool = False) -> jnp.ndarray:
    """[B, L, E] -> packed [B, S, r, hb, Mp, Dh] holding ONLY the diagonal
    (phase == band) data — 1/r of the dense volume. The old 7-D layout
    materialized all r^2 (phase, band) blocks and transposed the full
    tensor; the kernels only ever read the diagonal. One pallas_call,
    reading every dense byte exactly once."""
    B, L, E = x.shape
    hb = H // r
    Dh = E // H
    if S == 1 and r > 1 and pack_direct:
        bt = _pack_bt(Mp, r, E, x.dtype.itemsize)
        return pl.pallas_call(
            functools.partial(
                _pack_kernel_direct, r=r, hb=hb, Dh=Dh, bt=bt, L=L
            ),
            grid=(B, Mp // bt),
            in_specs=[
                pl.BlockSpec(
                    (1, bt * r, E), lambda b, i: (b, i, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (1, 1, r, hb, bt, Dh), lambda b, i: (b, 0, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((B, 1, r, hb, Mp, Dh), x.dtype),
            interpret=interpret,
        )(x)
    # [B, S, Mp, r*E]: rows are token groups of r, phases live on lanes
    xp = _pad_segments(x, g, S, Mp * r).reshape(B, S, Mp, r * E)
    bt = _pack_bt(Mp, r, E, xp.dtype.itemsize)
    return pl.pallas_call(
        functools.partial(_pack_kernel, r=r, hb=hb, Dh=Dh, bt=bt),
        grid=(B, S, Mp // bt),
        in_specs=[
            pl.BlockSpec(
                (1, 1, bt, r * E), lambda b, s, i: (b, s, i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 1, r, hb, bt, Dh), lambda b, s, i: (b, s, 0, 0, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, S, r, hb, Mp, Dh), x.dtype),
        interpret=interpret,
    )(xp)


def _unpack_phases(p6: jnp.ndarray, L: int, E: int, g: int, S: int,
                   r: int, interpret: bool,
                   pack_direct: bool = False) -> jnp.ndarray:
    """Packed [B, S, r, hb, Mp, Dh] -> dense [B, L, E]; off-band lanes are
    written as exact zeros by the kernel. The [B, S, Mp, r*E] output view
    is token-major already, so no XLA transpose exists on either side."""
    B, _, _, hb, Mp, Dh = p6.shape
    if p6.shape[1] == 1 and r > 1 and pack_direct:
        bt = _pack_bt(Mp, r, E, p6.dtype.itemsize)
        # Grid covers only blocks that START inside L: Pallas block DMAs
        # have dynamic-slice semantics — a straddling block's tail is
        # truncated, but a block starting PAST the array end would be
        # clamped BACKWARD and overwrite the last valid rows with padded-
        # row garbage. ceil(L / (bt*r)) blocks cover every dense row < L
        # (packed rows beyond nb*bt are padding with nothing to write).
        nb = min(Mp // bt, -(-L // (bt * r)))
        return pl.pallas_call(
            functools.partial(_unpack_kernel_direct, r=r, hb=hb, Dh=Dh, bt=bt),
            grid=(B, nb),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, r, hb, bt, Dh), lambda b, i: (b, 0, 0, 0, i, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (1, bt * r, E), lambda b, i: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((B, L, E), p6.dtype),
            interpret=interpret,
        )(p6)
    bt = _pack_bt(Mp, r, E, p6.dtype.itemsize)
    x = pl.pallas_call(
        functools.partial(_unpack_kernel, r=r, hb=hb, Dh=Dh, bt=bt),
        grid=(B, S, Mp // bt),
        in_specs=[
            pl.BlockSpec(
                (1, 1, r, hb, bt, Dh), lambda b, s, i: (b, s, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bt, r * E), lambda b, s, i: (b, s, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((B, S, Mp, r * E), p6.dtype),
        interpret=interpret,
    )(p6)
    x = x.reshape(B, S, Mp * r, E)
    return x[:, :, :g].reshape(B, S * g, E)[:, :L]


def _phase_kvlen(S: int, g: int, r: int, m: int, real_len: int) -> np.ndarray:
    """[S, r] valid sparse keys per (segment, phase): position
    ``s*g + p + r*j`` must be a real token and inside its segment."""
    seg = np.arange(S)[:, None]
    phase = np.arange(r)[None, :]
    in_seg = np.clip(real_len - seg * g, 0, g)
    counts = np.ceil((in_seg - phase) / r)
    return np.clip(counts, 0, m).astype(np.int32)


def _scatter_lse(lse5: jnp.ndarray, B: int, L: int, H: int, g: int, S: int,
                 r: int, m: int) -> jnp.ndarray:
    """Kernel lse [B, S, r, Mp, LANES] -> dense [B, H, L] with NEG_INF at
    (token, head) pairs the branch does not cover. Small fp32 data; plain
    jnp reshapes + a where."""
    hb = H // r  # heads per band
    lse = lse5[:, :, :, :m, :hb]  # [B, S, r(phase), m, hb]
    lse = lse.transpose(0, 2, 4, 1, 3).reshape(B, H, S, m)  # head h = p*hb + t
    # token t = s*g + j*r + p is covered by head h iff phase(h) == p
    phase_of_head = jax.lax.broadcasted_iota(jnp.int32, (H, r), 0) // hb
    cover = phase_of_head == jax.lax.broadcasted_iota(jnp.int32, (H, r), 1)
    dense = jnp.where(cover[None, :, None, None, :], lse[..., None], NEG_INF)
    dense = dense.reshape(B, H, S, m * r)[:, :, :, :g].reshape(B, H, S * g)
    return dense[:, :, :L]


def _branch_kvlen(B, S, g, r, m, real_len, vl_dyn):
    """[B, S, r] int32 valid sparse-key counts: the static table from
    ``real_len`` combined (by minimum) with optional TRACED per-batch
    valid lengths — the kernels read the counts from SMEM at runtime, so
    traced collate pad masks need no retrace and keep the fused path."""
    static = jnp.asarray(
        np.broadcast_to(_phase_kvlen(S, g, r, m, real_len)[None], (B, S, r))
    )
    if vl_dyn is None:
        return static
    from gigapath_tpu.ops.dilated_attention import dyn_sparse_counts

    # shared dynamic-masking formula; [B, r, S] -> the kernels' [B, S, r]
    counts = dyn_sparse_counts(vl_dyn, g, r, m, jnp.arange(r), S)
    return jnp.minimum(static, counts.transpose(0, 2, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _dilated_branch(q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret,
                    flags):
    out, lse, _res = _dilated_branch_fwd_impl(
        q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret, flags
    )
    return out, lse


def _pipe_block_k(block_q: int, override: Optional[int]) -> int:
    """k-block for the pipelined forward: the PipelineFlags override
    (GIGAPATH_PIPE_BLOCK_K, snapshotted at dispatch) or a default that
    keeps the two parity logits tiles + the exp2 temp inside the
    scoped-VMEM envelope at any legal block_q (<= 1408)."""
    bk = override if override else 512
    return max(LANES, min(bk, block_q))


def _branch_packed_fwd_impl(q, k, v, vl_dyn, sl, r, H, real_len, causal,
                            interpret, flags):
    """Shared forward core: dense [B, L, E] q/k/v -> PACKED
    ``(out6 [B, S, r, hb, Mp, Dh], lse5 [B, S, r, Mp, LANES])`` — the
    kernel-native layout, consumed either by the dense unpack/scatter pair
    (:func:`_dilated_branch_fwd_impl`) or directly by the streaming fusion
    epilogue (which never materializes the dense per-branch tensors)."""
    B, L, E = q.shape
    Dh = E // H
    g, S, gp, m, Mp, block = _plan_geometry(L, E, sl, r, flags)
    q6 = _pack_phases(q, g, S, r, Mp, H, interpret, flags.pack_direct)
    k6 = _pack_phases(k, g, S, r, Mp, H, interpret, flags.pack_direct)
    v6 = _pack_phases(v, g, S, r, Mp, H, interpret, flags.pack_direct)
    kvlen = _branch_kvlen(B, S, g, r, m, real_len, vl_dyn)
    hb = H // r
    pipe_fwd, _ = _branch_pipelined(flags, sl, r)
    if not causal and pipe_fwd:
        out6, lse5 = _fwd_impl_pipe(
            q6, k6, v6, kvlen, Dh ** -0.5, hb, Dh,
            block, _pipe_block_k(block, flags.pipe_block_k), interpret,
        )
    else:
        out6, lse5 = _fwd_impl(
            q6, k6, v6, kvlen, causal, Dh ** -0.5, hb, Dh, block, block,
            interpret,
        )
    return out6, lse5


def _dilated_branch_fwd_impl(q, k, v, vl_dyn, sl, r, H, real_len, causal,
                             interpret, flags):
    B, L, E = q.shape
    g, S, gp, m, Mp, block = _plan_geometry(L, E, sl, r, flags)
    out6, lse5 = _branch_packed_fwd_impl(
        q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret, flags
    )
    # off-band lanes come back as exact zeros from the unpack kernel — the
    # branch's cover pattern needs no separate select
    out = _unpack_phases(out6, L, E, g, S, r, interpret, flags.pack_direct)
    lse = _scatter_lse(lse5, B, L, H, g, S, r, m)
    return out, lse, (out6, lse5)


def _dilated_branch_fwd(q, k, v, vl_dyn, sl, r, H, real_len, causal,
                        interpret, flags):
    out, lse, res = _dilated_branch_fwd_impl(
        q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret, flags
    )
    # Residuals are the DENSE q/k/v (shared buffers across every branch of
    # the multi-branch op — XLA stores one copy) plus this branch's packed
    # (out, lse), 1/r of dense volume. Saving the packed q6/k6/v6 instead
    # kept ~3 full dense-sized copies alive per branch; the backward
    # re-packs with the same cheap kernels.
    return (out, lse), ((q, k, v, vl_dyn) + res, q.shape)


def _branch_bwd_core(q, k, v, vl_dyn, do6, out6, lse5, sl, r, H, real_len,
                     causal, interpret, flags):
    """Shared backward core: PACKED cotangent ``do6`` (plus the saved
    packed forward results) -> dense ``(dq, dk, dv, vl_ct)``. Callers:
    the dense branch VJP (packs its dense ``do`` first) and the packed
    branch VJP behind the streaming fusion epilogue (whose epilogue
    backward emits ``do6`` already packed — no dense round-trip)."""
    B, L, E = q.shape
    Dh = E // H
    hb = H // r
    g, S, gp, m, Mp, block = _plan_geometry(L, E, sl, r, flags)
    q6 = _pack_phases(q, g, S, r, Mp, H, interpret, flags.pack_direct)
    k6 = _pack_phases(k, g, S, r, Mp, H, interpret, flags.pack_direct)
    v6 = _pack_phases(v, g, S, r, Mp, H, interpret, flags.pack_direct)
    # delta = rowsum(do * out) per (token, head), in the kernel's lse
    # layout [B, S, r, Mp, LANES] — the packed arrays ARE the diagonal
    delta = (do6.astype(jnp.float32) * out6.astype(jnp.float32)).sum(axis=-1)
    delta = delta.transpose(0, 1, 2, 4, 3)  # [B, S, r, Mp, hb]
    delta = jnp.pad(delta, ((0, 0),) * 4 + ((0, LANES - hb),))
    kvlen = _branch_kvlen(B, S, g, r, m, real_len, vl_dyn)
    _, pipe_bwd = _branch_pipelined(flags, sl, r)
    if not causal and pipe_bwd:
        dq6, dk6, dv6 = _bwd_impl_pipe(
            q6, k6, v6, do6, lse5, delta, kvlen, Dh ** -0.5,
            hb, Dh, block,
            _pipe_bwd_block_k(block, flags.pipe_bwd_block_k), interpret,
        )
    else:
        dq6, dk6, dv6 = _bwd_impl(
            q6, k6, v6, do6, lse5, delta, kvlen, causal, Dh ** -0.5,
            hb, Dh, block, block, interpret,
        )

    def undo(x6):
        # off-band lanes are exact zeros from the unpack kernel — which IS
        # the correct gradient there (the branch never reads those slots)
        return _unpack_phases(x6, L, E, g, S, r, interpret, flags.pack_direct)

    vl_ct = (
        None if vl_dyn is None
        else np.zeros(vl_dyn.shape, dtype=jax.dtypes.float0)
    )
    return undo(dq6), undo(dk6), undo(dv6), vl_ct


def _dilated_branch_bwd(sl, r, H, real_len, causal, interpret, flags, saved,
                        cotangents):
    (q, k, v, vl_dyn, out6, lse5), (B, L, E) = saved
    do, _dlse = cotangents  # no gradient flows through the lse output
    g, S, gp, m, Mp, block = _plan_geometry(L, E, sl, r, flags)
    do6 = _pack_phases(do, g, S, r, Mp, H, interpret, flags.pack_direct)
    return _branch_bwd_core(
        q, k, v, vl_dyn, do6, out6, lse5, sl, r, H, real_len, causal,
        interpret, flags,
    )


_dilated_branch.defvjp(_dilated_branch_fwd, _dilated_branch_bwd)


def dilated_branch_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sl: int,
    r: int,
    num_heads: int,
    *,
    real_len: Optional[int] = None,
    valid_len_dyn: Optional[jnp.ndarray] = None,
    is_causal: bool = False,
    interpret: bool = False,
    flags: Optional[PipelineFlags] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One dilated-attention branch on dense [B, L, E] activations.

    Returns ``(out [B, L, E], lse [B, H, L])`` where positions/heads not
    covered by this branch hold 0 / NEG_INF — ready for the cross-branch
    LSE-softmax fusion. Requires ``num_heads % r == 0`` and ``E % r == 0``
    (true for every LongNet config's power-of-two schedule).
    ``valid_len_dyn``: optional TRACED [B] suffix valid lengths (collate
    pad masks) — combined with the static masks in the kernels' SMEM
    valid-count tables at runtime.
    ``flags``: kernel-dispatch flag snapshot; by default the call's
    dispatch is resolved ONCE through the plan seam
    (:func:`gigapath_tpu.plan.resolve_plan`: env flags where set, the
    geometry's blessed registry plan where not — see the README
    "Execution plans" section). Pass an explicit :class:`PipelineFlags`
    to pin the dispatch independently of environment and registry.
    """
    B, L, E = q.shape
    assert E % num_heads == 0
    assert num_heads % r == 0 and E % r == 0, (num_heads, E, r)
    rl = L if real_len is None else min(int(real_len), L)
    if flags is None:
        from gigapath_tpu.plan import resolve_plan

        flags = resolve_plan("dilated_branch", (q, k, v))
    return _dilated_branch(
        q, k, v, valid_len_dyn, int(sl), int(r), num_heads, rl, is_causal,
        interpret, flags,
    )


# ---------------------------------------------------------------------------
# packed-boundary branch op (for the streaming fusion epilogue)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _dilated_branch_packed(q, k, v, vl_dyn, sl, r, H, real_len, causal,
                           interpret, flags):
    """Branch op with a PACKED output boundary: dense q/k/v in, packed
    ``(out6, lse5)`` out. Twin of :func:`_dilated_branch` whose backward
    accepts the cotangent *already in the packed layout* (the epilogue
    backward emits it there), so neither direction ever materializes the
    dense per-branch out/lse tensors."""
    out6, lse5 = _branch_packed_fwd_impl(
        q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret, flags
    )
    return out6, lse5


def _dilated_branch_packed_fwd(q, k, v, vl_dyn, sl, r, H, real_len, causal,
                               interpret, flags):
    out6, lse5 = _branch_packed_fwd_impl(
        q, k, v, vl_dyn, sl, r, H, real_len, causal, interpret, flags
    )
    # Residuals mirror _dilated_branch_fwd: dense q/k/v (shared across
    # branches — XLA stores one copy) + this branch's packed results.
    return (out6, lse5), (q, k, v, vl_dyn, out6, lse5)


def _dilated_branch_packed_bwd(sl, r, H, real_len, causal, interpret, flags,
                               saved, cotangents):
    q, k, v, vl_dyn, out6, lse5 = saved
    do6, _dlse5 = cotangents  # no gradient flows through the lse output
    return _branch_bwd_core(
        q, k, v, vl_dyn, do6, out6, lse5, sl, r, H, real_len, causal,
        interpret, flags,
    )


_dilated_branch_packed.defvjp(_dilated_branch_packed_fwd,
                              _dilated_branch_packed_bwd)


def dilated_branch_attention_packed(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sl: int,
    r: int,
    num_heads: int,
    *,
    real_len: Optional[int] = None,
    valid_len_dyn: Optional[jnp.ndarray] = None,
    is_causal: bool = False,
    interpret: bool = False,
    flags: Optional[PipelineFlags] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One dilated branch returning the PACKED phase-major results
    ``(out6 [B, S, r, hb, Mp, Dh], lse5 [B, S, r, Mp, LANES])`` — the
    streaming fusion epilogue's input contract. Same eligibility rules
    and plan-seam resolution as :func:`dilated_branch_attention`."""
    B, L, E = q.shape
    assert E % num_heads == 0
    assert num_heads % r == 0 and E % r == 0, (num_heads, E, r)
    rl = L if real_len is None else min(int(real_len), L)
    if flags is None:
        from gigapath_tpu.plan import resolve_plan

        flags = resolve_plan("dilated_branch", (q, k, v))
    return _dilated_branch_packed(
        q, k, v, valid_len_dyn, int(sl), int(r), num_heads, rl, is_causal,
        interpret, flags,
    )


# ---------------------------------------------------------------------------
# streaming cross-branch fusion epilogue
# ---------------------------------------------------------------------------
#
# The dense fusion path scatters every branch's packed (out, lse) back to
# dense [B, L, E] / [B, H, L] (one re-tile pass per packed tensor,
# ~40-53 us each, plus the lse scatter) and only then runs the
# cross-branch LSE-softmax — the ~1.7 ms/layer residual glue of the
# round-4 decomposition. The epilogue below consumes every branch's
# results directly in the packed phase-major layout: for each dense token
# block it reads the covering (phase, band-head) lanes of each branch,
# folds them through an online softmax over the BRANCH axis (the same
# "combine partials via stored log-sum-exp" trick flash attention uses
# inside one kernel), and writes only the final fused [B, L, E] output.
# The per-branch dense out/lse tensors are never materialized.
#
# Alignment: a single epilogue pass needs every consumed branch to map a
# dense token block of BT tokens onto whole packed row blocks — i.e.
# r | BT, BT/r >= the 8-row fp32 sublane tile, and (for multi-segment
# branches) BT | g so blocks never straddle a segment boundary. Schedules
# whose branches cannot share one BT (the flagship's 5792-token segment:
# 2^5 * 181) are split into alignment CLASSES: one pass per class,
# chained through compact running state (acc [B, L, E] f32 + per-head
# (m, l) [B, L, H] f32), the last pass finalizing out = acc / l and the
# fused lse = m + log(l) (the backward's only residual besides the
# branch lse tables themselves).


class EpiloguePlan(NamedTuple):
    """Static geometry of one streaming-fusion epilogue instance. Hashable
    (participates in jit cache keys via the custom_vjp's nondiff args)."""

    L: int
    E: int
    H: int
    Dh: int
    branches: Tuple[Tuple[int, int, int, int, int], ...]  # (r, hb, S, g, Mp)
    classes: Tuple[Tuple[int, Tuple[int, ...]], ...]  # (BT_tokens, members)
    bwd_bt: Tuple[int, ...]  # per-branch backward packed-row block
    interpret: bool = False


_EPILOGUE_BT_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)
# fwd per-cell fp32 dense temps: acc/m/l running state + 2 transient
# assemblies + the out block => keep ~6 [BT, E] fp32 buffers under budget
_EPILOGUE_VMEM_BUDGET = 10 * 2 ** 20


def _epilogue_bt_feasible(BT: int, r: int, S: int, g: int, Mp: int) -> bool:
    bt = BT // r
    return (
        BT % r == 0
        and bt >= 8
        and bt % 8 == 0
        and bt <= Mp
        and (S == 1 or g % BT == 0)
    )


def plan_stream_fusion(
    L: int, E: int, H: int,
    segment_lengths, dilated_ratios,
    interpret: bool = False,
    flags=None,
) -> Optional[EpiloguePlan]:
    """Build the epilogue's static plan, or None when the schedule's
    geometry admits no legal blocking (callers fall back to the dense
    scatter + stacked fusion path, which stays the parity oracle).
    ``flags``: the caller's resolved snapshot — its per-branch blessed
    block overrides change each branch's packed Mp, and the epilogue's
    blocking must agree with the branch kernels' layout exactly."""
    n = len(segment_lengths)
    if n < 2:
        return None
    Dh = E // H
    branches = []
    for sl, r in zip(segment_lengths, dilated_ratios):
        sl, r = int(sl), int(r)
        if H % r != 0 or E % r != 0:
            return None
        g, S, gp, m, Mp, block = _plan_geometry(L, E, sl, r, flags)
        branches.append((r, H // r, S, g, Mp))

    def feasible(bi: int, BT: int) -> bool:
        r, hb, S, g, Mp = branches[bi]
        return _epilogue_bt_feasible(BT, r, S, g, Mp)

    # greedy alignment classes: largest BT covering the most branches
    # first; leftovers get their own (largest feasible) class each
    remaining = set(range(n))
    classes = []
    while remaining:
        best_bt, best_members = None, []
        for BT in _EPILOGUE_BT_CANDIDATES:
            members = [i for i in sorted(remaining) if feasible(i, BT)]
            if len(members) > len(best_members):
                best_bt, best_members = BT, members
        if not best_members:
            return None
        # shrink BT while the class's fp32 dense temps overflow the VMEM
        # budget (halving preserves feasibility only while bt stays >= 8)
        BT = best_bt

        def est(bt_tokens: int) -> int:
            state = 6 * bt_tokens * E * 4
            packed = sum(
                3 * bt_tokens * E * 4 // branches[i][0] for i in best_members
            )
            return state + packed

        while (
            est(BT) > _EPILOGUE_VMEM_BUDGET
            and BT // 2 >= 8
            and all(feasible(i, BT // 2) for i in best_members)
        ):
            BT //= 2
        classes.append((BT, tuple(best_members)))
        remaining -= set(best_members)

    # per-branch backward row blocks: the backward is one independent
    # pallas_call per branch over ITS packed rows, so only that branch's
    # own geometry constrains the block
    bwd_bt = []
    for r, hb, S, g, Mp in branches:
        bt = None
        for cand in (128, 64, 32, 16, 8):
            if (
                cand <= Mp
                and r * cand <= 512
                and (S == 1 or g % (cand * r) == 0)
            ):
                bt = cand
                break
        if bt is None:
            return None
        bwd_bt.append(bt)

    return EpiloguePlan(
        L=L, E=E, H=H, Dh=Dh,
        branches=tuple(branches),
        classes=tuple(classes),
        bwd_bt=tuple(bwd_bt),
        interpret=bool(interpret),
    )


def _head_lane_mask(H: int, E: int, Dh: int) -> jnp.ndarray:
    """Static [H, E] 0/1 matrix: lane e belongs to head e // Dh. Built from
    iotas on-device (host constants show up as per-step pred[] DMAs).
    One matmul against it expands per-head [*, H] stats to the [*, E]
    broadcast form; the transposed contraction (scaled by 1/Dh) compresses
    the lane-duplicated [*, E] form back to [*, H] exactly."""
    hh = jax.lax.broadcasted_iota(jnp.int32, (H, E), 0)
    ee = jax.lax.broadcasted_iota(jnp.int32, (H, E), 1)
    return (ee // Dh == hh).astype(jnp.float32)


def _expand_heads(x, mask):
    """[BT, H] -> [BT, E] (each head's value broadcast over its Dh lanes)."""
    return jax.lax.dot_general(
        x, mask, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _compress_heads(x, mask, Dh):
    """[BT, E] lane-duplicated -> [BT, H] (exact: mean over the Dh copies)."""
    return jax.lax.dot_general(
        x, mask, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (1.0 / Dh)


def _assemble_lse(l_ref, r, hb, Dh, E, bt):
    """Packed lse block [.., r, bt, LANES] -> dense row-block [bt, r*E]
    fp32 with the branch lse broadcast over each band head's Dh lanes and
    NEG_INF everywhere off-band — the lse twin of :func:`_assemble_bands`
    (same _band_lanes layout; the two must never diverge)."""
    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (bt, LANES), 1)
    pieces = []
    cursor = 0
    for p, t, lane in _band_lanes(r, hb, Dh, E):
        if lane > cursor:
            pieces.append(jnp.full((bt, lane - cursor), NEG_INF, jnp.float32))
        # lane t of the [bt, LANES] block, extracted mask-and-rowsum (no
        # lane shuffles — same idiom as the backward kernels' _lane)
        col = jnp.sum(
            jnp.where(lane_iota == t, l_ref[0, 0, p], 0.0),
            axis=1, keepdims=True,
        )
        pieces.append(jnp.broadcast_to(col, (bt, Dh)))
        cursor = lane + Dh
    if r * E > cursor:
        pieces.append(jnp.full((bt, r * E - cursor), NEG_INF, jnp.float32))
    return jnp.concatenate(pieces, axis=-1)


def _epilogue_fwd_kernel(*refs, brs, E, H, Dh, BT, first, final):
    """One dense [BT, E] token block: fold every class branch's packed
    (out, lse) into the running (acc, m, l) online softmax over branches.

    refs layout: per branch (out6 block, lse5 block); then, unless
    ``first``, the incoming (acc [BT,E] f32, m [BT,H] f32, l [BT,H] f32)
    state; then the outputs — (out [BT,E] dtype, fused_lse [BT,H] f32)
    when ``final``, else the outgoing (acc, m, l) state."""
    n = len(brs)
    pos = 2 * n
    mask = _head_lane_mask(H, E, Dh)
    acc = m_run = l_run = None
    if not first:
        acc_in, m_in, l_in = refs[pos:pos + 3]
        pos += 3
        acc = acc_in[0]
        m_run = _expand_heads(m_in[0], mask)
        l_run = _expand_heads(l_in[0], mask)
    out_refs = refs[pos:]

    for bi, (r, hb, bt) in enumerate(brs):
        o_ref, l_ref = refs[2 * bi], refs[2 * bi + 1]
        o_d = _assemble_bands(o_ref, r, hb, Dh, E, bt, jnp.float32)
        o_d = o_d.reshape(BT, E)
        l_d = _assemble_lse(l_ref, r, hb, Dh, E, bt).reshape(BT, E)
        if acc is None:
            acc, m_run, l_run = o_d, l_d, jnp.ones_like(l_d)
        else:
            m_new = jnp.maximum(m_run, l_d)
            a = jnp.exp(m_run - m_new)
            b_ = jnp.exp(l_d - m_new)
            acc = acc * a + o_d * b_
            l_run = l_run * a + b_
            m_run = m_new

    if final:
        o_out, lse_out = out_refs
        o_out[0] = (acc / l_run).astype(o_out.dtype)
        lse_out[0] = _compress_heads(m_run + jnp.log(l_run), mask, Dh)
    else:
        acc_out, m_out, l_out = out_refs
        acc_out[0] = acc
        m_out[0] = _compress_heads(m_run, mask, Dh)
        l_out[0] = _compress_heads(l_run, mask, Dh)


def _epilogue_pass_call(operands, geoms, B, plan, BT, first, final,
                        out_dtype):
    """One class pass: grid over (batch, dense token blocks)."""
    L, E, H, Dh = plan.L, plan.E, plan.H, plan.Dh
    NB = -(-L // BT)
    brs = []
    in_specs = []
    for (r, hb, S, g, Mp) in geoms:
        bt = BT // r
        brs.append((r, hb, bt))
        bps = g // BT if S > 1 else 0

        def o_map(b, i, bps=bps):
            if bps:
                return (b, i // bps, 0, 0, i % bps, 0)
            return (b, 0, 0, 0, i, 0)

        def l_map(b, i, bps=bps):
            if bps:
                return (b, i // bps, 0, i % bps, 0)
            return (b, 0, 0, i, 0)

        in_specs.append(pl.BlockSpec(
            (1, 1, r, hb, bt, Dh), o_map, memory_space=pltpu.VMEM,
        ))
        in_specs.append(pl.BlockSpec(
            (1, 1, r, bt, LANES), l_map, memory_space=pltpu.VMEM,
        ))
    dense_spec = pl.BlockSpec(
        (1, BT, E), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM,
    )
    stat_spec = pl.BlockSpec(
        (1, BT, H), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM,
    )
    if not first:
        in_specs += [dense_spec, stat_spec, stat_spec]
    if final:
        out_specs = [dense_spec, stat_spec]
        out_shape = [
            jax.ShapeDtypeStruct((B, L, E), out_dtype),
            jax.ShapeDtypeStruct((B, L, H), jnp.float32),
        ]
    else:
        out_specs = [dense_spec, stat_spec, stat_spec]
        out_shape = [
            jax.ShapeDtypeStruct((B, L, E), jnp.float32),
            jax.ShapeDtypeStruct((B, L, H), jnp.float32),
            jax.ShapeDtypeStruct((B, L, H), jnp.float32),
        ]
    kernel = functools.partial(
        _epilogue_fwd_kernel, brs=tuple(brs), E=E, H=H, Dh=Dh, BT=BT,
        first=first, final=final,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, NB),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=plan.interpret,
    )(*operands)


def _epilogue_bwd_kernel(dy_ref, fl_ref, lse_ref, do_ref, *, r, hb, Dh, E,
                         bt, g, S, L):
    """One branch's packed cotangent block: d_out6 = w (x) extract(dY),
    where w = exp(lse_branch - fused_lse) re-derives the cross-branch
    softmax weight from the saved per-branch lse table and the fused
    (m + log l) residual — weights are constants in the backward
    (stop-gradient parity with the dense path / reference torch.no_grad).
    Rows past the real sequence (or the segment's dense extent) are
    zeroed by LOGICAL index, matching _pack_phases' zero padding — the
    downstream dK/dV kernels rely on padded query rows of do6 being
    exact zeros."""
    s = pl.program_id(1)
    i = pl.program_id(2)
    BT = bt * r
    H = r * hb
    mask = _head_lane_mask(H, E, Dh)
    fused = _expand_heads(fl_ref[0], mask)  # [BT, E]
    lse_d = _assemble_lse(lse_ref, r, hb, Dh, E, bt).reshape(BT, E)
    w = jnp.exp(lse_d - fused)
    x = dy_ref[0].astype(jnp.float32) * w
    rows = jax.lax.broadcasted_iota(jnp.int32, (BT, 1), 0) + i * BT
    limit = jnp.minimum(g, L - s * g)  # in-segment AND inside the sequence
    x = jnp.where(rows < limit, x, 0.0)
    _extract_bands(x.astype(do_ref.dtype).reshape(bt, r * E), do_ref,
                   r, hb, Dh)


def _epilogue_bwd_call(dy, fused_lse, lse5, geom, bt, plan):
    """One branch's backward pass: grid over (batch, segment, packed row
    blocks) — covering EVERY packed row (rows beyond the dense extent are
    written as exact zeros), so no uninitialized slot ever reaches the
    branch backward kernels."""
    L, E, Dh = plan.L, plan.E, plan.Dh
    r, hb, S, g, Mp = geom
    B = dy.shape[0]
    BT = bt * r
    bps = g // BT if S > 1 else 0

    def dense_map(b, s, i, bps=bps):
        if bps:
            return (b, s * bps + i, 0)
        return (b, i, 0)

    dy_spec = pl.BlockSpec((1, BT, E), dense_map, memory_space=pltpu.VMEM)
    fl_spec = pl.BlockSpec(
        (1, BT, r * hb), dense_map, memory_space=pltpu.VMEM,
    )
    lse_spec = pl.BlockSpec(
        (1, 1, r, bt, LANES), lambda b, s, i: (b, s, 0, i, 0),
        memory_space=pltpu.VMEM,
    )
    do_spec = pl.BlockSpec(
        (1, 1, r, hb, bt, Dh), lambda b, s, i: (b, s, 0, 0, i, 0),
        memory_space=pltpu.VMEM,
    )
    kernel = functools.partial(
        _epilogue_bwd_kernel, r=r, hb=hb, Dh=Dh, E=E, bt=bt, g=g, S=S, L=L,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, S, Mp // bt),
        in_specs=[dy_spec, fl_spec, lse_spec],
        out_specs=do_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, r, hb, Mp, Dh), dy.dtype),
        interpret=plan.interpret,
    )(dy, fused_lse, lse5)


def _fusion_epilogue_fwd_impl(outs, lses, plan):
    B = outs[0].shape[0]
    out_dtype = outs[0].dtype
    ncls = len(plan.classes)
    state = None
    for ci, (BT, members) in enumerate(plan.classes):
        first, final = ci == 0, ci == ncls - 1
        geoms = [plan.branches[bi] for bi in members]
        operands = []
        for bi in members:
            operands += [outs[bi], lses[bi]]
        if not first:
            operands += list(state)
        state = _epilogue_pass_call(
            operands, geoms, B, plan, BT, first, final, out_dtype,
        )
    out, fused_lse = state
    return out, fused_lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fusion_epilogue(outs, lses, plan):
    """Fused cross-branch softmax over PACKED branch results -> dense
    [B, L, E]. Same math as the stacked dense fusion (softmax of the
    branch LSEs, NEG_INF at uncovered slots -> weight 0, all-uncovered
    slots -> 0 output), weights constant in the backward."""
    out, _ = _fusion_epilogue_fwd_impl(outs, lses, plan)
    return out


def _fusion_epilogue_fwd(outs, lses, plan):
    out, fused_lse = _fusion_epilogue_fwd_impl(outs, lses, plan)
    # residuals: the branches' packed lse tables (shared with the branch
    # ops' own residuals — XLA stores one copy) + the compact fused
    # (m + log l) per (token, head); no dense per-branch tensor is saved
    return out, (lses, fused_lse)


def _fusion_epilogue_bwd(plan, res, dy):
    lses, fused_lse = res
    d_outs = tuple(
        _epilogue_bwd_call(
            dy, fused_lse, lses[bi], plan.branches[bi], plan.bwd_bt[bi], plan,
        )
        for bi in range(len(plan.branches))
    )
    # the fusion weights are constants in the backward: zero cotangent
    # into every branch lse (packed shape — never a dense [B, H, L])
    d_lses = tuple(jnp.zeros(l.shape, l.dtype) for l in lses)
    return d_outs, d_lses


_fusion_epilogue.defvjp(_fusion_epilogue_fwd, _fusion_epilogue_bwd)


def dilated_attention_stream_fused(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_lengths,
    dilated_ratios,
    num_heads: int,
    *,
    real_len: Optional[int] = None,
    valid_len_dyn: Optional[jnp.ndarray] = None,
    is_causal: bool = False,
    interpret: bool = False,
    flags: Optional[PipelineFlags] = None,
    plan: Optional[EpiloguePlan] = None,
) -> jnp.ndarray:
    """Multi-branch dilated attention on dense [B, L, E] with the
    streaming fusion epilogue: every branch runs the packed-boundary op
    and the packed results flow straight into :func:`_fusion_epilogue` —
    no dense per-branch out/lse is ever materialized, forward or
    backward. Callers must have checked :func:`plan_stream_fusion`
    feasibility (pass the plan in to avoid recomputing it)."""
    B, L, E = q.shape
    if flags is None:
        from gigapath_tpu.plan import resolve_plan

        flags = resolve_plan("dilated_stream", (q, k, v))
    if plan is None or plan.interpret != bool(interpret) \
            or getattr(flags, "branch_plans", ()):
        # a caller-built plan must agree with this call's interpret mode
        # AND with the resolved flags' per-branch block overrides — a
        # blessed block changes each branch's packed Mp, and an epilogue
        # plan built without the flags would read the packed arrays at
        # the wrong layout. Rebuilding is pure cheap Python; when the
        # caller already built it with these flags the rebuild is
        # identical (plan_stream_fusion is deterministic).
        plan = plan_stream_fusion(
            L, E, num_heads, segment_lengths, dilated_ratios,
            interpret=interpret, flags=flags,
        )
    assert plan is not None, "caller must gate on plan_stream_fusion"
    outs, lses = [], []
    for sl, r in zip(segment_lengths, dilated_ratios):
        o6, l5 = dilated_branch_attention_packed(
            q, k, v, int(sl), int(r), num_heads,
            real_len=real_len, valid_len_dyn=valid_len_dyn,
            is_causal=is_causal, interpret=interpret, flags=flags,
        )
        outs.append(o6)
        lses.append(l5)
    return _fusion_epilogue(tuple(outs), tuple(lses), plan)
