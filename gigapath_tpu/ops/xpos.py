"""xPos extrapolatable rotary position embedding.

Parity with reference ``torchscale/component/xpos_relative_position.py``:
rotate-every-two rotary embedding whose amplitude is scaled per-pair by
``((2i + 0.4d)/(1.4d)) ** (pos/scale_base)`` — keys are downscaled, queries
upscaled. Positions are centered around zero as in the reference
(``XPOS.forward:50-53``).
"""

from __future__ import annotations

import jax.numpy as jnp


def _rotate_every_two(x: jnp.ndarray) -> jnp.ndarray:
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack((-x2, x1), axis=-1).reshape(x.shape)


def _duplicate_interleave(m: jnp.ndarray) -> jnp.ndarray:
    """[L, D/2] -> [L, D] with each column doubled in place."""
    return jnp.repeat(m, 2, axis=-1)


def xpos_scale(length: int, head_dim: int, scale_base: int, offset: int = 0) -> jnp.ndarray:
    """Per-(position, pair) amplitude scale, centered positions. [L, D/2]."""
    per_dim = (jnp.arange(0, head_dim, 2) + 0.4 * head_dim) / (1.4 * head_dim)
    min_pos = -(length + offset) // 2
    positions = jnp.arange(min_pos, min_pos + length + offset, dtype=jnp.float32)
    scale = per_dim[None, :] ** (positions[:, None] / scale_base)
    return scale[-length:]


def apply_xpos(
    x: jnp.ndarray,
    *,
    scale_base: int = 512,
    offset: int = 0,
    downscale: bool = False,
) -> jnp.ndarray:
    """Apply xPos to [..., L, H, D] or [B, L, D] along the length axis.

    Accepts [B, L, H, D] (per-head) by operating on the last axis; length is
    taken from axis -3 for 4-D inputs, axis -2 otherwise.
    """
    head_dim = x.shape[-1]
    length = x.shape[-3] if x.ndim == 4 else x.shape[-2]
    scale = xpos_scale(length, head_dim, scale_base, offset)  # [L, D/2]
    if downscale:
        scale = 1.0 / scale

    # sinusoid positions run over length+offset rows then keep the last
    # `length`, exactly like the scale rows (reference builds sin/cos from the
    # same sliced matrix, xpos_relative_position.py:54-60)
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, scale.shape[-1]) / scale.shape[-1]))
    positions = jnp.arange(length + offset, dtype=jnp.float32)[-length:]
    sinusoid = positions[:, None] * inv_freq[None, :]
    sin = _duplicate_interleave(jnp.sin(sinusoid) * scale)
    cos = _duplicate_interleave(jnp.cos(sinusoid) * scale)

    if x.ndim == 4:  # [B, L, H, D]: broadcast over heads
        sin = sin[None, :, None, :]
        cos = cos[None, :, None, :]
    return (x * cos) + (_rotate_every_two(x) * sin)
