"""Embedding components (vision patch, text, learned positional, fused VL).

Parity with reference ``torchscale/component/embedding.py``: conv patch
embedding with optional mask token substitution and cls prepend
(``VisionEmbedding:28``), text embedding with ``D**-0.5`` init
(``TextEmbedding:93``), fairseq-convention learned positional embedding
(positions start at 2, ``PositionalEmbedding:99``), and the concat
vision+language embedding (``VisionLanguageEmbedding:9``).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn


class VisionEmbedding(nn.Module):
    """Image [B, H, W, C] -> patch tokens [B, (1+)N, D] (NHWC, TPU-native)."""

    img_size: int = 224
    patch_size: int = 16
    in_chans: int = 3
    embed_dim: int = 768
    contain_mask_token: bool = False
    prepend_cls_token: bool = False
    dtype: Any = None

    @property
    def num_patches(self) -> int:
        return (self.img_size // self.patch_size) ** 2

    def num_position_embeddings(self) -> int:
        return self.num_patches + (1 if self.prepend_cls_token else 0)

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, masked_position: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        B, H, W, C = x.shape
        assert H == self.img_size and W == self.img_size, (
            f"Input image size ({H}*{W}) doesn't match model "
            f"({self.img_size}*{self.img_size})."
        )
        x = nn.Conv(
            self.embed_dim,
            kernel_size=(self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            dtype=self.dtype,
            name="proj",
        )(x)
        x = x.reshape(B, -1, self.embed_dim)

        if masked_position is not None:
            assert self.contain_mask_token
            mask_token = self.param(
                "mask_token", nn.initializers.zeros, (1, 1, self.embed_dim)
            )
            w = masked_position[..., None].astype(x.dtype)
            x = x * (1 - w) + mask_token.astype(x.dtype) * w
        elif self.contain_mask_token:
            # keep the parameter in the tree even when unused this call
            self.param("mask_token", nn.initializers.zeros, (1, 1, self.embed_dim))

        if self.prepend_cls_token:
            cls_token = self.param(
                "cls_token", nn.initializers.zeros, (1, 1, self.embed_dim)
            )
            cls = jnp.broadcast_to(cls_token.astype(x.dtype), (B, 1, self.embed_dim))
            x = jnp.concatenate([cls, x], axis=1)
        return x


class TextEmbedding(nn.Module):
    """Token embedding with normal(std=D**-0.5) init."""

    vocab_size: int
    embed_dim: int
    dtype: Any = None

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        return nn.Embed(
            self.vocab_size,
            self.embed_dim,
            embedding_init=nn.initializers.normal(self.embed_dim**-0.5),
            dtype=self.dtype,
            name="weight",
        )(tokens)


class PositionalEmbedding(nn.Module):
    """Learned positional table; default positions are ``2..L+1`` (fairseq
    convention, reference ``embedding.py:104-109``)."""

    num_embeddings: int
    embed_dim: int
    dtype: Any = None

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, positions: Optional[jnp.ndarray] = None
    ) -> jnp.ndarray:
        if positions is None:
            positions = jnp.arange(2, x.shape[1] + 2)[None, :]
        table = nn.Embed(
            self.num_embeddings, self.embed_dim, dtype=self.dtype, name="weight"
        )
        return table(positions)


class VisionLanguageEmbedding(nn.Module):
    """Concat of vision tokens then text tokens (reference ``:9-26``)."""

    text_embed: nn.Module
    vision_embed: nn.Module

    def __call__(
        self,
        textual_tokens: Optional[jnp.ndarray],
        visual_tokens: Optional[jnp.ndarray],
    ) -> jnp.ndarray:
        if textual_tokens is None:
            return self.vision_embed(visual_tokens)
        if visual_tokens is None:
            return self.text_embed(textual_tokens)
        x1 = self.vision_embed(visual_tokens)
        x2 = self.text_embed(textual_tokens)
        return jnp.concatenate([x1, x2], axis=1)
