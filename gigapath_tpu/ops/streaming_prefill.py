"""Streaming chunked prefill: fold tile chunks into dilated attention
without ever materializing the slide sequence.

The slide encoder's dense path wants the whole ``[B, L, D]``
tile-embedding sequence resident before step one — at 10^5-10^6 tiles
per slide (PAPER.md §0) that is the last assemble-then-encode memory
wall. This module extends the stored-LSE online-softmax merge that
already powers the ring schedule and the stream-fusion epilogue
(:func:`~gigapath_tpu.ops.flash_attention.partial_attention` +
:func:`~gigapath_tpu.ops.flash_attention.combine_partials`) to the
INGEST axis: tile chunks arrive (from the tile encoder, the
``inference.py`` prefetch loader, or the ``dist/`` boundary), each new
chunk is attended against every already-resident chunk it shares a
dilated segment with, and the chunk-normalized partials fold into
running per-branch ``(out, lse)`` accumulators. Per-layer attention
TEMPORARIES are O(chunk^2 logits) regardless of slide length; the only
O(L) state is the accumulator/output itself — the same asymptotics flash
attention buys within one kernel, here bought across the ingest stream.

Semantics (kept in lockstep with ``ops/dilated_attention.py`` — the
dense path remains the fallback and the parity oracle):

- a branch ``(segment_length sl, ratio r)`` chops the sequence into
  segments of ``g = min(sl, L)``; within a segment, head ``h`` of phase
  ``p = h // ceil(H/r)`` covers exactly the positions with
  ``(pos % g) % r == p`` — as queries AND as keys. Uncovered query rows
  carry ``lse ~ NEG_INF`` so the cross-branch fusion gives them zero
  weight (the ``sparse_to_dense`` contract, expressed as masks instead
  of slices);
- partials over disjoint key CHUNKS of one branch merge through
  ``combine_partials`` (exact: softmax is associative under the stored
  LSE), so the within-branch math equals one softmax over the union;
- branches fuse by the same online softmax over the branch axis as
  ``dilated_attention_fused(streaming_fusion=True)``, with
  ``stop_gradient`` on the fusion weights (reference ``torch.no_grad``
  parity), so gradients match the dense oracle too.

Bit-exactness contract: :class:`StreamingPrefillState` folds chunks in
STRICT index order (``ingest`` asserts it). Floating-point combine is
not associative, so order-independence cannot come from the math — it
comes from the schedule: callers receiving chunks out of order (the
dist boundary under retransmits/reassignment) hold them in a frontier
buffer and fold at the deterministic frontier. Any arrival permutation
then executes the identical op sequence, which is what makes the dist
kill-recover check BIT-exact in streaming mode. The frontier buffer is
sized by the delivery REORDER WINDOW, not the slide: in-order producers
keep it at O(1) chunks, and the adversarial worst case (the first chunk
arrives last) degrades to holding the later chunks — never worse than
the dense assembler this path replaces, but not a hard bound; a
transport that wants one must cap its reorder window (e.g. ack-window
credits), which the directory channel's retransmit-by-seq already
encourages.

This module is streaming-sanctioned for gigalint GL014: chunk lists
must never be reassembled into a dense sequence here. The one sanctioned
exception is :func:`assemble_dense_fallback` (the oracle/fallback path),
which the rule exempts by name.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from gigapath_tpu.ops.attention import NEG_INF
from gigapath_tpu.ops.flash_attention import combine_partials


# ---------------------------------------------------------------------------
# the static chunk-fold plan
# ---------------------------------------------------------------------------

def chunk_bounds(n_tokens: int, chunk_tokens: int) -> Tuple[Tuple[int, int], ...]:
    """``((start, stop), ...)`` covering ``[0, n_tokens)`` in order, the
    final chunk ragged. Mirrors ``dist.boundary.plan_chunks`` (chunk ids
    double as fold indices there) without importing the dist layer into
    the ops layer."""
    if n_tokens < 1 or chunk_tokens < 1:
        raise ValueError(f"need n_tokens/chunk_tokens >= 1, got "
                         f"{n_tokens}/{chunk_tokens}")
    return tuple(
        (start, min(start + chunk_tokens, n_tokens))
        for start in range(0, n_tokens, chunk_tokens)
    )


def _branch_geometry(
    total_len: int, segment_lengths: Sequence[int], dilated_ratios: Sequence[int]
) -> Tuple[Tuple[int, int], ...]:
    """Per-branch ``(g, r)`` with the dense path's ``g = min(sl, L)``
    clamp. Multi-segment branches whose segment is not a multiple of the
    ratio are refused: the dense path zero-pads each segment to a ratio
    multiple there, a key set this masked formulation cannot express
    (never the case for LongNet's schedules — checked, not assumed)."""
    assert len(segment_lengths) == len(dilated_ratios)
    branches = []
    for sl, r in zip(segment_lengths, dilated_ratios):
        g, r = min(int(sl), total_len), int(r)
        if total_len > g and g % r != 0:
            raise NotImplementedError(
                f"streaming prefill: branch (sl={sl}, r={r}) has "
                f"{g} % {r} != 0 with multiple segments — the dense "
                "path's zero-pad key slots have no streaming counterpart"
            )
        branches.append((g, r))
    return tuple(branches)


def fold_plan(
    bounds: Sequence[Tuple[int, int]], segment_len: int
) -> Tuple[Tuple[int, ...], ...]:
    """For each chunk index ``i``: the sorted chunk indices ``j`` whose
    token range shares at least one ``segment_len``-segment with chunk
    ``i`` — exactly the (query-chunk, key-chunk) pairs one branch must
    fold. Pure trace-time integers; the pair set is a function of the
    slide geometry alone, so every process derives the same plan."""
    seg = [(start // segment_len, (stop - 1) // segment_len)
           for start, stop in bounds]
    plan = []
    for lo_i, hi_i in seg:
        plan.append(tuple(
            j for j, (lo_j, hi_j) in enumerate(seg)
            if not (hi_i < lo_j or hi_j < lo_i)
        ))
    return tuple(plan)


# ---------------------------------------------------------------------------
# one (query-chunk, key-chunk) partial of one branch
# ---------------------------------------------------------------------------

def pair_partial_attention(
    q_blk: jnp.ndarray,
    k_blk: jnp.ndarray,
    v_blk: jnp.ndarray,
    q0,
    k0,
    *,
    segment_len: int,
    ratio: int,
    valid_len=None,
    flags=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chunk-normalized ``(out [B,cq,H,D], lse [B,H,cq])`` of one dilated
    branch restricted to one resident key chunk — the ingest-axis twin of
    :func:`~gigapath_tpu.ops.flash_attention.partial_attention`.

    ``q0``/``k0`` are the chunks' global token offsets, passed as DYNAMIC
    scalars so one compiled executable serves every pair of the same
    block shapes (the position masks are iota comparisons). The segment
    and dilation structure of ``ops/dilated_attention.py`` is expressed
    as masks: key ``u`` is visible to query ``t`` of head phase ``p``
    iff they share a segment and both sit on phase ``p``'s dilated
    lattice; query rows off their phase's lattice come back fully
    masked (``lse ~ NEG_INF`` -> zero weight in the branch fusion),
    mirroring ``sparse_to_dense``'s uncovered-position contract.
    ``valid_len`` (optional dynamic scalar) masks keys at global
    positions >= it — the ragged/padded tail.

    ``flags``: a resolved ``PipelineFlags`` carrier (or None). With
    ``flags.fold_pallas`` the pair runs the Pallas tier
    (:mod:`gigapath_tpu.ops.pallas_streaming` — masks computed in-kernel
    from iota comparisons, no dense ``[H, cq, ck]`` mask tensor ever
    materialized); otherwise this jnp formulation below IS the dispatch
    — byte-identical to the pre-plan behavior and the parity oracle the
    Pallas tier is tested against. Fully-masked rows carry a
    large-negative lse SENTINEL in both tiers (~ -1e8 here, ~ -7e19 in
    the kernel's underflow discipline); downstream combines weight
    either to exactly 0.
    """
    if flags is not None and getattr(flags, "fold_pallas", False):
        from gigapath_tpu.ops.pallas_streaming import (
            fold_blocks,
            pallas_pair_partial,
        )

        bq, bk = fold_blocks(flags, segment_len, ratio)
        return pallas_pair_partial(
            q_blk, k_blk, v_blk, q0, k0,
            segment_len=segment_len, ratio=ratio, valid_len=valid_len,
            block_q=bq, block_k=bk,
            interpret=jax.default_backend() != "tpu",
        )
    B, cq, H, Dh = q_blk.shape
    ck = k_blk.shape[1]
    scale = Dh ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q_blk, k_blk, preferred_element_type=jnp.float32
    ).astype(jnp.float32) * scale

    tq = jnp.asarray(q0, jnp.int32) + jnp.arange(cq, dtype=jnp.int32)
    uk = jnp.asarray(k0, jnp.int32) + jnp.arange(ck, dtype=jnp.int32)
    heads_per_group = -(-H // ratio)
    phases = jnp.arange(H, dtype=jnp.int32) // heads_per_group  # [H]
    same_seg = (tq[:, None] // segment_len) == (uk[None, :] // segment_len)
    k_ok = ((uk % segment_len) % ratio)[None, :] == phases[:, None]  # [H, ck]
    q_ok = ((tq % segment_len) % ratio)[None, :] == phases[:, None]  # [H, cq]
    mask = same_seg[None, :, :] & k_ok[:, None, :] & q_ok[:, :, None]
    if valid_len is not None:
        mask = mask & (uk < jnp.asarray(valid_len, jnp.int32))[None, None, :]

    s = jnp.where(mask[None], s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, H, cq]
    p = jnp.exp(s - lse[..., None])
    p = jnp.where(mask[None], p, 0.0)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    ).astype(q_blk.dtype)
    return out, lse


def fold_pair(
    acc_out: jnp.ndarray,
    acc_lse: jnp.ndarray,
    q_blk: jnp.ndarray,
    k_blk: jnp.ndarray,
    v_blk: jnp.ndarray,
    q0,
    k0,
    valid_len,
    *,
    segment_len: int,
    ratio: int,
    flags=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fold step: the pair's partial merged into the running branch
    accumulator via the stored-LSE combine. ``acc_out`` stays fp32 end
    to end (``combine_partials`` returns ``out_a``'s dtype). This is the
    whole per-chunk streaming executable — its arguments and
    temporaries are all O(chunk), never O(L), which is what the XLA
    memory-analysis pins and the jaxpr guard assert. ``flags`` (a
    resolved ``PipelineFlags`` carrier or None, static under jit —
    NamedTuples hash, so plan on-vs-off lands distinct jit cache
    entries) selects the pair tier; None is the plain jnp path."""
    o, l = pair_partial_attention(
        q_blk, k_blk, v_blk, q0, k0,
        segment_len=segment_len, ratio=ratio, valid_len=valid_len,
        flags=flags,
    )
    return combine_partials(acc_out, acc_lse, o, l)


def fuse_branch_partials(
    outs: Sequence[jnp.ndarray],
    lses: Sequence[jnp.ndarray],
    out_dtype,
) -> jnp.ndarray:
    """Fold per-branch ``(out, lse)`` partials of ONE chunk into the
    fused output block — the same online softmax over the branch axis as
    ``dilated_attention_fused(streaming_fusion=True)``, weights constant
    in backward (stop_gradient; reference ``torch.no_grad`` parity)."""

    def bLH1(x):  # [B, H, c] -> broadcastable [B, c, H, 1]
        return x.transpose(0, 2, 1)[..., None]

    acc = m_run = l_run = None
    for o, l in zip(outs, lses):
        l = jax.lax.stop_gradient(l)
        if acc is None:
            m_run = l
            l_run = jnp.ones_like(l)
            acc = o.astype(jnp.float32)
        else:
            m_new = jnp.maximum(m_run, l)
            a = jnp.exp(m_run - m_new)
            b_ = jnp.exp(l - m_new)
            l_run = l_run * a + b_
            acc = acc * bLH1(a) + o.astype(jnp.float32) * bLH1(b_)
            m_run = m_new
    return (acc / bLH1(l_run)).astype(out_dtype)


# ---------------------------------------------------------------------------
# the streaming state
# ---------------------------------------------------------------------------

class StreamingPrefillState:
    """Running per-branch ``(out, lse)`` partials over an ingest stream.

    Construction fixes the geometry — chunk bounds, branch schedule,
    total length — so the fold schedule is a pure function of the slide,
    independent of which producer delivers which chunk when (the dist
    boundary's bit-parity contract extended to the fold).

    ``ingest(i, q, k, v)`` consumes chunk ``i``'s projected q/k/v blocks
    in strict index order and folds every newly-completable pair: chunk
    ``i``'s queries against each resident key chunk sharing a segment,
    and each resident query chunk against chunk ``i``'s keys. Blocks are
    retained only while a future chunk still needs them (branch-local
    chunks are dropped immediately after their last fold), so retained
    K/V — not just temporaries — stays bounded by the widest branch's
    actual reach. ``finalize()`` fuses the branch partials per chunk and
    returns the per-chunk output blocks — never a concatenated sequence
    (gigalint GL014).
    """

    def __init__(
        self,
        bounds: Sequence[Tuple[int, int]],
        segment_lengths: Sequence[int],
        dilated_ratios: Sequence[int],
        *,
        total_len: Optional[int] = None,
        valid_len=None,
        jit_pairs: bool = True,
        fold_fn=None,
        flags=None,
    ):
        """``fold_fn``: optional override for the per-pair fold callable
        (signature of :func:`fold_pair`) — how callers instrument the
        fold executable (e.g. a ``CompileWatchdog.wrap`` so retraces
        land on the obs bus); default is the plain jitted fold.
        ``flags``: resolved ``PipelineFlags`` (or None) threaded into
        every fold call as a static arg — callers resolve the plan ONCE
        (per session/geometry), never per chunk."""
        self.bounds = tuple((int(a), int(b)) for a, b in bounds)
        assert self.bounds and all(a < b for a, b in self.bounds)
        self.total_len = int(total_len or self.bounds[-1][1])
        self.branches = _branch_geometry(
            self.total_len, segment_lengths, dilated_ratios
        )
        self.plans = tuple(fold_plan(self.bounds, g) for g, _ in self.branches)
        self._valid = valid_len
        n = len(self.bounds)
        # last chunk index that still interacts with chunk j, any branch:
        # past it, chunk j's q/k/v blocks are dropped
        self._last_use = [
            max(max(plan[j]) for plan in self.plans) for j in range(n)
        ]
        self._qkv: Dict[int, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = {}
        # _acc[branch][chunk] = (out fp32, lse) or None until first fold
        self._acc: List[List[Optional[Tuple[jnp.ndarray, jnp.ndarray]]]] = [
            [None] * n for _ in self.branches
        ]
        self._next = 0
        self._flags = flags
        if fold_fn is not None:
            self._fold_fn = fold_fn
        else:
            self._fold_fn = (
                jax.jit(
                    fold_pair,
                    static_argnames=("segment_len", "ratio", "flags"),
                )
                if jit_pairs else fold_pair
            )
        self.folds = 0  # fold-count telemetry for the obs/smoke layers

    @property
    def n_chunks(self) -> int:
        return len(self.bounds)

    @property
    def next_index(self) -> int:
        return self._next

    def resident_blocks(self) -> int:
        """How many chunks' q/k/v blocks are currently retained — the
        honest memory signal the smoke reports next to the XLA pins."""
        return len(self._qkv)

    def _seed(self, i: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
        q = self._qkv[i][0]
        B, c, H, Dh = q.shape
        out = jnp.zeros((B, c, H, Dh), jnp.float32)
        lse = jnp.full((B, H, c), NEG_INF, jnp.float32)
        # match the q/k/v blocks' placement: a seed left on the default
        # SingleDeviceSharding while mesh-placed params give the blocks
        # a NamedSharding makes the SECOND fold per shape a fresh jit
        # cache entry (input shardings are part of the cache key) — one
        # silent recompile per (shape, branch), caught by the stage
        # watchdogs
        sharding = getattr(q, "sharding", None)
        if sharding is not None:
            try:
                out = jax.device_put(out, sharding)
                lse = jax.device_put(lse, sharding)
            except (ValueError, TypeError):
                pass  # rank-specific spec: keep the default placement
        return out, lse

    def _fold(self, b: int, qi: int, kj: int) -> None:
        g, r = self.branches[b]
        acc = self._acc[b][qi]
        if acc is None:
            acc = self._seed(qi)
        q_blk = self._qkv[qi][0]
        _, k_blk, v_blk = self._qkv[kj]
        valid = self.total_len if self._valid is None else self._valid
        self._acc[b][qi] = self._fold_fn(
            acc[0], acc[1], q_blk, k_blk, v_blk,
            jnp.int32(self.bounds[qi][0]), jnp.int32(self.bounds[kj][0]),
            jnp.int32(valid),
            segment_len=g, ratio=r, flags=self._flags,
        )
        self.folds += 1

    def ingest(self, idx: int, q_blk: jnp.ndarray, k_blk: jnp.ndarray,
               v_blk: jnp.ndarray) -> None:
        """Fold chunk ``idx``. STRICT in-order contract: callers seeing
        out-of-order arrivals frontier-buffer them (see module
        docstring) so every run executes the identical fold sequence."""
        if idx != self._next:
            raise ValueError(
                f"streaming prefill folds chunks in index order: got "
                f"chunk {idx}, expected {self._next} (frontier-buffer "
                "out-of-order arrivals at the caller)"
            )
        start, stop = self.bounds[idx]
        if q_blk.shape[1] != stop - start:
            raise ValueError(
                f"chunk {idx}: block rows {q_blk.shape[1]} != token range "
                f"[{start}, {stop})"
            )
        self._qkv[idx] = (q_blk, k_blk, v_blk)
        for b, plan in enumerate(self.plans):
            for a in plan[idx]:
                if a > idx or a not in self._qkv:
                    continue
                # resident queries vs the new keys...
                self._fold(b, a, idx)
                if a != idx:
                    # ...and the new queries vs the resident keys
                    self._fold(b, idx, a)
        self._next += 1
        # drop raw q/k/v blocks no future chunk interacts with (the
        # accumulators persist until finalize; residency tracks the
        # widest branch's actual reach, not the slide length)
        for j in [j for j in self._qkv if self._last_use[j] < self._next]:
            del self._qkv[j]

    def export_state(self) -> dict:
        """The fold's recovery-critical state as a flat-string-keyed
        pytree of host arrays: the fold frontier, the resident q/k/v
        blocks, and every branch's running ``(out, lse)`` partials.
        Geometry (bounds/branches/plans) is NOT exported — it is a pure
        function of the slide, reconstructed at restore by building the
        same state object. ``restore_state`` on a geometry-identical
        fresh instance is BIT-exact: the partials round-trip through
        host memory unchanged and the remaining folds execute the same
        deterministic schedule (the consumer-crash-recovery contract,
        ISSUE 13)."""
        import numpy as np

        state: dict = {"next": np.int64(self._next),
                       "folds": np.int64(self.folds)}
        for i, (q, k, v) in self._qkv.items():
            state[f"qkv_{i}"] = {
                "q": np.asarray(jax.device_get(q)),
                "k": np.asarray(jax.device_get(k)),
                "v": np.asarray(jax.device_get(v)),
            }
        for b, per_chunk in enumerate(self._acc):
            for i, acc in enumerate(per_chunk):
                if acc is None:
                    continue
                state[f"acc_{b}_{i}"] = {
                    "out": np.asarray(jax.device_get(acc[0])),
                    "lse": np.asarray(jax.device_get(acc[1])),
                }
        return state

    def restore_state(self, state: dict, *, sharding=None) -> None:
        """Inverse of :meth:`export_state` (same geometry required).

        ``sharding``: placement for the restored arrays — pass the LIVE
        jit outputs' sharding (the :meth:`_seed` lesson: a restored
        block left on the default SingleDeviceSharding while freshly
        computed blocks carry a NamedSharding makes every post-resume
        fold a fresh jit cache entry — one silent recompile per shape,
        flagged by the stage watchdogs)."""

        def place(x):
            arr = jnp.asarray(x)
            if sharding is not None:
                try:
                    arr = jax.device_put(arr, sharding)
                except (ValueError, TypeError):
                    pass  # rank-specific spec: keep the default placement
            return arr

        self._next = int(state["next"])
        self.folds = int(state["folds"])
        self._qkv = {}
        self._acc = [[None] * self.n_chunks for _ in self.branches]
        for key, value in state.items():
            if key.startswith("qkv_"):
                i = int(key[len("qkv_"):])
                self._qkv[i] = (
                    place(value["q"]), place(value["k"]), place(value["v"]),
                )
            elif key.startswith("acc_"):
                b, i = (int(p) for p in key[len("acc_"):].split("_"))
                self._acc[b][i] = (
                    place(value["out"]), place(value["lse"]),
                )

    def finalize(self) -> List[jnp.ndarray]:
        """-> per-chunk fused output blocks ``[B, c, H, D]`` in chunk
        order. Exact parity target: the dense oracle's per-position
        rows, sliced at the same bounds (fwd 1e-5 / grads 1e-4)."""
        if self._next != self.n_chunks:
            raise RuntimeError(
                f"finalize before the stream completed: folded "
                f"{self._next}/{self.n_chunks} chunks"
            )
        blocks: List[jnp.ndarray] = []
        for i in range(self.n_chunks):
            outs, lses = [], []
            for b in range(len(self.branches)):
                acc = self._acc[b][i]
                assert acc is not None  # (i, i) always folds
                outs.append(acc[0])
                lses.append(acc[1])
            blocks.append(fuse_branch_partials(outs, lses, jnp.float32))
        return blocks

    def peek_blocks(self) -> List[jnp.ndarray]:
        """Anytime read of the fold: fused output blocks for every chunk
        at or before the frontier, WITHOUT requiring (or mutating) a
        completed stream — :meth:`finalize`'s fusion loop minus the
        completeness check. Sound because the strict-order ingest folds
        ``(i, i)`` the moment chunk ``i`` lands, so every chunk ``<=``
        the frontier holds a non-None accumulator in every branch, and
        the stored-LSE combine is exact: the partials ARE the exact
        attention over the keys folded so far. The blocks are therefore
        provisional only in the sense that future chunks will extend
        the key set — the basis of ``StreamingEncoderSession.peek()``'s
        anytime-confidence surface."""
        if self._next < 1:
            raise RuntimeError("peek before any chunk folded")
        blocks: List[jnp.ndarray] = []
        for i in range(self._next):
            outs, lses = [], []
            for b in range(len(self.branches)):
                acc = self._acc[b][i]
                assert acc is not None  # (i, i) always folds
                outs.append(acc[0])
                lses.append(acc[1])
            blocks.append(fuse_branch_partials(outs, lses, jnp.float32))
        return blocks

    def lse_spread(self) -> float:
        """Per-branch numerics signal off the running partials: the
        spread (max − min over branches) of each branch's mean finite
        LSE across folded chunks. A branch whose logsumexp mass drifts
        far from its siblings is the streaming twin of a per-layer
        absmax blowup — surfaced through the ``numerics``/``stream_peek``
        events, host-side only (this syncs; call at peek cadence, never
        per fold)."""
        if self._next < 1:
            return 0.0
        means = []
        for b in range(len(self.branches)):
            total = jnp.float32(0.0)
            count = jnp.float32(0.0)
            for i in range(self._next):
                acc = self._acc[b][i]
                if acc is None:
                    continue
                lse = acc[1]
                finite = lse > (NEG_INF * 0.5)
                total = total + jnp.sum(jnp.where(finite, lse, 0.0))
                count = count + jnp.sum(finite)
            means.append(float(total) / max(float(count), 1.0))
        return float(max(means) - min(means)) if means else 0.0


def streaming_dilated_attention(
    q_blocks: Sequence[jnp.ndarray],
    k_blocks: Sequence[jnp.ndarray],
    v_blocks: Sequence[jnp.ndarray],
    bounds: Sequence[Tuple[int, int]],
    segment_lengths: Sequence[int],
    dilated_ratios: Sequence[int],
    *,
    total_len: Optional[int] = None,
    valid_len=None,
    jit_pairs: bool = True,
    flags=None,
) -> List[jnp.ndarray]:
    """Drive a :class:`StreamingPrefillState` over in-memory blocks —
    the pure-function surface the parity tests and the smoke A/B use
    (the dense ``dilated_attention`` is the oracle). Returns fp32 fused
    output blocks in chunk order."""
    state = StreamingPrefillState(
        bounds, segment_lengths, dilated_ratios,
        total_len=total_len, valid_len=valid_len, jit_pairs=jit_pairs,
        flags=flags,
    )
    for i, (q, k, v) in enumerate(zip(q_blocks, k_blocks, v_blocks)):
        state.ingest(i, q, k, v)
    return state.finalize()


# ---------------------------------------------------------------------------
# guards: the machine-checkable "never materializes the sequence" claim
# ---------------------------------------------------------------------------

def full_length_avals(fn, *args, full_len: int) -> List[str]:
    """Trace ``fn(*args)`` and list every jaxpr variable whose shape
    carries a ``full_len`` axis — empty for a genuinely chunked program.
    The streaming acceptance pins ``full_length_avals(fold, ...) == []``
    while the dense oracle (negative control) must be non-empty; choose
    ``full_len`` distinct from every chunk/head/feature dim."""
    closed = jax.make_jaxpr(fn)(*args)
    offending: List[str] = []

    def scan(jaxpr, depth: int) -> None:
        for eqn in jaxpr.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                shape = getattr(aval, "shape", ()) or ()
                if full_len in tuple(shape):
                    offending.append(
                        f"{eqn.primitive.name}: {tuple(shape)}"
                    )
            for sub in eqn.params.values():
                sub = getattr(sub, "jaxpr", None)
                if sub is not None:
                    scan(getattr(sub, "jaxpr", sub), depth + 1)

    scan(closed.jaxpr, 0)
    for var in closed.jaxpr.invars + closed.jaxpr.outvars:
        shape = getattr(getattr(var, "aval", None), "shape", ()) or ()
        if full_len in tuple(shape):
            offending.append(f"io: {tuple(shape)}")
    return offending


def assemble_dense_fallback(blocks: Sequence[jnp.ndarray],
                            axis: int = 1) -> jnp.ndarray:
    """The ONE sanctioned chunk-axis reassembly (gigalint GL014 exempts
    ``*dense_fallback*`` by name): concatenate blocks back into the
    dense sequence for the oracle/fallback path only. Anything on the
    streaming hot path calling this has defeated the feature."""
    return jnp.concatenate(list(blocks), axis=axis)
