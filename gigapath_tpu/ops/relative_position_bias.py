"""T5-style bucketed relative position bias.

Parity with reference ``torchscale/component/relative_position_bias.py``:
log-bucketed relative distances (half the buckets for exact small offsets,
half log-spaced up to ``max_distance``), an embedding of buckets -> per-head
bias, returned as ``[batch*heads, qlen, klen]`` additive logits.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def relative_position_bucket(
    relative_position: jnp.ndarray,
    bidirectional: bool = True,
    num_buckets: int = 32,
    max_distance: int = 128,
) -> jnp.ndarray:
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)

    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RelativePositionBias(nn.Module):
    bidirectional: bool = True
    num_buckets: int = 32
    max_distance: int = 128
    n_heads: int = 12

    @nn.compact
    def __call__(self, batch_size: int, qlen: int, klen: int, step: int = 0) -> jnp.ndarray:
        context = np.arange(step, step + qlen)[:, None]
        memory = np.arange(klen)[None, :]
        buckets = relative_position_bucket(
            jnp.asarray(memory - context),
            bidirectional=self.bidirectional,
            num_buckets=self.num_buckets,
            max_distance=self.max_distance,
        )
        table = nn.Embed(self.num_buckets, self.n_heads, name="relative_attention_bias")
        values = table(buckets)  # [qlen, klen, heads]
        values = values.transpose(2, 0, 1)[None]  # [1, heads, qlen, klen]
        values = jnp.broadcast_to(values, (batch_size,) + values.shape[1:])
        return values.reshape(-1, qlen, klen)
