"""Dilated attention (LongNet) — the long-context core of the slide encoder.

TPU-native counterpart of reference
``torchscale/component/dilated_attention.py``. Behavior parity:

- For each branch ``(segment_length sl, dilation r)`` the sequence is chopped
  into segments of ``min(sl, L)``; within a segment, heads are partitioned
  into ``r`` phase groups and head group ``p`` attends only positions
  ``p, p+r, ...`` (the reference implements this as a head-rotating
  einops-diagonal trick, ``dense_to_sparse:16-31``; here dilation is static
  phase *slices* — every index is a trace-time constant, so XLA lowers it
  to strided copies; TPU gathers/scatters over the token axis are slow).
- Attention runs per sparse segment through an op returning ``(out, lse)``.
- Three execution tiers, dispatched automatically: a head-major (BHLD)
  Pallas fast path on TPU (one relayout per op, segment-grid flash
  kernels), the phase-major fused kernels of
  :mod:`gigapath_tpu.ops.pallas_dilated` (opt-in), and a generic jnp path
  (CPU, dropout, traced masks, cross-attention, sequence parallelism).
- Branch outputs are scattered back to dense positions (uncovered positions
  get ``lse = NEG_INF``) and fused by softmax-weighting of the LSEs across
  branches (``scattering:100-131``); like the reference, the fusion weights
  are treated as constants in the backward pass (stop_gradient vs the
  reference's ``torch.no_grad``).
- Sequence parallelism: when a branch's segment spans more than the local
  sequence shard, K/V are all-gathered along the mesh ``seq`` axis and sliced
  to the ranks forming the current segment (``gather_kv:55-74``), queries
  staying local. The reference ships this dormant (never enabled); here it is
  a first-class code path driven by ``seq_axis_name`` inside ``shard_map``
  and covered by multi-device tests. Under ``GIGAPATH_RING_ATTN``
  (``PipelineFlags.ring_attn``) the oversized branches instead RING: local
  sparse K/V chunks rotate around the segment's sub-ring via ``ppermute``,
  partial attention runs per resident chunk, and partials merge through the
  stored-LSE online softmax — per-shard memory O(local chunk) instead of
  O(full segment), collectives overlapped with compute, with a custom VJP
  that rings in reverse (see the ring section below).

Everything is static-shape: the branch loop is a Python loop over a static
tuple, so ``jit`` unrolls it (5 branches in the flagship configs).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from gigapath_tpu.ops.attention import NEG_INF, MultiheadAttention, attention_with_lse
from gigapath_tpu.ops.common import round_up as _round_up

AttnFn = Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]


from gigapath_tpu.ops.common import env_flag as _env_flag  # shared convention


_WARNED: set = set()


def _warn_once(msg: str) -> None:
    """One warning per distinct message per process (dispatch runs inside
    trace-time Python, so an unguarded warn would fire on every retrace)."""
    if msg not in _WARNED:
        _WARNED.add(msg)
        import warnings

        warnings.warn(msg, stacklevel=3)


def _kv_valid_lengths(
    batch: int, n_seg: int, seg_len: int, ratio: int, m: int, num_heads: int, real_len: int
) -> Optional[np.ndarray]:
    """Static per-(batch*segment, head) count of sparse key slots that fall
    inside the real sequence (zero-padding from segmenting/dilation is
    excluded).

    The reference lets zero-pad keys participate in the softmax
    (``dense_to_sparse`` pads with zeros and flash attention sees them as
    logit-0 keys); masking them instead is strictly better math at segment
    tails. Returns ``[batch*n_seg, H]`` int or None when everything is
    valid. All inputs are trace-time constants, so this is free under jit.
    """
    heads_per_group = -(-num_heads // ratio)
    phases = np.arange(num_heads) // heads_per_group  # [H]
    seg = np.arange(n_seg)[:, None]
    # valid j satisfy seg*g + phase + ratio*j < real_len
    counts = np.ceil((real_len - seg * seg_len - phases[None, :]) / ratio)
    counts = np.clip(counts, 0, m).astype(np.int32)  # [n_seg, H]
    if (counts == m).all():
        return None
    return np.tile(counts, (batch, 1))  # [batch*n_seg, H]


def _pad_to_multiple(x: jnp.ndarray, mult: int, axis: int) -> jnp.ndarray:
    rem = x.shape[axis] % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, mult - rem)
    return jnp.pad(x, pads)


def _phase_head_ranges(num_heads: int, ratio: int):
    """Static (phase, head_start, head_end) triples: heads [hs, he) share
    ``phase`` — phases are contiguous head ranges by construction
    (``arange(H) // ceil(H/r)``), which is what makes the slice formulations
    below pure static slices."""
    heads_per_group = -(-num_heads // ratio)
    ranges = []
    for p in range(ratio):
        hs = p * heads_per_group
        he = min((p + 1) * heads_per_group, num_heads)
        if hs >= num_heads:
            break
        ranges.append((p, hs, he))
    return ranges


def dense_to_sparse(x: jnp.ndarray, ratio: int) -> jnp.ndarray:
    """Dilated subsample of segments: [b, g, H, D] -> [b, m, H, D], m=ceil(g/r).

    Head ``h`` keeps positions ``phase(h) + r*j``. Implemented as static
    phase slices of the ``[b, m, r, H, D]`` view concatenated over the head
    axis — every index is a trace-time constant, so XLA lowers this to plain
    strided copies (measured ~8x cheaper than the one-hot einsum select,
    whose ``r``-contraction forces a relayout; gathers over the token axis
    are slower still).
    """
    if ratio == 1:
        return x
    b, g, H, Dh = x.shape
    x = _pad_to_multiple(x, ratio, axis=1)
    m = x.shape[1] // ratio
    x5 = x.reshape(b, m, ratio, H, Dh)
    parts = [x5[:, :, p, hs:he, :] for p, hs, he in _phase_head_ranges(H, ratio)]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=2)


def sparse_to_dense(
    out_s: jnp.ndarray, lse_s: jnp.ndarray, ratio: int, seg_len: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter sparse branch results back to dense segment positions.

    ``out_s`` [b, m, H, D], ``lse_s`` [b, H, m] -> (out [b, g, H, D],
    lse [b, H, g]) with uncovered positions zero / NEG_INF, so they get zero
    weight in the cross-branch softmax fusion. The inverse of
    :func:`dense_to_sparse`: ``.at[...].set`` on static phase slices of the
    ``[b, m, r, H, D]`` view — static dynamic-update-slices, no scatter op.
    """
    b, m, H, Dh = out_s.shape
    if ratio == 1:
        return out_s[:, :seg_len], lse_s[..., :seg_len]
    out_d5 = jnp.zeros((b, m, ratio, H, Dh), out_s.dtype)
    lse_d5 = jnp.full((b, H, m, ratio), NEG_INF, lse_s.dtype)
    for p, hs, he in _phase_head_ranges(H, ratio):
        out_d5 = out_d5.at[:, :, p, hs:he, :].set(out_s[:, :, hs:he, :])
        lse_d5 = lse_d5.at[:, hs:he, :, p].set(lse_s[:, hs:he, :])
    out_d = out_d5.reshape(b, m * ratio, H, Dh)
    lse_d = lse_d5.reshape(b, H, m * ratio)
    return out_d[:, :seg_len], lse_d[..., :seg_len]


def _branch_kvlen_bhld(
    num_heads: int, n_seg: int, g: int, ratio: int, m: int, real_len: int
) -> Optional[np.ndarray]:
    """Static [H, n_seg] valid sparse-key counts for the head-major branch.

    Sparse slot ``j`` of segment ``s`` / head ``h`` maps to dense position
    ``s*g + phase(h) + ratio*j``; it is valid iff that position is a real
    token (< real_len) *and* falls inside the segment's own ``g`` dense slots
    (per-segment alignment padding beyond ``g`` belongs to no token).
    Returns None when every slot is valid. Trace-time constants: free under
    jit, and fully-padded key blocks are skipped by the kernel.
    """
    heads_per_group = -(-num_heads // ratio)
    phases = np.arange(num_heads) // heads_per_group  # [H]
    seg = np.arange(n_seg)[None, :]  # [1, n_seg]
    in_seg = np.clip(real_len - seg * g, 0, g)  # real dense tokens in segment
    counts = np.ceil((in_seg - phases[:, None]) / ratio)
    counts = np.clip(counts, 0, m).astype(np.int32)  # [H, n_seg]
    if (counts == m).all():
        return None
    return counts


def _dilate_bhld(x: jnp.ndarray, ratio: int) -> jnp.ndarray:
    """[B, H, n, gp, D] -> [B, H, n, gp/r, D] dilated subsample, head-phased.

    Same phase-slice trick as :func:`dense_to_sparse`, on the head-major
    layout: view the per-segment axis as (m, r) and take each phase's head
    range — all static slices.
    """
    if ratio == 1:
        return x
    B, H, n, gp, Dh = x.shape
    assert gp % ratio == 0, (gp, ratio)
    m = gp // ratio
    x6 = x.reshape(B, H, n, m, ratio, Dh)
    parts = [x6[:, hs:he, :, :, p, :] for p, hs, he in _phase_head_ranges(H, ratio)]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


def _undilate_bhld(
    out_s: jnp.ndarray, lse_s: jnp.ndarray, ratio: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse of :func:`_dilate_bhld`: sparse [B, H, n, m, D] back to dense
    [B, H, n, m*r, D] (+ lse [B, H, n, m*r]), uncovered slots zero / NEG_INF.

    One fused broadcast-select against a static [H, r] phase mask (a
    per-phase ``.at[].set`` loop re-copies the full dense buffer per phase —
    ~r x the write traffic)."""
    B, H, n, m, Dh = out_s.shape
    if ratio == 1:
        return out_s, lse_s
    # [H, r] phase mask built from iotas on-device: a host constant here
    # shows up as a per-step pred[] DMA in profiles
    h_idx = jax.lax.broadcasted_iota(jnp.int32, (H, ratio), 0)
    p_idx = jax.lax.broadcasted_iota(jnp.int32, (H, ratio), 1)
    mask = (h_idx // -(-H // ratio)) == p_idx  # [H, r]
    out_d = jnp.where(
        mask[None, :, None, None, :, None], out_s[:, :, :, :, None, :], 0
    )
    lse_d = jnp.where(mask[None, :, None, None, :], lse_s[..., None], NEG_INF)
    return out_d.reshape(B, H, n, m * ratio, Dh), lse_d.reshape(B, H, n, m * ratio)


def _segment_attention_jnp(
    q5: jnp.ndarray, k5: jnp.ndarray, v5: jnp.ndarray, kvlen, is_causal: bool
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense (out, lse) attention on the segment-batched head-major layout
    [B, H, S, M, D] — the fallback tier for short segments / non-TPU runs,
    numerically matching the Pallas kernel (fp32 softmax, masked rows -> 0)."""
    B, H, S, M, Dh = q5.shape
    scale = Dh ** -0.5
    s = jnp.einsum(
        "bhsqd,bhskd->bhsqk", q5, k5, preferred_element_type=jnp.float32
    ).astype(jnp.float32) * scale
    mask = None
    if kvlen is not None:
        lens = jnp.asarray(kvlen, jnp.int32).reshape(-1, H, S)
        mask = jnp.arange(k5.shape[3])[None, None, None, None, :] >= lens[..., None, None]
        s = jnp.where(mask, NEG_INF, s)
    if is_causal:
        qi = jnp.arange(M)[:, None] + (k5.shape[3] - M)
        ki = jnp.arange(k5.shape[3])[None, :]
        s = jnp.where(ki > qi, NEG_INF, s)
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, H, S, M]
    p = jnp.exp(s - lse[..., None])
    if mask is not None:
        p = jnp.where(mask, 0.0, p)
    out = jnp.einsum(
        "bhsqk,bhskd->bhsqd", p.astype(v5.dtype), v5,
        preferred_element_type=jnp.float32,
    ).astype(q5.dtype)
    return out, lse


def _bhld_geom(L: int, sl: int, r: int) -> Tuple[int, int, int, int, int, int]:
    """(g, Lp, n, gp, m, block) for one head-major branch."""
    g = min(sl, L)
    Lp = _round_up(L, g)
    n = Lp // g
    gp = _round_up(g, r)
    m = gp // r
    # Single-block-if-it-fits: a sparse length like m=1281 under fixed
    # 1024 blocks pads both q and k to 2048 (2.6x the intrinsic MXU work);
    # one 1408-square block wastes 10% per side and streams K/V exactly
    # once. The 1408 cap keeps the fp32 logits tile (block^2 = 7.9 MB)
    # plus stats/blocks inside the 16 MB VMEM.
    single = _round_up(m, 128)
    block = single if single <= 1408 else min(1024, single)
    return g, Lp, n, gp, m, block


def _seg_dilate(x: jnp.ndarray, g: int, Lp: int, n: int, gp: int, r: int) -> jnp.ndarray:
    """[B, H, L, D] -> dilated segment view [B, H, n, m, D] (static slices)."""
    B, H, L, Dh = x.shape
    if Lp != L:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, Lp - L), (0, 0)))
    x = x.reshape(B, H, n, g, Dh)
    if gp != g:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, gp - g), (0, 0)))
    return _dilate_bhld(x, r)


def _undilate_to_dense(out_s, lse_s, r, g, Lp, L):
    B, H = out_s.shape[:2]
    Dh = out_s.shape[-1]
    out_d, lse_d = _undilate_bhld(out_s, lse_s, r)  # [B, H, n, gp, D]
    out = out_d[:, :, :, :g].reshape(B, H, Lp, Dh)[:, :, :L]
    lse = lse_d[:, :, :, :g].reshape(B, H, Lp)[:, :, :L]
    return out, lse


def _bhld_kvlen(
    B: int, H: int, n: int, g: int, r: int, m: int, real_len: int,
    valid_len_dyn: Optional[jnp.ndarray],
) -> Optional[jnp.ndarray]:
    """[B, H, n] int32 valid sparse-key counts, or None when every slot is
    valid: static tail masks (alignment padding, ``real_len``) combined
    with the optional *traced* per-batch suffix valid lengths (collate pad
    masks) by minimum. Traced counts keep the Pallas path: the kernels
    read them from SMEM at runtime.

    The traced block mirrors the numpy formula of
    :func:`_branch_kvlen_bhld` (sparse slot j of head phase p is valid iff
    dense position ``p + r*j`` lies inside both the segment and the valid
    prefix) — keep the two in lockstep;
    ``test_traced_valid_len_matches_generic`` guards the equivalence."""
    static = _branch_kvlen_bhld(H, n, g, r, m, real_len)
    if static is None and valid_len_dyn is None:
        return None  # all slots valid: lets the jnp tier skip masking
    if static is None:
        static = np.full((H, n), m, np.int32)
    kv = jnp.asarray(np.broadcast_to(static[None], (B, H, n)))
    if valid_len_dyn is not None:
        heads_per_group = -(-H // r)
        phases = jnp.arange(H) // heads_per_group  # [H]: per-head phase id
        kv = jnp.minimum(
            kv, dyn_sparse_counts(valid_len_dyn, g, r, m, phases, n)
        )
    return kv


def dyn_sparse_counts(
    valid_dyn: jnp.ndarray, g: int, r: int, m: int, phases: jnp.ndarray,
    n_seg: int,
) -> jnp.ndarray:
    """[B, len(phases), n_seg] int32 valid sparse-key counts from TRACED
    per-batch valid lengths: sparse slot j of phase p is valid iff dense
    position ``seg*g + p + r*j`` lies inside both the segment and the
    valid prefix. The ONE dynamic-masking formula — shared by the
    head-major tier (phases = per-head phase ids) and the fused
    phase-major tier (phases = arange(r)); keep callers on it so the two
    kernel families can never disagree on boundary semantics."""
    seg = jnp.arange(n_seg)
    in_seg = jnp.clip(
        valid_dyn.reshape(-1)[:, None] - seg[None] * g, 0, g
    )  # [B, n_seg]
    counts = jnp.ceil((in_seg[:, None, :] - phases[None, :, None]) / r)
    return jnp.clip(counts, 0, m).astype(jnp.int32)


def _normalize_valid_len(valid_len, B: int, L: int):
    """(real_len static int, valid_dyn traced [B] or None) from the public
    ``valid_len`` contract: None = all valid, int = static suffix bound
    (folds into trace-time masks), array = TRACED per-batch suffix valid
    lengths (ride the kernels' SMEM valid-count tables at runtime)."""
    if valid_len is None:
        return L, None
    if isinstance(valid_len, (int, np.integer)):
        return min(int(valid_len), L), None
    return L, jnp.asarray(valid_len).reshape(B)


def _flat_eligible(g: int, r: int) -> bool:
    """True when an undilated branch takes the flat zero-glue kernel path
    instead of the segmented one. The single dispatch predicate — also
    consumed by scripts/tpu_selfcheck.py's kernel-coverage dedup key, which
    must compile exactly the kernel variants this choice selects."""
    from gigapath_tpu.ops.pallas_flash import FLAT_MAX_SEGMENT

    return r == 1 and g % 8 == 0 and g <= FLAT_MAX_SEGMENT


def _branch_pallas_fwd_impl(qh, kh, vh, kvlen, sl, r, is_causal, interpret):
    from gigapath_tpu.ops import pallas_flash as pf

    B, H, L, Dh = qh.shape
    g, Lp, n, gp, m, block = _bhld_geom(L, sl, r)
    q5 = _seg_dilate(qh, g, Lp, n, gp, r)
    k5 = _seg_dilate(kh, g, Lp, n, gp, r)
    v5 = _seg_dilate(vh, g, Lp, n, gp, r)
    out_s, lse_s = pf._fwd_impl(
        q5, k5, v5, kvlen, is_causal, Dh ** -0.5, block, block, interpret
    )
    return _undilate_to_dense(out_s, lse_s, r, g, Lp, L)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _branch_pallas(qh, kh, vh, kvlen, sl, r, is_causal, interpret):
    """One head-major dilated branch -> dense (out [B,H,L,D], lse [B,H,L]).

    The custom VJP sits at the BRANCH level, above the dilation: residuals
    are the UNDILATED q/k/v (shared buffers across every branch of the
    multi-branch op — XLA stores one copy) plus this branch's dense
    (out, lse). The flash-level VJP instead saved per-branch dilated
    q5/k5/v5 copies: on the flagship's 5-branch schedule that is ~15 extra
    [B, H, L, 48]-sized residual tensors per layer, the dominant train-step
    memory at PANDA-scale N (measured 53 GB at the 16k bucket; 12.4 GB
    here). Backward re-dilates with the same static slices — a bandwidth
    pass, no extra kernel work. ``kvlen`` [B, H, n] may be traced.
    """
    out, lse = _branch_pallas_fwd_impl(
        qh, kh, vh, kvlen, sl, r, is_causal, interpret
    )
    return out, lse


def _branch_pallas_fwd(qh, kh, vh, kvlen, sl, r, is_causal, interpret):
    out, lse = _branch_pallas_fwd_impl(
        qh, kh, vh, kvlen, sl, r, is_causal, interpret
    )
    return (out, lse), (qh, kh, vh, kvlen, out, lse)


def _branch_pallas_bwd(sl, r, is_causal, interpret, res, cots):
    from gigapath_tpu.ops import pallas_flash as pf

    qh, kh, vh, kvlen, out, lse = res
    do, _dlse = cots  # dense [B, H, L, D]; no gradient through the lse
    B, H, L, Dh = qh.shape
    g, Lp, n, gp, m, block = _bhld_geom(L, sl, r)
    # re-dilate the inputs + the dense cotangent/out/lse into the kernel
    # layout (static slices; the rank-3 lse/delta ride a trailing unit dim)
    q5 = _seg_dilate(qh, g, Lp, n, gp, r)
    k5 = _seg_dilate(kh, g, Lp, n, gp, r)
    v5 = _seg_dilate(vh, g, Lp, n, gp, r)
    do5 = _seg_dilate(do, g, Lp, n, gp, r)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta5 = _seg_dilate(delta[..., None], g, Lp, n, gp, r)[..., 0]
    lse5 = _seg_dilate(lse[..., None], g, Lp, n, gp, r)[..., 0]
    # Backward blocks are chosen independently of the forward single block:
    # the bwd kernels hold ~2.5 live fp32 logits tiles (vs the forward's
    # ~2), so the forward's 1408 choice overflows scoped vmem in the
    # backward (the BENCH_r03 crash). bwd_blocks keeps block_q = the
    # forward block (q side stays unpadded) and shrinks block_k to fit.
    bq, bk = pf.bwd_blocks(block)
    dq5, dk5, dv5 = pf._bwd_impl(
        q5, k5, v5, lse5, delta5, do5, kvlen, is_causal, Dh ** -0.5,
        bq, bk, interpret,
    )

    def undo(g5):
        dense, _ = _undilate_to_dense(g5, jnp.zeros(g5.shape[:-1], jnp.float32),
                                      r, g, Lp, L)
        return dense

    kvlen_ct = (
        None if kvlen is None else np.zeros(kvlen.shape, dtype=jax.dtypes.float0)
    )
    return undo(dq5), undo(dk5), undo(dv5), kvlen_ct


_branch_pallas.defvjp(_branch_pallas_fwd, _branch_pallas_bwd)


def _branch_bhld(
    qh: jnp.ndarray,
    kh: jnp.ndarray,
    vh: jnp.ndarray,
    sl: int,
    r: int,
    *,
    is_causal: bool,
    real_len: int,
    interpret: bool,
    use_pallas: Optional[bool],
    valid_len_dyn: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One dilated branch, entirely in [B, H, L, D]: segment via a free
    reshape, dilate via static phase slices, run the segment-grid flash
    kernel, and undo — no batch-axis reshuffling or relayouts anywhere."""
    B, H, L, Dh = qh.shape
    g, Lp, n, gp, m, block = _bhld_geom(L, sl, r)

    if use_pallas is None:
        from gigapath_tpu.ops.flash_attention import PALLAS_MIN_SEQ, _on_tpu

        use_pallas = (interpret or _on_tpu()) and m >= PALLAS_MIN_SEQ

    if use_pallas and valid_len_dyn is None and _flat_eligible(g, r):
        from gigapath_tpu.ops.pallas_flash import flat_segment_flash

        # undilated branch on the FLAT arrays: no pads, reshapes,
        # dilation, or scatter-back — the ragged tail rides Pallas OOB
        # auto-masking + the per-segment kvlen select. This removes the
        # branch's entire XLA glue (the L -> round_up(L, g) pad alone
        # copied the whole tensor, ~0.12 ms each for q/k/v at L=10k).
        return flat_segment_flash(
            qh, kh, vh, segment_len=g, real_len=real_len,
            is_causal=is_causal, interpret=interpret,
        )

    kvlen = _bhld_kvlen(B, H, n, g, r, m, real_len, valid_len_dyn)
    if use_pallas:
        return _branch_pallas(qh, kh, vh, kvlen, sl, r, is_causal, interpret)

    q5 = _seg_dilate(qh, g, Lp, n, gp, r)
    k5 = _seg_dilate(kh, g, Lp, n, gp, r)
    v5 = _seg_dilate(vh, g, Lp, n, gp, r)
    out_s, lse_s = _segment_attention_jnp(q5, k5, v5, kvlen, is_causal)
    return _undilate_to_dense(out_s, lse_s, r, g, Lp, L)


def dilated_attention_fused(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_lengths: Sequence[int],
    dilated_ratios: Sequence[int],
    *,
    is_causal: bool = False,
    valid_len=None,
    streaming_fusion: Optional[bool] = None,
    interpret: bool = False,
    flags=None,
) -> jnp.ndarray:
    """Fastest path: per-branch phase-major Pallas kernels on dense
    [B, L, E] activations (see :mod:`gigapath_tpu.ops.pallas_dilated`).

    ``flags``: one :class:`~gigapath_tpu.ops.pallas_dilated.PipelineFlags`
    snapshot shared by every branch of this op (None: resolve the
    dispatch here, once, through the plan seam —
    :func:`gigapath_tpu.plan.resolve_plan` — env flags where set, this
    geometry's blessed registry plan where not). ``flags.stream_fusion``
    (``GIGAPATH_STREAM_FUSION``) routes the whole op through the
    streaming fusion epilogue: branch results stay in the packed
    phase-major layout end to end and one epilogue kernel chain emits the
    fused dense output — the per-branch dense out/lse scatter (the
    round-4 glue) never runs. The dense scatter + stacked-softmax path
    below remains the fallback and the parity oracle.

    ``streaming_fusion``: fold each branch's (out, lse) into running
    (acc, m, l) instead of stacking all branch outputs (None — the
    default — inherits the resolved ``flags.streaming_fusion``; an
    explicit bool pins the choice) — each branch's
    packed temporaries AND its dense output die before the next branch
    computes, the peak-memory requirement for long-context forwards. All
    streaming state is 128-lane-clean here ([B, L, E] fp32 acc, [B, H, L]
    stats), unlike the head-major variant whose accumulator had to stay in
    the branch's padded layout to preserve XLA fusion.

    Activations never leave the 128-lane-aligned ``[B, L, E]`` layout:
    segmenting and dilation ride the kernels' BlockSpec index maps, each
    branch emits a dense ``(out [B,L,E], lse [B,H,L])`` pair, and the
    cross-branch LSE-softmax fusion is one fused elementwise pass. Branches
    whose ratio does not divide the head count (never the case for LongNet's
    power-of-two schedules) fall back to the head-major path.
    """
    from gigapath_tpu.ops.pallas_dilated import (
        dilated_attention_stream_fused,
        dilated_branch_attention,
        plan_stream_fusion,
    )

    B, L, H, Dh = q.shape
    E = H * Dh
    if flags is None:
        from gigapath_tpu.plan import resolve_plan

        flags = resolve_plan("dilated_fused", (q, k, v))
    if streaming_fusion is None:
        streaming_fusion = flags.streaming_fusion
    qE, kE, vE = (x.reshape(B, L, E) for x in (q, k, v))
    real_len, valid_dyn = _normalize_valid_len(valid_len, B, L)

    if flags.stream_fusion and len(segment_lengths) > 1:
        plan = plan_stream_fusion(
            L, E, H, segment_lengths, dilated_ratios, interpret=interpret,
            flags=flags,
        )
        if plan is not None:
            out = dilated_attention_stream_fused(
                qE, kE, vE, segment_lengths, dilated_ratios, H,
                real_len=real_len, valid_len_dyn=valid_dyn,
                is_causal=is_causal, interpret=interpret, flags=flags,
                plan=plan,
            )
            return out.reshape(B, L, H, Dh)
        # visible, once per schedule: the epilogue silently not engaging
        # would otherwise be indistinguishable from it being slow
        _warn_once(
            "GIGAPATH_STREAM_FUSION requested but schedule %s/%s at L=%d "
            "admits no epilogue blocking (ratio not dividing H=%d/E=%d, or "
            "no legal dense-block alignment): using the dense fusion path"
            % (list(segment_lengths), list(dilated_ratios), L, H, E)
        )

    def branch(sl, r):
        sl, r = int(sl), int(r)
        if H % r == 0 and E % r == 0:
            return dilated_branch_attention(
                qE, kE, vE, sl, r, H,
                real_len=real_len, valid_len_dyn=valid_dyn,
                is_causal=is_causal, interpret=interpret, flags=flags,
            )
        qh, kh, vh = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        o4, l = _branch_bhld(
            qh, kh, vh, sl, r, is_causal=is_causal, real_len=real_len,
            interpret=interpret, use_pallas=None, valid_len_dyn=valid_dyn,
        )
        return o4.transpose(0, 2, 1, 3).reshape(B, L, E), l

    if streaming_fusion and len(segment_lengths) > 1:
        # Online softmax over the branch axis (same math as the stacked
        # fusion below; weights constant in backward via stop_gradient).
        # Everything that lives ACROSS branches is lane-clean: acc is the
        # [B, L, H, Dh] view of [B, L, E] fp32 and the running stats stay
        # [B, H, L] (L on lanes); their transposed broadcasts inside the
        # update are fused temps.
        def bLH1(x):  # [B, H, L] -> broadcastable [B, L, H, 1] view
            return x.transpose(0, 2, 1)[..., None]

        acc = m_run = l_run = None
        for sl, r in zip(segment_lengths, dilated_ratios):
            o, l = branch(sl, r)
            l = jax.lax.stop_gradient(l)  # [B, H, L]
            o = o.reshape(B, L, H, Dh)
            if acc is None:
                m_run = l
                l_run = jnp.ones_like(l)
                acc = o.astype(jnp.float32)
            else:
                m_new = jnp.maximum(m_run, l)
                a = jnp.exp(m_run - m_new)
                b_ = jnp.exp(l - m_new)
                l_run = l_run * a + b_
                acc = acc * bLH1(a) + o.astype(jnp.float32) * bLH1(b_)
                m_run = m_new
        return (acc / bLH1(l_run)).astype(q.dtype)

    outs, lses = [], []
    for sl, r in zip(segment_lengths, dilated_ratios):
        o, l = branch(sl, r)
        outs.append(o)
        lses.append(l)

    if len(outs) == 1:
        out = outs[0]
    else:
        lse = jnp.stack(lses)  # [n_branch, B, H, L]
        weights = jax.nn.softmax(jax.lax.stop_gradient(lse), axis=0)
        acc = 0.0
        for o, w in zip(outs, weights):
            # w [B,H,L] -> [B,L,H,1] broadcast over the head's lanes; the
            # whole fusion is one elementwise pass over the branch outputs
            acc = acc + o.reshape(B, L, H, Dh).astype(jnp.float32) * (
                w.transpose(0, 2, 1)[..., None]
            )
        out = acc.reshape(B, L, E)
    return out.astype(q.dtype).reshape(B, L, H, Dh)


def dilated_attention_bhld(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_lengths: Sequence[int],
    dilated_ratios: Sequence[int],
    *,
    is_causal: bool = False,
    valid_len=None,
    interpret: bool = False,
    use_pallas: Optional[bool] = None,
    streaming_fusion: bool = False,
) -> jnp.ndarray:
    """Head-major fast path for multi-branch dilated attention.

    Same math as :func:`dilated_attention` (same branch schedule, same
    LSE-softmax fusion with stop-gradient weights), restructured for TPU
    memory layout: one [B,L,H,D] -> [B,H,L,D] relayout at entry, one at
    exit, and every per-branch step in between — segmenting, dilation,
    attention, scatter-back, fusion — is a free reshape, a static slice, or
    a segment-grid Pallas kernel. The per-branch transposes of the generic
    path (3 inputs + out + lse per branch, 5 branches in the flagship) are
    gone. ``valid_len``: suffix-padding bound — a static int (alignment
    padding) folds into trace-time masks; a *traced* [B] array (collate pad
    masks) rides the kernels' SMEM valid-count tables at runtime, keeping
    the Pallas path for masked batches.
    """
    B, L, H, Dh = q.shape
    real_len, valid_dyn = _normalize_valid_len(valid_len, B, L)
    # optimization barriers pin the op's boundaries: without them XLA fuses
    # the entry/exit relayouts into the surrounding layernorm/projection
    # fusions, which then read the 48-lane-minor head-major layout strided
    # (measured +0.65 ms/layer on the flagship, scripts/profile_slide.py)
    q, k, v = jax.lax.optimization_barrier((q, k, v))
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    if streaming_fusion and len(segment_lengths) > 1:
        # Online softmax over the BRANCH axis: each branch's (out, lse) is
        # folded into running (acc, m, l) and its buffers die before the
        # next branch computes — the stacked fusion below keeps all
        # n_branch dense outputs live simultaneously, which dominates peak
        # HBM at PANDA-scale N (the 1M-token operating point). Identical
        # math: final = sum_b softmax_b(lse)[b] * out_b, weights constant
        # in backward (stop_gradient, parity with reference torch.no_grad).
        #
        # Layout note (round 4, measured): keeping the accumulator in the
        # branch layout [B, H, L, D] lets XLA fuse each branch's undilate
        # write directly into the online update — one pass, no extra
        # buffer. A lane-clean [B, L, H, D] accumulator (tried to shave
        # the 48->128 tile padding) materializes every branch output in
        # BOTH layouts and pushed 256k from 12.7 GB to an OOM at 15.9 GB.
        acc = m_run = l_run = None
        for sl, r in zip(segment_lengths, dilated_ratios):
            o, l = _branch_bhld(
                qh, kh, vh, int(sl), int(r),
                is_causal=is_causal, real_len=real_len,
                interpret=interpret, use_pallas=use_pallas,
                valid_len_dyn=valid_dyn,
            )
            l = jax.lax.stop_gradient(l)[..., None]  # [B, H, L, 1]
            if acc is None:
                m_run = l
                l_run = jnp.ones_like(l)
                acc = o.astype(jnp.float32)
            else:
                m_new = jnp.maximum(m_run, l)
                a = jnp.exp(m_run - m_new)
                b_ = jnp.exp(l - m_new)
                l_run = l_run * a + b_
                acc = acc * a + o.astype(jnp.float32) * b_
                m_run = m_new
        out = acc / l_run
        return jax.lax.optimization_barrier(
            out.astype(q.dtype).transpose(0, 2, 1, 3)
        )

    outs, lses = [], []
    for sl, r in zip(segment_lengths, dilated_ratios):
        o, l = _branch_bhld(
            qh, kh, vh, int(sl), int(r),
            is_causal=is_causal, real_len=real_len,
            interpret=interpret, use_pallas=use_pallas,
            valid_len_dyn=valid_dyn,
        )
        outs.append(o)
        lses.append(l)

    if len(outs) == 1:
        out = outs[0]
    else:
        lse = jnp.stack(lses)  # [n_branch, B, H, L]
        weights = jax.nn.softmax(jax.lax.stop_gradient(lse), axis=0)[..., None]
        out = sum(o.astype(jnp.float32) * w for o, w in zip(outs, weights))
    return jax.lax.optimization_barrier(
        out.astype(q.dtype).transpose(0, 2, 1, 3)
    )


def _gather_kv_seq_parallel(
    x: jnp.ndarray, sl: int, local_len: int, axis_name: str
) -> jnp.ndarray:
    """All-gather sparse K/V along the seq axis, keep the ranks of my segment.

    ``x`` [b, m, H, D] is the local (single-segment) sparse view; returns
    [b, m * ranks_per_segment, H, D]. Counterpart of reference
    ``gather_kv:55-74`` (non-causal path), with the autograd all-gather /
    reduce-scatter pair replaced by ``jax.lax.all_gather`` which is
    differentiable by construction.
    """
    assert sl % local_len == 0, (sl, local_len)
    ranks_per_segment = sl // local_len
    gathered = jax.lax.all_gather(x, axis_name, axis=0, tiled=False)  # [W, b, m, H, D]
    rank = jax.lax.axis_index(axis_name)
    segment_start = rank // ranks_per_segment * ranks_per_segment
    segment = jax.lax.dynamic_slice_in_dim(gathered, segment_start, ranks_per_segment, axis=0)
    # [rps, b, m, H, D] -> [b, rps*m, H, D]
    segment = segment.transpose(1, 0, 2, 3, 4)
    b = segment.shape[0]
    return segment.reshape(b, ranks_per_segment * segment.shape[2], *segment.shape[3:])


# ---------------------------------------------------------------------------
# ring-scheduled K/V exchange (GIGAPATH_RING_ATTN)
# ---------------------------------------------------------------------------
#
# The all-gather path above materializes every oversized branch's ENTIRE
# segment K/V on every shard — per-shard memory O(full segment), with the
# collective serial on the critical path. The ring schedule below (Ring
# Attention, Liu et al. 2023, arXiv:2310.01889) keeps per-shard memory
# O(local chunk): each shard holds only its own sparse K/V chunk, the
# chunks rotate around the segment's sub-ring via jax.lax.ppermute, each
# step computes partial attention of the LOCAL queries against the
# RESIDENT chunk, and partials fold through the stored-LSE online-softmax
# combine (flash_attention.combine_partials — the same merge primitive
# the stream-fusion epilogue applies across branches, here applied across
# ring steps). The next chunk's ppermute is issued BEFORE the resident
# chunk's compute, so the collective has no data dependence on the
# attention math and XLA can overlap it with kernel time. The gather path
# stays as the fallback and parity oracle.


def _ring_perm(world: int, rps: int) -> Tuple[Tuple[int, int], ...]:
    """Static ppermute (src, dst) pairs rotating every ``rps``-sized
    sub-ring of the seq axis by one: rank r sends to the next rank of ITS
    OWN segment's ring (``rps < world`` = several independent sub-rings,
    the segment-spans-a-strict-subset-of-the-mesh case). After s
    applications, rank r holds the chunk of rank
    ``(r // rps) * rps + (r % rps - s) % rps``."""
    assert world % rps == 0, (world, rps)
    return tuple(
        (src, (src // rps) * rps + ((src % rps) + 1) % rps)
        for src in range(world)
    )


def _ring_step_counts(counts, my_rel, s: int, rps: int):
    """Valid-key counts [B, H] for ring step ``s``: the row of the
    per-origin-rank table [rps, B, H] belonging to the chunk resident at
    step s (origin ``(my_rel - s) mod rps``, a traced index — the counts
    stay in the table and the step selects its row, so the hoisted gather
    is shared by every step of every gathered branch)."""
    if counts is None:
        return None
    orig = jnp.mod(my_rel - s, rps)
    return jax.lax.dynamic_slice_in_dim(counts, orig, 1, axis=0)[0]


def _ring_attention_fwd_impl(qs, ks, vs, counts, axis_name, world, rps,
                             allow_pallas):
    """Forward ring: local sparse q [B, mq, H, D] against the rotating
    chunks [B, mk, H, D] -> (out [B, mq, H, D], lse [B, H, mq])."""
    from gigapath_tpu.obs.spans import ring_step
    from gigapath_tpu.ops.flash_attention import (
        combine_partials,
        partial_attention,
    )

    perm = _ring_perm(world, rps)
    my_rel = jnp.mod(jax.lax.axis_index(axis_name), rps)
    comm_bytes = 2 * int(np.prod(ks.shape)) * ks.dtype.itemsize  # k + v
    use_pallas = None if allow_pallas else False
    out = lse = None
    k_cur, v_cur = ks, vs
    for s in range(rps):
        with ring_step(s, rps, comm_bytes if s + 1 < rps else 0):
            # double-buffer: the permute reads only the resident chunk,
            # never this step's attention results — issued first, it can
            # ride the interconnect while the partial attention computes
            if s + 1 < rps:
                k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
                v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            cnt = _ring_step_counts(counts, my_rel, s, rps)
            o_s, l_s = partial_attention(
                qs, k_cur, v_cur, kv_valid_len=cnt, use_pallas=use_pallas
            )
            if out is None:
                # fp32 accumulator from the first partial on: every later
                # combine_partials keeps it fp32 (out_a's dtype)
                out, lse = o_s.astype(jnp.float32), l_s
            else:
                out, lse = combine_partials(out, lse, o_s, l_s)
            if s + 1 < rps:
                k_cur, v_cur = k_nxt, v_nxt
    return out.astype(qs.dtype), lse


def _ring_partial_bwd(qs, k_c, v_c, do, lse, delta, cnt, scale):
    """One ring step's gradient contributions, flash-backward style: the
    chunk's probabilities are recomputed from the logits and the FINAL
    combined lse (p = exp(s - lse_full) is already the full-softmax
    probability restricted to this chunk's keys), so no per-step
    normalization state needs saving. All math fp32; numerics mirror
    attention_with_lse (mask before lse-subtract, masked probs zeroed)."""
    q32 = qs.astype(jnp.float32)
    k32 = k_c.astype(jnp.float32)
    v32 = v_c.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    s_ = jnp.einsum("bqhd,bkhd->bhqk", q32, k32) * scale
    if cnt is not None:
        col_ok = (
            jnp.arange(k_c.shape[1])[None, None, None, :]
            < cnt[:, :, None, None]
        )
        s_ = jnp.where(col_ok, s_, NEG_INF)
    p = jnp.exp(s_ - lse[..., None])  # [B, H, mq, mk]
    if cnt is not None:
        p = jnp.where(col_ok, p, 0.0)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, v32)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k32) * scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32) * scale
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_attention(qs, ks, vs, counts, axis_name, world, rps, allow_pallas):
    """Ring-scheduled attention of local sparse queries against the
    segment's rotating sparse K/V chunks.

    ``qs`` [B, mq, H, D] local queries; ``ks``/``vs`` [B, mk, H, D] the
    LOCAL chunk (never gathered); ``counts`` optional [rps, B, H] valid
    sparse-key counts per ORIGIN rank of the sub-ring (from the hoisted
    per-call counts gather), or None when every slot is valid. Returns
    ``(out [B, mq, H, D], lse [B, H, mq])`` — identical math to
    attending the concatenated chunks (softmax is associative under the
    stored-LSE combine), so the all-gather path stays the parity oracle.

    The custom VJP rings in reverse order of memory, not of schedule:
    the same forward rotation replays, each shard computes its
    contribution to the RESIDENT chunk's dK/dV from the saved combined
    lse (no per-step softmax state is stored), accumulates it into a
    gradient buffer that rotates WITH the chunk, and after a full cycle
    every buffer arrives home holding all ``rps`` shards' contributions
    — the overlapped twin of the differentiable all-gather's implicit
    backward reduce-scatter.
    """
    return _ring_attention_fwd_impl(
        qs, ks, vs, counts, axis_name, world, rps, allow_pallas
    )


def _ring_attention_fwd(qs, ks, vs, counts, axis_name, world, rps,
                        allow_pallas):
    out, lse = _ring_attention_fwd_impl(
        qs, ks, vs, counts, axis_name, world, rps, allow_pallas
    )
    # residuals: the local inputs plus the combined (out, lse) — nothing
    # whose size scales with the segment, and no per-step state
    return (out, lse), (qs, ks, vs, counts, out, lse)


def _ring_attention_bwd(axis_name, world, rps, allow_pallas, res, cots):
    from gigapath_tpu.obs.spans import ring_step

    qs, ks, vs, counts, out, lse = res
    do, _dlse = cots  # no gradient flows through the lse output
    Dh = qs.shape[-1]
    scale = Dh ** -0.5
    perm = _ring_perm(world, rps)
    my_rel = jnp.mod(jax.lax.axis_index(axis_name), rps)
    kv_bytes = 2 * int(np.prod(ks.shape)) * ks.dtype.itemsize  # k + v
    # delta = rowsum(do * out) per (token, head) — constant across steps
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", do.astype(jnp.float32), out.astype(jnp.float32)
    )
    dq = jnp.zeros(qs.shape, jnp.float32)
    dk_acc = jnp.zeros(ks.shape, jnp.float32)
    dv_acc = jnp.zeros(vs.shape, jnp.float32)
    k_cur, v_cur = ks, vs
    # every step rotates the fp32 dk/dv accumulators; all but the last
    # also rotate the k/v double-buffer
    acc_bytes = 2 * int(np.prod(ks.shape)) * 4
    for s in range(rps):
        with ring_step(
            s, rps, acc_bytes + (kv_bytes if s + 1 < rps else 0)
        ):
            if s + 1 < rps:  # double-buffer: permute before the compute
                k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
                v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
            cnt = _ring_step_counts(counts, my_rel, s, rps)
            dq_s, dk_s, dv_s = _ring_partial_bwd(
                qs, k_cur, v_cur, do, lse, delta, cnt, scale
            )
            dq = dq + dq_s
            # dK/dV accumulate where computed and rotate WITH the chunk:
            # after the final step's permute each buffer is home (rotated
            # rps times == identity) carrying every shard's contribution
            dk_acc = jax.lax.ppermute(dk_acc + dk_s, axis_name, perm)
            dv_acc = jax.lax.ppermute(dv_acc + dv_s, axis_name, perm)
            if s + 1 < rps:
                k_cur, v_cur = k_nxt, v_nxt
    counts_ct = (
        None if counts is None
        else np.zeros(counts.shape, dtype=jax.dtypes.float0)
    )
    return (
        dq.astype(qs.dtype), dk_acc.astype(ks.dtype),
        dv_acc.astype(vs.dtype), counts_ct,
    )


_ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def dilated_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_lengths: Sequence[int],
    dilated_ratios: Sequence[int],
    *,
    is_causal: bool = False,
    offset: int = 0,
    attn_fn: Optional[AttnFn] = None,
    seq_axis_name: Optional[str] = None,
    seq_axis_size: int = 1,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    valid_len: Optional[jnp.ndarray] = None,
    flags=None,
) -> jnp.ndarray:
    """Multi-branch dilated attention on [B, L, H, D] tensors -> [B, L, H, D].

    ``attn_fn(q, k, v, is_causal=...) -> (out, lse)`` defaults to the fused
    jnp op; pass the Pallas flash kernel for long dense segments. When
    ``seq_axis_name`` is set (inside ``shard_map``), L is the *local* shard
    length and branches whose segment exceeds it gather K/V across the axis;
    fully-local branches route through the fused phase-major kernels on TPU.
    shard_map callers must pass ``check_vma=False`` when the Pallas tier is
    active (jax 0.9's vma checking cannot see through ``pallas_call``).
    ``dropout_rate`` is attention-probability dropout inside each branch
    (parity with the reference forwarding dropout to flash-attn).

    ``valid_len``: optional suffix-padding spec — tokens at positions
    ``>= valid_len`` are excluded from every branch's keys (the
    masked-batching extension the reference only sketches in its dead
    ``custom_*`` files). A static Python int (same for every row) folds into
    the trace-time tail masks; a traced [B] array (ragged batches) rides the
    Pallas kernels' runtime SMEM valid-count tables — both keep the compiled
    fast path. Under sequence parallelism ``valid_len`` is the LOCAL
    per-shard spec — an int bounds every shard's own suffix (correct for
    counts derived from the sharded mask, NOT for a global single-device
    bound carried into ``shard_map`` unchanged), and a traced [B] array is
    each shard's own valid count (sum the sharded ``key_padding_mask`` per
    shard, as :class:`DilatedAttention` does): segment-local branches
    consume it
    directly on the fused kernels, and gathered branches all-gather every
    rank's counts to mask the concatenated keys (global suffix padding
    keeps validity a contiguous prefix). A static int (same partial count
    on every shard — not a contiguous prefix) and causal + ``valid_len``
    both remain unsupported on gathered branches.

    ``flags``: one :class:`~gigapath_tpu.ops.pallas_dilated.PipelineFlags`
    snapshot shared by every branch of this op (None: snapshot the
    environment here, once — the same contract as
    :func:`dilated_attention_fused`). ``flags.ring_attn``
    (``GIGAPATH_RING_ATTN``) routes non-causal gathered branches through
    the ring-scheduled K/V exchange (:func:`_ring_attention`): per-shard
    memory O(local chunk) instead of O(full segment), ppermute overlapped
    with partial attention, the all-gather path remaining the fallback
    (causal gathered branches, custom ``attn_fn``, dropout) and the
    parity oracle.
    """
    attn_fn_was_default = attn_fn is None
    if attn_fn_was_default:
        from gigapath_tpu.ops.flash_attention import flash_attention

        attn_fn = flash_attention
    if dropout_rate > 0.0 and dropout_rng is not None:
        # attention-probability dropout requires materialized probs; the
        # default dispatcher is swapped for the jnp path (all gigapath
        # configs train with attention_dropout=0, so the flash kernel stays
        # on the hot path). An explicitly-supplied attn_fn is never silently
        # replaced.
        if not attn_fn_was_default:
            raise NotImplementedError(
                "attention dropout is not supported with a custom attn_fn"
            )
        base_fn = attention_with_lse
        rngs = jax.random.split(dropout_rng, len(segment_lengths))

        def make_attn_fn(branch_rng):
            return lambda *a, **kw: base_fn(
                *a, dropout_rate=dropout_rate, dropout_rng=branch_rng, **kw
            )
    assert len(segment_lengths) == len(dilated_ratios)
    if offset > 0 and k.shape[1] != offset + q.shape[1]:
        # incremental decoding contract (reference gathering:78-82): q holds
        # the new rows at global positions [offset, offset+Lq) and k/v hold
        # the full prefix-inclusive cache
        raise ValueError(
            f"offset={offset} decoding requires Lk == offset + Lq (full KV "
            f"cache); got Lq={q.shape[1]}, Lk={k.shape[1]}"
        )
    B, L, H, Dh = q.shape

    # ONE dispatch resolution per public call (the plan seam): env flags
    # where set, this geometry's blessed registry plan where not. Every
    # branch of this op — fused, head-major, gathered, ring — shares the
    # resolved snapshot, so branches can never observe different
    # dispatch decisions (the same invariant the flag snapshot held).
    if flags is None:
        from gigapath_tpu.plan import resolve_plan

        flags = resolve_plan("dilated_attention", (q, k, v))

    # ONE eligibility gate for the compiled-kernel paths (the single-device
    # fast path below and the seq-parallel fused-local routing further
    # down): no custom attn_fn, no dropout, no decoding offset, self-
    # attention shapes. Kept in one place so single-device and sharded
    # dispatch can never silently diverge.
    kernels_eligible = (
        attn_fn_was_default
        and not (dropout_rate > 0.0 and dropout_rng is not None)
        and offset == 0
        and q.shape == k.shape == v.shape
    )

    def _tpu_default_dispatch() -> bool:
        # escape hatch: GIGAPATH_FORCE_GENERIC_ATTN=1 re-routes the default
        # TPU dispatch to the generic jnp path (compiled-kernel triage aid;
        # the compiled kernels are otherwise validated by
        # scripts/tpu_selfcheck.py rather than the CPU/interpret CI tier)
        from gigapath_tpu.ops.flash_attention import _on_tpu

        return _on_tpu() and not _env_flag("GIGAPATH_FORCE_GENERIC_ATTN")

    # Head-major fast path (TPU): see dilated_attention_bhld. Taken whenever
    # nothing forces the generic layout and there is no sequence
    # parallelism. Both static AND traced valid_len ride this path (traced
    # counts live in the kernels' SMEM tables) — routing traced masks to
    # the generic jnp tier previously put the ENTIRE fine-tune train path
    # on dense-probability attention (53 GB at the 16k bucket).
    if kernels_eligible and (seq_axis_name is None or seq_axis_size <= 1):
        if _tpu_default_dispatch():
            # Phase-major fused path (pallas_dilated.py) is the default
            # since round 4's kernel-side packing landed: activations stay
            # [B, L, E], per-branch pack/unpack are single-pass Pallas copy
            # kernels over a diagonal-only layout, and the v5e op-time A/B
            # at N=10241 reads fused 5.19 ms vs head-major 6.69 ms forward
            # (grad step 15.1 vs 18.8 ms). Static AND traced valid_len both
            # ride it (traced counts live in the kernels' SMEM tables). The
            # head-major path remains for streaming branch fusion
            # (long-context memory) and ratios not dividing the heads.
            # flags.streaming_fusion (GIGAPATH_STREAMING_FUSION): fold
            # branches into running (acc, m, l) instead of stacking all
            # branch outputs — lower peak HBM, the enabler for the
            # 1M-token operating point. flags.stream_fusion
            # (GIGAPATH_STREAM_FUSION) engages the packed streaming
            # fusion epilogue inside dilated_attention_fused. Both ride
            # the ONE resolved snapshot taken at the top of this call
            # (plan seam) — no env read happens here (gigalint GL017).
            streaming = flags.streaming_fusion
            fused_ok = all(
                H % int(rr) == 0 and (H * Dh) % int(rr) == 0
                for rr in dilated_ratios
            )
            if fused_ok:
                return dilated_attention_fused(
                    q, k, v, segment_lengths, dilated_ratios,
                    is_causal=is_causal, valid_len=valid_len,
                    streaming_fusion=streaming, flags=flags,
                )
            # visible, once per schedule: this fallback is a perf cliff
            # (head-major re-tiles activations per branch) that no log
            # line would otherwise ever attribute
            _warn_once(
                "dilated-attention schedule %s/%s has a ratio not dividing "
                "H=%d (or H*Dh=%d): falling back from the fused phase-major "
                "path to the head-major path"
                % (list(segment_lengths), list(dilated_ratios), H, H * Dh)
            )
            return dilated_attention_bhld(
                q, k, v, segment_lengths, dilated_ratios,
                is_causal=is_causal, valid_len=valid_len,
                streaming_fusion=streaming,
            )

    # Under sequence parallelism, branches whose segment fits the local
    # shard need no gather and are, per shard, exactly a single-device
    # branch — route them through the fused phase-major kernels (the
    # single-chip default path, measured 5.19 vs 6.69 ms fwd head-major at
    # N=10241) instead of the head-major generic loop. Gathered branches
    # and every non-default case keep the generic path.
    def _vma_transparent() -> bool:
        # jax 0.9's vma checking cannot see through pallas_call: under a
        # shard_map with the default check_vma=True the traced avals carry
        # a non-empty vma and the kernel call would fail at trace time.
        # Auto-fall-back to the generic path there (warning once) instead
        # of hard-breaking existing callers; check_vma=False unlocks the
        # fused routing. jax 0.4.x has neither jax.typeof nor vma (its
        # shard_map uses check_rep, which pallas already satisfies) — the
        # fused routing is unconditionally available there.
        typeof = getattr(jax, "typeof", None)
        vma = (
            getattr(typeof(q), "vma", frozenset()) if typeof else frozenset()
        )
        if vma:
            _warn_once(
                "sequence-parallel dilated attention inside a "
                "check_vma=True shard_map: pallas kernels are vma-opaque "
                "in jax 0.9, so local branches fall back to the generic "
                "path — pass check_vma=False to shard_map to enable the "
                "fused kernels"
            )
            return False
        return True

    # Ragged slides no longer force the generic fallback here: a traced
    # [B] valid_len (the module derives it from the SHARDED
    # key_padding_mask, so under shard_map it is the per-shard LOCAL
    # valid count) rides the fused kernels' SMEM valid-count tables
    # exactly as on a single device, and gathered branches combine the
    # all-gathered per-rank counts below (_dilated_branch).
    seq_active = seq_axis_name is not None and seq_axis_size > 1
    # the resolved snapshot from the top of this call serves the
    # fused-local routing AND the ring dispatch below (same invariant as
    # the single-device dispatch above: branches of one op must never
    # observe different dispatch decisions)
    sp_flags = flags
    fused_local = (
        kernels_eligible
        and seq_active
        and _tpu_default_dispatch()
        and _vma_transparent()
    )
    sp_real_len, sp_valid_dyn = (
        _normalize_valid_len(valid_len, B, L) if fused_local else (L, None)
    )

    # Ring schedule (GIGAPATH_RING_ATTN) for the gathered branches: same
    # eligibility gate as the compiled kernels (default attn_fn, no
    # dropout, no offset, self-attention shapes) — the ring VJP implements
    # softmax-attention math and cannot honor an arbitrary attn_fn.
    # Causal gathered branches keep the gather path (its rank-bias
    # construction has no ring counterpart yet); _dilated_branch warns.
    ring_attn = bool(
        seq_active and kernels_eligible and sp_flags is not None
        and sp_flags.ring_attn
    )
    ring_allow_pallas = False
    if ring_attn:
        # flash_attention's Pallas tier for the per-step partials is only
        # reachable on TPU outside a vma-checking shard_map (same
        # constraint as the fused-local routing); the jnp tier is always
        # legal. Static: participates in the ring op's nondiff args.
        ring_allow_pallas = _tpu_default_dispatch() and _vma_transparent()

    # Hoisted per-call counts gather: the ragged valid counts are
    # rank-local data, identical across branches — ONE all_gather serves
    # every gathered branch (gather path and ring path alike) instead of
    # one per branch.
    gathered_counts = None
    if (
        seq_active
        and valid_len is not None
        and not isinstance(valid_len, (int, np.integer))
        and any(int(sl) > k.shape[1] for sl in segment_lengths)
    ):
        vl_local = jnp.asarray(valid_len, jnp.int32).reshape(B)
        gathered_counts = jax.lax.all_gather(
            vl_local, seq_axis_name, axis=0
        )  # [W, B]

    outs, lses = [], []
    for i, (sl, r) in enumerate(zip(segment_lengths, dilated_ratios)):
        sl_i, r_i = int(sl), int(r)
        if (
            fused_local
            and sl_i <= k.shape[1]
            and H % r_i == 0
            and (H * Dh) % r_i == 0
        ):
            from gigapath_tpu.ops.pallas_dilated import dilated_branch_attention

            oE, l = dilated_branch_attention(
                q.reshape(B, L, H * Dh), k.reshape(B, L, H * Dh),
                v.reshape(B, L, H * Dh), sl_i, r_i, H,
                real_len=sp_real_len, valid_len_dyn=sp_valid_dyn,
                is_causal=is_causal, flags=sp_flags,
            )
            outs.append(oE.reshape(B, L, H, Dh))
            lses.append(l)
            continue
        branch_fn = attn_fn
        if dropout_rate > 0.0 and dropout_rng is not None:
            branch_fn = make_attn_fn(rngs[i])
        o, l = _dilated_branch(
            q, k, v, sl_i, r_i,
            is_causal=is_causal, offset=offset, attn_fn=branch_fn,
            seq_axis_name=seq_axis_name, seq_axis_size=seq_axis_size,
            valid_len=valid_len, gathered_counts=gathered_counts,
            ring=ring_attn, ring_allow_pallas=ring_allow_pallas,
        )
        outs.append(o)
        lses.append(l)

    if len(outs) == 1:
        return outs[0]

    # LSE-weighted fusion across branches; weights are constants in backward
    # (parity with reference scattering:119-128 under torch.no_grad).
    lse = jnp.stack(lses)  # [n, B, H, L]
    weights = jax.nn.softmax(jax.lax.stop_gradient(lse), axis=0)
    out = sum(
        o.astype(jnp.float32) * w[..., None].transpose(0, 2, 1, 3)  # [B,H,L,1]->[B,L,H,1]
        for o, w in zip(outs, weights)
    )
    return out.astype(q.dtype)


def _dilated_branch(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sl: int,
    r: int,
    *,
    is_causal: bool,
    offset: int,
    attn_fn: AttnFn,
    seq_axis_name: Optional[str],
    seq_axis_size: int,
    valid_len: Optional[jnp.ndarray] = None,
    gathered_counts: Optional[jnp.ndarray] = None,
    ring: bool = False,
    ring_allow_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One (segment_length, ratio) branch -> (out [B,L,H,D], lse [B,H,L]).

    ``gathered_counts``: the caller's hoisted ``[W, B]`` all-gather of
    per-rank valid counts (rank-local data, identical across branches —
    gathered once per ``dilated_attention`` call, not per branch).
    ``ring``: route a non-causal gathered branch through the
    ring-scheduled K/V exchange instead of the all-gather."""
    B, L, H, Dh = q.shape

    if offset > 0:
        # Incremental decoding (reference gathering:78-82 / scattering:113):
        # in the full forward, a query at global position t only attends keys
        # inside its own segment t//sl — so earlier key segments are
        # invisible and can be dropped. Slicing K/V to the query's segment
        # start and front-padding q by offset % sl realigns both to a common
        # within-segment coordinate system with Lq == Lk, after which the
        # standard equal-length path (incl. its causal mask and real-length
        # tail masks on the *sliced* cache) is exactly the decode math.
        assert seq_axis_name is None or seq_axis_size <= 1, (
            "offset decoding + sequence parallelism are not supported together"
        )
        s0 = (offset // sl) * sl
        if s0 > 0:
            k = k[:, s0:]
            v = v[:, s0:]
        q = jnp.pad(q, ((0, 0), (offset % sl, 0), (0, 0), (0, 0)))
    Lq = q.shape[1]

    gather_kv = (
        seq_axis_name is not None and seq_axis_size > 1 and sl > k.shape[1]
    )

    g_q = min(sl, Lq)
    qp = _pad_to_multiple(q, g_q, axis=1)
    n_seg = qp.shape[1] // g_q
    qs = qp.reshape(B * n_seg, g_q, H, Dh)
    qs = dense_to_sparse(qs, r)

    g_k = min(sl, k.shape[1])
    kp = _pad_to_multiple(k, g_k, axis=1).reshape(-1, g_k, H, Dh)
    vp = _pad_to_multiple(v, g_k, axis=1).reshape(-1, g_k, H, Dh)
    ks = dense_to_sparse(kp, r)
    vs = dense_to_sparse(vp, r)

    kv_valid_len = None
    sp_causal_bias = None
    ring_result = None
    ring_counts = None
    if gather_kv:
        local_len = k.shape[1]
        use_ring = ring and not is_causal
        if ring and is_causal:
            # visible, once: silently taking the gather path would make
            # the flag look broken exactly where memory matters most
            _warn_once(
                "GIGAPATH_RING_ATTN requested on a CAUSAL gathered branch: "
                "the ring schedule has no rank-bias construction yet — "
                "using the all-gather path for this branch"
            )
        if valid_len is not None:
            if is_causal:
                raise NotImplementedError(
                    "causal + padding masks + sequence parallelism are not "
                    "supported together yet"
                )
            # Ragged gathered branch: ``valid_len`` is the LOCAL per-shard
            # suffix valid count (the module sums the sharded
            # key_padding_mask per shard). All-gather every rank's counts
            # and keep the ranks of my segment — mirroring
            # _gather_kv_seq_parallel's key selection — then count valid
            # sparse slots per (rank block, head phase): local slot j of
            # head phase p sits at local position p + r*j, valid iff
            # < that rank's count. GLOBAL suffix padding makes validity a
            # contiguous prefix of the concatenated key axis (every rank
            # before the cut is full), so a single per-(batch, head)
            # count is exact. A static int CANNOT express that: it is the
            # same partial count on EVERY rank, i.e. holes mid-axis that
            # a prefix count would silently mis-mask — refuse it.
            if isinstance(valid_len, (int, np.integer)):
                raise NotImplementedError(
                    "a static-int valid_len on a gathered sequence-parallel "
                    "branch would mask the same suffix on every shard — not "
                    "a contiguous prefix of the concatenated key axis; pass "
                    "the traced per-shard counts of a suffix-padded batch "
                    "(sum the sharded key_padding_mask) instead"
                )
            rps = sl // local_len
            m_loc = ks.shape[1]
            all_counts = gathered_counts  # hoisted: ONE gather per call
            if all_counts is None:  # direct/partial callers only
                vl_local = jnp.asarray(valid_len, jnp.int32).reshape(B)
                all_counts = jax.lax.all_gather(
                    vl_local, seq_axis_name, axis=0
                )  # [W, B]
            rank = jax.lax.axis_index(seq_axis_name)
            seg_counts = jax.lax.dynamic_slice_in_dim(
                all_counts, rank // rps * rps, rps, axis=0
            )  # [rps, B]
            heads_per_group = -(-H // r)
            phases = jnp.arange(H) // heads_per_group  # [H]
            per_rank = jnp.ceil(
                (seg_counts[:, :, None] - phases[None, None, :]) / r
            )
            per_rank = jnp.clip(per_rank, 0, m_loc).astype(jnp.int32)
            if use_ring:
                # keep the per-ORIGIN-rank table [rps, B, H]: each ring
                # step selects the resident chunk's row; the prefix sum
                # over concatenated keys never exists on the ring path
                ring_counts = per_rank
            else:
                kv_valid_len = per_rank.sum(axis=0)  # [B, H] == [B*n_seg, H]
            valid_len = None  # consumed
        if use_ring:
            assert sl % local_len == 0, (sl, local_len)
            rps = sl // local_len
            assert rps <= seq_axis_size, (
                f"gathered branch needs {rps} ranks but the seq axis has "
                f"{seq_axis_size}"
            )
            ring_result = _ring_attention(
                qs, ks, vs, ring_counts,
                seq_axis_name, seq_axis_size, rps, ring_allow_pallas,
            )
        else:
            ks = _gather_kv_seq_parallel(ks, sl, local_len, seq_axis_name)
            vs = _gather_kv_seq_parallel(vs, sl, local_len, seq_axis_name)
        if is_causal:
            # Causal sequence parallelism (reference gather_kv:64-68): ranks
            # of my segment *ahead* of me must be invisible, earlier ranks
            # fully visible, my own rank causally visible. Key slot j of rank
            # block w' and query slot i share a head phase p, so global order
            # reduces to block-and-slot order: key (w', j) <= query (w, i)
            # iff j_cat <= w_rel*m + i in the concatenated key axis. The
            # reference's literal dormant code instead drops the current
            # rank's own keys and zero-stubs rank 0 (`x[:1] * 0`), which
            # breaks self-attention; this implements the evident intent (see
            # PARITY.md). The rank index is traced, so the mask rides as an
            # additive bias instead of the static causal flag.
            rps = sl // local_len
            m_loc = ks.shape[1] // rps
            w_rel = jax.lax.axis_index(seq_axis_name) % rps
            qi = jnp.arange(qs.shape[1])[:, None]
            kj = jnp.arange(ks.shape[1])[None, :]
            sp_causal_bias = jnp.where(
                kj <= qi + w_rel * m_loc, 0.0, NEG_INF
            )[None, None]  # [1, 1, Lq_sparse, Lk_cat]
            is_causal = False  # superseded by the bias
    else:
        static_len = k.shape[1]
        if isinstance(valid_len, int):
            static_len = min(valid_len, static_len)
            valid_len = None  # folded into the static tail masks below
        kv_valid_len = _kv_valid_lengths(
            B, kp.shape[0] // B, g_k, r, ks.shape[1], H, static_len
        )
        if valid_len is not None:
            # dynamic per-batch suffix padding: same segment/dilation count
            # formula as _kv_valid_lengths, with the traced valid length in
            # place of the static real length; combined by min
            n_seg_k = kp.shape[0] // B
            m = ks.shape[1]
            heads_per_group = -(-H // r)
            phases = jnp.arange(H) // heads_per_group  # [H]
            seg = jnp.arange(n_seg_k)  # [n_seg]
            counts = jnp.ceil(
                (
                    valid_len[:, None, None]
                    - seg[None, :, None] * g_k
                    - phases[None, None, :]
                )
                / r
            )
            counts = jnp.clip(counts, 0, m).astype(jnp.int32).reshape(B * n_seg_k, H)
            kv_valid_len = (
                counts
                if kv_valid_len is None
                else jnp.minimum(counts, jnp.asarray(kv_valid_len, jnp.int32))
            )

    if ring_result is not None:
        out_s, lse_s = ring_result
    elif sp_causal_bias is not None:
        out_s, lse_s = attn_fn(
            qs, ks, vs, is_causal=False, kv_valid_len=None, bias=sp_causal_bias
        )
    else:
        out_s, lse_s = attn_fn(
            qs, ks, vs, is_causal=is_causal, kv_valid_len=kv_valid_len
        )

    out_d, lse_d = sparse_to_dense(out_s, lse_s, r, g_q)
    out = out_d.reshape(B, n_seg * g_q, H, Dh)
    lse = lse_d.reshape(B, n_seg, H, g_q).transpose(0, 2, 1, 3).reshape(B, H, -1)
    start = offset % sl if offset > 0 else 0
    return out[:, start : start + L], lse[..., start : start + L]


class DilatedAttention(MultiheadAttention):
    """LongNet attention module: MHA projections around dilated attention.

    Parity with reference ``DilatedAttention(MultiheadAttention)``
    (``dilated_attention.py:14``): same q/k/v/out projections, sub-LN, and
    branch schedule from the config. ``seq_axis_name`` activates sequence
    parallelism when the module runs inside ``shard_map``.
    """

    segment_length: Sequence[int] = ()
    dilated_ratio: Sequence[int] = ()
    seq_parallel: bool = False
    seq_axis_name: Optional[str] = None
    seq_axis_size: int = 1
    attn_fn: Optional[AttnFn] = None

    def _cached_attend_inputs(self, k, v, cur, Lq, attn_mask, is_causal):
        """Positional (offset-based) incremental decode.

        The segment/dilation structure depends on absolute positions, so the
        cache is consumed as ``offset = cur`` plus the live prefix of the
        buffer — not as a dense mask over the full static buffer (the base
        class mechanism), which dilated attention cannot honor. The cache
        index must be concrete (eager generation loop, as in the reference's
        fairseq-style decoding); a traced index raises with guidance.
        """
        try:
            off = int(cur)
        except jax.errors.ConcretizationTypeError as e:
            raise NotImplementedError(
                "DilatedAttention incremental decode requires a concrete "
                "cache index (run the generation loop eagerly, outside jit): "
                "segment boundaries are position-dependent static shapes"
            ) from e
        k = k[:, : off + Lq]
        v = v[:, : off + Lq]
        return k, v, attn_mask, is_causal, off

    def _attend(
        self,
        q,
        k,
        v,
        *,
        key_padding_mask=None,
        attn_mask=None,
        rel_pos=None,
        is_causal: bool = False,
        deterministic: bool = True,
        offset: int = 0,
    ):
        assert rel_pos is None, "dilated attention does not support rel_pos bias"
        assert attn_mask is None, "dilated attention does not support attn_mask"
        # key_padding_mask (True = pad) is consumed as a *suffix* valid
        # length: batches are collated with trailing padding (data/collate.py),
        # so per-row valid counts capture the mask exactly. (The reference's
        # live path drops the mask entirely, SURVEY §2.7; its dead custom_*
        # files sketch the same per-branch masking implemented here.)
        # A concrete (numpy) mask with one shared count — the slide encoder's
        # internal alignment padding — stays a static int, keeping Pallas.
        valid_len = None
        if key_padding_mask is not None:
            if isinstance(key_padding_mask, np.ndarray):
                counts = (~key_padding_mask).sum(axis=-1)
                assert (counts == counts[0]).all(), (
                    "concrete ragged masks unsupported; pass a traced mask"
                )
                valid_len = int(counts[0])
            else:
                valid_len = (~key_padding_mask).sum(axis=-1).astype(jnp.int32)
        rng = None
        if self.dropout > 0.0 and not deterministic:
            rng = self.make_rng("dropout")
        out = dilated_attention(
            q,
            k,
            v,
            tuple(self.segment_length),
            tuple(self.dilated_ratio),
            is_causal=is_causal,
            offset=offset,
            attn_fn=self.attn_fn,
            seq_axis_name=self.seq_axis_name if self.seq_parallel else None,
            seq_axis_size=self.seq_axis_size if self.seq_parallel else 1,
            dropout_rate=0.0 if deterministic else self.dropout,
            dropout_rng=rng,
            valid_len=valid_len,
        )
        return out.reshape(out.shape[0], out.shape[1], self.embed_dim)
