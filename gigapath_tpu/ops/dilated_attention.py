"""Dilated attention (LongNet) — the long-context core of the slide encoder.

TPU-native counterpart of reference
``torchscale/component/dilated_attention.py``. Behavior parity:

- For each branch ``(segment_length sl, dilation r)`` the sequence is chopped
  into segments of ``min(sl, L)``; within a segment, heads are partitioned
  into ``r`` phase groups and head group ``p`` attends only positions
  ``p, p+r, ...`` (the reference implements this as a head-rotating
  einops-diagonal trick, ``dense_to_sparse:16-31``; here it is a scatter-free
  one-hot einsum select — TPU gathers/scatters over the token axis are slow,
  a phase-mask contraction is a cheap VPU multiply-add).
- Attention runs per sparse segment through an op returning ``(out, lse)``.
- Branch outputs are scattered back to dense positions (uncovered positions
  get ``lse = NEG_INF``) and fused by softmax-weighting of the LSEs across
  branches (``scattering:100-131``); like the reference, the fusion weights
  are treated as constants in the backward pass (stop_gradient vs the
  reference's ``torch.no_grad``).
- Sequence parallelism: when a branch's segment spans more than the local
  sequence shard, K/V are all-gathered along the mesh ``seq`` axis and sliced
  to the ranks forming the current segment (``gather_kv:55-74``), queries
  staying local. The reference ships this dormant (never enabled); here it is
  a first-class code path driven by ``seq_axis_name`` inside ``shard_map``
  and covered by multi-device tests.

Everything is static-shape: the branch loop is a Python loop over a static
tuple, so ``jit`` unrolls it (5 branches in the flagship configs).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from gigapath_tpu.ops.attention import NEG_INF, MultiheadAttention, attention_with_lse

AttnFn = Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]


def _kv_valid_lengths(
    batch: int, n_seg: int, seg_len: int, ratio: int, m: int, num_heads: int, real_len: int
) -> Optional[np.ndarray]:
    """Static per-(batch*segment, head) count of sparse key slots that fall
    inside the real sequence (zero-padding from segmenting/dilation is
    excluded).

    The reference lets zero-pad keys participate in the softmax
    (``dense_to_sparse`` pads with zeros and flash attention sees them as
    logit-0 keys); masking them instead is strictly better math at segment
    tails. Returns ``[batch*n_seg, H]`` int or None when everything is
    valid. All inputs are trace-time constants, so this is free under jit.
    """
    heads_per_group = -(-num_heads // ratio)
    phases = np.arange(num_heads) // heads_per_group  # [H]
    seg = np.arange(n_seg)[:, None]
    # valid j satisfy seg*g + phase + ratio*j < real_len
    counts = np.ceil((real_len - seg * seg_len - phases[None, :]) / ratio)
    counts = np.clip(counts, 0, m).astype(np.int32)  # [n_seg, H]
    if (counts == m).all():
        return None
    return np.tile(counts, (batch, 1))  # [batch*n_seg, H]


def _pad_to_multiple(x: jnp.ndarray, mult: int, axis: int) -> jnp.ndarray:
    rem = x.shape[axis] % mult
    if rem == 0:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, mult - rem)
    return jnp.pad(x, pads)


def _head_phases(num_heads: int, ratio: int) -> jnp.ndarray:
    """Phase (position offset mod ratio) assigned to each head.

    Matches the reference's head-rotated diagonal: heads are split into
    ``ratio`` groups of ``ceil(H/ratio)`` and group ``p`` samples positions
    congruent to ``p`` (``dense_to_sparse:24-26``).
    """
    heads_per_group = -(-num_heads // ratio)
    return jnp.arange(num_heads) // heads_per_group


def _phase_onehot(num_heads: int, ratio: int, dtype) -> jnp.ndarray:
    """[ratio, H] one-hot: entry (p, h) = 1 iff head h has phase p."""
    phases = _head_phases(num_heads, ratio)
    return (phases[None, :] == jnp.arange(ratio)[:, None]).astype(dtype)


def dense_to_sparse(x: jnp.ndarray, ratio: int) -> jnp.ndarray:
    """Dilated subsample of segments: [b, g, H, D] -> [b, m, H, D], m=ceil(g/r).

    Head ``h`` keeps positions ``phase(h) + r*j``. Implemented as a one-hot
    einsum select (a VPU multiply-add) rather than a gather — TPU scatters /
    gathers over the token axis are far slower than this contraction.
    """
    if ratio == 1:
        return x
    b, g, H, Dh = x.shape
    x = _pad_to_multiple(x, ratio, axis=1)
    m = x.shape[1] // ratio
    x5 = x.reshape(b, m, ratio, H, Dh)
    onehot = _phase_onehot(H, ratio, x.dtype)  # [r, H]
    return jnp.einsum("bmrhd,rh->bmhd", x5, onehot)


def sparse_to_dense(
    out_s: jnp.ndarray, lse_s: jnp.ndarray, ratio: int, seg_len: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter sparse branch results back to dense segment positions.

    ``out_s`` [b, m, H, D], ``lse_s`` [b, H, m] -> (out [b, g, H, D],
    lse [b, H, g]) with uncovered positions zero / NEG_INF, so they get zero
    weight in the cross-branch softmax fusion. Scatter-free: the inverse
    one-hot broadcast of :func:`dense_to_sparse`.
    """
    b, m, H, Dh = out_s.shape
    if ratio == 1:
        return out_s[:, :seg_len], lse_s[..., :seg_len]
    onehot = _phase_onehot(H, ratio, out_s.dtype)  # [r, H]
    out_d = jnp.einsum("bmhd,rh->bmrhd", out_s, onehot).reshape(b, m * ratio, H, Dh)
    oh_t = _phase_onehot(H, ratio, lse_s.dtype).T  # [H, r]
    lse_d = lse_s[:, :, :, None] * oh_t[None, :, None, :] + NEG_INF * (1.0 - oh_t[None, :, None, :])
    lse_d = lse_d.reshape(b, H, m * ratio)
    return out_d[:, :seg_len], lse_d[..., :seg_len]


def _gather_kv_seq_parallel(
    x: jnp.ndarray, sl: int, local_len: int, axis_name: str
) -> jnp.ndarray:
    """All-gather sparse K/V along the seq axis, keep the ranks of my segment.

    ``x`` [b, m, H, D] is the local (single-segment) sparse view; returns
    [b, m * ranks_per_segment, H, D]. Counterpart of reference
    ``gather_kv:55-74`` (non-causal path), with the autograd all-gather /
    reduce-scatter pair replaced by ``jax.lax.all_gather`` which is
    differentiable by construction.
    """
    assert sl % local_len == 0, (sl, local_len)
    ranks_per_segment = sl // local_len
    gathered = jax.lax.all_gather(x, axis_name, axis=0, tiled=False)  # [W, b, m, H, D]
    rank = jax.lax.axis_index(axis_name)
    segment_start = rank // ranks_per_segment * ranks_per_segment
    segment = jax.lax.dynamic_slice_in_dim(gathered, segment_start, ranks_per_segment, axis=0)
    # [rps, b, m, H, D] -> [b, rps*m, H, D]
    segment = segment.transpose(1, 0, 2, 3, 4)
    b = segment.shape[0]
    return segment.reshape(b, ranks_per_segment * segment.shape[2], *segment.shape[3:])


def dilated_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_lengths: Sequence[int],
    dilated_ratios: Sequence[int],
    *,
    is_causal: bool = False,
    offset: int = 0,
    attn_fn: Optional[AttnFn] = None,
    seq_axis_name: Optional[str] = None,
    seq_axis_size: int = 1,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    valid_len: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Multi-branch dilated attention on [B, L, H, D] tensors -> [B, L, H, D].

    ``attn_fn(q, k, v, is_causal=...) -> (out, lse)`` defaults to the fused
    jnp op; pass the Pallas flash kernel for long dense segments. When
    ``seq_axis_name`` is set (inside ``shard_map``), L is the *local* shard
    length and branches whose segment exceeds it gather K/V across the axis.
    ``dropout_rate`` is attention-probability dropout inside each branch
    (parity with the reference forwarding dropout to flash-attn).

    ``valid_len``: optional suffix-padding spec — tokens at positions
    ``>= valid_len`` are excluded from every branch's keys (the
    masked-batching extension the reference only sketches in its dead
    ``custom_*`` files). A static Python int (same for every row) folds into
    the existing trace-time tail masks and keeps the Pallas path; a traced
    [B] array (ragged batches) forces the jnp attention path (dynamic counts
    can't bake into the Pallas grid).
    """
    attn_fn_was_default = attn_fn is None
    if attn_fn_was_default:
        from gigapath_tpu.ops.flash_attention import flash_attention

        attn_fn = flash_attention
    if dropout_rate > 0.0 and dropout_rng is not None:
        # attention-probability dropout requires materialized probs; the
        # default dispatcher is swapped for the jnp path (all gigapath
        # configs train with attention_dropout=0, so the flash kernel stays
        # on the hot path). An explicitly-supplied attn_fn is never silently
        # replaced.
        if not attn_fn_was_default:
            raise NotImplementedError(
                "attention dropout is not supported with a custom attn_fn"
            )
        base_fn = attention_with_lse
        rngs = jax.random.split(dropout_rng, len(segment_lengths))

        def make_attn_fn(branch_rng):
            return lambda *a, **kw: base_fn(
                *a, dropout_rate=dropout_rate, dropout_rng=branch_rng, **kw
            )
    assert len(segment_lengths) == len(dilated_ratios)
    if offset > 0 and q.shape[1] != k.shape[1]:
        # queries and keys are segmented independently, so Lq != Lk with a
        # nonzero offset produces mismatched segment counts inside attn_fn
        raise NotImplementedError(
            "incremental decoding (offset > 0) requires Lq == Lk; pad q/k to "
            "a common length (the encoder path uses offset=0)"
        )
    B, L, H, Dh = q.shape

    outs, lses = [], []
    for i, (sl, r) in enumerate(zip(segment_lengths, dilated_ratios)):
        branch_fn = attn_fn
        if dropout_rate > 0.0 and dropout_rng is not None:
            branch_fn = make_attn_fn(rngs[i])
        o, l = _dilated_branch(
            q, k, v, int(sl), int(r),
            is_causal=is_causal, offset=offset, attn_fn=branch_fn,
            seq_axis_name=seq_axis_name, seq_axis_size=seq_axis_size,
            valid_len=valid_len,
        )
        outs.append(o)
        lses.append(l)

    if len(outs) == 1:
        return outs[0]

    # LSE-weighted fusion across branches; weights are constants in backward
    # (parity with reference scattering:119-128 under torch.no_grad).
    lse = jnp.stack(lses)  # [n, B, H, L]
    weights = jax.nn.softmax(jax.lax.stop_gradient(lse), axis=0)
    out = sum(
        o.astype(jnp.float32) * w[..., None].transpose(0, 2, 1, 3)  # [B,H,L,1]->[B,L,H,1]
        for o, w in zip(outs, weights)
    )
    return out.astype(q.dtype)


def _dilated_branch(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    sl: int,
    r: int,
    *,
    is_causal: bool,
    offset: int,
    attn_fn: AttnFn,
    seq_axis_name: Optional[str],
    seq_axis_size: int,
    valid_len: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One (segment_length, ratio) branch -> (out [B,L,H,D], lse [B,H,L])."""
    B, L, H, Dh = q.shape

    if offset > 0:  # incremental decoding: align the query into its segment
        q = jnp.pad(q, ((0, 0), (offset % sl, 0), (0, 0), (0, 0)))
    Lq = q.shape[1]

    gather_kv = (
        seq_axis_name is not None and seq_axis_size > 1 and sl > k.shape[1]
    )
    if gather_kv and is_causal:
        raise NotImplementedError(
            "causal sequence-parallel dilated attention is not supported yet "
            "(the encoder path is non-causal; reference ships this dormant)"
        )

    g_q = min(sl, Lq)
    qp = _pad_to_multiple(q, g_q, axis=1)
    n_seg = qp.shape[1] // g_q
    qs = qp.reshape(B * n_seg, g_q, H, Dh)
    qs = dense_to_sparse(qs, r)

    g_k = min(sl, k.shape[1])
    kp = _pad_to_multiple(k, g_k, axis=1).reshape(-1, g_k, H, Dh)
    vp = _pad_to_multiple(v, g_k, axis=1).reshape(-1, g_k, H, Dh)
    ks = dense_to_sparse(kp, r)
    vs = dense_to_sparse(vp, r)

    kv_valid_len = None
    if gather_kv:
        if valid_len is not None:
            raise NotImplementedError(
                "dynamic padding masks + sequence parallelism are not "
                "supported together yet"
            )
        ks = _gather_kv_seq_parallel(ks, sl, k.shape[1], seq_axis_name)
        vs = _gather_kv_seq_parallel(vs, sl, k.shape[1], seq_axis_name)
    else:
        static_len = k.shape[1]
        if isinstance(valid_len, int):
            static_len = min(valid_len, static_len)
            valid_len = None  # folded into the static tail masks below
        kv_valid_len = _kv_valid_lengths(
            B, kp.shape[0] // B, g_k, r, ks.shape[1], H, static_len
        )
        if valid_len is not None:
            # dynamic per-batch suffix padding: same segment/dilation count
            # formula as _kv_valid_lengths, with the traced valid length in
            # place of the static real length; combined by min
            n_seg_k = kp.shape[0] // B
            m = ks.shape[1]
            heads_per_group = -(-H // r)
            phases = jnp.arange(H) // heads_per_group  # [H]
            seg = jnp.arange(n_seg_k)  # [n_seg]
            counts = jnp.ceil(
                (
                    valid_len[:, None, None]
                    - seg[None, :, None] * g_k
                    - phases[None, None, :]
                )
                / r
            )
            counts = jnp.clip(counts, 0, m).astype(jnp.int32).reshape(B * n_seg_k, H)
            kv_valid_len = (
                counts
                if kv_valid_len is None
                else jnp.minimum(counts, jnp.asarray(kv_valid_len, jnp.int32))
            )

    out_s, lse_s = attn_fn(qs, ks, vs, is_causal=is_causal, kv_valid_len=kv_valid_len)

    out_d, lse_d = sparse_to_dense(out_s, lse_s, r, g_q)
    out = out_d.reshape(B, n_seg * g_q, H, Dh)
    lse = lse_d.reshape(B, n_seg, H, g_q).transpose(0, 2, 1, 3).reshape(B, H, -1)
    start = offset % sl if offset > 0 else 0
    return out[:, start : start + L], lse[..., start : start + L]


class DilatedAttention(MultiheadAttention):
    """LongNet attention module: MHA projections around dilated attention.

    Parity with reference ``DilatedAttention(MultiheadAttention)``
    (``dilated_attention.py:14``): same q/k/v/out projections, sub-LN, and
    branch schedule from the config. ``seq_axis_name`` activates sequence
    parallelism when the module runs inside ``shard_map``.
    """

    segment_length: Sequence[int] = ()
    dilated_ratio: Sequence[int] = ()
    seq_parallel: bool = False
    seq_axis_name: Optional[str] = None
    seq_axis_size: int = 1
    attn_fn: Optional[AttnFn] = None

    def _attend(
        self,
        q,
        k,
        v,
        *,
        key_padding_mask=None,
        attn_mask=None,
        rel_pos=None,
        is_causal: bool = False,
        deterministic: bool = True,
    ):
        assert rel_pos is None, "dilated attention does not support rel_pos bias"
        assert attn_mask is None, "dilated attention does not support attn_mask"
        # key_padding_mask (True = pad) is consumed as a *suffix* valid
        # length: batches are collated with trailing padding (data/collate.py),
        # so per-row valid counts capture the mask exactly. (The reference's
        # live path drops the mask entirely, SURVEY §2.7; its dead custom_*
        # files sketch the same per-branch masking implemented here.)
        # A concrete (numpy) mask with one shared count — the slide encoder's
        # internal alignment padding — stays a static int, keeping Pallas.
        valid_len = None
        if key_padding_mask is not None:
            if isinstance(key_padding_mask, np.ndarray):
                counts = (~key_padding_mask).sum(axis=-1)
                assert (counts == counts[0]).all(), (
                    "concrete ragged masks unsupported; pass a traced mask"
                )
                valid_len = int(counts[0])
            else:
                valid_len = (~key_padding_mask).sum(axis=-1).astype(jnp.int32)
        rng = None
        if self.dropout > 0.0 and not deterministic:
            rng = self.make_rng("dropout")
        out = dilated_attention(
            q,
            k,
            v,
            tuple(self.segment_length),
            tuple(self.dilated_ratio),
            is_causal=is_causal,
            attn_fn=self.attn_fn,
            seq_axis_name=self.seq_axis_name if self.seq_parallel else None,
            seq_axis_size=self.seq_axis_size if self.seq_parallel else 1,
            dropout_rate=0.0 if deterministic else self.dropout,
            dropout_rng=rng,
            valid_len=valid_len,
        )
        return out.reshape(out.shape[0], out.shape[1], self.embed_dim)
