"""GShard-style MoE gating (top-1 / top-2) as pure, static-shape jnp.

Parity with reference ``torchscale/component/xmoe/routing.py``: softmax gates,
capacity = ``cf * ceil(S/E)`` (top-1) or ``2 * ceil(S/E)`` (top-2) with the
eval-mode token-fraction override (``routing.py:58-62,278-282``), location
assignment by cumsum-minus-one over the token axis, the balance loss
``l_aux = mean(me * ce) * E^2`` (``routing.py:94-99,345-349``), the xmoe
cosine router (16-dim reduction + L2-normalized expert embeddings,
``routing.py:187-193,220-225``), and the gating telemetry (entropy, unused
experts, balance top/bottom fractions, ``routing.py:53,72-87``).

TPU-first notes: capacity is a Python int derived from static shapes, so the
dispatch/combine tensors have static ``[S, E, C]`` shapes under ``jit``; the
scatter-based ``one_hot`` becomes ``jax.nn.one_hot`` (einsum-friendly); the
custom Gumbel sampler is ``jax.random.gumbel``; there is no fused-cumsum
special case — XLA fuses ``cumsum`` fine. The torch in-place renorm of the
xmoe expert embeddings (``routing.py:190-191``) is redundant with the
cosine's own normalization and becomes a plain normalized matmul here.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

# fixed constants, parity with reference routing.py:25-33
EVAL_CAPACITY_TOKEN_FRACTION = 0.25
SAMPLE_FRACTION = 0.2

GatingResult = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]


def _entropy(probs: jnp.ndarray) -> jnp.ndarray:
    logp = jnp.log(jnp.clip(probs, 1e-9))
    return -(probs * logp).sum(-1)


def _balance_metadata(
    indices_s: jnp.ndarray, num_experts: int, num_tokens: int, prefix: str
) -> Dict[str, jnp.ndarray]:
    """Percent-of-tokens-per-expert histogram stats (routing.py:72-87)."""
    hist = 100.0 * jnp.bincount(indices_s, length=num_experts) / num_tokens
    sample_count = max(math.ceil(num_experts * SAMPLE_FRACTION), 1)
    hist_sorted = jnp.sort(hist)[::-1] + jnp.finfo(jnp.float32).tiny
    return {
        f"unused_{prefix}_count": (hist == 0).sum(),
        f"{prefix}_balance_top": hist_sorted[:sample_count].sum(),
        f"{prefix}_balance_bottom": hist_sorted[-sample_count:].sum(),
    }


def _capacity(
    num_tokens: int,
    num_experts: int,
    *,
    capacity_factor: float,
    eval_mode: bool,
    eval_capacity_token_fraction: float,
) -> int:
    if eval_capacity_token_fraction > 0.0 and eval_mode:
        return math.ceil(eval_capacity_token_fraction * num_tokens)
    return int(capacity_factor * math.ceil(num_tokens / num_experts))


def top1_gating(
    logits: jnp.ndarray,
    input_mask: Optional[jnp.ndarray] = None,
    *,
    use_fp32: bool = True,
    capacity_factor: float = 1.0,
    eval_mode: bool = False,
    eval_capacity_token_fraction: float = EVAL_CAPACITY_TOKEN_FRACTION,
) -> GatingResult:
    """Top-1 gating on ``logits [S, E]``.

    Returns ``(l_aux, combine_weights [S,E,C], dispatch_mask [S,E,C],
    metadata)``; semantics of reference ``top1gating`` (routing.py:36-137).
    """
    orig_dtype = logits.dtype
    if use_fp32:
        logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    num_tokens, num_experts = gates.shape
    capacity = _capacity(
        num_tokens,
        num_experts,
        capacity_factor=capacity_factor,
        eval_mode=eval_mode,
        eval_capacity_token_fraction=eval_capacity_token_fraction,
    )

    indices1_s = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(indices1_s, num_experts, dtype=gates.dtype)
    if input_mask is not None:
        mask1 = mask1 * (~input_mask)[:, None].astype(mask1.dtype)

    metadata = {"entropy_gating": _entropy(gates).mean()}
    metadata.update(_balance_metadata(indices1_s, num_experts, num_tokens, "expert1"))

    gates1_s = (gates * mask1).sum(axis=-1)
    locations1 = jnp.cumsum(mask1, axis=0) - 1

    # balance loss (fraction-routed x mean-gate, scaled E^2)
    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = (me * ce).mean() * num_experts * num_experts

    mask1 = mask1 * (locations1 < capacity)
    locations1_s = (locations1 * mask1).sum(axis=-1).astype(jnp.int32)

    gates1 = gates1_s[:, None] * mask1  # [S, E]
    locations1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=gates.dtype)
    combine_sec = jnp.einsum("se,sc->sec", gates1, locations1_sc)
    dispatch_mask = combine_sec > 0
    if use_fp32:
        combine_sec = combine_sec.astype(orig_dtype)
    return l_aux, combine_sec, dispatch_mask, metadata


def top2_gating(
    logits: jnp.ndarray,
    input_mask: Optional[jnp.ndarray] = None,
    *,
    rng: Optional[jax.Array] = None,
    use_fp32: bool = True,
    second_expert_policy: str = "sampling",
    normalize_gate_prob_before_dropping: bool = False,
    eval_mode: bool = False,
    eval_capacity_token_fraction: float = EVAL_CAPACITY_TOKEN_FRACTION,
    batch_prioritized_routing: bool = False,
) -> GatingResult:
    """Top-2 gating on ``logits [S, E]`` (reference ``top2gating``,
    routing.py:258-445).

    ``rng`` drives the stochastic second-expert policies (``sampling`` adds
    Gumbel noise to the second-expert argmax; ``random`` keeps the second
    expert with probability ``min(1, 2*gate2)``); with ``rng=None`` both
    policies fall back to their noise-free deterministic core — the
    functional-API equivalent of inference without sampling.
    """
    orig_dtype = logits.dtype
    if use_fp32:
        logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    num_tokens, num_experts = gates.shape
    if eval_capacity_token_fraction > 0.0 and eval_mode:
        capacity = math.ceil(eval_capacity_token_fraction * num_tokens)
    else:
        capacity = 2 * math.ceil(num_tokens / num_experts)

    indices1_s = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(indices1_s, num_experts, dtype=gates.dtype)

    if second_expert_policy == "sampling" and rng is not None:
        logits_w_noise = logits + jax.random.gumbel(rng, logits.shape, logits.dtype)
    else:
        logits_w_noise = logits
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits_w_noise)
    indices2_s = jnp.argmax(logits_except1, axis=-1)
    mask2 = jax.nn.one_hot(indices2_s, num_experts, dtype=gates.dtype)

    gates1_s = (gates * mask1).sum(axis=-1)
    gates2_s = (gates * mask2).sum(axis=-1)

    if normalize_gate_prob_before_dropping:
        denom_s = jnp.clip(gates1_s + gates2_s, jnp.finfo(gates.dtype).eps)
        gates1_s = gates1_s / denom_s
        gates2_s = gates2_s / denom_s

    if second_expert_policy == "random" and rng is not None:
        sampled = (2 * gates2_s) > jax.random.uniform(rng, gates2_s.shape, gates2_s.dtype)
        mask2 = mask2 * sampled[:, None].astype(mask2.dtype)

    if input_mask is not None:
        nonpad = (~input_mask)[:, None].astype(mask1.dtype)
        mask1 = mask1 * nonpad
        mask2 = mask2 * nonpad

    if batch_prioritized_routing:
        # sort tokens by gate confidence; assign capacity in that order
        # (routing.py:318-338) — argsort/inverse-argsort, all static shapes
        importance = -gates.max(axis=-1)
        order = jnp.argsort(importance, axis=0)
        inverse = jnp.argsort(order, axis=0)
        sorted_mask1 = mask1[order]
        locations1 = ((jnp.cumsum(sorted_mask1, axis=0) - 1) * sorted_mask1)[inverse]
        sorted_mask2 = mask2[order]
        locations2 = ((jnp.cumsum(sorted_mask2, axis=0) - 1) * sorted_mask2)[inverse]
        locations2 = locations2 + mask1.sum(axis=0, keepdims=True)
    else:
        locations1 = jnp.cumsum(mask1, axis=0) - 1
        locations2 = jnp.cumsum(mask2, axis=0) - 1
        locations2 = locations2 + mask1.sum(axis=0, keepdims=True)

    me = gates.mean(axis=0)
    ce = mask1.mean(axis=0)
    l_aux = (me * ce).mean() * num_experts * num_experts

    metadata = {
        "entropy_gating": _entropy(gates).mean(),
        "overflow_expert1": 100.0
        * (mask1 * (locations1 >= capacity)).sum()
        / jnp.clip(mask1.sum(), 1.0),
        "overflow_expert2": 100.0
        * (mask2 * (locations2 >= capacity)).sum()
        / jnp.clip(mask2.sum(), 1.0),
    }
    metadata.update(_balance_metadata(indices1_s, num_experts, num_tokens, "expert1"))
    metadata.update(_balance_metadata(indices2_s, num_experts, num_tokens, "expert2"))

    mask1 = mask1 * (locations1 < capacity)
    mask2 = mask2 * (locations2 < capacity)
    locations1_s = (locations1 * mask1).sum(axis=-1).astype(jnp.int32)
    locations2_s = (locations2 * mask2).sum(axis=-1).astype(jnp.int32)

    if not normalize_gate_prob_before_dropping:
        gates1_s = (gates * mask1).sum(axis=-1)
        gates2_s = (gates * mask2).sum(axis=-1)
        denom_s = jnp.clip(gates1_s + gates2_s, jnp.finfo(gates.dtype).eps)
        gates1_s = gates1_s / denom_s
        gates2_s = gates2_s / denom_s

    gates1 = gates1_s[:, None] * mask1
    gates2 = gates2_s[:, None] * mask2
    locations1_sc = jax.nn.one_hot(locations1_s, capacity, dtype=gates.dtype)
    locations2_sc = jax.nn.one_hot(locations2_s, capacity, dtype=gates.dtype)
    combine_sec = jnp.einsum("se,sc->sec", gates1, locations1_sc) + jnp.einsum(
        "se,sc->sec", gates2, locations2_sc
    )
    dispatch_mask = combine_sec > 0
    if use_fp32:
        combine_sec = combine_sec.astype(orig_dtype)
    return l_aux, combine_sec, dispatch_mask, metadata


class _GateBase(nn.Module):
    """Shared router projection: plain linear or xmoe cosine router."""

    model_dim: int = 768
    num_experts: int = 8
    use_xmoe: bool = False
    dtype: Any = None

    def _logits(self, x: jnp.ndarray) -> jnp.ndarray:
        if not self.use_xmoe:
            return nn.Dense(
                self.num_experts, use_bias=False, dtype=self.dtype, name="wg"
            )(x)
        # xmoe cosine router: reduce to 16-d, cosine vs orthogonal-init
        # expert embeddings (routing.py:175-178,220-225)
        reduced = nn.Dense(16, use_bias=False, dtype=self.dtype, name="wg_reduction")(x)
        wg = self.param(
            "wg", nn.initializers.orthogonal(scale=0.32), (self.num_experts, 16)
        )
        wg = wg / jnp.clip(jnp.linalg.norm(wg, axis=-1, keepdims=True), 1e-4)
        logits = reduced.astype(jnp.float32) @ wg.astype(jnp.float32).T
        logits = jnp.where(jnp.isfinite(logits), logits, jnp.finfo(jnp.float32).min)
        return logits.astype(reduced.dtype)


class Top1Gate(_GateBase):
    """Flax Top-1 gate (reference ``Top1Gate``, routing.py:140-225)."""

    use_fp32: bool = True
    capacity_factor: float = 1.0
    eval_capacity_token_fraction: float = EVAL_CAPACITY_TOKEN_FRACTION

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, mask: Optional[jnp.ndarray] = None, *, eval_mode: bool = True
    ) -> GatingResult:
        return top1_gating(
            self._logits(x),
            mask,
            use_fp32=self.use_fp32,
            capacity_factor=self.capacity_factor,
            eval_mode=eval_mode,
            eval_capacity_token_fraction=self.eval_capacity_token_fraction,
        )


class Top2Gate(_GateBase):
    """Flax Top-2 gate (reference ``Top2Gate``, routing.py:448-525)."""

    use_fp32: bool = True
    second_expert_policy: str = "sampling"
    normalize_gate_prob_before_dropping: bool = False
    eval_capacity_token_fraction: float = EVAL_CAPACITY_TOKEN_FRACTION
    batch_prioritized_routing: bool = False

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        mask: Optional[jnp.ndarray] = None,
        *,
        rng: Optional[jax.Array] = None,
        eval_mode: bool = True,
    ) -> GatingResult:
        return top2_gating(
            self._logits(x),
            mask,
            rng=rng,
            use_fp32=self.use_fp32,
            second_expert_policy=self.second_expert_policy,
            normalize_gate_prob_before_dropping=self.normalize_gate_prob_before_dropping,
            eval_mode=eval_mode,
            eval_capacity_token_fraction=self.eval_capacity_token_fraction,
            batch_prioritized_routing=self.batch_prioritized_routing,
        )
