"""GShard MoE layer: gate -> dispatch einsum -> vmapped experts -> combine.

Parity with reference ``torchscale/component/xmoe/moe_layer.py``: the same
Algorithm-2 einsum choreography (``sec,sm->ecm`` dispatch, ``sec,ecm->sm``
combine, ``moe_layer.py:229-262``) and the same (output, l_aux) contract
(``moe_layer.py:271``). The distributed pieces map to TPU idioms:

- per-rank expert construction with per-rank seeds
  (``feedforward_network.py:43-91``) -> one vmapped parameter axis of size E
  with split init RNGs (each expert gets distinct init, all experts live in
  one array tree, shardable over the mesh ``expert`` axis);
- ``_AllToAll`` autograd function + NCCL all2all groups
  (``moe_layer.py:48-63``, ``global_groups.py``) -> GSPMD: a sharding
  constraint on the ``[E, C, M]`` dispatch tensor makes XLA insert the
  all-to-all over ICI, differentiable by construction. The explicit
  shard_map choreography lives in
  :mod:`gigapath_tpu.ops.moe.expert_parallel` for when manual control or
  per-shard gating is wanted;
- a2a CUDA-event timing (``moe_layer.py:276-307``) -> ``jax.profiler`` traces
  cover collectives natively; gating telemetry is sowed under
  ``intermediates/moe_metadata``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from gigapath_tpu.ops.feedforward import FeedForwardNetwork
from gigapath_tpu.ops.moe.routing import Top1Gate, Top2Gate


def _maybe_expert_constraint(x: jnp.ndarray, axis: str = "expert") -> jnp.ndarray:
    """Constrain the leading (expert) dim over the mesh ``expert`` axis when a
    physical mesh with that axis is active; no-op otherwise."""
    try:
        from jax.sharding import PartitionSpec as P

        mesh = jax.sharding.get_abstract_mesh()
        if (
            mesh is not None
            and not mesh.empty
            and axis in mesh.axis_names
            and mesh.shape[axis] > 1
        ):
            spec = P(axis, *([None] * (x.ndim - 1)))
            return jax.lax.with_sharding_constraint(x, spec)
    except Exception:  # pragma: no cover - constraint is best-effort
        pass
    return x


class MOELayer(nn.Module):
    """Mixture-of-experts block over ``[B, L, M]`` tokens.

    Returns ``(output [B, L, M], l_aux scalar)``. Gating metadata is sowed to
    ``intermediates`` as ``moe_metadata`` (collect with
    ``model.apply(..., mutable=["intermediates"])``).
    """

    embed_dim: int
    ffn_dim: int
    num_experts: int
    top1: bool = False
    activation_fn: str = "gelu"
    dropout: float = 0.0
    activation_dropout: float = 0.0
    layernorm_eps: float = 1e-5
    subln: bool = False
    gating_use_fp32: bool = True
    eval_capacity_token_fraction: float = 0.25
    second_expert_policy: str = "random"
    normalize_gate_prob_before_dropping: bool = False
    use_xmoe: bool = False
    capacity_factor: float = 1.0
    dtype: Any = None

    @classmethod
    def from_config(
        cls,
        args,
        *,
        prefix: Optional[str] = None,
        dtype=None,
        name: Optional[str] = None,
    ) -> "MOELayer":
        """Build from an Encoder/Decoder config (the EncoderLayer MoE hook).

        ``prefix`` ("encoder" / "decoder") selects which dim fields to read —
        required for EncoderDecoderConfig, which defines both; when omitted
        it is inferred from whichever single prefix the config carries."""
        if prefix is None:
            has_enc = hasattr(args, "encoder_embed_dim")
            has_dec = hasattr(args, "decoder_embed_dim")
            assert has_enc ^ has_dec, (
                "config defines both encoder_* and decoder_* dims; pass "
                "prefix='encoder' or 'decoder'"
            )
            prefix = "encoder" if has_enc else "decoder"
        embed = getattr(args, f"{prefix}_embed_dim")
        ffn = getattr(args, f"{prefix}_ffn_embed_dim")
        return cls(
            embed_dim=embed,
            ffn_dim=ffn,
            num_experts=args.moe_expert_count,
            top1=args.moe_top1_expert,
            activation_fn=args.activation_fn,
            dropout=args.dropout,
            activation_dropout=args.activation_dropout,
            layernorm_eps=args.layernorm_eps,
            subln=args.subln,
            gating_use_fp32=args.moe_gating_use_fp32,
            eval_capacity_token_fraction=args.moe_eval_capacity_token_fraction,
            second_expert_policy=args.moe_second_expert_policy,
            normalize_gate_prob_before_dropping=args.moe_normalize_gate_prob_before_dropping,
            use_xmoe=args.use_xmoe,
            dtype=dtype,
            name=name,
        )

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        input_padding_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        B, L, M = x.shape
        assert M == self.embed_dim, (M, self.embed_dim)
        tokens = x.reshape(B * L, M)
        pad = (
            input_padding_mask.reshape(B * L)
            if input_padding_mask is not None
            else None
        )

        if self.top1:
            gate = Top1Gate(
                model_dim=self.embed_dim,
                num_experts=self.num_experts,
                use_xmoe=self.use_xmoe,
                use_fp32=self.gating_use_fp32,
                capacity_factor=self.capacity_factor,
                eval_capacity_token_fraction=self.eval_capacity_token_fraction,
                dtype=self.dtype,
                name="gate",
            )
            l_aux, combine, dispatch, metadata = gate(
                tokens, pad, eval_mode=deterministic
            )
        else:
            gate = Top2Gate(
                model_dim=self.embed_dim,
                num_experts=self.num_experts,
                use_xmoe=self.use_xmoe,
                use_fp32=self.gating_use_fp32,
                second_expert_policy=self.second_expert_policy,
                normalize_gate_prob_before_dropping=self.normalize_gate_prob_before_dropping,
                eval_capacity_token_fraction=self.eval_capacity_token_fraction,
                dtype=self.dtype,
                name="gate",
            )
            needs_rng = not deterministic and self.second_expert_policy in (
                "sampling",
                "random",
            )
            rng = self.make_rng("dropout") if needs_rng else None
            l_aux, combine, dispatch, metadata = gate(
                tokens, pad, rng=rng, eval_mode=deterministic
            )
        self.sow("intermediates", "moe_metadata", metadata)

        # dispatch: [S,E,C] x [S,M] -> [E,C,M]; the expert axis is the mesh
        # collective boundary (GSPMD inserts the all-to-all here)
        dispatched = jnp.einsum(
            "sec,sm->ecm", dispatch.astype(tokens.dtype), tokens
        )
        dispatched = _maybe_expert_constraint(dispatched)

        experts = nn.vmap(
            FeedForwardNetwork,
            in_axes=(0, None),
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )(
            embed_dim=self.embed_dim,
            ffn_dim=self.ffn_dim,
            activation_fn=self.activation_fn,
            dropout=self.dropout,
            activation_dropout=self.activation_dropout,
            layernorm_eps=self.layernorm_eps,
            subln=self.subln,
            dtype=self.dtype,
            name="experts",
        )
        expert_output = experts(dispatched, deterministic)
        expert_output = _maybe_expert_constraint(expert_output)

        combined = jnp.einsum(
            "sec,ecm->sm", combine.astype(tokens.dtype), expert_output
        )
        return combined.reshape(B, L, M), l_aux.astype(jnp.float32)
