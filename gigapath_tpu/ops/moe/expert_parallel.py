"""Explicit expert-parallel MoE choreography (shard_map + all_to_all).

The reference's distributed MoE path: each rank gates its *local* tokens,
dispatches into an ``[E, C_local, M]`` buffer, exchanges it with
``dist.all_to_all_single`` so every rank ends up holding all shards' tokens
for its *local* experts, runs them, and all-to-alls back before the local
combine (``xmoe/moe_layer.py:229-262``; the ``_AllToAll`` autograd function
at ``moe_layer.py:48-63``; group construction at ``global_groups.py:36-61``).

TPU-native version: the same choreography inside one ``shard_map`` region
over the mesh ``expert`` axis, with ``jax.lax.all_to_all`` — which is
differentiable by construction, so both custom autograd functions of the
reference disappear. ``tiled=True`` splits the expert dim and concatenates
along capacity, exactly the ``ecm -> gecm`` reshape dance of
``moe_layer.py:236-251``.

Prefer the GSPMD path in :class:`~gigapath_tpu.ops.moe.moe_layer.MOELayer`
(annotation-only) for training; this module is the manual-control variant
and doubles as the executable spec of the collective pattern.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def moe_shard_fn(
    gate_fn: Callable,
    expert_fn: Callable,
    axis_name: str = "expert",
) -> Callable:
    """Per-shard MoE body for use inside ``shard_map``.

    ``gate_fn(tokens [S_loc, M]) -> (l_aux, combine, dispatch, metadata)``;
    ``expert_fn(local_expert_params, dispatched [E_loc, D*C_loc, M]) ->
    same shape``. The returned function maps
    ``(local_expert_params, tokens [S_loc, M]) -> ([S_loc, M], l_aux)``.
    """

    def fn(local_expert_params, tokens: jnp.ndarray):
        l_aux, combine, dispatch, _ = gate_fn(tokens)
        # local dispatch: [S_loc, E, C_loc] x [S_loc, M] -> [E, C_loc, M]
        dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype), tokens)
        n_shards = jax.lax.psum(1, axis_name)
        if n_shards > 1:
            # exchange: every shard keeps its E/D local experts and receives
            # the other shards' capacity slots -> [E/D, D*C_loc, M]
            dispatched = jax.lax.all_to_all(
                dispatched, axis_name, split_axis=0, concat_axis=1, tiled=True
            )
        expert_output = expert_fn(local_expert_params, dispatched)
        if n_shards > 1:
            # inverse exchange back to [E, C_loc, M]
            expert_output = jax.lax.all_to_all(
                expert_output, axis_name, split_axis=1, concat_axis=0, tiled=True
            )
        combined = jnp.einsum(
            "sec,ecm->sm", combine.astype(tokens.dtype), expert_output
        )
        # average the balance loss across shards (each gated locally)
        l_aux = jax.lax.pmean(l_aux, axis_name)
        return combined, l_aux

    return fn


def moe_expert_parallel(
    mesh: Mesh,
    gate_fn: Callable,
    expert_fn: Callable,
    expert_params,
    tokens: jnp.ndarray,
    axis_name: str = "expert",
):
    """Run the expert-parallel MoE over ``tokens [S, M]`` sharded on
    ``axis_name``; ``expert_params`` leaves carry a leading E axis sharded the
    same way. Returns ``(output [S, M], l_aux)``."""
    body = moe_shard_fn(gate_fn, expert_fn, axis_name)
    param_specs = jax.tree.map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), expert_params
    )
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, P(axis_name, None)),
        out_specs=(P(axis_name, None), P()),
    )(expert_params, tokens)
