from gigapath_tpu.ops.moe.routing import (  # noqa: F401
    Top1Gate,
    Top2Gate,
    top1_gating,
    top2_gating,
)
from gigapath_tpu.ops.moe.moe_layer import MOELayer  # noqa: F401
