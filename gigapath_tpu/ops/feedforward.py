"""Feed-forward blocks: FFN (with optional sub-LN) and GLU.

Parity with reference ``torchscale/component/feedforward_network.py`` and
``gate_linear_unit.py``: fc1 -> activation (fp32) -> [sub-LN] -> fc2 with
activation- and output-dropout; GLU variant gates fc1 with a parallel linear
(all bias-free). Expert construction for MoE lives in
:mod:`gigapath_tpu.ops.moe` (per-expert init is a vmapped param axis there,
replacing the reference's per-rank seeded loop, ``feedforward_network.py:43-91``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from flax import linen as nn


def get_activation_fn(activation: str) -> Callable[[jnp.ndarray], jnp.ndarray]:
    if activation == "relu":
        return nn.relu
    if activation == "gelu":
        return nn.gelu
    if activation == "swish":
        return nn.silu
    raise NotImplementedError(f"unknown activation: {activation}")


class FeedForwardNetwork(nn.Module):
    embed_dim: int
    ffn_dim: int
    activation_fn: str = "gelu"
    dropout: float = 0.0
    activation_dropout: float = 0.0
    layernorm_eps: float = 1e-5
    subln: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        act = get_activation_fn(self.activation_fn)
        h = nn.Dense(
            self.ffn_dim,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name="fc1",
        )(x)
        h = act(h.astype(jnp.float32)).astype(h.dtype)
        h = nn.Dropout(self.activation_dropout)(h, deterministic=deterministic)
        if self.subln:
            h = nn.LayerNorm(epsilon=self.layernorm_eps, dtype=self.dtype, name="ffn_layernorm")(h)
        out = nn.Dense(
            self.embed_dim,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name="fc2",
        )(h)
        return nn.Dropout(self.dropout)(out, deterministic=deterministic)


class GLU(nn.Module):
    embed_dim: int
    ffn_dim: int
    activation_fn: str = "gelu"
    dropout: float = 0.0
    activation_dropout: float = 0.0
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        act = get_activation_fn(self.activation_fn)
        dense = lambda n: nn.Dense(  # noqa: E731
            self.ffn_dim,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name=n,
        )
        g = dense("gate")(x)
        h = dense("fc1")(x)
        h = act(h.astype(jnp.float32)).astype(h.dtype) * g
        h = nn.Dropout(self.activation_dropout)(h, deterministic=deterministic)
        out = nn.Dense(
            self.embed_dim,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name="fc2",
        )(h)
        return nn.Dropout(self.dropout)(out, deterministic=deterministic)
