"""2-D sin-cos positional embeddings, computed on the fly.

Reference parity: ``gigapath/pos_embed.py:30-77`` builds a full
``(grid_size^2 + 1, D)`` table with numpy and registers it as a buffer
(``gigapath/slide_encoder.py:104,124-125``). At the GigaPath default
``slide_ngrids=1000, embed_dim=768`` that table is ~3 GB of fp32 — almost all
of it never touched for a given slide.

TPU-first redesign: the embedding is a closed-form function of the grid
position, so we compute it *on the fly* from the (at most ~10^5) positions a
slide actually uses. That trades a trivial amount of VPU transcendental work
for 3 GB of HBM and the associated gather bandwidth. A numpy table builder is
kept for checkpoint-conversion parity tests.

Layout parity (important for loading reference checkpoints): the reference
table is built from ``np.meshgrid(grid_w, grid_h)`` ("w goes first",
``pos_embed.py:38``), so for table row ``p = i*G + j`` the *first* D/2 channels
encode ``j`` and the *second* D/2 encode ``i``. ``coords_to_pos``
(``slide_encoder.py:166-179``) maps ``coords=(c0, c1)`` to
``p = floor(c0/tile)*G + floor(c1/tile)``, i.e. ``c0 -> i``, ``c1 -> j``.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _sincos_1d_np(embed_dim: int, pos: np.ndarray) -> np.ndarray:
    assert embed_dim % 2 == 0
    omega = np.arange(embed_dim // 2, dtype=np.float64)
    omega /= embed_dim / 2.0
    omega = 1.0 / 10000**omega
    out = np.einsum("m,d->md", pos.reshape(-1).astype(np.float64), omega)
    return np.concatenate([np.sin(out), np.cos(out)], axis=1)


def get_1d_sincos_pos_embed_from_grid(embed_dim: int, pos: np.ndarray) -> np.ndarray:
    """Numpy 1-D sincos embedding (reference ``pos_embed.py:59-77``)."""
    return _sincos_1d_np(embed_dim, np.asarray(pos))


def get_2d_sincos_pos_embed_from_grid(embed_dim: int, grid: np.ndarray) -> np.ndarray:
    """Numpy 2-D sincos from a stacked meshgrid (reference ``pos_embed.py:48-56``)."""
    assert embed_dim % 2 == 0
    emb_h = _sincos_1d_np(embed_dim // 2, grid[0])
    emb_w = _sincos_1d_np(embed_dim // 2, grid[1])
    return np.concatenate([emb_h, emb_w], axis=1)


def get_2d_sincos_pos_embed(
    embed_dim: int, grid_size: int, cls_token: bool = False
) -> np.ndarray:
    """Full numpy table, `(G*G [+1], D)` — for converter/parity tests only.

    Matches reference ``pos_embed.py:30-45`` exactly (row-major over (h, w),
    w-coordinate encoded in the first half of channels).
    """
    grid_h = np.arange(grid_size, dtype=np.float32)
    grid_w = np.arange(grid_size, dtype=np.float32)
    grid = np.stack(np.meshgrid(grid_w, grid_h), axis=0)
    grid = grid.reshape([2, 1, grid_size, grid_size])
    pos_embed = get_2d_sincos_pos_embed_from_grid(embed_dim, grid)
    if cls_token:
        pos_embed = np.concatenate([np.zeros([1, embed_dim]), pos_embed], axis=0)
    return pos_embed


def _sincos_1d(embed_dim: int, pos: jnp.ndarray) -> jnp.ndarray:
    """JAX 1-D sincos: pos [...,] -> [..., embed_dim]. fp32 accumulation."""
    omega = jnp.arange(embed_dim // 2, dtype=jnp.float32) / (embed_dim / 2.0)
    omega = 1.0 / 10000**omega
    out = pos.astype(jnp.float32)[..., None] * omega
    return jnp.concatenate([jnp.sin(out), jnp.cos(out)], axis=-1)


def sincos_pos_embed_from_grid_pos(
    embed_dim: int, pos: jnp.ndarray, ngrids: int
) -> jnp.ndarray:
    """On-the-fly embedding for flat table indices ``pos`` (cls offset removed).

    ``pos`` is the flat row index ``i*ngrids + j``; this reproduces the exact
    table-row the reference would have gathered, including the wrap-around a
    flat index implies when ``j >= ngrids`` and torch's negative-index
    wrapping for negative positions (padded edge tiles can have negative
    coords). A wrapped index landing on the cls row (all zeros in the table)
    is reproduced as zeros.
    """
    table_rows = ngrids * ngrids + 1
    pos = pos.astype(jnp.int32) + 1  # back to full-table row index
    pos = jnp.where(pos < 0, pos + table_rows, pos)  # torch negative indexing
    is_cls_row = pos == 0
    grid_pos = pos - 1
    i = grid_pos // ngrids
    j = grid_pos % ngrids
    emb_j = _sincos_1d(embed_dim // 2, j)  # first half encodes the w/j coord
    emb_i = _sincos_1d(embed_dim // 2, i)
    emb = jnp.concatenate([emb_j, emb_i], axis=-1)
    return jnp.where(is_cls_row[..., None], 0.0, emb)


def coords_to_pos(coords: jnp.ndarray, tile_size: int, ngrids: int) -> jnp.ndarray:
    """Coordinates [..., 2] -> flat positional index [...] (+1 for cls).

    Parity with reference ``slide_encoder.py:166-179``.
    """
    c = jnp.floor(coords.astype(jnp.float32) / float(tile_size)).astype(jnp.int32)
    return c[..., 0] * ngrids + c[..., 1] + 1


def pos_embed_for_coords(
    embed_dim: int, coords: jnp.ndarray, tile_size: int, ngrids: int
) -> jnp.ndarray:
    """Positional embedding for tile coords [..., 2] -> [..., embed_dim].

    Equivalent to ``pos_embed[coords_to_pos(coords)]`` against the reference
    table, without materializing it. Index 0 (cls) is all-zeros in the table;
    callers handle the cls token separately.
    """
    pos = coords_to_pos(coords, tile_size, ngrids) - 1
    return sincos_pos_embed_from_grid_pos(embed_dim, pos, ngrids)


def interpolate_pos_embed_table(
    table: np.ndarray, new_grid_size: int, num_extra_tokens: int = 1
) -> np.ndarray:
    """Bicubic-resize a square sincos/learned table to a new grid size.

    Functional counterpart of reference ``pos_embed.py:85-105`` (which mutates
    a torch checkpoint dict in place). Uses torch's bicubic interpolation with
    ``align_corners=False`` when torch is available, which is bit-for-bit the
    reference behavior; falls back to a scipy spline zoom (approximate) in
    torch-free environments.
    """
    table = np.asarray(table)
    if table.ndim == 3:  # [1, N, D] -> [N, D]
        table = table[0]
    extra = table[:num_extra_tokens]
    grid = table[num_extra_tokens:]
    orig = int(round(len(grid) ** 0.5))
    if orig == new_grid_size:
        return table
    d = grid.shape[-1]
    grid = grid.reshape(orig, orig, d)
    try:
        import torch
        import torch.nn.functional as F

        t = torch.from_numpy(np.ascontiguousarray(grid)).permute(2, 0, 1)[None]
        t = F.interpolate(
            t, size=(new_grid_size, new_grid_size), mode="bicubic", align_corners=False
        )
        grid = t[0].permute(1, 2, 0).numpy()
    except ImportError:  # pragma: no cover - approximate fallback
        import scipy.ndimage

        zoom = (new_grid_size / orig, new_grid_size / orig, 1)
        grid = scipy.ndimage.zoom(grid, zoom, order=3)
    return np.concatenate([extra, grid.reshape(-1, d)], axis=0)
