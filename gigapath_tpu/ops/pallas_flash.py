"""Pallas TPU flash attention with LSE output (forward + backward).

The one hard kernel (SURVEY §7.3): everything in LongNet leans on a fused
attention that also emits the log-sum-exp, because dilated attention's
branch recombination needs it (reference ``dilated_attention.py:119-128``
consumes the LSE the flash-attn CUDA kernel returns). This is the TPU
replacement for that CUDA dependency:

- forward: online-softmax blocks over K/V, carrying (m, l, acc) in VMEM
  scratch across the innermost grid dimension; emits ``(out, lse)``;
- backward: two kernels — dQ (grid over Q blocks, loop K) and dK/dV (grid
  over K blocks, loop Q) — recomputing probabilities from the saved LSE
  rather than storing the attention matrix.

Performance notes (v5e measurements in scripts/profile_slide.py):
- kernels run on ``[B, H, L, D]`` layout with ``(1, 1, block_q, D)`` blocks —
  the only layout whose trailing block dims satisfy Mosaic's (8, 128)
  tiling rule for head counts > 1; the public API stays ``[B, L, H, D]``
  and the wrapper transposes (XLA folds the relayout into the surrounding
  projection reshapes);
- the softmax scale is folded into the small q block (``block_q x D``
  elements) instead of the ``block_q x block_k`` logits — the inner loop is
  VPU-bound, so per-logit ops are what matter;
- the online softmax runs in base-2 units (log2(e) folded into the q
  scale, ``exp2`` in the hot loop — one fewer VPU pass per logit than
  ``exp``); the emitted lse is converted back to natural log;
- masked slots rely on exp2 underflow instead of a second ``where``: the
  running max is floored at ``M_FLOOR`` so ``exp2(NEG_INF - m)`` is exactly
  0.0 in fp32, which also makes fully-masked rows produce out=0 and an lse
  sentinel of ~ -7e19 (ignored by the branch fusion) without extra
  per-element work;
- head_dim is NOT padded: a block whose last dim equals the full array dim
  satisfies TPU tiling, and padding 64 -> 128 lanes would waste 2x MXU
  work on the contractions;
- sequence length is zero-padded to the block size with padded *keys masked*
  in every kernel; ragged per-(batch,head) key counts (``kv_len``) are
  masked the same way from an SMEM table.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# Floor for the running softmax max: low enough to never clip real logits,
# high enough that exp(NEG_INF - M_FLOOR) == 0.0 exactly in fp32.
M_FLOOR = -1e20
LANES = 128
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453
# 1024x1024 blocks measured ~2.3x faster than 512x1024 on the LongNet branch
# shapes (v5e, head_dim 48): fewer K/V restreams per q row and fuller MXU
# rows; fp32 logits block = 4 MB, comfortably under the 16 MB VMEM budget.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

# Backward-pass logits budget, in block_q*block_k ELEMENTS. The backward
# kernels keep ~2.5 live fp32 (block_q, block_k) tiles on the scoped-vmem
# stack (s, p/dp, ds — measured: 20.12 MB scoped at 1408x1408, i.e. 2.54
# tiles), vs the forward's ~2. A block pair is bwd-safe when ~2.6 live
# tiles fit under the 16 MB limit with headroom: 2.6 * 4 B * budget
# <= 14 MB  =>  budget <= ~1.35M elements. 1024x1024 (1.05M, the default)
# passes; 1408x1408 (1.98M, round 3's single-block choice) does not — that
# exact overflow shipped a HEAD whose own benchmark crashed (BENCH_r03).
_BWD_LOGITS_BUDGET = 1_350_000


def bwd_blocks(fwd_block: int) -> Tuple[int, int]:
    """Backward block sizes (block_q, block_k) given the forward's block.

    Keeps block_q = the forward block (so the q/do/lse/delta arrays need no
    extra padding beyond the forward's), then shrinks block_k until the
    backward's live fp32 logits tiles fit the scoped-vmem budget — the two
    kernels take block_q/block_k independently, and nothing forces the
    backward to share the forward's block (the branch VJP re-dilates
    anyway)."""
    if fwd_block * fwd_block <= _BWD_LOGITS_BUDGET:
        return fwd_block, fwd_block
    # contract is total: even the thinnest k block must fit the budget
    assert fwd_block * LANES <= _BWD_LOGITS_BUDGET, fwd_block
    bk = _BWD_LOGITS_BUDGET // fwd_block // LANES * LANES
    return fwd_block, bk


from gigapath_tpu.ops.common import round_up  # noqa: E402  (re-export)

_round_up = round_up  # internal alias


def _fwd_kernel(q_ref, k_ref, v_ref, kvlen_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                *, scale, causal, block_q, block_k):
    b, h, sg = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    i, j = pl.program_id(3), pl.program_id(4)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, M_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _online_step(masked: bool):
        # scale (with log2(e) folded in: the hot loop runs exp2, one fewer
        # VPU pass per logit than exp) applied to q: block_q*D elements
        # instead of block_q*block_k
        q = (q_ref[0, 0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(q_ref.dtype)
        k = k_ref[0, 0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BQ, BK), in log2 units

        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            s = jnp.where(cols > rows, NEG_INF, s)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        if masked:
            # kv-length masking as a per-COLUMN select (the mask depends
            # only on the column, so it broadcasts from one [1, bk] row).
            # A select, not an additive bias: masked keys can be REAL
            # activations (alignment padding becomes nonzero after the
            # first residual layer) or — on the flat path — out-of-bounds
            # DMA garbage that may be non-finite, and NaN + NEG_INF stays
            # NaN where the select yields exactly NEG_INF. Masking must
            # precede the running max; M_FLOOR keeps m_new finite even for
            # fully-masked rows, so exp2(NEG_INF - m_new) underflows to 0.
            col_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
                < kvlen_ref[b, h, sg]
            )
            s = jnp.where(col_ok, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp2(s - m_new)
        v = v_ref[0, 0, 0]
        if masked:
            # masked key rows of V can be OOB garbage on the flat path
            # (non-finite bits); p is exactly 0 there but 0 * NaN = NaN in
            # the PV contraction, so V itself must be zeroed
            row_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (block_k, 1), 0) + j * block_k
                < kvlen_ref[b, h, sg]
            )
            v = jnp.where(row_ok, v, 0)
        if pl.num_programs(4) == 1:
            # single k block: no online carry — skip the acc rescale and
            # write the stats once (saves two [bq, 1] scratch stores and an
            # alpha pass on every single-segment branch)
            l_new = jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            alpha = jnp.exp2(m_prev - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        # single-lane stats stores (the broadcast-to-128-lane form wrote
        # 128x the bytes per step)
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    # full key blocks skip the col-bias pass entirely (one fewer VPU pass
    # over the [bq, bk] tile — the inner loop is VPU-bound); only the block
    # straddling the valid-key boundary pays for masking
    @pl.when((j + 1) * block_k <= kvlen_ref[b, h, sg])
    def _compute_full():
        _online_step(masked=False)

    @pl.when(
        (j * block_k < kvlen_ref[b, h, sg])
        & ((j + 1) * block_k > kvlen_ref[b, h, sg])
    )
    def _compute_partial():
        _online_step(masked=True)

    @pl.when(j == pl.num_programs(4) - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0, 0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # natural-log lse recovered from the base-2 running stats; carried
        # at LANES width (TPU tiling needs a 128-lane last dim); the
        # wrapper slices lane 0
        lse_ref[0, 0, 0] = jnp.broadcast_to(
            (m_ref[:, :1] + jnp.log2(safe_l)) * LN2, (block_q, LANES)
        )


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kvlen_ref, dq_ref, dq_acc,
               *, scale, causal, block_q, block_k, flat=False):
    b, h, sg = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    i, j = pl.program_id(3), pl.program_id(4)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(j * block_k < kvlen_ref[b, h, sg])
    def _compute():
        q = q_ref[0, 0, 0]
        k = k_ref[0, 0, 0]
        v = v_ref[0, 0, 0]
        col_ok = (
            jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
            < kvlen_ref[b, h, sg]
        )
        if flat:
            # flat mode reads the unpadded arrays: masked key rows can be
            # OOB garbage (possibly non-finite), and 0 * NaN = NaN inside
            # the contractions — zero K/V rows before any matmul touches
            # them (padded mode's masked rows are provably zero already)
            krow_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (block_k, 1), 0) + j * block_k
                < kvlen_ref[b, h, sg]
            )
            k = jnp.where(krow_ok, k, 0)
            v = jnp.where(krow_ok, v, 0)
        # log2-units recompute (exp2 is one fewer VPU pass than exp); the
        # natural-log lse is rescaled on its [bq, 1] column, not per logit
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (scale * LOG2E)
        # masking BEFORE the exp: a post-hoc zero-multiply would compute
        # exp of unbounded masked logits — inf * 0 = NaN in the gradients
        p = jnp.exp2(
            jnp.where(col_ok, s, NEG_INF) - lse_ref[0, 0, 0][:, :1] * LOG2E
        )
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            p = jnp.where(cols > rows, 0.0, p)

        dp = jax.lax.dot_general(
            do_ref[0, 0, 0].astype(jnp.float32), v.astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0, 0][:, :1])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == pl.num_programs(4) - 1)
    def _finalize():
        dq_ref[0, 0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kvlen_ref, dk_ref, dv_ref,
                dk_acc, dv_acc, *, scale, causal, block_q, block_k, flat=False):
    b, h, sg = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    j, i = pl.program_id(3), pl.program_id(4)  # grid: (B, H, S, nk, nq)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(j * block_k < kvlen_ref[b, h, sg])
    def _compute():
        q = q_ref[0, 0, 0]
        k = k_ref[0, 0, 0]
        do = do_ref[0, 0, 0].astype(jnp.float32)
        if flat:
            # flat self-attention: valid q rows == valid key rows per
            # segment; OOB q/do rows are garbage and would pollute the
            # dk/dv row-sums through the transposed contractions
            qrow_ok = (
                jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0) + i * block_q
                < kvlen_ref[b, h, sg]
            )
            q = jnp.where(qrow_ok, q, 0)
            do = jnp.where(qrow_ok, do, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (scale * LOG2E)  # (BQ, BK), log2 units (see _dq_kernel)
        col_ok = (
            jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
            < kvlen_ref[b, h, sg]
        )
        p = jnp.exp2(
            jnp.where(col_ok, s, NEG_INF) - lse_ref[0, 0, 0][:, :1] * LOG2E
        )  # (BQ, BK)
        if flat:
            # OOB q rows carry garbage lse — their p rows must be exact 0
            p = jnp.where(qrow_ok, p, 0.0)
        if causal:
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + j * block_k
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + i * block_q
            p = jnp.where(cols > rows, 0.0, p)

        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (BK, D)
        dp = jax.lax.dot_general(
            do, v_ref[0, 0, 0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        ds = p * (dp - delta_ref[0, 0, 0][:, :1])
        if flat:
            ds = jnp.where(qrow_ok, ds, 0.0)
        dk_acc[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (BK, D)

    @pl.when(i == pl.num_programs(4) - 1)
    def _finalize():
        dk_ref[0, 0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _kvlen_array(kv_lens, B: int, H: int, S: int, Lk: int) -> jnp.ndarray:
    """[B, H, S] int32 valid-key counts (None = all valid).

    Accepts a static tuple/np array OR a *traced* jnp array: the kernels
    read the counts from SMEM at runtime (``pl.when`` on SMEM scalars), so
    dynamic per-batch padding needs no retrace — only the shapes are
    static."""
    if kv_lens is None:
        return jnp.asarray(np.full((B, H, S), Lk, np.int32))
    if isinstance(kv_lens, (jax.Array, jax.core.Tracer)):
        return kv_lens.reshape(B, H, S).astype(jnp.int32)
    return jnp.asarray(np.asarray(kv_lens, np.int32).reshape(B, H, S))


def _pad_seg(x: jnp.ndarray, M: int) -> jnp.ndarray:
    """[B, H, S, M0, D] zero-padded to M on the per-segment axis."""
    if x.shape[3] == M:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, 0), (0, M - x.shape[3]), (0, 0)))


def _fwd_impl(q, k, v, kv_lens, causal, scale, block_q, block_k, interpret):
    """Segment-batched flash forward on [B, H, S, M, D] -> (out, lse [B,H,S,M]).

    Each of the S segments attends independently (block-diagonal attention);
    the segment axis is a grid dimension, so segmented layouts coming from
    dilated attention need no batch-axis reshuffling.
    """
    B, H, S, Mq, D = q.shape
    Mk = k.shape[3]
    block_q = min(block_q, _round_up(Mq, LANES))
    block_k = min(block_k, _round_up(Mk, LANES))
    Mqp, Mkp = _round_up(Mq, block_q), _round_up(Mk, block_k)
    qp, kp, vp = _pad_seg(q, Mqp), _pad_seg(k, Mkp), _pad_seg(v, Mkp)
    nq, nk = Mqp // block_q, Mkp // block_k
    kvlen = _kvlen_array(kv_lens, B, H, S, Mk)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    q_spec = pl.BlockSpec((1, 1, 1, block_q, D), lambda b, h, s, i, j: (b, h, s, i, 0), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, 1, 1, block_k, D), lambda b, h, s, i, j: (b, h, s, j, 0), memory_space=pltpu.VMEM)
    kvlen_spec = pl.BlockSpec(memory_space=pltpu.SMEM)  # whole (B,H,S) array; indexed by program_id
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, S, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, kvlen_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, 1, 1, block_q, LANES), lambda b, h, s, i, j: (b, h, s, i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, Mqp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, Mqp, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, kvlen)
    return out[:, :, :, :Mq], lse[:, :, :, :Mq, 0]


def _bwd_impl(q, k, v, lse, delta, do, kv_lens, causal, scale, block_q, block_k, interpret):
    B, H, S, Mq, D = q.shape
    Mk = k.shape[3]
    block_q = min(block_q, _round_up(Mq, LANES))
    block_k = min(block_k, _round_up(Mk, LANES))
    Mqp, Mkp = _round_up(Mq, block_q), _round_up(Mk, block_k)
    qp, kp, vp = _pad_seg(q, Mqp), _pad_seg(k, Mkp), _pad_seg(v, Mkp)
    dop = _pad_seg(do, Mqp)
    # lse/delta carried at LANES width for TPU tiling; padded q rows get
    # lse=0, which is harmless (their p rows multiply masked ds/do = 0)
    lsep = jnp.broadcast_to(
        jnp.pad(lse, ((0, 0), (0, 0), (0, 0), (0, Mqp - Mq)))[..., None],
        (B, H, S, Mqp, LANES),
    )
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, 0), (0, 0), (0, Mqp - Mq)))[..., None],
        (B, H, S, Mqp, LANES),
    )
    nq, nk = Mqp // block_q, Mkp // block_k
    kvlen = _kvlen_array(kv_lens, B, H, S, Mk)

    q_spec = pl.BlockSpec((1, 1, 1, block_q, D), lambda b, h, s, i, j: (b, h, s, i, 0), memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, 1, 1, block_k, D), lambda b, h, s, i, j: (b, h, s, j, 0), memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, 1, 1, block_q, LANES), lambda b, h, s, i, j: (b, h, s, i, 0), memory_space=pltpu.VMEM)
    kvlen_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, H, S, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, vec_spec, vec_spec, kvlen_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, S, Mqp, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, kvlen)[0]

    # grid (B, H, S, nk, nq): index maps see (b, h, s, j, i)
    q_spec_kv = pl.BlockSpec((1, 1, 1, block_q, D), lambda b, h, s, j, i: (b, h, s, i, 0), memory_space=pltpu.VMEM)
    k_spec_kv = pl.BlockSpec((1, 1, 1, block_k, D), lambda b, h, s, j, i: (b, h, s, j, 0), memory_space=pltpu.VMEM)
    vec_spec_kv = pl.BlockSpec((1, 1, 1, block_q, LANES), lambda b, h, s, j, i: (b, h, s, i, 0), memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(B, H, S, nk, nq),
        in_specs=[q_spec_kv, k_spec_kv, k_spec_kv, q_spec_kv, vec_spec_kv, vec_spec_kv, kvlen_spec],
        out_specs=[k_spec_kv, k_spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, Mkp, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, Mkp, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, kvlen)
    return (
        dq[:, :, :, :Mq],
        dk[:, :, :, :Mk],
        dv[:, :, :, :Mk],
    )


# ---------------------------------------------------------------------------
# flat (zero-pad) segment path
# ---------------------------------------------------------------------------


def _flat_specs(g, D):
    """Specs over flat [B, H, 1, L, D] views: segment s = row block s
    (block size g) on the L axis, exploiting Pallas auto-masking for the
    non-divisible tail — the branch needs NO pads, reshapes, or slices at
    all (OOB reads are masked in-kernel, OOB writes dropped). The size-1
    third dim keeps the block rank identical to the segmented path so the
    kernels are shared verbatim."""
    q_spec = pl.BlockSpec(
        (1, 1, 1, g, D), lambda b, h, s, i, j: (b, h, 0, s, 0),
        memory_space=pltpu.VMEM,
    )
    lse_spec = pl.BlockSpec(
        (1, 1, 1, g, LANES), lambda b, h, s, i, j: (b, h, 0, s, 0),
        memory_space=pltpu.VMEM,
    )
    return q_spec, lse_spec


def _flat_fwd_impl(q, k, v, g, real_len, causal, interpret):
    """Flat segment flash: [B, H, L, D] -> (out [B, H, L, D], lse [B, H, L]).

    Segment s attends within itself; g is the segment length (one q and one
    k block per segment — requires g small enough for a single block)."""
    B, H, L, D = q.shape
    S = _round_up(L, g) // g
    kvlen = np.clip(real_len - np.arange(S) * g, 0, g).astype(np.int32)
    kvlen = jnp.asarray(np.broadcast_to(kvlen[None, None], (B, H, S)))
    q_spec, lse_spec = _flat_specs(g, D)
    q5, k5, v5 = q[:, :, None], k[:, :, None], v[:, :, None]
    kernel = functools.partial(
        _fwd_kernel, scale=D ** -0.5, causal=causal, block_q=g, block_k=g
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, S, 1, 1),
        in_specs=[q_spec, q_spec, q_spec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, 1, L, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, L, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, LANES), jnp.float32),
            pltpu.VMEM((g, LANES), jnp.float32),
            pltpu.VMEM((g, D), jnp.float32),
        ],
        interpret=interpret,
    )(q5, k5, v5, kvlen)
    return out[:, :, 0], lse[:, :, 0, :, 0]


def _flat_bwd_impl(q, k, v, lse, delta, do, g, real_len, causal, interpret):
    B, H, L, D = q.shape
    S = _round_up(L, g) // g
    kvlen = np.clip(real_len - np.arange(S) * g, 0, g).astype(np.int32)
    kvlen = jnp.asarray(np.broadcast_to(kvlen[None, None], (B, H, S)))
    if g * g > _BWD_LOGITS_BUDGET:
        # The forward's zero-glue single block is bwd-unsafe above ~1161
        # (see _BWD_LOGITS_BUDGET): re-segment into the padded [B,H,S,g,D]
        # layout and run the generic backward with a bwd-safe asymmetric
        # block pair. Glue (one pad + reshape per array) only ever runs in
        # training, where the backward's 2x FLOPs dominate it anyway.
        # Zeroing do/delta rows beyond real_len reproduces the flat=True
        # kernels' qrow masking: those rows' out is garbage by contract, so
        # they must contribute nothing to dk/dv (and get dq = 0) — without
        # this, gradient semantics would flip across the budget threshold
        # for callers whose cotangent touches rows in [real_len, L).
        if real_len < L:
            row_ok = (jnp.arange(L) < real_len)[None, None, :]
            do = jnp.where(row_ok[..., None], do, 0)
            delta = jnp.where(row_ok, delta, 0)
        Lp = S * g

        def seg(x):
            if Lp != L:
                x = jnp.pad(x, ((0, 0), (0, 0), (0, Lp - L)) + ((0, 0),) * (x.ndim - 3))
            return x.reshape(B, H, S, g, *x.shape[3:])

        bq, bk = bwd_blocks(g)
        dq5, dk5, dv5 = _bwd_impl(
            seg(q), seg(k), seg(v), seg(lse), seg(delta), seg(do),
            kvlen, causal, D ** -0.5, bq, bk, interpret,
        )
        undo = lambda x5: x5.reshape(B, H, Lp, D)[:, :, :L]
        return undo(dq5), undo(dk5), undo(dv5)
    # lse/delta carried at LANES width for TPU tiling
    lseL = jnp.broadcast_to(lse[:, :, None, :, None], (B, H, 1, L, LANES))
    deltaL = jnp.broadcast_to(delta[:, :, None, :, None], (B, H, 1, L, LANES))
    q_spec, lse_spec = _flat_specs(g, D)
    q5, k5, v5, do5 = q[:, :, None], k[:, :, None], v[:, :, None], do[:, :, None]
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    scale = D ** -0.5

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=g, block_k=g,
            flat=True,
        ),
        grid=(B, H, S, 1, 1),
        in_specs=[q_spec, q_spec, q_spec, q_spec, lse_spec, lse_spec, smem],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, 1, L, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((g, D), jnp.float32)],
        interpret=interpret,
    )(q5, k5, v5, do5, lseL, deltaL, kvlen)[0]

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=g, block_k=g,
            flat=True,
        ),
        grid=(B, H, S, 1, 1),
        in_specs=[q_spec, q_spec, q_spec, q_spec, lse_spec, lse_spec, smem],
        out_specs=[q_spec, q_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, 1, L, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, 1, L, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((g, D), jnp.float32),
            pltpu.VMEM((g, D), jnp.float32),
        ],
        interpret=interpret,
    )(q5, k5, v5, do5, lseL, deltaL, kvlen)
    return dq[:, :, 0], dk[:, :, 0], dv[:, :, 0]


def _flat_fwd_rule(g, real_len, causal, interpret, q, k, v):
    out, lse = _flat_fwd_impl(q, k, v, g, real_len, causal, interpret)
    return (out, lse), (q, k, v, out, lse)


def _flat_bwd_rule(g, real_len, causal, interpret, res, cotangents):
    q, k, v, out, lse = res
    do, _dlse = cotangents
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    return _flat_bwd_impl(
        q, k, v, lse, delta, do, g, real_len, causal, interpret
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flat_with_lse(g, real_len, causal, interpret, q, k, v):
    return _flat_fwd_impl(q, k, v, g, real_len, causal, interpret)


_flat_with_lse.defvjp(_flat_fwd_rule, _flat_bwd_rule)

# g (= block) beyond this exceeds the per-cell VMEM budget (fp32 logits
# tile g^2 plus blocks and stats)
FLAT_MAX_SEGMENT = 1408


def flat_segment_flash(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    segment_len: int,
    real_len: Optional[int] = None,
    is_causal: bool = False,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zero-glue segmented flash on flat [B, H, L, D] (undilated branches).

    Each ``segment_len`` chunk attends within itself; the ragged tail rides
    Pallas OOB auto-masking + the kvlen select, so the caller needs no
    pads/reshapes — the dominant XLA glue of short-segment branches.
    Requires ``segment_len % 8 == 0`` and ``segment_len <= FLAT_MAX_SEGMENT``.
    """
    B, H, L, D = q.shape
    assert segment_len % 8 == 0 and segment_len <= FLAT_MAX_SEGMENT
    rl = L if real_len is None else min(int(real_len), L)
    return _flat_with_lse(segment_len, rl, is_causal, interpret, q, k, v)


def _flash_fwd_rule(kv_lens, causal, interpret, block_q, block_k, q, k, v):
    scale = q.shape[-1] ** -0.5
    out, lse = _fwd_impl(
        q, k, v, kv_lens, causal, scale, block_q, block_k, interpret
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_bwd_rule(kv_lens, causal, interpret, block_q, block_k, res, cotangents):
    q, k, v, out, lse = res
    do, _dlse = cotangents  # no gradient flows through the lse output
    scale = q.shape[-1] ** -0.5
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [B, H, S, Mq]
    dq, dk, dv = _bwd_impl(
        q, k, v, lse, delta, do, kv_lens, causal, scale,
        block_q, block_k, interpret,
    )
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_with_lse(kv_lens, causal, interpret, block_q, block_k, q, k, v):
    out, lse = _fwd_impl(
        q, k, v, kv_lens, causal, q.shape[-1] ** -0.5,
        block_q, block_k, interpret,
    )
    return out, lse


_flash_with_lse.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# NOTE: the segment-batched entry point for dilated attention is the
# branch-level custom VJP in ops/dilated_attention.py (_branch_pallas),
# which calls _fwd_impl/_bwd_impl directly with (possibly traced) kvlen
# arrays — there is deliberately no second segment-level wrapper here.


def pallas_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    is_causal: bool = False,
    kv_len=None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flash attention on [B, L, H, D] -> (out [B,L,H,D], lse [B,H,L]).

    ``kv_len``: optional static [B, H] array-like of per-(batch, head)
    valid key counts (trace-time constants — this wrapper's custom VJP
    carries them as nondiff args; for TRACED counts use the branch-level
    VJP in ops/dilated_attention.py, whose kvlen is a runtime argument).

    Kernels run on ``[B, H, S, M, D]`` blocks with a single segment — the
    head-major layout whose trailing block dims satisfy Mosaic's (8, 128)
    tiling rule — and the wrapper transposes (XLA folds the relayout into
    surrounding reshapes).
    """
    B, Lq, H, D = q.shape
    kv_lens = None
    if kv_len is not None:
        kv_lens = tuple(int(x) for x in np.asarray(kv_len).reshape(B * H))
    q5 = q.transpose(0, 2, 1, 3)[:, :, None]
    k5 = k.transpose(0, 2, 1, 3)[:, :, None]
    v5 = v.transpose(0, 2, 1, 3)[:, :, None]
    out, lse = _flash_with_lse(
        kv_lens, is_causal, interpret, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K, q5, k5, v5
    )
    return out[:, :, 0].transpose(0, 2, 1, 3), lse[:, :, 0]
