"""Multiway (BEiT-3 style) two-branch module split.

Parity with reference ``torchscale/component/multiway_network.py``: a wrapper
holding two copies (A/B) of a module; tokens before ``split_position`` go
through A, the rest through B. The reference mutates ``split_position`` on
module objects via ``apply`` (``set_split_position``); functional flax passes
it as a call argument instead, which is also what makes it jittable (the
split position is static per trace).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from flax import linen as nn


class MultiwayNetwork(nn.Module):
    """Wraps ``module_fn`` twice (branches A and B), splitting on an axis.

    During ``init`` both branches are always traced (whatever the split), so
    the parameter tree is complete no matter which modality the init inputs
    exercise — the functional analogue of the reference eagerly deep-copying
    module B in ``MultiwayNetwork.__init__``.
    """

    module_fn: Callable[..., nn.Module]
    dim: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray, *args, split_position: int = -1, **kwargs):
        a = self.module_fn(name="A")
        b = self.module_fn(name="B")
        if self.is_initializing():
            a(x, *args, **kwargs)
            b(x, *args, **kwargs)
        if split_position == -1:
            return a(x, *args, **kwargs)
        if split_position == 0:
            return b(x, *args, **kwargs)
        x1, x2 = jnp.split(x, [split_position], axis=self.dim)
        return jnp.concatenate([a(x1, *args, **kwargs), b(x2, *args, **kwargs)], axis=self.dim)


def multiway_layernorm(
    multiway: bool, name: str, *, epsilon: float, dtype=None
) -> Callable:
    """LayerNorm that may be multiway-split: the one construction used by
    every norm site in the encoder/attention stack. Returns
    ``fn(x, split_position=-1)``. Must be called in the parent's compact
    scope."""
    from flax import linen as nn

    make = lambda name: nn.LayerNorm(epsilon=epsilon, dtype=dtype, name=name)  # noqa: E731
    return maybe_multiway(multiway, make, name)


def maybe_multiway(
    multiway: bool, module_fn: Callable[..., nn.Module], name: str, dim: int = 1
) -> Callable:
    """One call surface for both paths (parity with ``MultiwayWrapper``):
    returns ``fn(x, *args, split_position=-1, **kwargs)`` that routes through
    a two-branch :class:`MultiwayNetwork` when ``multiway`` and through a
    single ``module_fn(name=name)`` (ignoring the split) otherwise. Must be
    called from inside the parent module's compact scope."""
    if multiway:
        mod = MultiwayNetwork(module_fn=module_fn, dim=dim, name=name)
        return lambda x, *a, split_position=-1, **kw: mod(
            x, *a, split_position=split_position, **kw
        )
    mod = module_fn(name=name)
    return lambda x, *a, split_position=-1, **kw: mod(x, *a, **kw)
