"""Multi-scale retention (RetNet) with parallel / recurrent / chunkwise modes.

Parity with reference ``torchscale/component/multiscale_retention.py`` and
the relative-position machinery in ``architecture/retnet.py:22-69``: xPos-like
theta rotation of q/k, per-head exponential decay mask, the three
mathematically-equivalent execution modes (O(T^2) parallel, O(1)-state
recurrent, chunked recurrent), head-wise RMS group norm (no affine), swish
output gate, and the stability normalizations (row abs-sum clamps with
detached denominators).

TPU mapping: the recurrent state rides the flax ``cache`` collection
(``prev_key_value [B,H,Dk,Dv]`` + ``scale [H]``) instead of fairseq
incremental dicts; the chunkwise cross-chunk accumulation is a
``jax.lax.scan`` instead of a Python loop (``multiscale_retention.py:147-151``)
so long sequences compile to one fused loop.
"""

from __future__ import annotations

import math
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.ops.norms import RMSNorm


def rotate_every_two(x: jnp.ndarray) -> jnp.ndarray:
    x1 = x[..., ::2]
    x2 = x[..., 1::2]
    return jnp.stack((-x2, x1), axis=-1).reshape(x.shape)


def theta_shift(x: jnp.ndarray, sin: jnp.ndarray, cos: jnp.ndarray) -> jnp.ndarray:
    return x * cos + rotate_every_two(x) * sin


def retnet_angle_decay(embed_dim: int, num_heads: int) -> Tuple[np.ndarray, np.ndarray]:
    """(angle [Dk], decay [H]) constants (reference ``RetNetRelPos:22-30``)."""
    key_dim = embed_dim // num_heads
    angle = 1.0 / (10000 ** np.linspace(0, 1, key_dim // 2))
    angle = np.repeat(angle, 2)
    decay = np.log(1 - 2.0 ** (-5 - np.arange(num_heads, dtype=np.float64)))
    return angle.astype(np.float32), decay.astype(np.float32)


def retnet_rel_pos(
    slen: int,
    embed_dim: int,
    num_heads: int,
    *,
    activate_recurrent: bool = False,
    chunkwise_recurrent: bool = False,
    recurrent_chunk_size: int = 512,
):
    """((sin, cos), inner_mask) for one mode (reference ``RetNetRelPos.forward``).

    All outputs are trace-time numpy constants (static ``slen``), so under
    ``jit`` they fold into the compiled program.
    """
    angle, decay = retnet_angle_decay(embed_dim, num_heads)
    if activate_recurrent:
        sin = np.sin(angle * (slen - 1))
        cos = np.cos(angle * (slen - 1))
        return (jnp.asarray(sin), jnp.asarray(cos)), jnp.asarray(np.exp(decay))

    index = np.arange(slen, dtype=np.float64)
    sin = np.sin(index[:, None] * angle[None, :]).astype(np.float32)
    cos = np.cos(index[:, None] * angle[None, :]).astype(np.float32)

    if chunkwise_recurrent:
        C = recurrent_chunk_size
        block = np.arange(C, dtype=np.float64)
        tri = block[:, None] >= block[None, :]
        diff = np.where(tri, block[:, None] - block[None, :], np.inf)
        mask = np.exp(diff[None] * decay[:, None, None])  # [H, C, C]
        mask = np.nan_to_num(mask)
        value_inner_decay = mask[:, -1] / mask[:, -1].sum(axis=-1, keepdims=True)
        value_inner_decay = value_inner_decay[:, :, None]
        scale = np.sqrt(mask.sum(axis=-1, keepdims=True))
        inner_mask = mask / scale
        cross_decay = np.exp(decay * C)[:, None, None]
        query_inner_decay = np.exp(decay[:, None] * (block + 1))
        query_inner_decay = query_inner_decay[:, :, None] / (
            scale / mask[:, -1].sum(axis=-1)[:, None, None]
        )
        return (
            (jnp.asarray(sin), jnp.asarray(cos)),
            (
                jnp.asarray(inner_mask.astype(np.float32)),
                jnp.asarray(cross_decay.astype(np.float32)),
                jnp.asarray(query_inner_decay.astype(np.float32)),
                jnp.asarray(value_inner_decay.astype(np.float32)),
            ),
        )

    tri = index[:, None] >= index[None, :]
    diff = np.where(tri, index[:, None] - index[None, :], np.inf)
    mask = np.exp(diff[None] * decay[:, None, None])  # [H, T, T]
    mask = np.nan_to_num(mask)
    mask = mask / np.sqrt(mask.sum(axis=-1, keepdims=True))
    return (jnp.asarray(sin), jnp.asarray(cos)), jnp.asarray(mask.astype(np.float32))


class MultiScaleRetention(nn.Module):
    """Retention op over ``[B, T, E]`` (reference ``MultiScaleRetention:39``).

    Call with the matching ``rel_pos`` structure from :func:`retnet_rel_pos`;
    ``decode=True`` (+ ``mutable=["cache"]``) runs the O(1)-state recurrent
    step.
    """

    embed_dim: int
    value_dim: int
    num_heads: int
    gate_fn: str = "swish"
    layernorm_eps: float = 1e-6
    dtype: Any = None

    @property
    def key_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def head_dim(self) -> int:
        return self.value_dim // self.num_heads

    def _parallel(self, qr, kr, v, mask):
        B, T, _ = v.shape
        vr = v.reshape(B, T, self.num_heads, self.head_dim).transpose(0, 2, 1, 3)
        qk = jnp.einsum("bhtd,bhsd->bhts", qr, kr) * mask
        denom = jnp.clip(
            jnp.abs(jax.lax.stop_gradient(qk)).sum(-1, keepdims=True), 1.0, 5e4
        )
        out = jnp.einsum("bhts,bhsd->bhtd", qk / denom, vr)
        return out.transpose(0, 2, 1, 3)  # [B, T, H, Dv]

    def _chunkwise(self, qr, kr, v, inner):
        mask, cross_decay, query_inner_decay, value_inner_decay = inner
        B, T, _ = v.shape
        H, Dk, Dv = self.num_heads, self.key_dim, self.head_dim
        C = mask.shape[1]
        N = T // C
        assert T % C == 0, (T, C)
        qr = qr.reshape(B, H, N, C, Dk).transpose(0, 2, 1, 3, 4)  # [B,N,H,C,Dk]
        kr = kr.reshape(B, H, N, C, Dk).transpose(0, 2, 1, 3, 4)
        vr = v.reshape(B, N, C, H, Dv).transpose(0, 1, 3, 2, 4)  # [B,N,H,C,Dv]

        qk = jnp.einsum("bnhtd,bnhsd->bnhts", qr, kr) * mask
        inner_scale = jnp.clip(
            jnp.abs(jax.lax.stop_gradient(qk)).sum(-1, keepdims=True), 1.0
        )
        inner_output = jnp.einsum("bnhts,bnhsd->bnhtd", qk / inner_scale, vr)

        # per-chunk kv summaries, then a scan threading (kv_state, kv_scale)
        kv = jnp.einsum("bnhsd,bnhsv->bnhdv", kr, vr * value_inner_decay[None, None])

        kv0 = jnp.zeros((B, H, Dk, Dv), v.dtype)
        s0 = jnp.ones((B, H, 1, 1), v.dtype)

        def step(carry, kv_i):
            kv_state, kv_scale = carry
            out = (kv_state / kv_scale, kv_scale)
            kv_state = kv_state * cross_decay + kv_i
            kv_scale = jnp.clip(
                jnp.abs(jax.lax.stop_gradient(kv_state))
                .sum(-2, keepdims=True)
                .max(-1, keepdims=True),
                1.0,
            )
            return (kv_state, kv_scale), out

        _, (kv_recurrent, cross_scale) = jax.lax.scan(
            step, (kv0, s0), kv.transpose(1, 0, 2, 3, 4)
        )
        kv_recurrent = kv_recurrent.transpose(1, 0, 2, 3, 4)  # [B,N,H,Dk,Dv]
        cross_scale = cross_scale.transpose(1, 0, 2, 3, 4)  # [B,N,H,1,1]

        all_scale = jnp.maximum(inner_scale, cross_scale)
        cross_output = jnp.einsum(
            "bnhtd,bnhdv->bnhtv", qr * query_inner_decay[None, None], kv_recurrent
        )
        output = inner_output / (all_scale / inner_scale) + cross_output / (
            all_scale / cross_scale
        )
        return output.transpose(0, 1, 3, 2, 4).reshape(B, T, H, Dv)

    def _recurrent(self, qr, kr, v, decay):
        """One-token step against the flax cache (reference
        ``recurrent_forward:89-112``)."""
        B = v.shape[0]
        H, Dk, Dv = self.num_heads, self.key_dim, self.head_dim
        vr = v.reshape(B, H, Dv)
        kv = jnp.einsum("bhd,bhv->bhdv", kr[:, :, 0, :], vr)

        # cache starts at zeros; the first real step then computes
        # scale = 0*decay + 1 = 1 and kv = kv/sqrt(1), matching the
        # reference's explicit first-step branch (``recurrent_forward:105-106``).
        # Writes happen only on real (post-init) steps so the init trace
        # cannot seed the cache with the dummy input.
        has_cache = self.has_variable("cache", "prev_key_value")
        prev_kv = self.variable(
            "cache", "prev_key_value", jnp.zeros, (B, H, Dk, Dv), v.dtype
        )
        prev_scale = self.variable("cache", "scale", jnp.zeros, (H,), jnp.float32)
        if has_cache:
            scale = prev_scale.value * decay + 1
            kv = prev_kv.value * (
                jnp.sqrt(prev_scale.value) * decay / jnp.sqrt(scale)
            ).reshape(1, H, 1, 1) + kv / jnp.sqrt(scale).reshape(1, H, 1, 1)
            prev_kv.value = kv
            prev_scale.value = scale
        out = jnp.einsum("bhd,bhdv->bhv", qr[:, :, 0, :], kv)
        return out.reshape(B, 1, H, Dv)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        rel_pos,
        chunkwise_recurrent: bool = False,
        decode: bool = False,
    ) -> jnp.ndarray:
        B, T, _ = x.shape
        (sin, cos), inner_mask = rel_pos
        gain = 2.0**-2.5

        proj = lambda dim, name, g=gain: nn.Dense(  # noqa: E731
            dim,
            use_bias=False,
            dtype=self.dtype,
            # torch xavier_uniform(gain=g) == variance_scaling(g^2, fan_avg,
            # uniform): both give Var = g^2 / fan_avg
            kernel_init=nn.initializers.variance_scaling(g * g, "fan_avg", "uniform"),
            name=name,
        )
        q = proj(self.embed_dim, "q_proj")(x)
        k = proj(self.embed_dim, "k_proj")(x) * (self.key_dim**-0.5)
        v = proj(self.value_dim, "v_proj")(x)
        g = proj(self.value_dim, "g_proj")(x)

        q = q.reshape(B, T, self.num_heads, self.key_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, self.num_heads, self.key_dim).transpose(0, 2, 1, 3)
        qr = theta_shift(q, sin, cos)
        kr = theta_shift(k, sin, cos)

        if decode:
            output = self._recurrent(qr, kr, v, inner_mask)
        elif chunkwise_recurrent:
            output = self._chunkwise(qr, kr, v, inner_mask)
        else:
            output = self._parallel(qr, kr, v, inner_mask)

        output = RMSNorm(
            self.head_dim,
            eps=self.layernorm_eps,
            elementwise_affine=False,
            name="group_norm",
        )(output)
        output = output.reshape(B, T, self.value_dim)
        output = nn.silu(g) * output if self.gate_fn == "swish" else nn.gelu(g) * output
        return proj(self.embed_dim, "out_proj", 2.0**-1)(output)
