"""Pallas TPU tier for the streaming-fold pair partial (fwd + bwd).

The streaming chunked prefill's inner loop —
:func:`gigapath_tpu.ops.streaming_prefill.pair_partial_attention` — is a
jnp formulation that materializes a dense ``[H, cq, ck]`` boolean
segment/phase/validity mask per chunk pair before the softmax touches a
single logit. At the paper-scale operating point (10^5-10^6 tiles per
slide, every chunk pair of every branch of every layer) that mask is
pure overhead: it is a function of nothing but iota comparisons the
kernel grid can evaluate per block.

This module is the FlashAttention-style replacement (the same treatment
``pallas_flash.py`` gave the dense path):

- forward: one kernel per (batch, head, q-block) running the base-2
  online softmax over key blocks, with the segment / dilation-phase /
  ragged-``valid_len`` masks computed IN-KERNEL from
  ``broadcasted_iota`` against the chunks' global offsets — no dense
  mask tensor ever exists in the compiled program (the golden ledger's
  ``jaxpr.mask`` column pins this at 0 vs the jnp control's nonzero
  count);
- backward: dQ and dK/dV kernels recomputing probabilities from the
  stored LSE (the ``_branch_bwd_core`` discipline), with one twist the
  branch VJPs don't need: ``combine_partials`` DIFFERENTIATES through
  the lse output, so the incoming ``dlse`` cotangent folds into the
  delta term (``ds = p * (dp - (delta - dlse))``) instead of being
  dropped;
- the chunks' global offsets, the ragged valid length, and the true
  (unpadded) block extents travel as ONE dynamic int32 SMEM array, so a
  single compiled executable serves every chunk pair of a branch class
  — the fold loop never retraces on chunk position.

Numerics contract vs the jnp oracle: covered query rows match fwd 1e-5
/ grads 1e-4. Fully-masked rows produce ``out = 0`` in both
formulations; their lse is a large-negative SENTINEL in both (~ -7e19
here via the ``M_FLOOR`` underflow discipline, ~ -1e30 in the oracle)
and the two interoperate identically downstream: ``combine_partials``
folds either in with weight ``exp(sentinel - lse) == 0`` and
``fuse_branch_partials`` gives either zero fusion weight. Parity tests
therefore compare lse on covered rows and the fused OUTPUT everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gigapath_tpu.ops.common import round_up
from gigapath_tpu.ops.pallas_flash import (
    LANES,
    LN2,
    LOG2E,
    M_FLOOR,
    NEG_INF,
    bwd_blocks,
)

# Chunk blocks are small next to the dense path's sequences (the 16k
# smoke geometry folds 2048-token chunks), so the flash default of
# 1024x1024 — fp32 logits tile 4 MB, well under the 16 MB VMEM budget —
# is also the fold's default; blessed plans override per branch class.
DEFAULT_FOLD_BLOCK = 1024

# layout of the dynamic int32 SMEM info array (ONE executable serves
# every chunk pair): global q offset, global k offset, ragged valid
# length (sentinel INT32_MAX = no ragged tail), true q rows, true k rows
_INFO_Q0, _INFO_K0, _INFO_VALID, _INFO_CQ, _INFO_CK = range(5)
_NO_VALID = np.int32(2**31 - 1)


def fold_blocks(flags, segment_len: int, ratio: int) -> Tuple[int, int]:
    """(block_q, block_k) for one fold branch class from a resolved
    flags carrier: a ``fold_branches`` plan entry matched on the
    branch's own (segment_len, ratio) wins, then the global
    ``fold_block_q``/``fold_block_k`` fields, then the default."""
    bq = bk = None
    if flags is not None:
        for entry in getattr(flags, "fold_branches", ()) or ():
            if int(entry[0]) == int(segment_len) and int(entry[1]) == int(ratio):
                bq = int(entry[2]) or None
                bk = int(entry[3]) or None
                break
        if bq is None:
            bq = getattr(flags, "fold_block_q", None)
        if bk is None:
            bk = getattr(flags, "fold_block_k", None)
    return int(bq or DEFAULT_FOLD_BLOCK), int(bk or DEFAULT_FOLD_BLOCK)


# ---------------------------------------------------------------------------
# in-kernel masks
# ---------------------------------------------------------------------------

def _pair_masks(info_ref, i, j, phase, *, segment_len, ratio,
                block_q, block_k):
    """(row_ok [bq,1], col_ok [1,bk], seg_ok [bq,bk]) from iota
    comparisons against the SMEM scalars — the dense ``[H, cq, ck]``
    mask of the jnp oracle, re-expressed as three per-block predicates
    that never materialize outside VMEM."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0) + i * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1) + j * block_k
    t = info_ref[_INFO_Q0] + rows  # global query positions
    u = info_ref[_INFO_K0] + cols  # global key positions
    # local bounds first: padded rows/cols sit at global positions that
    # could otherwise pass the segment/lattice tests
    row_ok = (rows < info_ref[_INFO_CQ]) \
        & (((t % segment_len) % ratio) == phase)
    col_ok = (cols < info_ref[_INFO_CK]) \
        & (((u % segment_len) % ratio) == phase) \
        & (u < info_ref[_INFO_VALID])
    seg_ok = (t // segment_len) == (u // segment_len)
    return row_ok, col_ok, seg_ok


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(info_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref,
                *, scale, segment_len, ratio, hpg, block_q, block_k):
    h = pl.program_id(1)
    i, j = pl.program_id(2), pl.program_id(3)
    phase = h // hpg

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, M_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # scale (with log2(e) folded in: the hot loop runs exp2) applied to
    # the small q block, not the [bq, bk] logits — the pallas_flash
    # discipline
    q = (q_ref[0, 0].astype(jnp.float32) * (scale * LOG2E)).astype(q_ref.dtype)
    k = k_ref[0, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (BQ, BK), log2 units

    row_ok, col_ok, seg_ok = _pair_masks(
        info_ref, i, j, phase,
        segment_len=segment_len, ratio=ratio,
        block_q=block_q, block_k=block_k,
    )
    # select BEFORE the running max (a post-hoc zero-multiply would see
    # inf * 0 = NaN); M_FLOOR keeps m_new finite for fully-masked rows
    # so exp2(NEG_INF - m_new) underflows to exactly 0.0 in fp32
    s = jnp.where(seg_ok & row_ok & col_ok, s, NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp2(s - m_new)
    # padded key rows of V are exact zeros (the wrapper zero-pads) and p
    # is exactly 0 there — no NaN hazard, no extra select needed
    v = v_ref[0, 0]
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if pl.num_programs(3) == 1:
        # single k block: no online carry — skip the acc rescale
        l_new = jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = pv
    else:
        alpha = jnp.exp2(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:, :1] = m_new
    l_ref[:, :1] = l_new

    @pl.when(j == pl.num_programs(3) - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0, 0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # natural-log lse from the base-2 running stats, carried at
        # LANES width (TPU tiling); the wrapper slices lane 0
        lse_ref[0, 0] = jnp.broadcast_to(
            (m_ref[:, :1] + jnp.log2(safe_l)) * LN2, (block_q, LANES)
        )


# ---------------------------------------------------------------------------
# backward kernels (stored-LSE recompute, the _branch_bwd_core discipline)
# ---------------------------------------------------------------------------

def _dq_kernel(info_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc,
               *, scale, segment_len, ratio, hpg, block_q, block_k):
    h = pl.program_id(1)
    i, j = pl.program_id(2), pl.program_id(3)
    phase = h // hpg

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    v = v_ref[0, 0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * LOG2E)
    row_ok, col_ok, seg_ok = _pair_masks(
        info_ref, i, j, phase,
        segment_len=segment_len, ratio=ratio,
        block_q=block_q, block_k=block_k,
    )
    # masking BEFORE the exp (inf * 0 = NaN in the gradients otherwise);
    # masked/padded rows carry lse = 0 from the wrapper's pad, and
    # exp2(NEG_INF - 0) is exactly 0 — their p rows vanish
    p = jnp.exp2(
        jnp.where(seg_ok & row_ok & col_ok, s, NEG_INF)
        - lse_ref[0, 0][:, :1] * LOG2E
    )
    dp = jax.lax.dot_general(
        do_ref[0, 0].astype(jnp.float32), v.astype(jnp.float32),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )
    # delta arrives PRE-FOLDED with the lse cotangent:
    # delta' = rowsum(do * out) - dlse  (combine_partials differentiates
    # through lse, unlike the branch VJPs that drop it)
    ds = p * (dp - delta_ref[0, 0][:, :1])
    dq_acc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(j == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(info_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, segment_len, ratio, hpg, block_q, block_k):
    h = pl.program_id(1)
    j, i = pl.program_id(2), pl.program_id(3)  # grid: (B, H, nk, nq)
    phase = h // hpg

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = q_ref[0, 0]
    k = k_ref[0, 0]
    do = do_ref[0, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * (scale * LOG2E)
    row_ok, col_ok, seg_ok = _pair_masks(
        info_ref, i, j, phase,
        segment_len=segment_len, ratio=ratio,
        block_q=block_q, block_k=block_k,
    )
    p = jnp.exp2(
        jnp.where(seg_ok & row_ok & col_ok, s, NEG_INF)
        - lse_ref[0, 0][:, :1] * LOG2E
    )  # (BQ, BK)
    dv_acc[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (BK, D)
    dp = jax.lax.dot_general(
        do, v_ref[0, 0].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (BQ, BK)
    ds = p * (dp - delta_ref[0, 0][:, :1])
    dk_acc[:] += jax.lax.dot_general(
        ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # (BK, D)

    @pl.when(i == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# impls ([B, H, c, D] head-major layout; padding handled here)
# ---------------------------------------------------------------------------

def _pad_rows(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """[B, H, c, ...] zero-padded to n rows on axis 2."""
    if x.shape[2] == n:
        return x
    pads = [(0, 0), (0, 0), (0, n - x.shape[2])] + [(0, 0)] * (x.ndim - 3)
    return jnp.pad(x, pads)


def _blocks_for(cq: int, ck: int, block_q: int, block_k: int):
    bq = min(block_q, round_up(cq, LANES))
    bk = min(block_k, round_up(ck, LANES))
    return bq, bk, round_up(cq, bq), round_up(ck, bk)


def _fwd_impl(info, q, k, v, segment_len, ratio, block_q, block_k,
              interpret):
    B, H, cq, D = q.shape
    ck = k.shape[2]
    scale = D ** -0.5
    bq, bk, cqp, ckp = _blocks_for(cq, ck, block_q, block_k)
    qp = _pad_rows(q, cqp)
    kp, vp = _pad_rows(k, ckp), _pad_rows(v, ckp)
    nq, nk = cqp // bq, ckp // bk
    hpg = -(-H // ratio)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, segment_len=segment_len, ratio=ratio,
        hpg=hpg, block_q=bq, block_k=bk,
    )
    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                          memory_space=pltpu.VMEM)
    info_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B, H, nq, nk),
        in_specs=[info_spec, q_spec, k_spec, k_spec],
        out_specs=[
            q_spec,
            pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, cqp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, cqp, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(info, qp, kp, vp)
    return out[:, :, :cq], lse[:, :, :cq, 0]


def _bwd_impl(info, q, k, v, lse, delta, do, segment_len, ratio,
              block_q, block_k, interpret):
    B, H, cq, D = q.shape
    ck = k.shape[2]
    scale = D ** -0.5
    bq, bk = bwd_blocks(block_q)
    bk = min(bk, block_k)
    bq, bk, cqp, ckp = _blocks_for(cq, ck, bq, bk)
    qp = _pad_rows(q, cqp)
    kp, vp = _pad_rows(k, ckp), _pad_rows(v, ckp)
    dop = _pad_rows(do, cqp)
    # lse/delta carried at LANES width; padded q rows get lse = 0, which
    # is harmless: their mask rows are all-False, so p = exp2(NEG_INF -
    # 0) = 0 and nothing leaks into dk/dv
    lsep = jnp.broadcast_to(
        _pad_rows(lse[..., None], cqp), (B, H, cqp, LANES)
    )
    deltap = jnp.broadcast_to(
        _pad_rows(delta[..., None], cqp), (B, H, cqp, LANES)
    )
    nq, nk = cqp // bq, ckp // bk
    hpg = -(-H // ratio)

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0),
                          memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, 1, bq, LANES), lambda b, h, i, j: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    info_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, segment_len=segment_len, ratio=ratio,
            hpg=hpg, block_q=bq, block_k=bk,
        ),
        grid=(B, H, nq, nk),
        in_specs=[info_spec, q_spec, k_spec, k_spec, q_spec, vec_spec,
                  vec_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((B, H, cqp, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(info, qp, kp, vp, dop, lsep, deltap)[0]

    # grid (B, H, nk, nq): index maps see (b, h, j, i)
    q_spec_kv = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0),
                             memory_space=pltpu.VMEM)
    k_spec_kv = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0),
                             memory_space=pltpu.VMEM)
    vec_spec_kv = pl.BlockSpec(
        (1, 1, bq, LANES), lambda b, h, j, i: (b, h, i, 0),
        memory_space=pltpu.VMEM,
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, segment_len=segment_len, ratio=ratio,
            hpg=hpg, block_q=bq, block_k=bk,
        ),
        grid=(B, H, nk, nq),
        in_specs=[info_spec, q_spec_kv, k_spec_kv, k_spec_kv, q_spec_kv,
                  vec_spec_kv, vec_spec_kv],
        out_specs=[k_spec_kv, k_spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, ckp, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, ckp, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(info, qp, kp, vp, dop, lsep, deltap)
    return dq[:, :, :cq], dk[:, :, :ck], dv[:, :, :ck]


# ---------------------------------------------------------------------------
# custom VJP
# ---------------------------------------------------------------------------

def _pair_fwd_rule(segment_len, ratio, block_q, block_k, interpret,
                   info, q, k, v):
    out, lse = _fwd_impl(
        info, q, k, v, segment_len, ratio, block_q, block_k, interpret
    )
    return (out, lse), (info, q, k, v, out, lse)


def _pair_bwd_rule(segment_len, ratio, block_q, block_k, interpret,
                   res, cotangents):
    info, q, k, v, out, lse = res
    do, dlse = cotangents
    # the lse output IS differentiated downstream (combine_partials
    # merges through it), so its cotangent folds into the delta term:
    # ds = p * (dp - (rowsum(do*out) - dlse))
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ) - dlse.astype(jnp.float32)
    dq, dk, dv = _bwd_impl(
        info, q, k, v, lse, delta, do, segment_len, ratio,
        block_q, block_k, interpret,
    )
    # int32 info carries no gradient: float0 cotangent (the repo's
    # integer-residual idiom, pallas_dilated/_dilated_branch_bwd)
    info_ct = np.zeros(info.shape, dtype=jax.dtypes.float0)
    return info_ct, dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _pair_with_lse(segment_len, ratio, block_q, block_k, interpret,
                   info, q, k, v):
    return _fwd_impl(
        info, q, k, v, segment_len, ratio, block_q, block_k, interpret
    )


_pair_with_lse.defvjp(_pair_fwd_rule, _pair_bwd_rule)


# ---------------------------------------------------------------------------
# public wrapper (the pair_partial_attention contract)
# ---------------------------------------------------------------------------

def pallas_pair_partial(
    q_blk: jnp.ndarray,
    k_blk: jnp.ndarray,
    v_blk: jnp.ndarray,
    q0,
    k0,
    *,
    segment_len: int,
    ratio: int,
    valid_len=None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pallas twin of
    :func:`~gigapath_tpu.ops.streaming_prefill.pair_partial_attention`:
    ``(out [B,cq,H,D] q-dtype, lse [B,H,cq] f32)`` of one dilated branch
    restricted to one resident key chunk, masks computed in-kernel.

    ``q0``/``k0``/``valid_len`` are DYNAMIC int32 scalars packed into
    one SMEM array, so one compiled executable serves every chunk pair
    of the same block shapes. Kernels run on the head-major
    ``[B, H, c, D]`` layout (Mosaic's (8, 128) tiling rule); this
    wrapper transposes, like the flash wrapper.
    """
    B, cq, H, Dh = q_blk.shape
    ck = k_blk.shape[1]
    valid = _NO_VALID if valid_len is None \
        else jnp.asarray(valid_len, jnp.int32)
    info = jnp.stack([
        jnp.asarray(q0, jnp.int32),
        jnp.asarray(k0, jnp.int32),
        jnp.asarray(valid, jnp.int32),
        jnp.int32(cq),
        jnp.int32(ck),
    ])
    q4 = q_blk.transpose(0, 2, 1, 3)
    k4 = k_blk.transpose(0, 2, 1, 3)
    v4 = v_blk.transpose(0, 2, 1, 3)
    out, lse = _pair_with_lse(
        int(segment_len), int(ratio),
        int(block_q or DEFAULT_FOLD_BLOCK),
        int(block_k or DEFAULT_FOLD_BLOCK),
        bool(interpret), info, q4, k4, v4,
    )
    return out.transpose(0, 2, 1, 3), lse
