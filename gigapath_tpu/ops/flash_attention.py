"""Fused attention entry point: (out, lse) with backend dispatch.

Role parity with reference ``torchscale/component/flash_attention.py``, which
tiers flash-attn CUDA -> xformers CUTLASS -> None by GPU capability. On TPU
the tiers are: Pallas flash kernel (long segments, memory-bound) or the
XLA-fused jnp op (short segments, default) — both emit the LSE that dilated
attention's branch fusion requires.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from gigapath_tpu.ops.attention import attention_with_lse

# Segments at least this long route to the Pallas kernel on TPU by default:
# below it, XLA's fused dense attention is faster than paying kernel overhead.
PALLAS_MIN_SEQ = 512


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:  # pragma: no cover
        return False


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    is_causal: bool = False,
    bias: Optional[jnp.ndarray] = None,
    kv_valid_len=None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Attention on [B, L, H, D] returning ``(out [B,L,H,D], lse [B,H,L])``.

    ``kv_valid_len``: [B, H] valid-key counts (ragged tail masking). Static
    (numpy/tuple) counts ride both backends; *traced* counts (dynamic
    per-batch padding) are only supported by the jnp path — the Pallas
    wrapper bakes them into the compiled grid.
    """
    kvlen_is_dynamic = isinstance(kv_valid_len, jax.Array) or isinstance(
        kv_valid_len, jax.core.Tracer
    )
    if use_pallas is None:
        use_pallas = (
            _on_tpu()
            and bias is None
            and not kvlen_is_dynamic
            and q.shape[1] >= PALLAS_MIN_SEQ
            and _pallas_available()
        )
    elif use_pallas and kvlen_is_dynamic:
        raise ValueError(
            "use_pallas=True requires static kv_valid_len; traced counts "
            "(dynamic padding masks) need the jnp path"
        )
    elif use_pallas and bias is not None:
        # the Pallas kernel takes no bias; silently dropping it would produce
        # wrong attention output for an explicit override
        raise ValueError(
            "use_pallas=True is incompatible with a non-None bias; "
            "use the jnp path (use_pallas=False) for biased attention"
        )
    if use_pallas:
        from gigapath_tpu.ops.pallas_flash import pallas_flash_attention

        return pallas_flash_attention(q, k, v, is_causal=is_causal, kv_len=kv_valid_len)
    return attention_with_lse(
        q, k, v, is_causal=is_causal, bias=bias, kv_valid_len=kv_valid_len
    )


def _pallas_available() -> bool:
    try:
        import gigapath_tpu.ops.pallas_flash  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False
