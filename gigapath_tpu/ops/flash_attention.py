"""Fused attention entry point: (out, lse) with backend dispatch.

Role parity with reference ``torchscale/component/flash_attention.py``, which
tiers flash-attn CUDA -> xformers CUTLASS -> None by GPU capability. On TPU
the tiers are: Pallas flash kernel (long segments, memory-bound) or the
XLA-fused jnp op (short segments, default) — both emit the LSE that dilated
attention's branch fusion requires.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from gigapath_tpu.ops.attention import attention_with_lse

# Segments at least this long route to the Pallas kernel on TPU by default:
# below it, XLA's fused dense attention is faster than paying kernel overhead.
PALLAS_MIN_SEQ = 512


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:  # pragma: no cover
        return False


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    is_causal: bool = False,
    bias: Optional[jnp.ndarray] = None,
    kv_valid_len=None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Attention on [B, L, H, D] returning ``(out [B,L,H,D], lse [B,H,L])``.

    ``kv_valid_len``: [B, H] valid-key counts (ragged tail masking). Static
    (numpy/tuple) counts ride both backends; *traced* counts (dynamic
    per-batch padding) are only supported by the jnp path — the Pallas
    wrapper bakes them into the compiled grid.
    """
    kvlen_is_dynamic = isinstance(kv_valid_len, jax.Array) or isinstance(
        kv_valid_len, jax.core.Tracer
    )
    if use_pallas is None:
        use_pallas = (
            _on_tpu()
            and bias is None
            and not kvlen_is_dynamic
            and q.shape[1] >= PALLAS_MIN_SEQ
            and _pallas_available()
        )
    elif use_pallas and kvlen_is_dynamic:
        raise ValueError(
            "use_pallas=True requires static kv_valid_len; traced counts "
            "(dynamic padding masks) need the jnp path"
        )
    elif use_pallas and bias is not None:
        # the Pallas kernel takes no bias; silently dropping it would produce
        # wrong attention output for an explicit override
        raise ValueError(
            "use_pallas=True is incompatible with a non-None bias; "
            "use the jnp path (use_pallas=False) for biased attention"
        )
    if use_pallas:
        from gigapath_tpu.ops.pallas_flash import pallas_flash_attention

        return pallas_flash_attention(q, k, v, is_causal=is_causal, kv_len=kv_valid_len)
    return attention_with_lse(
        q, k, v, is_causal=is_causal, bias=bias, kv_valid_len=kv_valid_len
    )


def _pallas_available() -> bool:
    try:
        import gigapath_tpu.ops.pallas_flash  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def partial_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    kv_valid_len=None,
    use_pallas: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Partial-softmax entry point for blockwise/ring schedules.

    Attention of ``q`` against ONE resident K/V chunk, returning the
    chunk-normalized ``(out [B,Lq,H,D], lse [B,H,Lq])`` pair — exactly
    the state :func:`combine_partials` folds across chunks: because the
    output is normalized by its own softmax sum and the sum's log rides
    in the lse, partials over disjoint key sets merge into the full
    softmax without ever materializing the concatenated key axis. This
    is :func:`flash_attention` restricted to the non-causal self-shape
    case (a ring step has no global causal structure — callers mask
    before/at the chunk level via ``kv_valid_len``); it exists as a
    named entry so ring-step call sites read as partial-softmax by
    contract, not by accident of the default path.
    """
    return flash_attention(
        q, k, v, is_causal=False, kv_valid_len=kv_valid_len,
        use_pallas=use_pallas,
    )


def combine_partials(
    out_a: jnp.ndarray,
    lse_a: jnp.ndarray,
    out_b: jnp.ndarray,
    lse_b: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge two partial-softmax results by their stored log-sum-exps.

    ``out_*`` are ``[B, L, H, D]`` attention outputs each normalized
    over its OWN key set, ``lse_*`` the matching ``[B, H, L]``
    log-sum-exps; returns the pair normalized over the UNION of the key
    sets — the same online-softmax identity flash attention applies
    across key blocks inside one kernel and the stream-fusion epilogue
    applies across branches (pallas_dilated.py), here applied across
    ring steps. Fully-masked partials carry ``lse ~ NEG_INF`` and fold
    in with weight ``exp(NEG_INF - lse) == 0``, so no special-casing.

    Accumulates in fp32 and returns ``out`` in ``out_a``'s dtype — ring
    loops keep the accumulator fp32 end to end by seeding with an fp32
    first partial.
    """
    lse = jnp.logaddexp(lse_a, lse_b)  # [B, H, L]

    def w4(w):  # [B, H, L] -> broadcastable [B, L, H, 1]
        return w.transpose(0, 2, 1)[..., None]

    out = (
        out_a.astype(jnp.float32) * w4(jnp.exp(lse_a - lse))
        + out_b.astype(jnp.float32) * w4(jnp.exp(lse_b - lse))
    )
    return out.astype(out_a.dtype), lse
