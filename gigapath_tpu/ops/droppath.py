"""Stochastic depth (DropPath).

Parity with reference ``torchscale/component/droppath.py`` (which delegates to
timm's ``drop_path``): per-sample Bernoulli keep on the batch axis, rescaled
by the keep probability at train time, identity at eval.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


class DropPath(nn.Module):
    drop_prob: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.drop_prob == 0.0 or deterministic:
            return x
        keep_prob = 1.0 - self.drop_prob
        rng = self.make_rng("dropout")
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep_prob, shape)
        return jnp.where(mask, x / keep_prob, jnp.zeros_like(x))
