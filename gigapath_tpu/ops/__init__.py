from gigapath_tpu.ops import pos_embed  # noqa: F401
