"""Normalization layers.

LayerNorm is plain ``flax.linen.LayerNorm`` — XLA fuses it for free, which
replaces the reference's optional ``apex.normalization.FusedLayerNorm``
(``multihead_attention.py:10-13`` et al.). RMSNorm has parity with reference
``torchscale/component/rms_norm.py`` (fp32 accumulation, optional affine).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class RMSNorm(nn.Module):
    dim: int
    eps: float = 1e-6
    elementwise_affine: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        normed = normed.astype(x.dtype)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, (self.dim,))
            normed = normed * weight.astype(normed.dtype)
        return normed
