"""Tiny dependency-free helpers shared across the ops layer.

Kept separate from the Pallas kernel modules so CPU-only import paths
(e.g. the data layer pulling in dilated_attention via the model stack)
never load ``jax.experimental.pallas`` just for arithmetic.
"""


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m
