"""Tiny dependency-free helpers shared across the ops layer.

Kept separate from the Pallas kernel modules so CPU-only import paths
(e.g. the data layer pulling in dilated_attention via the model stack)
never load ``jax.experimental.pallas`` just for arithmetic.
"""


def round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def env_flag(name: str) -> bool:
    """Truthy env flag; ''/'0'/'false'/'no' all mean OFF — the one
    truthiness convention for every GIGAPATH_* flag (and mirrored by
    tests/conftest.py's RUN_SLOW check)."""
    import os

    return os.environ.get(name, "").strip().lower() not in ("", "0", "false", "no")
