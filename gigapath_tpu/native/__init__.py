"""Native (C++) host-runtime kernels with build-on-demand + numpy fallback.

The reference's performance-critical code is all external native binaries
(SURVEY §2.9); the TPU compute path here is Pallas/XLA, and this package is
the native piece of the *host* runtime: tile normalization, occupancy
filtering, ragged-batch padding. The shared library compiles once from
``tile_ops.cpp`` with the system ``g++`` into a per-user cache and binds via
ctypes — no pybind11 required. Every entry point has an exact numpy
fallback, so the package degrades gracefully where no toolchain exists.

>>> from gigapath_tpu import native
>>> native.available()          # True when the .so built
>>> native.normalize_tiles(u8_batch)   # fast path or numpy, same results
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional, Sequence

import numpy as np

from gigapath_tpu.obs import console

_SRC = os.path.join(os.path.dirname(__file__), "tile_ops.cpp")
_lib: Optional[ctypes.CDLL] = None
_build_failed = False



def _build() -> Optional[ctypes.CDLL]:
    """Compile tile_ops.cpp once (content-hashed cache) and dlopen it."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache_dir = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "gigapath_tpu",
        )
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"tile_ops_{digest}.so")
        if not os.path.exists(so_path):
            with tempfile.NamedTemporaryFile(
                suffix=".so", dir=cache_dir, delete=False
            ) as tmp:
                tmp_path = tmp.name
            cmd = [
                "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                _SRC, "-o", tmp_path,
            ]
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(tmp_path, so_path)
        lib = ctypes.CDLL(so_path)
        lib.normalize_tiles.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.normalize_tiles.restype = ctypes.c_int
        lib.luminance_occupancy.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_float, ctypes.c_void_p,
        ]
        lib.pad_sequences.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        _lib = lib
    except Exception as e:  # toolchain absent / compile error -> numpy path
        console(f"gigapath_tpu.native: falling back to numpy ({e})")
        _build_failed = True
    return _lib


def available() -> bool:
    return _build() is not None


def normalize_tiles(
    batch_u8: np.ndarray,
    mean: Optional[Sequence[float]] = None,
    std: Optional[Sequence[float]] = None,
) -> np.ndarray:
    """uint8 [..., H, W, C] -> float32 ``(x/255 - mean) / std``.

    Defaults to the canonical ImageNet constants from
    ``gigapath_tpu.models.tile_encoder`` (single source of truth)."""
    if mean is None or std is None:
        from gigapath_tpu.models.tile_encoder import IMAGENET_MEAN, IMAGENET_STD

        mean = IMAGENET_MEAN if mean is None else mean
        std = IMAGENET_STD if std is None else std
    batch_u8 = np.ascontiguousarray(batch_u8, np.uint8)
    c = batch_u8.shape[-1]
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib = _build()
    if lib is None:
        return ((batch_u8.astype(np.float32) / 255.0) - mean) / std
    out = np.empty(batch_u8.shape, np.float32)
    # rc != 0 = channel count outside the kernel's affine table -> numpy
    rc = lib.normalize_tiles(
        batch_u8.ctypes.data, out.ctypes.data,
        batch_u8.size // c, mean.ctypes.data, std.ctypes.data, c,
    )
    if rc != 0:
        return ((batch_u8.astype(np.float32) / 255.0) - mean) / std
    return out


def luminance_occupancy(
    tiles_u8: np.ndarray, threshold: float
) -> np.ndarray:
    """NCHW uint8 tiles -> per-tile fraction of pixels with mean-channel
    luminance below ``threshold`` (== ``segment_foreground`` +
    ``select_tiles`` occupancy, computed in one pass)."""
    tiles_u8 = np.ascontiguousarray(tiles_u8, np.uint8)
    n, c, h, w = tiles_u8.shape
    lib = _build()
    if lib is None:
        # mirror the C kernel bit-for-bit: exact integer channel sums
        # compared against float32(threshold) * float32(c), so tile
        # selection is identical with or without a toolchain
        lum_sum = tiles_u8.astype(np.int32).sum(axis=1)
        thr = np.float32(threshold) * np.float32(c)
        count = (lum_sum.astype(np.float32) < thr).sum(axis=(-2, -1))
        return (count.astype(np.float32) / np.float32(h * w)).astype(np.float32)
    out = np.empty(n, np.float32)
    lib.luminance_occupancy(
        tiles_u8.ctypes.data, n, c, h, w, ctypes.c_float(threshold),
        out.ctypes.data,
    )
    return out


def pad_sequences(seqs: Sequence[np.ndarray], max_len: int) -> np.ndarray:
    """List of float32 [len_i, dim] -> zero-padded [n, max_len, dim]."""
    n = len(seqs)
    dim = seqs[0].shape[1]
    seqs = [np.ascontiguousarray(s, np.float32) for s in seqs]
    for s in seqs:
        # validated for BOTH paths: the C kernel trusts `dim` (a mismatch
        # would read past the buffer) and the numpy fallback would silently
        # broadcast
        if s.ndim != 2 or s.shape[1] != dim:
            raise ValueError(
                f"pad_sequences: expected [len, {dim}] sequences, got {s.shape}"
            )
    lib = _build()
    if lib is None:
        out = np.zeros((n, max_len, dim), np.float32)
        for i, s in enumerate(seqs):
            rows = min(len(s), max_len)
            out[i, :rows] = s[:rows]
        return out
    # per-sequence pointers: no concatenate (which would copy every row an
    # extra time before the kernel copies it again)
    ptrs = (ctypes.c_void_p * n)(*[s.ctypes.data for s in seqs])
    lengths = np.asarray([len(s) for s in seqs], np.int64)
    out = np.empty((n, max_len, dim), np.float32)
    lib.pad_sequences(
        ptrs, lengths.ctypes.data, n, max_len, dim, out.ctypes.data
    )
    return out
