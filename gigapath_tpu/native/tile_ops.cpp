// Native host-side kernels for the tile ingestion path.
//
// The reference leans on external native code for every hot loop (SURVEY
// §2.9: flash-attn/xformers CUDA for attention, openslide C for WSI IO).
// The TPU compute path is Pallas/XLA; this file is the native piece of the
// *host* runtime: the per-tile preprocessing loops that feed the device.
// Exposed via ctypes (gigapath_tpu/native/__init__.py) with numpy fallbacks.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 (see _build() in __init__.py).

#include <cstdint>
#include <cstddef>

extern "C" {

// uint8 NHWC tiles -> float32 normalized (value/255 - mean) / std.
// The transform hot loop of gigapath/pipeline.py:106-115 (resize/crop stay
// in PIL; the scale+normalize is the O(N*H*W*C) part).
// Returns 0 on success, -1 when channels is out of range (the Python
// binding then falls back to numpy): the per-channel affine table is a
// fixed-size stack array, and indexing past it would be undefined behavior.
int normalize_tiles(const uint8_t* in, float* out, int64_t n_pixels,
                    const float* mean, const float* std_, int channels) {
  constexpr int kMaxChannels = 8;
  if (channels < 1 || channels > kMaxChannels) {
    return -1;
  }
  // precompute per-channel affine: out = px * a[c] + b[c]
  float a[kMaxChannels];
  float b[kMaxChannels];
  for (int c = 0; c < channels; ++c) {
    a[c] = 1.0f / (255.0f * std_[c]);
    b[c] = -mean[c] / std_[c];
  }
  for (int64_t i = 0; i < n_pixels; ++i) {
    const uint8_t* px = in + i * channels;
    float* o = out + i * channels;
    for (int c = 0; c < channels; ++c) {
      o[c] = static_cast<float>(px[c]) * a[c] + b[c];
    }
  }
  return 0;
}

// Per-tile foreground occupancy from NCHW uint8 tiles: fraction of pixels
// whose mean-channel luminance is below `threshold` (the
// segment_foreground + select_tiles hot loop,
// gigapath_tpu/preprocessing/create_tiles_dataset.py).
void luminance_occupancy(const uint8_t* tiles, int64_t n, int64_t c,
                         int64_t h, int64_t w, float threshold,
                         float* occupancy) {
  const int64_t plane = h * w;
  for (int64_t t = 0; t < n; ++t) {
    const uint8_t* tile = tiles + t * c * plane;
    int64_t count = 0;
    for (int64_t p = 0; p < plane; ++p) {
      int32_t sum = 0;
      for (int64_t ch = 0; ch < c; ++ch) {
        sum += tile[ch * plane + p];
      }
      if (static_cast<float>(sum) < threshold * static_cast<float>(c)) {
        ++count;
      }
    }
    occupancy[t] = static_cast<float>(count) / static_cast<float>(plane);
  }
}

// Pad a ragged [len, dim] float32 sequence list into one [n, max_len, dim]
// zero-padded batch (the collate hot loop, data/collate.py:pad_tensors).
// `seqs[i]` points at sequence i's rows — per-sequence pointers so the
// caller never has to concatenate (a full extra copy) first.
void pad_sequences(const float* const* seqs, const int64_t* lengths,
                   int64_t n, int64_t max_len, int64_t dim, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    const float* src = seqs[i];
    float* dst = out + i * max_len * dim;
    const int64_t rows = lengths[i] < max_len ? lengths[i] : max_len;
    for (int64_t r = 0; r < rows * dim; ++r) {
      dst[r] = src[r];
    }
    for (int64_t r = rows * dim; r < max_len * dim; ++r) {
      dst[r] = 0.0f;
    }
  }
}

}  // extern "C"
