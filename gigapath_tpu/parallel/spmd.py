"""SPMD training-step builders over a named mesh.

One ``jit``-compiled train step, sharded by annotation only: batch over
``data``, tokens over ``seq``, tensor-parallel kernels over ``model``
(:mod:`gigapath_tpu.parallel.sharding`). Gradient all-reduce over ``data``
is inserted by XLA — the explicit NCCL choreography of the reference
(SURVEY §5.8) has no counterpart here by design.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray, task: str = "multi_class") -> jnp.ndarray:
    if task == "multi_label":
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    *,
    task: str = "multi_class",
    loss_fn: Optional[Callable] = None,
) -> Callable:
    """Returns ``train_step(params, opt_state, batch, rng) ->
    (params, opt_state, loss)`` for a classification model taking
    ``(images, coords)``. Pure and jittable; shard by device_put-ing the
    inputs with NamedShardings and wrapping in ``jax.jit``."""

    def _loss(params, batch: Dict[str, Any], rng):
        logits = model.apply(
            {"params": params},
            batch["images"],
            batch["coords"],
            deterministic=False,
            rngs={"dropout": rng},
        )
        if loss_fn is not None:
            return loss_fn(logits, batch["labels"])
        return cross_entropy_loss(logits, batch["labels"], task)

    def train_step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(_loss)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def make_eval_step(model) -> Callable:
    def eval_step(params, batch):
        return model.apply(
            {"params": params}, batch["images"], batch["coords"], deterministic=True
        )

    return eval_step
