"""SPMD training-step builders over a named mesh.

One ``jit``-compiled train step, sharded by annotation only: batch over
``data``, tokens over ``seq``, tensor-parallel kernels over ``model``
(:mod:`gigapath_tpu.parallel.sharding`). Gradient all-reduce over ``data``
is inserted by XLA — the explicit NCCL choreography of the reference
(SURVEY §5.8) has no counterpart here by design.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray, task: str = "multi_class") -> jnp.ndarray:
    if task == "multi_label":
        return optax.sigmoid_binary_cross_entropy(logits, labels).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def collect_moe_l_aux(intermediates: Dict[str, Any]) -> jnp.ndarray:
    """Sum every ``moe_l_aux`` sown by Encoder/Decoder stacks (see
    ``architecture/encoder.py``); 0 when the model has no MoE layers."""
    total = jnp.float32(0.0)
    flat = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in flat:
        if any(getattr(p, "key", None) == "moe_l_aux" for p in path):
            total = total + jnp.asarray(leaf, jnp.float32)
    return total


def make_train_step(
    model,
    optimizer: optax.GradientTransformation,
    *,
    task: str = "multi_class",
    loss_fn: Optional[Callable] = None,
    moe_aux_loss_weight: float = 0.0,
) -> Callable:
    """Returns ``train_step(params, opt_state, batch, rng) ->
    (params, opt_state, loss)`` for a classification model taking
    ``(images, coords)``. Pure and jittable; shard by device_put-ing the
    inputs with NamedShardings and wrapping in ``jax.jit``.

    ``moe_aux_loss_weight`` adds the GShard balance loss sown by MoE layers
    (the reference computes l_aux in the gate and hands it to the criterion
    wrapper; here it rides the intermediates collection)."""

    def _loss(params, batch: Dict[str, Any], rng):
        logits, mutated = model.apply(
            {"params": params},
            batch["images"],
            batch["coords"],
            deterministic=False,
            rngs={"dropout": rng},
            mutable=["intermediates"],
        )
        if loss_fn is not None:
            loss = loss_fn(logits, batch["labels"])
        else:
            loss = cross_entropy_loss(logits, batch["labels"], task)
        if moe_aux_loss_weight:
            loss = loss + moe_aux_loss_weight * collect_moe_l_aux(
                mutated.get("intermediates", {})
            )
        return loss

    def train_step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(_loss)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def make_eval_step(model) -> Callable:
    def eval_step(params, batch):
        return model.apply(
            {"params": params}, batch["images"], batch["coords"], deterministic=True
        )

    return eval_step
