"""Parameter/activation sharding rules (GSPMD annotations).

Tensor-parallel layout for the transformer stack: attention and FFN kernels
split over the ``model`` axis (column-parallel fc1/q/k/v, row-parallel
fc2/out_proj), everything else replicated; optional ZeRO-style sharding of
the largest replicated kernels over ``data``. XLA inserts the matching
collectives — this file contains *only* layout decisions, no communication
code. (The reference has no TP at all, SURVEY §2.6; FSDP maps to the ZeRO
rule here.)
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# leaf module name -> (spec for `kernel`); biases/scales stay replicated.
# Coverage of these lists against every Dense construction site in the
# model stack is enforced mechanically by gigalint GL003
# (tools/gigalint/sharding_coverage.py) — a name in neither list falls
# through to replicated P() below, silently.
_COLUMN_PARALLEL = (
    "q_proj", "k_proj", "v_proj", "fc1", "gate",
    # retention gate projection: [E, value_dim], split like q/k/v
    "g_proj",
    # ViT packed qkv: [D, 3D], output-dim split (megatron fused-qkv rule)
    "qkv",
    # vocab head: [E, V], vocab-dim split (softmax gathers under GSPMD)
    "output_projection",
)
_ROW_PARALLEL = (
    "out_proj", "fc2",
    # ViT attention output projection (models/tile_encoder.py); the
    # PatchEmbed Dense shares the name — its [in_chans, E] kernel also
    # input-dim splits correctly (GSPMD inserts the gather)
    "proj",
)

# Sequence-parallel collective registry: the explicit communication the
# library is ALLOWED to perform over the ``seq`` mesh axis, by module.
# Unlike the GSPMD parameter rules above (layout only, XLA inserts the
# collectives), the seq-parallel attention paths issue collectives BY
# HAND inside shard_map — each one is a deliberate sharding decision
# (what crosses the axis, and in which schedule) and must be recorded
# here so the layout story stays auditable in one file. Coverage is
# enforced mechanically by gigalint GL009
# (tools/gigalint/sharding_coverage.py): a ``ppermute``/``all_gather``
# call in library code whose module has no matching entry flags.
#
# Keys are module-path suffixes; values the sanctioned collective names.
_SEQ_COLLECTIVES: Dict[str, tuple] = {
    # gathered dilated branches: the hoisted per-call all_gather of
    # rank-local valid counts ([W, B] ints, shared by every gathered
    # branch), the legacy full-segment K/V all_gather (fallback + parity
    # oracle), and the ring schedule's sub-ring ppermute rotation of
    # local sparse K/V chunks (GIGAPATH_RING_ATTN, fwd + reverse ring in
    # the custom VJP)
    "gigapath_tpu/ops/dilated_attention.py": ("all_gather", "ppermute"),
}


def shard_map_compat():
    """(shard_map, check_kwargs) across jax spellings: jax >= 0.9 exposes
    ``jax.shard_map`` and checks vma (``check_vma`` — pallas-opaque, so
    the kwarg disables it); 0.4.x has the experimental spelling and
    ``check_rep``. The ONE compat shim — scripts and tests building
    seq-parallel regions by hand unpack it instead of re-deriving the
    signature dance per call site::

        shard_map, check_kw = shard_map_compat()
        fn = shard_map(body, mesh=mesh, in_specs=..., out_specs=..., **check_kw)
    """
    import inspect

    try:  # jax >= 0.9 spells it jax.shard_map
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    sig = inspect.signature(shard_map).parameters
    check_kw = (
        {"check_vma": False} if "check_vma" in sig else {"check_rep": False}
    )
    return shard_map, check_kw


def param_spec(
    path_names,
    leaf,
    *,
    model_axis: str | None = "model",
    expert_axis: str | None = None,
) -> P:
    """PartitionSpec for one parameter, by its module path. Either axis may
    be None, disabling that rule."""
    if expert_axis and "experts" in path_names and hasattr(leaf, "ndim") and leaf.ndim >= 1:
        # vmapped MoE expert params carry a leading E axis
        # (ops/moe/moe_layer.py) — shard it over the mesh ``expert`` axis
        return P(expert_axis, *([None] * (leaf.ndim - 1)))
    if (
        model_axis
        and path_names
        and path_names[-1] == "kernel"
        and hasattr(leaf, "ndim")
        and leaf.ndim == 2
    ):
        owner = path_names[-2] if len(path_names) >= 2 else ""
        if owner in _COLUMN_PARALLEL:
            return P(None, model_axis)
        if owner in _ROW_PARALLEL:
            return P(model_axis, None)
    return P()


def param_shardings(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """NamedSharding tree for a param tree under ``mesh``.

    If the mesh has no ``model`` axis (or size 1), everything is replicated —
    the rules degrade gracefully to pure DP/SP meshes.
    """
    has_model = "model" in mesh.axis_names and mesh.shape["model"] > 1
    expert_axis = (
        "expert"
        if "expert" in mesh.axis_names and mesh.shape["expert"] > 1
        else None
    )

    def one(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        spec = param_spec(
            names,
            leaf,
            model_axis="model" if has_model else None,
            expert_axis=expert_axis,
        )
        return NamedSharding(mesh, spec)

    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = [one(path, leaf) for path, leaf in flat[0]]
    return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), leaves)


def apply_shardings(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """device_put the param tree with its sharding rules."""
    return jax.device_put(params, param_shardings(params, mesh))
