"""Device mesh construction — the single distributed-backend primitive.

The reference's entire communication surface (NCCL process groups, custom
all-gather/reduce-scatter autograd functions, MoE all-to-all groups —
SURVEY §2.6/§5.8) maps to one ``jax.sharding.Mesh`` with named axes:

- ``data``   — batch / ZeRO parameter sharding (DP group, ``component/utils.py:13``)
- ``seq``    — sequence/context parallelism (``dilated_attention.gather_kv``)
- ``model``  — tensor parallelism over hidden/head dims (absent in the
  reference; free on TPU via GSPMD)
- ``expert`` — MoE expert parallelism (``xmoe/global_groups.py``)

Collectives ride ICI when the mesh is built over a physical slice; XLA
inserts them from sharding annotations (GSPMD), so there is no hand-written
communication code outside shard_map regions.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MESH_AXES = ("data", "seq", "model", "expert")


def factorize(n: int, axes: Sequence[str]) -> Dict[str, int]:
    """Spread ``n`` devices over axes, preferring seq > data > model.

    Long-context is first-class: sequence parallelism gets devices first
    (the slide encoder's token count dwarfs batch size), then data, then
    tensor parallelism.
    """
    sizes = {a: 1 for a in axes}
    remaining = n
    order = [a for a in ("seq", "data", "model", "expert") if a in axes]
    i = 0
    while remaining > 1 and order:
        axis = order[i % len(order)]
        if remaining % 2 == 0:
            sizes[axis] *= 2
            remaining //= 2
        else:
            sizes[axis] *= remaining
            remaining = 1
        i += 1
    return sizes


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    axis_sizes: Optional[Dict[str, int]] = None,
    axes: Sequence[str] = ("data", "seq"),
    devices=None,
) -> Mesh:
    """Build a mesh over the first ``n_devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = factorize(n, axes)
    else:
        axes = tuple(axis_sizes.keys())
    shape = tuple(axis_sizes[a] for a in axes)
    assert int(np.prod(shape)) == n, f"mesh {axis_sizes} != {n} devices"
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_batch_seq(mesh: Mesh, batch_axis: str = "data", seq_axis: str = "seq") -> NamedSharding:
    """Sharding for [B, L, ...] activations: batch over data, tokens over seq."""
    names = mesh.axis_names
    spec = [batch_axis if batch_axis in names else None,
            seq_axis if seq_axis in names else None]
    return NamedSharding(mesh, PartitionSpec(*spec))
