"""Chunk-granular LongNetViT forward — the model half of streaming
chunked prefill.

:class:`StreamingEncoderSession` is the ``LongNetViT`` entry that
accepts an ingest stream instead of a dense ``[B, L, D]`` array: tile
chunks are patch-embedded + positionally embedded the moment they
arrive, layer 0's q/k/v projections and dilated-branch partial folds run
DURING ingest (overlapping stage-1 tile encoding with stage-2 folding —
the disaggregated pipeline's missing piece, ROADMAP item 4), and
``finalize()`` runs the remaining layers chunk-blocked through one
:class:`~gigapath_tpu.ops.streaming_prefill.StreamingPrefillState` per
layer. The residual stream lives as a list of per-chunk blocks from
ingest to readout; the raw tile-embedding sequence ``[B, L, in_chans]``
is never materialized, and the readout (cls row / masked global-pool
mean) folds across blocks by summation.

Layer math is the pure-function mirror of the flax modules the dense
path runs (``architecture/encoder.py`` + ``ops/attention.py`` +
``ops/feedforward.py``), reading the SAME param tree — pre-LN,
q/k/v/out projections, sub-LN on attention output and inside the FFN,
residuals — so the dense ``LongNetViT.__call__`` stays the parity
oracle at fwd 1e-5. :func:`check_streamable` refuses configurations the
mirror does not cover (multiway, MoE, xPos, deepnorm, post-LN, rel-pos
bias) instead of silently diverging; every registry slide-encoder arch
passes.

``feed`` tolerates OUT-OF-ORDER chunks: arrivals ahead of the fold
frontier are held and folded the moment their predecessors land, so the
executed fold sequence — and therefore the result, BIT-exact — is a
pure function of the slide geometry, not of delivery order (the dist
boundary's retransmit/reassignment parity contract extended through the
encoder).

This module is streaming-sanctioned for gigalint GL014: no chunk-axis
reassembly outside the ``*dense_fallback*`` oracle surface.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.ops import pos_embed as pe
from gigapath_tpu.ops.streaming_prefill import (
    StreamingPrefillState,
    chunk_bounds,
)

DEFAULT_CHUNK_TILES = 2048


def prefill_chunk_tiles(default: int = DEFAULT_CHUNK_TILES) -> int:
    """The ``GIGAPATH_PREFILL_CHUNK`` host flag (session-construction
    read, like the dist boundary's ``GIGAPATH_DIST_CHUNK_TILES`` — never
    at trace time): tiles per streaming-prefill chunk."""
    from gigapath_tpu.obs.runlog import env_number

    return int(env_number("GIGAPATH_PREFILL_CHUNK", default))


def encoder_config(model):
    """The EncoderConfig the dense path would build for ``model`` —
    derived through the same factory so the two paths can never read
    different hyperparameters."""
    from gigapath_tpu.models.longnet import make_longnet_from_name
    from gigapath_tpu.models.slide_encoder import get_optimal_segment_length

    segment_length = model.segment_length or get_optimal_segment_length(
        model.max_wsi_size, model.tile_size
    )
    _, cfg = make_longnet_from_name(
        model.encoder_name,
        dilated_ratio=model.dilated_ratio,
        segment_length=list(segment_length),
        drop_path_rate=model.drop_path_rate,
        dropout=model.dropout,
        dtype=model.dtype,
    )
    return cfg


def check_streamable(cfg) -> None:
    """Raise NotImplementedError for encoder features the streaming
    mirror does not implement. The gate is explicit so an unsupported
    config can never silently produce near-miss numbers."""
    unsupported = []
    if cfg.multiway:
        unsupported.append("multiway")
    if cfg.moe_freq:
        unsupported.append("moe")
    if cfg.xpos_rel_pos:
        unsupported.append("xpos_rel_pos")
    if cfg.deepnorm:
        unsupported.append("deepnorm")
    if not cfg.encoder_normalize_before:
        unsupported.append("post-LN")
    if cfg.rel_pos_buckets or cfg.max_rel_pos:
        unsupported.append("relative_position_bias")
    if cfg.layernorm_embedding:
        unsupported.append("layernorm_embedding")
    if cfg.vocab_size > 0 and not cfg.no_output_layer:
        unsupported.append("output_projection")
    if unsupported:
        raise NotImplementedError(
            "streaming prefill does not cover encoder features "
            f"{unsupported}; use the dense path (the fallback/oracle)"
        )


# ---------------------------------------------------------------------------
# pure-function mirrors of the flax layer math
# ---------------------------------------------------------------------------

def _layer_norm(x: jnp.ndarray, p: Dict[str, jnp.ndarray],
                eps: float) -> jnp.ndarray:
    """flax ``nn.LayerNorm`` mirror (fast-variance form, fp32 stats)."""
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    mean2 = (x32 * x32).mean(axis=-1, keepdims=True)
    var = jnp.maximum(mean2 - mean * mean, 0.0)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def _dense(x: jnp.ndarray, p: Dict[str, jnp.ndarray]) -> jnp.ndarray:
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def _embed_block(proj, embeds: jnp.ndarray, coords: jnp.ndarray, *,
                 embed_dim: int, tile_size: int, ngrids: int,
                 dtype) -> jnp.ndarray:
    """[c, in_chans] + [c, 2] -> [1, c, E]: patch embed + positional
    embedding computed from coords (no table, no sequence)."""
    x = embeds[None].astype(dtype)
    x = _dense(x, proj)
    pos = pe.pos_embed_for_coords(embed_dim, coords[None], tile_size, ngrids)
    return x + pos.astype(x.dtype)


def _qkv_block(lp, h_blk: jnp.ndarray, *, num_heads: int,
               eps: float) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pre-LN + q/k/v projections of one residual block ->
    ``[B, c, H, Dh]`` triples (EncoderLayer + MultiheadAttention entry)."""
    B, c, E = h_blk.shape
    Dh = E // num_heads
    xn = _layer_norm(h_blk, lp["self_attn_layer_norm"], eps)
    sa = lp["self_attn"]
    q = _dense(xn, sa["q_proj"]).reshape(B, c, num_heads, Dh)
    k = _dense(xn, sa["k_proj"]).reshape(B, c, num_heads, Dh)
    v = _dense(xn, sa["v_proj"]).reshape(B, c, num_heads, Dh)
    return q, k, v


def _post_attention_block(lp, h_blk: jnp.ndarray, attn_blk: jnp.ndarray,
                          *, eps: float, subln: bool) -> jnp.ndarray:
    """Everything after the attention core for one block: inner sub-LN,
    out projection, residual, FFN sublayer (fc1 -> fp32 gelu -> sub-LN
    -> fc2), residual. Mirrors EncoderLayer.__call__ at
    deterministic=True (dropout/drop-path no-ops)."""
    B, c, E = h_blk.shape
    sa = lp["self_attn"]
    a = attn_blk.astype(h_blk.dtype).reshape(B, c, E)
    if subln:
        a = _layer_norm(a, sa["inner_attn_ln"], eps)
    a = _dense(a, sa["out_proj"])
    h = h_blk + a

    ffn = lp["ffn"]
    f = _layer_norm(h, lp["final_layer_norm"], eps)
    f = _dense(f, ffn["fc1"])
    f = jax.nn.gelu(f.astype(jnp.float32)).astype(f.dtype)
    if subln:
        f = _layer_norm(f, ffn["ffn_layernorm"], eps)
    f = _dense(f, ffn["fc2"])
    return h + f


# ---------------------------------------------------------------------------
# the session
# ---------------------------------------------------------------------------

class StreamingEncoderSession:
    """One slide's streaming LongNetViT forward.

    ``feed(idx, tile_embeds [c, in_chans], coords [c, 2])`` consumes the
    deterministic chunk plan's chunks (``chunk_bounds(n_tiles,
    chunk_tiles)`` — the same cut the dist boundary ships), any arrival
    order; ``finalize()`` returns the same list of ``[1, embed_dim]``
    outputs as ``LongNetViT.__call__``. The cls token rides as its own
    single-row block at token position 0, so no chunk is ever
    concatenated with anything.
    """

    def __init__(
        self,
        model,
        params,
        n_tiles: int,
        *,
        chunk_tiles: Optional[int] = None,
        all_layer_embed: bool = False,
        dtype: Any = None,
        runlog=None,
    ):
        """``runlog``: optional obs run log — when set, every stage
        executable (embed / qkv / fold / post-attention) is wrapped in
        its own :class:`~gigapath_tpu.obs.watchdog.CompileWatchdog`, so
        per-shape compiles land as ``compile`` events and any retrace on
        a seen shape is flagged unexpected — the same observability
        contract the dense consumer's watched forward has."""
        cfg = encoder_config(model)
        check_streamable(cfg)
        self.model = model
        self.params = params
        self.cfg = cfg
        self.all_layer_embed = bool(all_layer_embed)
        self.dtype = dtype or model.dtype or jnp.float32
        self.n_tiles = int(n_tiles)
        self.chunk_tiles = int(chunk_tiles or prefill_chunk_tiles())
        self.tile_bounds = chunk_bounds(self.n_tiles, self.chunk_tiles)
        # token space: block 0 is the cls token; tile chunk i becomes
        # token block i+1 shifted by one position. Every tile block —
        # including the ragged final chunk — is PADDED to chunk_tiles
        # rows, with ``valid_len`` masking the suffix out of every
        # branch's keys and the readout: middle and tail chunks share
        # ONE block shape, so slides of every length share the same
        # compiled stage executables (the serving claim; the dense
        # oracle does the same with its 128-multiple alignment pad).
        self.token_bounds = ((0, 1),) + tuple(
            (1 + i * self.chunk_tiles, 1 + (i + 1) * self.chunk_tiles)
            for i in range(len(self.tile_bounds))
        )
        self.valid_tokens = 1 + self.n_tiles  # cls + real tiles
        # fold geometry from the ONE factory-built config (cfg), never
        # re-derived by hand — the single-source invariant
        self.segment_lengths = [int(s) for s in cfg.segment_length]
        self.dilated_ratios = [int(r) for r in cfg.dilated_ratio]
        self.num_heads = int(cfg.encoder_attention_heads)
        self.eps = float(cfg.layernorm_eps)
        self.subln = bool(cfg.subln)
        self.depth = int(cfg.encoder_layers)
        # THE fold plan resolution — once per session, never per chunk
        # or per fold (the registry stat test pins lookups == 1). The
        # geometry key is one fold pair's q/k/v block avals, so every
        # session sharing a chunk geometry shares the blessed entry;
        # the resolved PipelineFlags ride every fold call as a static
        # arg. Empty registry -> snapshot_flags() -> flags-default
        # dispatch, byte-identical to the pre-plan jnp fold.
        from gigapath_tpu.plan.executionplan import resolve_plan

        head_dim = int(self.model.embed_dim) // self.num_heads
        blk = jax.ShapeDtypeStruct(
            (1, self.chunk_tiles, self.num_heads, head_dim), self.dtype
        )
        self.fold_flags = resolve_plan("stream_fold", (blk, blk, blk))

        self._embed_fn = jax.jit(
            _embed_block,
            static_argnames=("embed_dim", "tile_size", "ngrids", "dtype"),
        )
        self._qkv_fn = jax.jit(
            _qkv_block, static_argnames=("num_heads", "eps")
        )
        self._post_fn = jax.jit(
            _post_attention_block, static_argnames=("eps", "subln")
        )
        self._fold_fn = None
        if runlog is not None:
            from gigapath_tpu.obs.watchdog import CompileWatchdog
            from gigapath_tpu.ops.streaming_prefill import fold_pair

            # one watchdog per stage: the cache-size retrace probe is
            # per-attached-callable, so stages must not share one
            self._embed_fn = CompileWatchdog(
                "stream.embed", runlog).wrap(self._embed_fn)
            self._qkv_fn = CompileWatchdog(
                "stream.qkv", runlog).wrap(self._qkv_fn)
            self._post_fn = CompileWatchdog(
                "stream.post", runlog).wrap(self._post_fn)

            def fold_key(*args, **kwargs):
                # the fold's branch geometry AND resolved flags are
                # STATIC kwargs: without them in the key, the second
                # branch's (or the plan-on path's) legitimate compile
                # would be flagged as a retrace of the first's
                return tuple(
                    (tuple(a.shape), str(a.dtype))
                    for a in args if hasattr(a, "shape")
                ) + (kwargs.get("segment_len"), kwargs.get("ratio"),
                     kwargs.get("flags"))

            self._fold_fn = CompileWatchdog("stream.fold", runlog).wrap(
                jax.jit(
                    fold_pair,
                    static_argnames=("segment_len", "ratio", "flags"),
                ),
                key_fn=fold_key,
            )
        self._h_blocks: List[Optional[jnp.ndarray]] = (
            [None] * len(self.token_bounds)
        )
        self._layer0 = self._new_state()
        self._held: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._next_tile_chunk = 0
        # the cls token is resident from the start: fold it immediately
        cls = self.params["cls_token"].astype(self.dtype).reshape(1, 1, -1)
        self._ingest_block(0, cls)

    # -- plumbing -----------------------------------------------------------

    def _new_state(self) -> StreamingPrefillState:
        return StreamingPrefillState(
            self.token_bounds, self.segment_lengths, self.dilated_ratios,
            valid_len=self.valid_tokens, fold_fn=self._fold_fn,
            flags=self.fold_flags,
        )

    def _layer_params(self, depth: int):
        return self.params["encoder"][f"layers_{depth}"]

    def _ingest_block(self, block_idx: int, h_blk: jnp.ndarray) -> None:
        """Store the residual block and fold it into layer 0 — the part
        of the stack that runs DURING ingest."""
        self._h_blocks[block_idx] = h_blk
        q, k, v = self._qkv_fn(
            self._layer_params(0), h_blk,
            num_heads=self.num_heads, eps=self.eps,
        )
        self._layer0.ingest(block_idx, q, k, v)

    # -- the public surface -------------------------------------------------

    @property
    def n_chunks(self) -> int:
        return len(self.tile_bounds)

    def expected_bounds(self, idx: int) -> Tuple[int, int]:
        return self.tile_bounds[idx]

    def feed(self, idx: int, tile_embeds, coords) -> int:
        """Deliver tile chunk ``idx`` (any order; the frontier buffer
        reorders — it holds raw chunks ahead of the frontier, so its
        residency is the delivery reorder window: O(1) for in-order
        producers, degrading toward the dense assembler's footprint
        only in the adversarial first-chunk-arrives-last case; see
        ``ops/streaming_prefill.py`` on bounding the window at the
        transport). Returns how many chunks have been FOLDED so far."""
        idx = int(idx)
        if not 0 <= idx < self.n_chunks:
            raise ValueError(f"chunk {idx} outside plan of {self.n_chunks}")
        a, b = self.tile_bounds[idx]
        tile_embeds = np.asarray(tile_embeds)
        if tile_embeds.shape[0] != b - a:
            raise ValueError(
                f"chunk {idx}: {tile_embeds.shape[0]} rows != tile range "
                f"[{a}, {b})"
            )
        if idx < self._next_tile_chunk or idx in self._held:
            return self._next_tile_chunk  # duplicate: already folded/held
        if coords is None:
            # the dense path's documented coords fallback (EmbeddingChunk
            # carries coords as Optional): zeros collapse the positional
            # signal to one grid cell but never crash or feed NaN grid
            # indices into the positional embedding
            coords = np.zeros((b - a, 2), np.float32)
        coords = np.asarray(coords, np.float32)
        if coords.shape[0] != b - a:
            raise ValueError(
                f"chunk {idx}: {coords.shape[0]} coord rows != tile "
                f"range [{a}, {b})"
            )
        pad = self.chunk_tiles - (b - a)
        if pad:  # ragged final chunk -> the one shared block shape;
            # the padded rows are masked out of every branch's keys
            # (valid_len) and out of the readout
            tile_embeds = np.pad(tile_embeds, ((0, pad), (0, 0)))
            coords = np.pad(coords, ((0, pad), (0, 0)))
        self._held[idx] = (tile_embeds, coords)
        while self._next_tile_chunk in self._held:
            i = self._next_tile_chunk
            embeds_i, coords_i = self._held.pop(i)
            h = self._embed_fn(
                self.params["patch_embed"]["proj"],
                jnp.asarray(embeds_i, jnp.float32),
                jnp.asarray(coords_i, jnp.float32),
                embed_dim=self.model.embed_dim,
                tile_size=self.model.tile_size,
                ngrids=self.model.slide_ngrids,
                dtype=self.dtype,
            )
            self._ingest_block(i + 1, h)
            self._next_tile_chunk += 1
        return self._next_tile_chunk

    def pending(self) -> List[int]:
        """Chunk indices not yet folded (missing or frontier-held)."""
        return [i for i in range(self._next_tile_chunk, self.n_chunks)
                if i not in self._held] + sorted(self._held)

    # -- consumer crash recovery (ISSUE 13) ---------------------------------

    def export_state(self) -> dict:
        """The session's recovery-critical state as a string-keyed
        pytree of host arrays: the ingest frontier, the resident
        per-block residual stream, the frontier-held raw chunks, and the
        layer-0 fold partials (:meth:`StreamingPrefillState.
        export_state`). Saved by the dist consumer through
        ``resilience/checkpoint.py``'s atomic manifest discipline;
        restored into a geometry-identical fresh session, the remaining
        feeds execute the same deterministic fold schedule and the final
        embedding is BIT-exact vs an uninterrupted run."""
        state: dict = {
            "next_tile_chunk": np.int64(self._next_tile_chunk),
        }
        for i, blk in enumerate(self._h_blocks):
            if blk is not None:
                state[f"h_{i}"] = np.asarray(jax.device_get(blk))
        for i, (embeds, coords) in self._held.items():
            state[f"held_{i}"] = {"embeds": np.asarray(embeds),
                                  "coords": np.asarray(coords)}
        state["layer0"] = self._layer0.export_state()
        return state

    def restore_state(self, state: dict) -> None:
        """Inverse of :meth:`export_state` — the session must have been
        constructed with the same (model, n_tiles, chunk_tiles)
        geometry; everything the constructor folded (the cls block) is
        overwritten wholesale by the restored frontier.

        Restored arrays are placed with the LIVE stage executables'
        output sharding (taken from the constructor's own cls-block
        fold): a restored block on the default SingleDeviceSharding
        next to mesh-placed fresh blocks would give every post-resume
        stage call a fresh jit cache key — one silent recompile per
        shape, exactly what the per-stage watchdogs flag."""
        sharding = None
        cls_qkv = getattr(self._layer0, "_qkv", {}).get(0)
        if cls_qkv is not None:
            sharding = getattr(cls_qkv[0], "sharding", None)

        def place(x):
            arr = jnp.asarray(x, self.dtype)
            if sharding is not None:
                try:
                    arr = jax.device_put(arr, sharding)
                except (ValueError, TypeError):
                    pass
            return arr

        self._next_tile_chunk = int(state["next_tile_chunk"])
        self._h_blocks = [None] * len(self.token_bounds)
        self._held = {}
        for key, value in state.items():
            if key.startswith("h_"):
                self._h_blocks[int(key[len("h_"):])] = place(value)
            elif key.startswith("held_"):
                self._held[int(key[len("held_"):])] = (
                    np.asarray(value["embeds"]),
                    np.asarray(value["coords"], np.float32),
                )
        self._layer0.restore_state(state["layer0"], sharding=sharding)

    def complete(self) -> bool:
        return self._next_tile_chunk == self.n_chunks

    def _run_layer(self, depth: int,
                   h_blocks: List[jnp.ndarray],
                   state: Optional[StreamingPrefillState]) -> List[jnp.ndarray]:
        lp = self._layer_params(depth)
        if state is None:
            state = self._new_state()
            for i, h in enumerate(h_blocks):
                state.ingest(i, *self._qkv_fn(
                    lp, h, num_heads=self.num_heads, eps=self.eps,
                ))
        attn_blocks = state.finalize()
        return [
            self._post_fn(lp, h, a, eps=self.eps, subln=self.subln)
            for h, a in zip(h_blocks, attn_blocks)
        ]

    def _readout(self, h_blocks: List[jnp.ndarray]) -> jnp.ndarray:
        """cls-row or global-pool readout + the model norm, folded
        across blocks by summation (never concatenated)."""
        if self.model.global_pool:
            total = 0.0
            count = 0
            for i, blk in enumerate(h_blocks[1:]):  # tiles, cls excluded
                # static per-block valid count: the tail block's padded
                # suffix rows are excluded from the mean, like the dense
                # path's pad_mask pooling
                a, b = self.tile_bounds[i]
                blk = blk[:, : b - a]
                total = total + blk.astype(jnp.float32).sum(axis=1)
                count += b - a
            pooled = total / jnp.maximum(jnp.float32(count), 1.0)
            return _layer_norm(
                pooled.astype(self.dtype), self.params["norm"],
                float(self.model.norm_eps),
            )
        cls_row = h_blocks[0][:, 0]
        return _layer_norm(
            cls_row, self.params["norm"], float(self.model.norm_eps)
        )

    def finalize(self) -> List[jnp.ndarray]:
        """Run the remaining layers chunk-blocked and read out — the
        same output list as ``LongNetViT.__call__(x, coords,
        all_layer_embed=...)``."""
        if not self.complete():
            raise RuntimeError(
                f"finalize with chunks still missing: {self.pending()}"
            )
        h_blocks = [b for b in self._h_blocks]
        assert all(b is not None for b in h_blocks)
        states = [h_blocks] if self.all_layer_embed else []
        h_blocks = self._run_layer(0, h_blocks, self._layer0)
        if self.all_layer_embed:
            states.append(h_blocks)
        for depth in range(1, self.depth):
            h_blocks = self._run_layer(depth, h_blocks, None)
            if self.all_layer_embed:
                states.append(h_blocks)
        if not self.all_layer_embed:
            # encoder_out carries the encoder's final LN; the all-layer
            # states list does not (dense-path parity,
            # architecture/encoder.py encoder_states vs encoder_out)
            final_ln = self.params["encoder"]["layer_norm"]
            states = [[
                _layer_norm(b, final_ln, self.eps) for b in h_blocks
            ]]
        return [self._readout(blocks) for blocks in states]

    # -- anytime embeddings (ROADMAP item 4 / ISSUE 19) ----------------------

    def _truncated_state(self, n_blocks: int,
                         valid_len: int) -> StreamingPrefillState:
        """A fold state over the FIRST ``n_blocks`` token blocks.
        ``total_len`` stays the full slide length so ``_branch_geometry``'s
        ``g = min(sl, L)`` clamp — and therefore the branch schedule and
        the compiled fold executables — is identical to the final pass;
        only the chunk list and the valid-key horizon shrink."""
        return StreamingPrefillState(
            self.token_bounds[:n_blocks], self.segment_lengths,
            self.dilated_ratios, total_len=self.token_bounds[-1][1],
            valid_len=valid_len, fold_fn=self._fold_fn,
            flags=self.fold_flags,
        )

    def peek(self) -> List[jnp.ndarray]:
        """Provisional embeddings from the chunks folded so far — the
        anytime read of the stream. Layer 0 comes straight off the LIVE
        running ``(out, lse)`` partials (:meth:`StreamingPrefillState.
        peek_blocks` — exact attention over the folded keys, nothing
        recomputed, nothing mutated); layers 1+ run chunk-blocked over
        the truncated block list through the SAME stage executables as
        ``finalize`` (same block shapes, same static fold geometry — a
        peek adds zero compiles once the stages are warm). Returns the
        same per-layer embed list shape as :meth:`finalize`; with every
        chunk folded the two are BIT-exact (identical op sequence) —
        the convergence anchor of the ``serve.stream_confidence``
        surface."""
        f = self._next_tile_chunk
        if f < 1:
            raise RuntimeError("peek before any tile chunk folded")
        n_blocks = 1 + f  # cls + folded tile chunks
        valid = 1 + min(self.n_tiles, f * self.chunk_tiles)
        h_blocks = [b for b in self._h_blocks[:n_blocks]]
        assert all(b is not None for b in h_blocks)
        states = [h_blocks] if self.all_layer_embed else []
        lp = self._layer_params(0)
        attn_blocks = self._layer0.peek_blocks()
        h_blocks = [
            self._post_fn(lp, h, a, eps=self.eps, subln=self.subln)
            for h, a in zip(h_blocks, attn_blocks)
        ]
        if self.all_layer_embed:
            states.append(h_blocks)
        for depth in range(1, self.depth):
            lp = self._layer_params(depth)
            state = self._truncated_state(n_blocks, valid)
            for i, h in enumerate(h_blocks):
                state.ingest(i, *self._qkv_fn(
                    lp, h, num_heads=self.num_heads, eps=self.eps,
                ))
            attn_blocks = state.finalize()
            h_blocks = [
                self._post_fn(lp, h, a, eps=self.eps, subln=self.subln)
                for h, a in zip(h_blocks, attn_blocks)
            ]
            if self.all_layer_embed:
                states.append(h_blocks)
        if not self.all_layer_embed:
            final_ln = self.params["encoder"]["layer_norm"]
            states = [[
                _layer_norm(b, final_ln, self.eps) for b in h_blocks
            ]]
        return [self._readout(blocks) for blocks in states]

    def lse_spread(self) -> float:
        """Layer-0 per-branch LSE spread off the live partials — the
        streaming numerics signal attached to ``stream_peek`` events.
        Syncs to host: call at peek cadence, never per fold."""
        return self._layer0.lse_spread()


def embeds_to_outputs(embeds: List) -> Dict[str, np.ndarray]:
    """The ONE encoder-output contract: a session's per-layer embed list
    -> the ``layer_{i}_embed`` / ``last_layer_embed`` dict of
    ``pipeline.run_inference_with_slide_encoder`` (shared by the serve
    streaming session and the pipeline chunk-iterator entry so the
    parity surfaces cannot diverge)."""
    outputs = {
        f"layer_{i}_embed": np.asarray(e, np.float32)
        for i, e in enumerate(embeds)
    }
    outputs["last_layer_embed"] = np.asarray(embeds[-1], np.float32)
    return outputs


def streaming_forward(
    model,
    params,
    tile_embeds,
    coords,
    *,
    chunk_tiles: Optional[int] = None,
    all_layer_embed: bool = False,
) -> List[jnp.ndarray]:
    """Dense-array convenience wrapper over the session — the surface
    the parity tests drive against ``model.apply`` (the oracle). Accepts
    ``[N, in_chans]`` or ``[1, N, in_chans]``."""
    tile_embeds = np.asarray(tile_embeds)
    coords = np.asarray(coords)
    if tile_embeds.ndim == 3:
        assert tile_embeds.shape[0] == 1, "streaming prefill folds B=1 slides"
        tile_embeds, coords = tile_embeds[0], coords[0]
    session = StreamingEncoderSession(
        model, params, tile_embeds.shape[0], chunk_tiles=chunk_tiles,
        all_layer_embed=all_layer_embed,
    )
    for i, (a, b) in enumerate(session.tile_bounds):
        session.feed(i, tile_embeds[a:b], coords[a:b])
    return session.finalize()
