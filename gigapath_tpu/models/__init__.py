from gigapath_tpu.models import slide_encoder  # noqa: F401  (registers archs)
