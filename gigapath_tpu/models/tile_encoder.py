"""Flax ViT-G/14 tile encoder (DINOv2-style) + timm checkpoint conversion.

The reference consumes the tile encoder entirely through timm
(``timm.create_model("hf_hub:prov-gigapath/prov-gigapath")``,
``gigapath/pipeline.py:126-128``); the architecture itself lives outside the
reference repo. The facts the reference pins: "ViT-G/14" with 1536-d output
(``README.md:83``), ~1.13 B params printed at load (``gigapath/pipeline.py:129``),
224 px input after resize-256/center-crop-224 (``gigapath/pipeline.py:106-115``).
The timm architecture matching those facts is ``vit_giant_patch14_dinov2``
overridden to patch 16 / embed 1536 / depth 40 / 24 heads / SwiGLU
(mlp_ratio 5.33334) / LayerScale: per-block params
qkv 7,082,496 + proj 2,360,832 + norms 6,144 + layerscales 3,072 +
swiglu-fc1 12,591,104 + swiglu-fc2 6,292,992 = 28,336,640; x40 plus patch
embed (1,181,184), cls (1,536), pos (302,592), final norm (3,072) =
**1,134,953,984** — the unique configuration reproducing the printed count
(a standard GELU MLP would give 1.39 B). Verified in
``tests/test_tile_encoder.py``.

TPU-first notes: attention rides the shared fused ``attention_with_lse``
(fp32 softmax statistics, bf16-safe); there is no interpolate-at-forward —
positional embeddings are resized once at conversion time so every shape
under ``jit`` is static; ``param_dtype`` lets the 1.13 B params live in bf16
end-to-end (no fp16 GradScaler needed on TPU).
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.obs import console
from gigapath_tpu.ops.attention import attention_with_lse
from gigapath_tpu.ops.droppath import DropPath
from gigapath_tpu.utils.registry import create_model_from_registry, register_model
from gigapath_tpu.utils.torch_convert import (
    convert_torch_entry,
    load_torch_state_dict,
    merge_into_params,
)

# ImageNet normalization used by the reference's tile transforms
# (gigapath/pipeline.py:113-114).
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


class PatchEmbedConv(nn.Module):
    """Conv patch embedding: [B, H, W, 3] -> [B, N, D] (timm ``patch_embed``)."""

    patch_size: int = 16
    embed_dim: int = 1536
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(
            self.embed_dim,
            kernel_size=(self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="proj",
        )(x)
        B, h, w, D = x.shape
        return x.reshape(B, h * w, D)


class LayerScale(nn.Module):
    """Per-channel learned residual scale (DINOv2 ``ls1``/``ls2``)."""

    dim: int
    init_values: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        gamma = self.param(
            "gamma",
            nn.initializers.constant(self.init_values),
            (self.dim,),
            self.param_dtype,
        )
        return x * gamma.astype(x.dtype)


def _dense(features: int, *, quant: str, quant_pallas: bool, dtype,
           param_dtype, name: str):
    """The quantized-tier seam: ``nn.Dense`` when ``quant`` is empty
    (the f32/bf16 fallback and parity oracle — byte-identical trace to
    the pre-quant program), else the ``QuantDense`` twin (same param
    names/shapes, so checkpoints and the sharding-rule name lists are
    oblivious). ``quant``/``quant_pallas`` come from the caller's
    ``PipelineFlags`` snapshot — never from the environment here."""
    if not quant:
        return nn.Dense(
            features, dtype=dtype, param_dtype=param_dtype, name=name
        )
    from gigapath_tpu.quant.qmatmul import QuantDense

    return QuantDense(
        features, mode=quant, use_pallas=quant_pallas, dtype=dtype,
        param_dtype=param_dtype, name=name,
    )


class ViTAttention(nn.Module):
    """Packed-qkv multi-head self-attention (timm ``Attention``).

    ``quant`` routes the qkv/proj matmuls through the quantized tier
    (gigapath_tpu/quant/); the ``+attn`` rider additionally computes
    the attention logits from dynamically-quantized int8 Q/K
    (quant/qflash.py) — f32 softmax statistics either way."""

    dim: int
    num_heads: int
    quant: str = ""
    quant_pallas: bool = False
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        B, N, D = x.shape
        H = self.num_heads
        hd = D // H
        qkv = _dense(
            3 * D, quant=self.quant, quant_pallas=self.quant_pallas,
            dtype=self.dtype, param_dtype=self.param_dtype, name="qkv"
        )(x)
        qkv = qkv.reshape(B, N, 3, H, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.quant and self.quant.endswith("+attn"):
            from gigapath_tpu.quant.qflash import q_flash_attention

            out, _ = q_flash_attention(q, k, v, use_pallas=self.quant_pallas)
        else:
            out, _ = attention_with_lse(q, k, v)
        out = out.reshape(B, N, D)
        return _dense(
            D, quant=self.quant, quant_pallas=self.quant_pallas,
            dtype=self.dtype, param_dtype=self.param_dtype, name="proj"
        )(out)


class SwiGLUPacked(nn.Module):
    """Packed SwiGLU MLP: fc1 -> chunk2 -> silu(x1) * x2 -> fc2 (timm
    ``SwiGLUPacked``/``GluMlp(gate_last=False)``)."""

    hidden_dim: int
    out_dim: int
    quant: str = ""
    quant_pallas: bool = False
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = _dense(
            self.hidden_dim, quant=self.quant,
            quant_pallas=self.quant_pallas, dtype=self.dtype,
            param_dtype=self.param_dtype, name="fc1"
        )(x)
        x1, x2 = jnp.split(x, 2, axis=-1)
        x = nn.silu(x1) * x2
        return _dense(
            self.out_dim, quant=self.quant,
            quant_pallas=self.quant_pallas, dtype=self.dtype,
            param_dtype=self.param_dtype, name="fc2"
        )(x)


class Mlp(nn.Module):
    """Standard ViT MLP: fc1 -> gelu -> fc2 (timm ``Mlp``)."""

    hidden_dim: int
    out_dim: int
    quant: str = ""
    quant_pallas: bool = False
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = _dense(
            self.hidden_dim, quant=self.quant,
            quant_pallas=self.quant_pallas, dtype=self.dtype,
            param_dtype=self.param_dtype, name="fc1"
        )(x)
        x = nn.gelu(x, approximate=False)
        return _dense(
            self.out_dim, quant=self.quant,
            quant_pallas=self.quant_pallas, dtype=self.dtype,
            param_dtype=self.param_dtype, name="fc2"
        )(x)


class ViTBlock(nn.Module):
    """Pre-norm transformer block with LayerScale + DropPath (timm/DINOv2)."""

    dim: int
    num_heads: int
    mlp_hidden_dim: int
    swiglu: bool = True
    init_values: Optional[float] = 1e-5
    drop_path: float = 0.0
    norm_eps: float = 1e-6
    quant: str = ""
    quant_pallas: bool = False
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=self.norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name=name,
        )
        dp = DropPath(drop_prob=self.drop_path)
        h = ViTAttention(
            self.dim,
            self.num_heads,
            quant=self.quant,
            quant_pallas=self.quant_pallas,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="attn",
        )(ln("norm1")(x))
        if self.init_values is not None:
            h = LayerScale(
                self.dim, self.init_values, param_dtype=self.param_dtype, name="ls1"
            )(h)
        x = x + dp(h, deterministic=deterministic)

        mlp_cls = SwiGLUPacked if self.swiglu else Mlp
        h = mlp_cls(
            self.mlp_hidden_dim,
            self.dim,
            quant=self.quant,
            quant_pallas=self.quant_pallas,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="mlp",
        )(ln("norm2")(x))
        if self.init_values is not None:
            h = LayerScale(
                self.dim, self.init_values, param_dtype=self.param_dtype, name="ls2"
            )(h)
        return x + dp(h, deterministic=deterministic)


class VisionTransformer(nn.Module):
    """DINOv2-style ViT: conv patch embed + cls token + learned pos embed +
    pre-norm blocks + final LN; ``token`` pooling returns the normed cls.

    ``__call__(images [B, H, W, 3]) -> [B, embed_dim]`` (num_classes=0 /
    feature mode, which is how the reference uses the tile encoder).
    ``forward_features`` returns all tokens ``[B, 1+N, D]`` for PCA-style
    visualization (reference ``demo/gigapath_pca_visualization*.py``).
    """

    img_size: int = 224
    patch_size: int = 16
    embed_dim: int = 1536
    depth: int = 40
    num_heads: int = 24
    mlp_ratio: float = 5.33334
    swiglu: bool = True
    init_values: Optional[float] = 1e-5
    drop_path_rate: float = 0.0
    norm_eps: float = 1e-6
    global_pool: str = "token"
    # quantized-weight tier ('' = off — the f32/bf16 fallback and parity
    # oracle; 'int8' / 'fp8_e4m3', optionally '+attn'): the value of the
    # caller's PipelineFlags.quant_tile snapshot (GIGAPATH_QUANT_TILE),
    # passed at construction so the traced program — and therefore the
    # jit cache key — is distinct per tier
    quant: str = ""
    quant_pallas: bool = False
    dtype: Any = None
    param_dtype: Any = jnp.float32

    @property
    def grid_size(self) -> int:
        return self.img_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.grid_size**2

    @property
    def mlp_hidden_dim(self) -> int:
        return int(self.embed_dim * self.mlp_ratio)

    @nn.compact
    def forward_features(
        self, x: jnp.ndarray, deterministic: bool = True
    ) -> jnp.ndarray:
        B = x.shape[0]
        x = PatchEmbedConv(
            self.patch_size,
            self.embed_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="patch_embed",
        )(x)
        cls_token = self.param(
            "cls_token",
            nn.initializers.normal(1e-6),
            (1, 1, self.embed_dim),
            self.param_dtype,
        )
        pos_embed = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, 1 + self.num_patches, self.embed_dim),
            self.param_dtype,
        )
        cls = jnp.broadcast_to(cls_token.astype(x.dtype), (B, 1, self.embed_dim))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + pos_embed.astype(x.dtype)

        dpr = np.linspace(0.0, self.drop_path_rate, self.depth)
        for i in range(self.depth):
            x = ViTBlock(
                self.embed_dim,
                self.num_heads,
                self.mlp_hidden_dim,
                swiglu=self.swiglu,
                init_values=self.init_values,
                drop_path=float(dpr[i]),
                norm_eps=self.norm_eps,
                quant=self.quant,
                quant_pallas=self.quant_pallas,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=f"blocks_{i}",
            )(x, deterministic=deterministic)
        return nn.LayerNorm(
            epsilon=self.norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="norm",
        )(x)

    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        x = self.forward_features(x, deterministic=deterministic)
        if self.global_pool == "avg":
            return x[:, 1:].mean(axis=1)
        return x[:, 0]


# --------------------------------------------------------------------------
# timm checkpoint conversion


def interpolate_pos_embed(
    pos_embed: np.ndarray, new_grid: int
) -> np.ndarray:
    """Bicubic-resize a [1, 1+g*g, D] pos table to [1, 1+new_grid^2, D].

    Counterpart of reference ``gigapath/pos_embed.py:85`` (torch
    ``F.interpolate(mode="bicubic")``), applied once at conversion time so
    forward shapes stay static under jit.
    """
    n_tok = pos_embed.shape[1] - 1
    g = int(math.isqrt(n_tok))
    assert g * g == n_tok, f"pos_embed patch count {n_tok} is not square"
    if g == new_grid:
        return pos_embed
    cls, patches = pos_embed[:, :1], pos_embed[:, 1:]
    D = patches.shape[-1]
    grid = patches.reshape(g, g, D)
    resized = jax.image.resize(
        jnp.asarray(grid, jnp.float32), (new_grid, new_grid, D), method="bicubic"
    )
    resized = np.asarray(resized).reshape(1, new_grid * new_grid, D)
    return np.concatenate([cls, resized], axis=1).astype(pos_embed.dtype)


def convert_timm_state_dict(
    state_dict: Dict[str, Any], target_grid: Optional[int] = None
) -> Dict[Tuple[str, ...], np.ndarray]:
    """timm ViT state dict -> ``{flax path: array}``.

    Handles the timm naming (``blocks.N.`` module lists, packed ``qkv``,
    ``ls1.gamma``); Linear kernels transpose and the patch-embed conv moves
    OIHW -> HWIO via :func:`convert_torch_entry`. ``target_grid`` resizes the
    positional table when checkpoint and model grids differ.
    """
    out: Dict[Tuple[str, ...], np.ndarray] = {}
    for key, value in state_dict.items():
        if key.startswith("head.") or key in ("mask_token",):
            continue  # feature mode: no classifier head
        key = re.sub(r"\bblocks\.(\d+)\b", r"blocks_\1", key)
        path, arr = convert_torch_entry(key, value)
        if path[0] == "pos_embed" and target_grid is not None:
            arr = interpolate_pos_embed(arr, target_grid)
        out[path] = arr
    return out


# --------------------------------------------------------------------------
# factories


@register_model
def gigapath_tile_enc(**kwargs) -> VisionTransformer:
    """The prov-gigapath ViT-G/14 tile encoder (1,134,953,984 params)."""
    defaults = dict(
        img_size=224,
        patch_size=16,
        embed_dim=1536,
        depth=40,
        num_heads=24,
        mlp_ratio=5.33334,
        swiglu=True,
        init_values=1e-5,
    )
    return VisionTransformer(**{**defaults, **kwargs})


@register_model
def vit_tile_enc_test(**kwargs) -> VisionTransformer:
    """Tiny smoke-test tile encoder (parallel of ``LongNet_test``)."""
    defaults = dict(
        img_size=32,
        patch_size=16,
        embed_dim=32,
        depth=2,
        num_heads=4,
        mlp_ratio=4.0,
        swiglu=True,
        init_values=1e-5,
    )
    return VisionTransformer(**{**defaults, **kwargs})


def init_params(
    model: VisionTransformer, rng: Optional[jax.Array] = None
) -> Dict[str, Any]:
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    x = jnp.zeros((1, model.img_size, model.img_size, 3), jnp.float32)
    return model.init(rng, x)["params"]


def create_tile_encoder(
    pretrained: str = "",
    model_arch: str = "gigapath_tile_enc",
    *,
    rng: Optional[jax.Array] = None,
    flags=None,
    **kwargs,
):
    """Build the tile encoder and optionally load a timm torch checkpoint.

    Returns ``(module, params)``; non-strict load with missing/unexpected key
    reporting, matching the slide-encoder factory and the reference's timm
    ``checkpoint_path`` loading (``gigapath/pipeline.py:126``).

    Quant-tier routing rides the plan seam: when the caller passes no
    explicit ``quant``/``quant_pallas`` kwargs, the tier is resolved
    ONCE through :func:`gigapath_tpu.plan.resolve_plan` at the arch's
    canonical image geometry — ``GIGAPATH_QUANT_TILE`` /
    ``GIGAPATH_QUANT_PALLAS`` where set, the registry's blessed
    ``tile_encoder.<arch>`` plan where not. An explicit kwarg (or a
    caller-held ``flags`` snapshot) pins the tier regardless; with no
    env, no plan and no kwarg the result is the byte-identical f32/bf16
    program, exactly as before the plan refactor.
    """
    model = create_model_from_registry(model_arch, **kwargs)
    if "quant" not in kwargs and "quant_pallas" not in kwargs:
        from gigapath_tpu.plan import resolve_plan

        shape = jax.ShapeDtypeStruct(
            (1, model.img_size, model.img_size, 3), jnp.float32
        )
        resolved = resolve_plan(f"tile_encoder.{model_arch}", (shape,), flags)
        if resolved.quant_tile:
            # rebuild with the resolved tier (module construction is a
            # frozen dataclass — params are untouched); the common
            # no-tier path keeps the one construction above
            model = create_model_from_registry(
                model_arch, quant=resolved.quant_tile,
                quant_pallas=resolved.quant_pallas, **kwargs,
            )
    params = init_params(model, rng=rng)
    if pretrained and os.path.isdir(pretrained) and os.path.exists(
        os.path.join(pretrained, "manifest.json")
    ):
        # a quantized artifact (quant/convert.py): manifest-verified
        # load, then the f32 dequant contract back into the param tree
        # (QuantDense re-quantizes in-graph to the identical grid —
        # the round-trip is idempotent by construction)
        from gigapath_tpu.quant.convert import (
            _walk,
            dequantize_params,
            load_quantized,
        )

        qparams, qmeta = load_quantized(pretrained)
        converted = dict(_walk(dequantize_params(qparams)))
        params, missing, unexpected = merge_into_params(params, converted)
        console(
            f"\033[92m Loaded quantized tile-encoder artifact from "
            f"{pretrained} (mode={qmeta.get('mode')}, "
            f"{qmeta.get('n_quantized')} quantized kernels, "
            f"{len(missing)} missing, {len(unexpected)} unexpected) \033[00m"
        )
        return model, params
    if pretrained and os.path.exists(pretrained):
        state = load_torch_state_dict(pretrained)
        converted = convert_timm_state_dict(state, target_grid=model.grid_size)
        params, missing, unexpected = merge_into_params(params, converted)
        console(
            f"\033[92m Successfully loaded tile encoder from {pretrained} "
            f"({len(missing)} missing, {len(unexpected)} unexpected) \033[00m"
        )
    elif pretrained:
        console(
            f"\033[93m Tile-encoder weights not found at {pretrained}. "
            f"Randomly initialized the model! \033[00m"
        )
    return model, params


def count_params(model: VisionTransformer) -> int:
    """Analytic param count via abstract init (no 1.13 B-param allocation)."""
    x = jax.ShapeDtypeStruct((1, model.img_size, model.img_size, 3), jnp.float32)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0), x)
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(shapes))
