"""LongNet: encoder/decoder with dilated self-attention + factories.

Parity with reference ``torchscale/model/LongNet.py``: subclasses swapping
self-attention for DilatedAttention, and the ``make_longnet_from_name``
factory resolving a named config from the registry and injecting
dropout/drop-path/segment schedule.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

from flax import linen as nn

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.architecture.decoder import Decoder, DecoderLayer
from gigapath_tpu.architecture.encoder import Encoder, EncoderLayer
from gigapath_tpu.models import longnet_config
from gigapath_tpu.ops.dilated_attention import DilatedAttention


class LongNetDecoderLayer(DecoderLayer):
    """Decoder block with dilated self-attention (reference ``LongNet.py:17-28``)."""

    def build_self_attention(self) -> nn.Module:
        args = self.args
        assert args.segment_length and args.dilated_ratio, (
            "LongNet requires a segment_length/dilated_ratio schedule"
        )
        return DilatedAttention(
            embed_dim=args.decoder_embed_dim,
            num_heads=args.decoder_attention_heads,
            dropout=args.attention_dropout,
            self_attention=True,
            subln=args.subln,
            layernorm_eps=args.layernorm_eps,
            xpos_rel_pos=args.xpos_rel_pos,
            xpos_scale_base=args.xpos_scale_base,
            segment_length=tuple(args.segment_length),
            dilated_ratio=tuple(args.dilated_ratio),
            seq_parallel=args.seq_parallel,
            seq_axis_name=args.extras.get("seq_axis_name"),
            seq_axis_size=args.extras.get("seq_axis_size", 1),
            dtype=self.dtype,
            name="self_attn",
        )


class LongNetDecoder(Decoder):
    """Causal LongNet (reference ``LongNet.py:30-45``): supports full-sequence
    forward and eager incremental generation (``decode=True`` + a concrete
    cache index; see ``DilatedAttention._cached_attend_inputs``)."""

    layer_cls = LongNetDecoderLayer


class LongNetEncoderLayer(EncoderLayer):
    def build_self_attention(self) -> nn.Module:
        args = self.args
        assert args.segment_length and args.dilated_ratio, (
            "LongNet requires a segment_length/dilated_ratio schedule"
        )
        return DilatedAttention(
            embed_dim=args.encoder_embed_dim,
            num_heads=args.encoder_attention_heads,
            dropout=args.attention_dropout,
            self_attention=True,
            subln=args.subln,
            layernorm_eps=args.layernorm_eps,
            xpos_rel_pos=args.xpos_rel_pos,
            xpos_scale_base=args.xpos_scale_base,
            multiway=args.multiway,
            segment_length=tuple(args.segment_length),
            dilated_ratio=tuple(args.dilated_ratio),
            seq_parallel=args.seq_parallel,
            seq_axis_name=args.extras.get("seq_axis_name"),
            seq_axis_size=args.extras.get("seq_axis_size", 1),
            dtype=self.dtype,
            name="self_attn",
        )


class LongNetEncoder(Encoder):
    layer_cls = LongNetEncoderLayer


def make_longnet(args) -> Tuple[LongNetEncoder, EncoderConfig]:
    """Factory parity with reference ``make_longnet:78`` (arch name + overrides)."""
    cfg_dict = longnet_config.get_config(args.arch)
    if hasattr(args, "dropout"):
        cfg_dict["dropout"] = args.dropout
    if hasattr(args, "drop_path_rate"):
        cfg_dict["drop_path_rate"] = args.drop_path_rate
    cfg = EncoderConfig.from_dict(cfg_dict)
    return LongNetEncoder(args=cfg), cfg


def make_longnet_from_name(
    config_name: str,
    dilated_ratio: Union[str, list] = "[1, 2, 4, 8, 16]",
    segment_length: Union[str, list] = "[1024, 2048, 4096, 8192, 16384]",
    drop_path_rate: float = 0.1,
    dropout: float = 0.1,
    *,
    dtype: Any = None,
    seq_parallel: bool = False,
    seq_axis_name: Optional[str] = None,
    seq_axis_size: int = 1,
    checkpoint_activations: bool = False,
    **overrides,
) -> Tuple[LongNetEncoder, EncoderConfig]:
    """Build a LongNet encoder from a registry name.

    Returns ``(module, config)`` — flax modules are constructed lazily, so
    unlike the reference (which prints the param count at build,
    ``LongNet.py:127``) parameters exist only after ``module.init``.
    ``**overrides`` update any EncoderConfig field (e.g. ``moe_freq=2,
    moe_expert_count=8`` turns a registry config into its MoE variant).
    """
    cfg_dict = longnet_config.get_config(config_name)
    cfg_dict.update(
        dropout=dropout,
        drop_path_rate=drop_path_rate,
        dilated_ratio=dilated_ratio,
        segment_length=segment_length,
        seq_parallel=seq_parallel,
        checkpoint_activations=checkpoint_activations,
        **overrides,
    )
    cfg = EncoderConfig.from_dict(cfg_dict)
    cfg.extras["seq_axis_name"] = seq_axis_name
    cfg.extras["seq_axis_size"] = seq_axis_size
    return LongNetEncoder(args=cfg, dtype=dtype), cfg
