"""Named LongNet config registry.

Parity with reference ``torchscale/model/LongNetConfig.py`` — the same 22
named configurations (hyperparameter data, not code), expressed through a
generator instead of 330 lines of copy-pasted dicts. ``block_shift`` is kept
for name/key parity but is dead in the reference too (EncoderConfig never
consumes it, ``architecture/config.py:5-61``).

The "Vanilla" variants (dilated ratio [1], one 10^7-token segment) are the
reference's own statement that dilated attention with ratio 1 and an
unsegmented sequence equals full attention — our equivalence tests rely on
the same property.
"""

from __future__ import annotations

import re
from typing import Dict, List

SHORT_SCHEDULE = {"dilated_ratio": "[1, 2, 4]", "segment_length": "[512, 1024, 2048]"}
FULL_SCHEDULE = {
    "dilated_ratio": "[1, 2, 4, 8, 16]",
    "segment_length": "[1024, 2048, 4096, 8192, 16384]",
}
VANILLA_SCHEDULE = {"dilated_ratio": "[1]", "segment_length": "[10000000]"}


def _config(layers, dim, ffn, heads, schedule, block_shift=True):
    return {
        "encoder_layers": layers,
        "encoder_embed_dim": dim,
        "encoder_ffn_embed_dim": ffn,
        "encoder_attention_heads": heads,
        "flash_attention": True,
        "block_shift": block_shift,
        "use_xmoe": False,
        "moe_top1_expert": False,
        "moe_freq": 0,
        "moe_expert_count": 0,
        **schedule,
    }


REGISTRY: Dict[str, dict] = {
    "LongNet_8_layers_256_dim_mlp2": _config(8, 256, 512, 16, SHORT_SCHEDULE),
    "LongNet_12_layers_256_dim_mlp2": _config(12, 256, 512, 16, SHORT_SCHEDULE),
    "LongNet_8_layers_256_dim": _config(8, 256, 1024, 16, FULL_SCHEDULE),
    "LongNet_12_layers_256_dim": _config(12, 256, 1024, 16, FULL_SCHEDULE),
    "LongNet_3_layers_384_dim": _config(3, 384, 1536, 16, FULL_SCHEDULE),
    "LongNet_6_layers_384_dim": _config(6, 384, 1536, 16, FULL_SCHEDULE),
    "LongNet_12_layers_384_dim": _config(12, 384, 1536, 16, FULL_SCHEDULE),
    "LongNet_12_layers_512_dim": _config(12, 512, 1024, 8, SHORT_SCHEDULE),
    "LongNet_3_layers_768_dim": _config(3, 768, 3072, 16, FULL_SCHEDULE),
    "LongNet_6_layers_768_dim": _config(
        6, 768, 3072, 16,
        {"dilated_ratio": "[1, 2, 4, 8, 16]",
         "segment_length": "[1024, 4096, 8192, 16384, 65536]"},
    ),
    "LongNet_8_layers_768_dim": _config(8, 768, 3072, 16, FULL_SCHEDULE),
    "LongNet_12_layers_768_dim": _config(12, 768, 3072, 16, FULL_SCHEDULE),
    "LongNet_8_layers_1024_dim": _config(8, 1024, 4096, 16, FULL_SCHEDULE),
    "LongNet_24_layers_1024_dim": _config(24, 1024, 4096, 16, FULL_SCHEDULE),
    "LongNet_3_layers_1536_dim": _config(3, 1536, 6144, 16, FULL_SCHEDULE),
    "LongNet_6_layers_1536_dim": _config(6, 1536, 6144, 16, FULL_SCHEDULE),
    "LongNet_8_layers_1536_dim": _config(8, 1536, 6144, 16, FULL_SCHEDULE),
    "LongNet_12_layers_1536_dim": _config(12, 1536, 6144, 16, FULL_SCHEDULE),
    "LongNet_Vanilla_12_layers_256_dim": _config(12, 256, 512, 8, VANILLA_SCHEDULE, block_shift=False),
    "LongNet_Vanilla_6_layers_768_dim": _config(6, 768, 3072, 16, VANILLA_SCHEDULE, block_shift=False),
    "LongNet_Vanilla_6_layers_1536_dim": _config(6, 1536, 6144, 16, VANILLA_SCHEDULE, block_shift=False),
    "LongNet_test": _config(1, 192, 192, 8, SHORT_SCHEDULE),
}


_NAME_PATTERN = re.compile(
    r"^LongNet_(?P<layers>\d+)_layers_(?P<dim>\d+)_dim(?:_mlp(?P<mlp>[\d.]+))?$"
)


def get_config(name: str) -> dict:
    if name in REGISTRY:
        return dict(REGISTRY[name])
    # Synthesize configs for names following the reference naming scheme
    # (slide_encoder.py:106-108 generates names this way) that were never
    # added to the registry — e.g. custom depths/dims for ablations.
    m = _NAME_PATTERN.match(name)
    if m:
        dim = int(m.group("dim"))
        mlp = float(m.group("mlp")) if m.group("mlp") else 4.0
        return _config(int(m.group("layers")), dim, int(dim * mlp), 16, FULL_SCHEDULE)
    raise KeyError(f"unknown LongNet config: {name!r}; known: {sorted(REGISTRY)}")


def list_configs() -> List[str]:
    return sorted(REGISTRY)


def flagship_geometry() -> dict:
    """Single source of truth for the flagship slide encoder's geometry
    (gigapath_slide_enc12l768d): benchmark/profiling scripts derive shapes
    from here instead of re-hardcoding them (bench.py, scripts/)."""
    from gigapath_tpu.models.slide_encoder import get_optimal_segment_length

    cfg = get_config("LongNet_12_layers_768_dim")
    heads = cfg["encoder_attention_heads"]
    dim = cfg["encoder_embed_dim"]
    return {
        "depth": cfg["encoder_layers"],
        "embed_dim": dim,
        "heads": heads,
        "head_dim": dim // heads,
        "ffn_dim": cfg["encoder_ffn_embed_dim"],
        "in_chans": 1536,
        "segment_lengths": get_optimal_segment_length(),
        "dilated_ratios": [1, 2, 4, 8, 16],
    }
