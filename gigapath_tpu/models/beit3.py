"""BEiT-3: multiway vision-language encoder.

Parity with reference ``torchscale/model/BEiT3.py``: text embedding + conv
vision embedding (mask token, cls prepend), a multiway pair of learned
positional tables (vision positions / text positions, both fairseq-offset by
2), and the multiway Encoder. The ``multiway_split_position`` is the static
vision token count (cls + patches), so the two-branch split is free under
``jit``. Unused by the gigapath pipeline (the reference ships it dormant);
implemented for component parity.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
from flax import linen as nn

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.architecture.encoder import Encoder
from gigapath_tpu.ops.embedding import (
    PositionalEmbedding,
    TextEmbedding,
    VisionEmbedding,
)


class MultiwayPositionalEmbedding(nn.Module):
    """A/B positional tables split at ``split_position`` (reference
    ``MutliwayEmbedding``, multiway_network.py:47-55): branch A embeds the
    vision span with positions 2..n_vis+1, branch B the text span with
    positions 2..n_text+1."""

    num_a: int
    num_b: int
    embed_dim: int
    dtype: Any = None

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        positions: Optional[jnp.ndarray] = None,
        split_position: int = -1,
    ) -> jnp.ndarray:
        a = PositionalEmbedding(self.num_a, self.embed_dim, dtype=self.dtype, name="A")
        b = PositionalEmbedding(self.num_b, self.embed_dim, dtype=self.dtype, name="B")
        if self.is_initializing():
            a(x, positions)
            b(x, positions)
        if split_position == -1:
            return a(x, positions)
        if split_position == 0:
            return b(x, positions)
        x1, x2 = jnp.split(x, [split_position], axis=1)
        p1 = p2 = None
        if positions is not None:
            p1, p2 = positions[:, :split_position], positions[:, split_position:]
        return jnp.concatenate([a(x1, p1), b(x2, p2)], axis=1)


class BEiT3(nn.Module):
    args: EncoderConfig
    dtype: Any = None

    def setup(self):
        args = self.args
        assert args.multiway
        assert args.vocab_size > 0
        assert not args.share_encoder_input_output_embed
        # positions are added pre-scale; hold the reference's default
        # no_scale_embedding=True so the addition orders agree
        assert args.no_scale_embedding
        self.text_embed = TextEmbedding(
            args.vocab_size, args.encoder_embed_dim, dtype=self.dtype
        )
        self.vision_embed = VisionEmbedding(
            args.img_size,
            args.patch_size,
            args.in_chans,
            args.encoder_embed_dim,
            contain_mask_token=True,
            prepend_cls_token=True,
            dtype=self.dtype,
        )
        self.embed_positions = MultiwayPositionalEmbedding(
            num_a=self.vision_embed.num_position_embeddings() + 2,
            num_b=args.max_source_positions,
            embed_dim=args.encoder_embed_dim,
            dtype=self.dtype,
        )
        self.encoder = Encoder(args=self.args, dtype=self.dtype)

    def __call__(
        self,
        textual_tokens: Optional[jnp.ndarray] = None,
        visual_tokens: Optional[jnp.ndarray] = None,
        text_padding_position: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        vision_masked_position: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> Dict[str, Any]:
        assert textual_tokens is not None or visual_tokens is not None

        if self.is_initializing():
            # materialize every branch (both embedders, both multiway sides)
            # regardless of which modality the init inputs carry, so any
            # later call pattern finds a complete parameter tree
            args = self.args
            B = (textual_tokens if visual_tokens is None else visual_tokens).shape[0]
            if textual_tokens is None:
                textual_tokens = jnp.zeros((B, 1), jnp.int32)
            if visual_tokens is None:
                visual_tokens = jnp.zeros(
                    (B, args.img_size, args.img_size, args.in_chans), jnp.float32
                )

        if textual_tokens is None:
            x = self.vision_embed(visual_tokens, vision_masked_position)
            encoder_padding_mask = None
            multiway_split_position = -1
        elif visual_tokens is None:
            x = self.text_embed(textual_tokens)
            encoder_padding_mask = text_padding_position
            multiway_split_position = 0
        else:
            x1 = self.vision_embed(visual_tokens, vision_masked_position)
            multiway_split_position = x1.shape[1]
            x2 = self.text_embed(textual_tokens)
            x = jnp.concatenate([x1, x2], axis=1)
            if text_padding_position is not None:
                encoder_padding_mask = jnp.concatenate(
                    [
                        jnp.zeros(x1.shape[:-1], bool),
                        text_padding_position,
                    ],
                    axis=1,
                )
            else:
                encoder_padding_mask = None

        encoder_out = self.encoder(
            token_embeddings=x,
            encoder_padding_mask=encoder_padding_mask,
            attn_mask=attn_mask,
            multiway_split_position=multiway_split_position,
            positions=positions,
            embed_positions=self.embed_positions,
            features_only=True,
            deterministic=deterministic,
        )
        encoder_out["multiway_split_position"] = multiway_split_position
        return encoder_out
