"""LongNetViT slide encoder + factory.

Parity with reference ``gigapath/slide_encoder.py``: a MAE-style ViT over
tile *embeddings* — linear patch embed (1536 -> D), 2-D sincos positional
embedding looked up by tile coordinates, a cls token, a LongNet encoder, and
cls/global-pool readout per selected layer.

TPU-first deltas:

- the `(slide_ngrids^2+1, D)` positional table (~3 GB at defaults,
  ``slide_encoder.py:104``) is never materialized — embeddings are computed
  from coords on the fly with exact gather parity
  (:mod:`gigapath_tpu.ops.pos_embed`);
- ``get_optimal_segment_length`` (``slide_encoder.py:137-154``) returns the
  same log2-spaced schedule but as ints, and the model is built for a padded
  power-of-two bucket of sequence lengths so jit recompilation is bounded;
- bf16 activations via ``dtype=jnp.bfloat16`` replace fp16 GradScaler
  autocast;
- a chunk-granular entry (:func:`create_streaming_session`, streaming
  chunked prefill): tile chunks fold into the encoder as they arrive
  instead of assembling the dense ``[B, L, D]`` sequence first — the
  ``__call__`` path below stays the fallback and parity oracle
  (:mod:`gigapath_tpu.models.streaming_encoder`).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.models.longnet import make_longnet_from_name
from gigapath_tpu.obs import console
from gigapath_tpu.ops import pos_embed as pe
from gigapath_tpu.utils.registry import create_model_from_registry, register_model
from gigapath_tpu.utils.torch_convert import (
    convert_state_dict,
    load_torch_state_dict,
    merge_into_params,
)


class PatchEmbed(nn.Module):
    """Linear projection of tile embeddings (reference ``PatchEmbed:32-51``)."""

    in_chans: int = 1536
    embed_dim: int = 768
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return nn.Dense(
            self.embed_dim,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name="proj",
        )(x)


def get_optimal_segment_length(max_wsi_size: int = 262144, tile_size: int = 256) -> List[int]:
    """Log2-spaced 5-segment schedule from the max WSI size
    (parity with reference ``slide_encoder.py:137-154``)."""
    max_seq_len = (max_wsi_size // tile_size) ** 2
    exponents = np.linspace(np.log2(1024), int(np.log2(max_seq_len)), 5)
    return [int(x) for x in np.power(2, exponents).astype(int)]


class LongNetViT(nn.Module):
    """Slide encoder over ``(tile_embeddings [B,L,in_chans], coords [B,L,2])``.

    Returns a list of slide-level embeddings (one per selected layer when
    ``all_layer_embed``, else just the final), each ``[B, embed_dim]``.
    """

    in_chans: int = 1536
    embed_dim: int = 768
    depth: int = 12
    slide_ngrids: int = 1000
    tile_size: int = 256
    max_wsi_size: int = 262144
    global_pool: bool = False
    dropout: float = 0.25
    drop_path_rate: float = 0.1
    norm_eps: float = 1e-6
    mlp_ratio: float = 4.0
    segment_length: Optional[List[int]] = None
    dilated_ratio: str = "[1, 2, 4, 8, 16]"
    dtype: Any = None
    checkpoint_activations: bool = False
    seq_parallel: bool = False
    seq_axis_name: Optional[str] = None
    seq_axis_size: int = 1

    @property
    def encoder_name(self) -> str:
        name = f"LongNet_{self.depth}_layers_{self.embed_dim}_dim"
        if self.mlp_ratio != 4.0:
            name += f"_mlp{self.mlp_ratio:g}"
        return name

    def coords_to_pos(self, coords: jnp.ndarray) -> jnp.ndarray:
        return pe.coords_to_pos(coords, self.tile_size, self.slide_ngrids)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        coords: jnp.ndarray,
        all_layer_embed: bool = False,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> List[jnp.ndarray]:
        """``pad_mask``: optional [B, L] bool, True = VALID tile (the
        collate convention, data/collate.py). Padded suffix tokens are
        zeroed, excluded from every attention branch's keys, and excluded
        from the global-pool mean."""
        B, L, _ = x.shape
        x = PatchEmbed(self.in_chans, self.embed_dim, dtype=self.dtype, name="patch_embed")(x)

        # positional embedding computed from coords (no 3 GB table)
        pos = pe.pos_embed_for_coords(self.embed_dim, coords, self.tile_size, self.slide_ngrids)
        x = x + pos.astype(x.dtype)

        cls_token = self.param(
            "cls_token", nn.initializers.normal(0.02), (1, 1, self.embed_dim)
        )
        # cls positional embedding is table row 0 == zeros, so cls = cls_token
        cls = jnp.broadcast_to(cls_token.astype(x.dtype), (B, 1, self.embed_dim))
        x = jnp.concatenate([cls, x], axis=1)

        segment_length = self.segment_length or get_optimal_segment_length(
            self.max_wsi_size, self.tile_size
        )
        encoder, _ = make_longnet_from_name(
            self.encoder_name,
            dilated_ratio=self.dilated_ratio,
            segment_length=list(segment_length),
            drop_path_rate=self.drop_path_rate,
            dropout=self.dropout,
            dtype=self.dtype,
            seq_parallel=self.seq_parallel,
            seq_axis_name=self.seq_axis_name,
            seq_axis_size=self.seq_axis_size,
            checkpoint_activations=self.checkpoint_activations,
        )
        encoder = type(encoder)(args=encoder.args, dtype=self.dtype, name="encoder")

        encoder_padding_mask = None
        if pad_mask is not None:
            # cls (position 0) is always valid; model convention is True=pad
            encoder_padding_mask = jnp.concatenate(
                [jnp.zeros((B, 1), bool), ~pad_mask.astype(bool)], axis=1
            )

        # TPU alignment: L+1 (the cls token) is odd, which costs ~20% in the
        # attention kernels (odd segment reshapes defeat Mosaic tiling). Pad
        # the internal sequence to a 128 multiple with a *concrete* suffix
        # mask — a static valid length downstream, so the Pallas path and
        # trace-time tail masks absorb it for free. Skipped under sequence
        # parallelism (gather_kv branches don't take a valid length yet;
        # shard lengths are the caller's alignment concern there).
        L1 = x.shape[1]
        pad_to = L1 if self.seq_parallel else -(-L1 // 128) * 128
        if pad_to != L1:
            x = jnp.pad(x, ((0, 0), (0, pad_to - L1), (0, 0)))
            tail = np.zeros((B, pad_to), bool)
            tail[:, L1:] = True
            if encoder_padding_mask is None:
                encoder_padding_mask = tail
            else:
                encoder_padding_mask = jnp.concatenate(
                    [encoder_padding_mask, jnp.ones((B, pad_to - L1), bool)],
                    axis=1,
                )

        out = encoder(
            token_embeddings=x,
            encoder_padding_mask=encoder_padding_mask,
            return_all_hiddens=all_layer_embed,
            deterministic=deterministic,
        )
        x_list = out["encoder_states"] if all_layer_embed else [out["encoder_out"]]
        if pad_to != L1:
            x_list = [h[:, :L1] for h in x_list]

        norm = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.dtype, name="norm")
        outcomes = []
        for h in x_list:
            if self.global_pool:
                if pad_mask is not None:
                    valid = pad_mask.astype(h.dtype)[..., None]
                    pooled = (h[:, 1:, :] * valid).sum(axis=1) / jnp.clip(
                        valid.sum(axis=1), 1.0
                    )
                else:
                    pooled = h[:, 1:, :].mean(axis=1)
                outcomes.append(norm(pooled))
            else:
                outcomes.append(norm(h)[:, 0])
        return outcomes


def _arch(defaults: dict, kwargs: dict) -> LongNetViT:
    return LongNetViT(**{**defaults, **kwargs})


@register_model
def gigapath_slide_enc12l768d(**kwargs):
    return _arch(dict(embed_dim=768, depth=12, mlp_ratio=4.0, norm_eps=1e-6), kwargs)


@register_model
def gigapath_slide_enc24l1024d(**kwargs):
    return _arch(dict(embed_dim=1024, depth=24, mlp_ratio=4.0, norm_eps=1e-6), kwargs)


@register_model
def gigapath_slide_enc12l1536d(**kwargs):
    return _arch(dict(embed_dim=1536, depth=12, mlp_ratio=4.0, norm_eps=1e-6), kwargs)


@register_model
def gigapath_slide_enc_tiny(**kwargs):
    """2-layer/32-dim smoke-test arch (parallel of ``LongNet_test``,
    reference LongNetConfig.py:321-334)."""
    return _arch(
        dict(
            embed_dim=32,
            depth=2,
            mlp_ratio=2.0,
            norm_eps=1e-6,
            segment_length=[16, 32],
            dilated_ratio="[1, 2]",
        ),
        kwargs,
    )


def create_streaming_session(
    model: LongNetViT,
    params,
    n_tiles: int,
    *,
    chunk_tiles: Optional[int] = None,
    all_layer_embed: bool = False,
):
    """The chunk-granular ``LongNetViT`` entry (streaming chunked
    prefill): returns a
    :class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`
    whose ``feed(idx, tile_embeds, coords)`` consumes the deterministic
    chunk plan in any arrival order and whose ``finalize()`` returns the
    same output list as ``model.apply`` — which remains the dense
    fallback and parity oracle. ``chunk_tiles`` defaults to the
    ``GIGAPATH_PREFILL_CHUNK`` host flag."""
    from gigapath_tpu.models.streaming_encoder import StreamingEncoderSession

    return StreamingEncoderSession(
        model, params, n_tiles, chunk_tiles=chunk_tiles,
        all_layer_embed=all_layer_embed,
    )


def init_params(model: LongNetViT, rng: Optional[jax.Array] = None, seq_len: int = 4):
    """Initialize a param tree (tiny dummy inputs; shapes are L-independent).

    Init runs under ``jit``: eager flax init dispatches each initializer as
    its own device op, which over the remote (axon) TPU tunnel costs a round
    trip per parameter — measured 217 s for the 86M-param flagship vs one
    ~5 s compile jitted."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    x = jnp.zeros((1, seq_len, model.in_chans), jnp.float32)
    coords = jnp.zeros((1, seq_len, 2), jnp.float32)
    variables = jax.jit(model.init)(rng, x, coords)
    # No sub-LN init rescale here: the reference's initialize_vit_weights
    # re-inits every nn.Linear with xavier_uniform AFTER the encoder applied
    # its sub-LN scaling (slide_encoder.py:134-135 overwrites
    # encoder.py:254-270), so the effective reference init is plain xavier —
    # which is exactly what the flax modules use. apply_init_scaling remains
    # available for standalone make_longnet() users (parity with that path).
    return variables["params"]


def create_model(
    pretrained: str = "",
    model_arch: str = "gigapath_slide_enc12l768d",
    in_chans: int = 1536,
    *,
    rng: Optional[jax.Array] = None,
    **kwargs,
):
    """Build a slide encoder and optionally load a (torch) checkpoint.

    Returns ``(module, params)``. Parity with reference ``create_model:226``:
    local ``slide_encoder.pth`` paths load non-strictly with missing /
    unexpected key reporting; absent checkpoints leave random init with a
    warning. (HF-hub download is out of scope in the zero-egress build; pass
    a local path.)
    """
    model = create_model_from_registry(model_arch, in_chans=in_chans, **kwargs)
    params = init_params(model, rng=rng)

    local_path = pretrained
    if pretrained.startswith("hf_hub:"):
        cached = os.path.join(os.path.expanduser("~"), ".cache", "slide_encoder.pth")
        local_path = cached

    if local_path and os.path.exists(local_path):
        state = load_torch_state_dict(local_path)
        converted = convert_state_dict(state)
        params, missing, unexpected = merge_into_params(params, converted)
        console(
            f"\033[92m Successfully loaded pretrained GigaPath slide encoder "
            f"from {local_path} ({len(missing)} missing, {len(unexpected)} unexpected) \033[00m"
        )
    elif pretrained:
        console(
            f"\033[93m Pretrained weights not found at {local_path}. "
            f"Randomly initialized the model! \033[00m"
        )
    return model, params
