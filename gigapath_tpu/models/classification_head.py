"""Slide-level classification head.

Parity with reference ``gigapath/classification_head.py``: wraps the slide
encoder, concatenates the selected per-layer embeddings (``feat_layer``
"5-11" -> layers 5 and 11 of the all-layer output list), and applies a single
linear classifier. ``feat_layer`` is parsed with int() instead of the
reference's ``eval`` (``classification_head.py:54``).

Freezing the pretrained encoder is an optimizer concern in JAX — use
:func:`frozen_param_labels` with ``optax.multi_transform`` instead of
``requires_grad`` mutation.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from gigapath_tpu.obs import console
from gigapath_tpu.utils.registry import create_model_from_registry


def parse_feat_layer(feat_layer: str) -> List[int]:
    return [int(x) for x in str(feat_layer).split("-")]


class ClassificationHead(nn.Module):
    input_dim: int = 1536
    latent_dim: int = 768
    feat_layer: str = "11"
    n_classes: int = 2
    model_arch: str = "gigapath_slide_enc12l768d"
    global_pool: bool = False
    dtype: Any = None
    slide_kwargs: Optional[dict] = None

    @nn.compact
    def __call__(
        self,
        images: jnp.ndarray,
        coords: jnp.ndarray,
        pad_mask: Optional[jnp.ndarray] = None,
        deterministic: bool = True,
    ) -> jnp.ndarray:
        if images.ndim == 2:
            images = images[None]
        assert images.ndim == 3
        layers = parse_feat_layer(self.feat_layer)

        slide_encoder = create_model_from_registry(
            self.model_arch,
            in_chans=self.input_dim,
            global_pool=self.global_pool,
            dtype=self.dtype,
            name="slide_encoder",
            **(self.slide_kwargs or {}),
        )
        embeds = slide_encoder(
            images,
            coords,
            all_layer_embed=True,
            pad_mask=pad_mask,
            deterministic=deterministic,
        )
        h = jnp.concatenate([embeds[i] for i in layers], axis=-1)
        assert h.shape[-1] == len(layers) * self.latent_dim, (
            f"feat dim {h.shape[-1]} != {len(layers)} layers x latent_dim "
            f"{self.latent_dim}; latent_dim must match the slide encoder width"
        )
        logits = nn.Dense(self.n_classes, dtype=self.dtype, name="classifier")(
            h.reshape(-1, h.shape[-1])
        )
        return logits


def frozen_param_labels(params, frozen_subtree: str = "slide_encoder"):
    """Label tree for optax.multi_transform: 'frozen' under the encoder,
    'trainable' elsewhere (counterpart of the reference's freeze flag,
    ``classification_head.py:58-63``)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    labels = [
        "frozen"
        if any(getattr(p, "key", None) == frozen_subtree for p in path)
        else "trainable"
        for path, _ in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, labels)


def get_model(
    *,
    input_dim: int = 1536,
    latent_dim: int = 768,
    feat_layer: str = "11",
    n_classes: int = 2,
    model_arch: str = "gigapath_slide_enc12l768d",
    pretrained: str = "",
    freeze: bool = False,
    global_pool: bool = False,
    rng=None,
    dtype: Any = None,
    **kwargs,
):
    """Factory returning ``(module, params)`` with pretrained encoder weights
    merged into the ``slide_encoder`` subtree (non-strict)."""
    import os

    from gigapath_tpu.utils.torch_convert import (
        convert_state_dict,
        load_torch_state_dict,
        merge_into_params,
    )

    model = ClassificationHead(
        input_dim=input_dim,
        latent_dim=latent_dim,
        feat_layer=feat_layer,
        n_classes=n_classes,
        model_arch=model_arch,
        global_pool=global_pool,
        dtype=dtype,
        slide_kwargs=kwargs or None,
    )
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    x = jnp.zeros((1, 4, input_dim), jnp.float32)
    coords = jnp.zeros((1, 4, 2), jnp.float32)
    params = model.init(rng, x, coords)["params"]

    if pretrained and os.path.exists(pretrained):
        state = load_torch_state_dict(pretrained)
        converted = convert_state_dict(state)
        params["slide_encoder"], missing, unexpected = merge_into_params(
            params["slide_encoder"], converted
        )
        console(
            f"\033[92m Loaded pretrained slide encoder from {pretrained} "
            f"({len(missing)} missing, {len(unexpected)} unexpected) \033[00m"
        )
    elif pretrained:
        console(f"\033[93m Pretrained weights not found at {pretrained} \033[00m")

    if freeze:
        console("Freezing is applied at the optimizer: use frozen_param_labels()")
    return model, params
