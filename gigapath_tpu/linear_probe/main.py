"""Tile-level linear probe (PCam recipe).

Parity with reference ``linear_probe/main.py``: a single linear classifier
on frozen 1536-d tile embeddings, SGD (or Adam) + cosine annealing over
``train_iters`` iterations of an infinitely-cycled loader, eval every
``eval_interval`` (accuracy / weighted-f1 / macro precision+recall / macro
AUROC+AUPRC), best-f1 model selection, ``results.txt`` artifact
(``main.py:65-260``). This is the cheapest path to the PCam AUC-parity
north star (BASELINE config 2).

TPU shape: the whole train step (forward, CE loss, SGD update, cosine LR)
is one jitted function; embeddings are tiny, so batches stream from numpy.
"""

from __future__ import annotations

import argparse
import itertools
import os
import time
from typing import Iterator, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gigapath_tpu.data.pcam import EmbeddingDataset, Processor
from gigapath_tpu.finetune.utils import log_writer, make_writer, seed_everything
from gigapath_tpu.obs import (
    CompileWatchdog,
    Heartbeat,
    console,
    get_ledger,
    get_metrics,
    get_run_log,
    span,
)
from gigapath_tpu.obs.runlog import fail_run
from gigapath_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint


def build_argparser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="Linear Probe")
    # Dataset
    parser.add_argument("--dataset_csv", type=str, default="", help="csv with input samples and labels")
    parser.add_argument("--input_path", type=str, default="", help="The input embedding zip")
    parser.add_argument("--embed_dim", type=int, default=1536, help="The dimension of the embeddings")
    # Training
    parser.add_argument("--batch_size", type=int, default=512, help="Batch size")
    parser.add_argument("--train_iters", type=int, default=12500, help="Number of iterations")
    parser.add_argument("--lr", type=float, default=0.01, help="Learning rate")
    parser.add_argument("--min_lr", type=float, default=0.0, help="Minimum learning rate")
    parser.add_argument("--optim", type=str, default="sgd", help="Optimizer")
    parser.add_argument("--momentum", type=float, default=0.0, help="Momentum")
    parser.add_argument("--weight_decay", type=float, default=0.0, help="Weight decay")
    parser.add_argument("--eval_interval", type=int, default=10000, help="Evaluation interval")
    parser.add_argument("--model_select", type=str, default="best", help="Model selection")
    parser.add_argument("--num_workers", type=int, default=10, help="Accepted for compatibility (unused)")
    parser.add_argument("--seed", type=int, default=42, help="Random seed")
    parser.add_argument("--z_score", action="store_true", default=False, help="Use z-score normalization")
    parser.add_argument("--report_to", type=str, default="tensorboard", choices=["tensorboard", "jsonl"])
    # Output
    parser.add_argument("--output_dir", type=str, default="outputs", help="Output directory")
    return parser


def to_onehot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    onehot = np.zeros((labels.shape[0], num_classes))
    onehot[np.arange(labels.shape[0]), labels] = 1
    return onehot


def _batches(
    dataset, batch_size: int, rng: np.random.Generator, infinite: bool
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    n = len(dataset)

    def epoch_indices():
        if infinite:
            while True:
                yield rng.integers(0, n, size=batch_size)  # with replacement
        else:
            order = np.arange(n)
            for start in range(0, n, batch_size):
                yield order[start : start + batch_size]

    for idx in epoch_indices():
        embeds, targets = zip(*(dataset[int(i)] for i in idx))
        yield np.stack(embeds).astype(np.float32), np.asarray(targets, np.int64)


def init_linear_probe(embed_dim: int, num_classes: int, seed: int = 0):
    """Params of the single nn.Linear (reference ``LinearProbe:276``)."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    bound = 1.0 / np.sqrt(embed_dim)
    return {
        "kernel": jax.random.uniform(k1, (embed_dim, num_classes), jnp.float32, -bound, bound),
        "bias": jax.random.uniform(k2, (num_classes,), jnp.float32, -bound, bound),
    }


def evaluate(params, loader_fn) -> Tuple[float, float, float, float, float, float]:
    """(accuracy, weighted-f1, macro precision, macro recall, macro auroc,
    macro auprc) — reference ``evaluate:204``."""
    from sklearn.metrics import (
        average_precision_score,
        f1_score,
        precision_recall_fscore_support,
        roc_auc_score,
    )

    preds, targets = [], []
    for embed, target in loader_fn():
        logits = np.asarray(embed @ np.asarray(params["kernel"]) + np.asarray(params["bias"]))
        preds.append(logits)
        targets.append(target)
    pred = np.concatenate(preds)
    target = np.concatenate(targets)
    accuracy = float((pred.argmax(1) == target).mean())
    f1 = f1_score(target, pred.argmax(1), average="weighted")
    precision, recall, _, _ = precision_recall_fscore_support(
        target, pred.argmax(1), average="macro", zero_division=0
    )
    auroc = roc_auc_score(to_onehot(target, pred.shape[1]), pred, average="macro")
    auprc = average_precision_score(to_onehot(target, pred.shape[1]), pred, average="macro")
    return accuracy, f1, precision, recall, auroc, auprc


def train(
    params,
    train_dataset,
    val_dataset,
    test_dataset,
    *,
    train_iters: int,
    batch_size: int = 512,
    lr: float = 0.01,
    min_lr: float = 0.0,
    optim: str = "sgd",
    weight_decay: float = 0.0,
    momentum: float = 0.0,
    output_dir: str = "outputs",
    eval_interval: int = 10000,
    model_select: str = "best",
    seed: int = 42,
    report_to: str = "jsonl",
    **kwargs,
):
    """Train the probe; writes best/last checkpoints + results.txt
    (reference ``train:65-201``)."""
    os.makedirs(output_dir, exist_ok=True)

    class _Args:
        exp_code = "linear_probe"

    writer, report_to = make_writer(report_to, os.path.join(output_dir, "tensorboard"), _Args)
    runlog = get_run_log(
        "linear_probe", out_dir=output_dir,
        config={"train_iters": train_iters, "batch_size": batch_size,
                "lr": lr, "min_lr": min_lr, "optim": optim,
                "weight_decay": weight_decay, "momentum": momentum,
                "eval_interval": eval_interval, "seed": seed},
    )

    schedule = optax.cosine_decay_schedule(lr, train_iters, alpha=min_lr / max(lr, 1e-12))
    if optim == "sgd":
        tx = optax.chain(
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.sgd(schedule, momentum=momentum or None),
        )
    elif optim == "adam":
        tx = optax.adamw(schedule, weight_decay=weight_decay)
    else:
        raise ValueError("Invalid optimizer")
    runlog.echo(f"Set the optimizer as {optim}")
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, embed, target):
        def loss_fn(p):
            logits = embed @ p["kernel"] + p["bias"]
            return optax.softmax_cross_entropy_with_integer_labels(logits, target).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    train_stream = _batches(train_dataset, batch_size, rng, infinite=True)
    val_loader = lambda: _batches(val_dataset, batch_size, rng, infinite=False)  # noqa: E731
    test_loader = lambda: _batches(test_dataset, batch_size, rng, infinite=False)  # noqa: E731

    ledger = get_ledger(runlog)
    watchdog = CompileWatchdog("linear_probe.step", runlog, ledger=ledger)
    instrumented_step = watchdog.wrap(step)
    runlog.echo("Start training")
    try:
        params, best_f1, f1 = _train_loop(
            params, opt_state, instrumented_step, train_stream, train_iters,
            schedule, eval_interval, val_loader, output_dir, report_to,
            writer, runlog,
        )

        if model_select == "best" and best_f1 > 0:
            val_f1 = best_f1
            params = restore_checkpoint(os.path.join(output_dir, "best_model"))
        else:
            val_f1 = f1
            params = restore_checkpoint(os.path.join(output_dir, "model"))

        accuracy, f1, precision, recall, auroc, auprc = evaluate(params, test_loader)
        runlog.echo(
            f"Test Accuracy: {accuracy} f1: {f1} Precision: {precision} Recall: "
            f"{recall} AUROC: {auroc} AUPRC: {auprc}"
        )
        with open(os.path.join(output_dir, "results.txt"), "w") as f:
            f.write(f"Val f1: {val_f1}\n")
            f.write(f"Test f1: {f1} Test AUROC: {auroc} Test AUPRC: {auprc}\n")
    except Exception as e:
        # a crashed run must still leave a terminal event in its artifact
        # (the shared obs failure tail: error event -> flight dump ->
        # terminal run_end)
        fail_run(runlog, "linear_probe.train", e)
        raise
    runlog.run_end(
        status="ok", val_f1=val_f1, test_f1=f1, test_auroc=auroc,
        test_auprc=auprc,
        compile_seconds_total=watchdog.compile_seconds_total(),
        ledger_path=ledger.path,
    )
    return {"val_f1": val_f1, "test_f1": f1, "test_auroc": auroc, "test_auprc": auprc}


def _train_loop(
    params, opt_state, instrumented_step, train_stream, train_iters,
    schedule, eval_interval, val_loader, output_dir, report_to, writer,
    runlog,
):
    """The heartbeat-monitored iteration loop; returns
    ``(params, best_f1, last_f1)``."""
    best_f1, f1 = 0.0, 0.0
    # typed metrics (attach-once: same registry as the driver's; the
    # final snapshot flushes inside run_end via the registry's closer)
    metrics = get_metrics(runlog)
    step_walls = metrics.histogram("linear_probe.step_wall_s")
    with Heartbeat(runlog, name="linear_probe") as heartbeat:
        t_prev = time.time()
        for i, (embed, target) in enumerate(itertools.islice(train_stream, train_iters)):
            params, opt_state, loss = instrumented_step(
                params, opt_state, jnp.asarray(embed), jnp.asarray(target)
            )
            heartbeat.beat(i)
            if (i + 1) % 10 == 0:
                cur_lr = float(schedule(i))
                t_now = time.time()
                runlog.step(
                    i, wall_s=round(t_now - t_prev, 6), synced=True,
                    loss=float(loss), lr=cur_lr,
                )
                step_walls.observe(round(t_now - t_prev, 6))
                metrics.maybe_flush()
                t_prev = t_now
                runlog.echo(
                    f"Iteration [{i}/{train_iters}]\tLoss: {float(loss)}\tLR: {cur_lr}",
                    step=i,
                )
                log_writer({"Train Loss": float(loss), "Learning Rate": cur_lr}, i, report_to, writer)
            if (i + 1) % eval_interval == 0 or (i + 1) == train_iters:
                runlog.echo("Start evaluating ...")
                with span("eval", runlog, iteration=i):
                    accuracy, f1, precision, recall, auroc, auprc = evaluate(params, val_loader)
                runlog.eval_event(
                    i, accuracy=accuracy, f1=f1, precision=precision,
                    recall=recall, auroc=auroc, auprc=auprc,
                )
                runlog.echo(
                    f"Val [{i}/{train_iters}] Accuracy: {accuracy} f1: {f1} Precision: "
                    f"{precision} Recall: {recall} AUROC: {auroc} AUPRC: {auprc}",
                    step=i,
                )
                log_writer(
                    {
                        "Val Accuracy": accuracy,
                        "Val f1": f1,
                        "Val AUROC": auroc,
                        "Val AUPRC": auprc,
                        "Val Precision": precision,
                        "Val Recall": recall,
                        "Best f1": best_f1,
                    },
                    i,
                    report_to,
                    writer,
                )
                if f1 > best_f1:
                    runlog.echo(f"Best f1 increase from {best_f1} to {f1}")
                    best_f1 = f1
                    save_checkpoint(os.path.join(output_dir, "best_model"), jax.device_get(params))

    save_checkpoint(os.path.join(output_dir, "model"), jax.device_get(params))
    return params, best_f1, f1


def main(argv=None):
    args = build_argparser().parse_args(argv)
    console(str(args))
    seed_everything(args.seed)
    processor = Processor()
    splits = ["train", "val", "test"]
    train_dataset, val_dataset, test_dataset = [
        EmbeddingDataset(
            args.dataset_csv, args.input_path, split=split,
            z_score=args.z_score, processor=processor,
        )
        for split in splits
    ]
    args.num_classes = len(train_dataset.label_dict)
    console(f"Train: {len(train_dataset)}\tVal: {len(val_dataset)}\tTest: {len(test_dataset)}")
    params = init_linear_probe(args.embed_dim, args.num_classes, args.seed)
    return train(params, train_dataset, val_dataset, test_dataset, **vars(args))


if __name__ == "__main__":
    main()
