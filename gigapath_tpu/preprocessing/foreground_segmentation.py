"""Foreground segmentation + ROI loading for whole-slide images.

Parity with reference ``gigapath/preprocessing/data/foreground_segmentation.py``:
luminance-mean grayscale, Otsu (or fixed) thresholding with luminance <
threshold as foreground, bounding-box estimation at the lowest-resolution
pyramid level scaled to level-0, margin, and the ROI crop read at the target
level (``LoadROId:113-180``).

Deltas for this environment: Otsu is implemented directly in numpy (skimage
is not shipped); the OpenSlide/MONAI reader pair collapses into one small
``SlideReader`` interface with an OpenSlide-backed implementation (gated
import — WSI IO stays host-side C via openslide where available,
SURVEY §2.9) and a PIL/numpy pyramid for ordinary images and tests.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

import numpy as np

from gigapath_tpu.data import box_utils


def get_luminance(slide: np.ndarray) -> np.ndarray:
    """(*, C, H, W) RGB -> (*, H, W) mean-channel luminance."""
    return slide.mean(axis=-3, dtype=np.float16)


def otsu_threshold(values: np.ndarray, nbins: int = 256) -> float:
    """Otsu's method on a value array (numpy stand-in for
    ``skimage.filters.threshold_otsu``): the threshold maximizing
    between-class variance of the histogram."""
    values = np.asarray(values, np.float32).ravel()
    counts, bin_edges = np.histogram(values, bins=nbins)
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    counts = counts.astype(np.float64)
    w0 = np.cumsum(counts)
    w1 = w0[-1] - w0
    sum0 = np.cumsum(counts * centers)
    mu0 = sum0 / np.maximum(w0, 1e-12)
    mu1 = (sum0[-1] - sum0) / np.maximum(w1, 1e-12)
    between = w0 * w1 * (mu0 - mu1) ** 2
    between[(w0 == 0) | (w1 == 0)] = -1
    # the variance is flat across any empty gap between modes; take the
    # middle of the maximal plateau rather than its first edge
    best = np.isclose(between, between.max())
    return float(centers[best].mean())


def segment_foreground(
    slide: np.ndarray, threshold: Optional[float] = None
) -> Tuple[np.ndarray, float]:
    """Boolean foreground mask (luminance < threshold) + the threshold used
    (reference ``segment_foreground:33-46``)."""
    luminance = get_luminance(slide)
    if threshold is None:
        threshold = otsu_threshold(luminance)
    logging.info(f"Otsu threshold from luminance: {threshold}")
    return luminance < threshold, threshold


class SlideReader:
    """Minimal pyramid-reader interface (the OpenSlide surface the reference
    actually uses: level count, per-level dims/downsamples, region reads)."""

    @property
    def level_count(self) -> int:
        raise NotImplementedError

    @property
    def level_downsamples(self):
        raise NotImplementedError

    @property
    def level_dimensions(self):
        """Per level: (width, height), OpenSlide convention."""
        raise NotImplementedError

    @property
    def dimensions(self):
        return self.level_dimensions[0]

    def read_level(self, level: int) -> np.ndarray:
        """Full image at ``level`` as (C, H, W) uint8."""
        raise NotImplementedError

    def read_region(self, location_yx, level: int, size_hw) -> np.ndarray:
        """(C, h, w) crop; ``location_yx`` in level-0 coords, ``size_hw`` at
        ``level`` (the reference's swapped-argument MONAI convention,
        ``LoadROId.__call__:165-169``)."""
        raise NotImplementedError

    def close(self):
        pass


class OpenSlideReader(SlideReader):
    """OpenSlide-backed reader (gated import; unavailable in this image)."""

    def __init__(self, path: str):
        from openslide import OpenSlide

        self._slide = OpenSlide(str(path))

    @property
    def level_count(self):
        return self._slide.level_count

    @property
    def level_downsamples(self):
        return self._slide.level_downsamples

    @property
    def level_dimensions(self):
        return self._slide.level_dimensions

    def read_level(self, level):
        w, h = self._slide.level_dimensions[level]
        region = self._slide.read_region((0, 0), level, (w, h)).convert("RGB")
        return np.moveaxis(np.asarray(region, np.uint8), -1, 0)

    def read_region(self, location_yx, level, size_hw):
        y, x = int(location_yx[0]), int(location_yx[1])
        h, w = int(size_hw[0]), int(size_hw[1])
        region = self._slide.read_region((x, y), level, (w, h)).convert("RGB")
        return np.moveaxis(np.asarray(region, np.uint8), -1, 0)

    def close(self):
        self._slide.close()


class ImageSlideReader(SlideReader):
    """Plain-image pyramid: loads a PNG/JPEG (or takes an array) and builds
    ``n_levels`` of 2x downsamples — the test/synthetic stand-in for WSIs."""

    def __init__(self, path_or_array, n_levels: int = 3):
        if isinstance(path_or_array, np.ndarray):
            arr = path_or_array
        else:
            from PIL import Image

            arr = np.asarray(Image.open(str(path_or_array)).convert("RGB"))
        self._levels = [np.moveaxis(arr.astype(np.uint8), -1, 0)]  # (C, H, W)
        for _ in range(1, n_levels):
            prev = self._levels[-1]
            if min(prev.shape[1:]) < 2:
                break
            self._levels.append(prev[:, ::2, ::2])

    @property
    def level_count(self):
        return len(self._levels)

    @property
    def level_downsamples(self):
        return [2.0**i for i in range(len(self._levels))]

    @property
    def level_dimensions(self):
        return [(lv.shape[2], lv.shape[1]) for lv in self._levels]

    def read_level(self, level):
        return self._levels[level]

    def read_region(self, location_yx, level, size_hw):
        ds = self.level_downsamples[level]
        y, x = int(round(location_yx[0] / ds)), int(round(location_yx[1] / ds))
        h, w = int(size_hw[0]), int(size_hw[1])
        lv = self._levels[level]
        crop = lv[:, y : y + h, x : x + w]
        if crop.shape[1:] != (h, w):  # pad reads past the edge with white
            out = np.full((lv.shape[0], h, w), 255, np.uint8)
            out[:, : crop.shape[1], : crop.shape[2]] = crop
            crop = out
        return crop


def open_slide(path, n_levels: int = 3) -> SlideReader:
    """OpenSlide when importable, image-pyramid fallback otherwise."""
    try:
        return OpenSlideReader(path)
    except ImportError:
        return ImageSlideReader(path, n_levels=n_levels)


class LoadROId:
    """Load a slide cropped to its foreground bounding box
    (reference ``LoadROId:113-180``). ``__call__`` maps
    ``{"image": path, ...}`` to the loaded dict with ``origin`` / ``scale``
    / ``foreground_threshold`` metadata added."""

    def __init__(
        self,
        image_key: str = "image",
        level: int = 0,
        margin: int = 0,
        foreground_threshold: Optional[float] = None,
        reader_fn=open_slide,
    ):
        self.image_key = image_key
        self.level = level
        self.margin = margin
        self.foreground_threshold = foreground_threshold
        self.reader_fn = reader_fn

    def _get_bounding_box(self, slide_obj: SlideReader):
        highest_level = slide_obj.level_count - 1
        if slide_obj.level_count == 1:
            logging.warning(
                "Only one image level found. segment_foreground will use a lot of memory."
            )
        slide = slide_obj.read_level(highest_level)
        foreground_mask, threshold = segment_foreground(
            slide, self.foreground_threshold
        )
        scale = slide_obj.level_downsamples[highest_level]
        bbox = scale * box_utils.get_bounding_box(foreground_mask).add_margin(
            self.margin
        )
        return bbox, threshold

    def __call__(self, data: Dict) -> Dict:
        logging.info(f"LoadROId: read {data[self.image_key]}")
        image_obj = self.reader_fn(data[self.image_key])
        level0_bbox, threshold = self._get_bounding_box(image_obj)
        logging.info(f"LoadROId: level0_bbox: {level0_bbox}")

        scale = image_obj.level_downsamples[self.level]
        scaled_bbox = level0_bbox / scale
        origin = (level0_bbox.y, level0_bbox.x)
        img_data = image_obj.read_region(
            origin, self.level, (scaled_bbox.h, scaled_bbox.w)
        )
        data[self.image_key] = img_data
        data.update(
            location=origin, size=(scaled_bbox.h, scaled_bbox.w), level=self.level
        )
        data["origin"] = origin
        data["scale"] = scale
        data["foreground_threshold"] = threshold
        image_obj.close()
        return data
