"""Slide -> tiles preprocessing pipeline.

Parity with reference ``gigapath/preprocessing/data/create_tiles_dataset.py``:
occupancy-filtered tiling of the foreground ROI, per-tile PNGs named
``{x:05d}x_{y:05d}y.png``, per-slide ``dataset.csv`` + ``failed_tiles.csv``
ledgers, thumbnails + tile-location overlay, resume-if-processed idempotence
(``is_already_processed:221``), per-dataset csv merge, and a multiprocessing
slide map. Host-side CPU work feeding the TPU tile encoder — no jax here.
"""

from __future__ import annotations

import functools
import logging
import shutil
import traceback
import warnings
from pathlib import Path
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple, Union

import numpy as np

from gigapath_tpu.data import tiling
from gigapath_tpu.preprocessing.foreground_segmentation import (
    LoadROId,
    open_slide,
    segment_foreground,
)


def select_tiles(
    foreground_mask: np.ndarray, occupancy_threshold: float
) -> Tuple[np.ndarray, np.ndarray]:
    """Keep tiles whose foreground occupancy exceeds the threshold
    (reference ``select_tiles:30-42``)."""
    if occupancy_threshold < 0.0 or occupancy_threshold > 1.0:
        raise ValueError("Tile occupancy threshold must be between 0 and 1")
    occupancy = foreground_mask.mean(axis=(-2, -1), dtype=np.float16)
    return (occupancy > occupancy_threshold).squeeze(), occupancy.squeeze()


def get_tile_descriptor(tile_location: Sequence[int]) -> str:
    return f"{tile_location[0]:05d}x_{tile_location[1]:05d}y"


def get_tile_id(slide_id: str, tile_location: Sequence[int]) -> str:
    return f"{slide_id}.{get_tile_descriptor(tile_location)}"


def save_image(array_chw: np.ndarray, path: Path):
    """Save a (C, H, W) array as an RGB image."""
    import PIL

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    array_hwc = np.moveaxis(array_chw, 0, -1).astype(np.uint8).squeeze()
    pil_image = PIL.Image.fromarray(array_hwc)
    pil_image.convert("RGB").save(path)
    return pil_image


def check_empty_tiles(
    tiles: np.ndarray, std_th: int = 5, extreme_value_portion_th: float = 0.5
) -> np.ndarray:
    """Low-variance / extreme-value emptiness heuristic
    (reference ``check_empty_tiles:64-84``)."""
    b, c, h, w = tiles.shape
    flat = tiles.reshape(b, c, h * w)
    std_rgb_mean = flat.std(axis=2).mean(axis=1)
    low_std_mask = std_rgb_mean < std_th
    extreme_value_proportion = (flat == 0).sum(axis=2) / (h * w)
    extreme_value_mask = extreme_value_proportion.max(axis=1) > extreme_value_portion_th
    return low_std_mask | extreme_value_mask


def generate_tiles(
    slide_image: np.ndarray,
    tile_size: int,
    foreground_threshold: float,
    occupancy_threshold: float,
    strict_parity: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Tile the ROI and drop background tiles (reference
    ``generate_tiles:87-124``). Returns (tiles [N,C,h,w], locations [N,2],
    occupancies [N], n_discarded).

    ``strict_parity`` forces the reference's fp16-*accumulated* occupancy
    mean (``select_tiles:38``) instead of the native kernel's exact integer
    count cast to fp16 afterwards — tile selection can differ at threshold
    boundaries between the two (documented in PARITY.md).
    """
    image_tiles, tile_locations = tiling.tile_array_2d(
        slide_image, tile_size=tile_size, constant_values=255
    )
    logging.info(f"Tiled {slide_image.shape} to {image_tiles.shape}")
    if occupancy_threshold < 0.0 or occupancy_threshold > 1.0:
        raise ValueError("Tile occupancy threshold must be between 0 and 1")
    if (
        not strict_parity
        and isinstance(foreground_threshold, (int, float))
        and image_tiles.dtype == np.uint8
    ):
        # fixed threshold (Otsu already ran at ROI load): the luminance +
        # compare + occupancy mean collapses into one pass through the
        # native C++ kernel. Exact integer luminance counts (the kernel and
        # its numpy fallback are bit-identical) — deliberately *better* math
        # than the reference's lossy fp16-accumulated means
        # (select_tiles:38); the fp16 cast below only keeps the stored
        # occupancy dtype for csv parity.
        from gigapath_tpu import native

        occupancies = native.luminance_occupancy(
            image_tiles, float(foreground_threshold)
        ).astype(np.float16)
        selected = occupancies > occupancy_threshold
    else:
        foreground_mask, _ = segment_foreground(image_tiles, foreground_threshold)
        selected, occupancies = select_tiles(foreground_mask, occupancy_threshold)
        # select_tiles squeezes to scalars for a single-tile slide
        selected = np.atleast_1d(selected)
        occupancies = np.atleast_1d(occupancies)
    n_discarded = int((~selected).sum())
    logging.info(f"Percentage tiles discarded: {n_discarded / len(selected) * 100:.2f}")

    image_tiles = image_tiles[selected]
    tile_locations = tile_locations[selected]
    occupancies = occupancies[selected]
    if len(tile_locations) == 0:
        logging.warning("No tiles selected")
    return image_tiles, tile_locations, occupancies, n_discarded


def get_tile_info(
    sample: Dict[str, Any],
    occupancy: float,
    tile_location: Sequence[int],
    rel_slide_dir: Path,
) -> Dict[str, Any]:
    slide_id = sample["slide_id"]
    descriptor = get_tile_descriptor(tile_location)
    return {
        "slide_id": slide_id,
        "tile_id": get_tile_id(slide_id, tile_location),
        "image": f"{rel_slide_dir}/{descriptor}.png",
        "label": sample.get("label", None),
        "tile_x": tile_location[0],
        "tile_y": tile_location[1],
        "occupancy": occupancy,
        "metadata": {
            "slide_" + key: value for key, value in sample.get("metadata", {}).items()
        },
    }


def format_csv_row(
    tile_info: Dict[str, Any],
    keys_to_save: Iterable[str],
    metadata_keys: Iterable[str],
) -> str:
    tile_slide_metadata = tile_info.pop("metadata")
    fields = [str(tile_info[key]) for key in keys_to_save]
    fields.extend(str(tile_slide_metadata[key]) for key in metadata_keys)
    return ",".join(fields)


def save_thumbnail(slide_path, output_path, size_target: int = 1024) -> None:
    """Downscaled whole-slide thumbnail (reference ``save_thumbnail:192``)."""
    from PIL import Image

    reader = open_slide(slide_path)
    try:
        arr = reader.read_level(reader.level_count - 1)
        img = Image.fromarray(np.moveaxis(arr, 0, -1))
        scale = size_target / max(img.size)
        if scale < 1:
            img = img.resize([max(1, int(m * scale)) for m in img.size])
        img.save(output_path)
        logging.info(f"Saving thumbnail {output_path}, shape {img.size}")
    finally:
        reader.close()


def visualize_tile_locations(
    slide_sample, output_path, tile_info_list, tile_size, origin_offset
) -> None:
    """Overlay of selected tile boxes on the ROI thumbnail
    (reference ``visualize_tile_locations:200-218``)."""
    import matplotlib

    matplotlib.use("Agg")
    from matplotlib import collections, patches, pyplot as plt

    slide_image = slide_sample["image"]
    downscale_factor = slide_sample["scale"]
    fig, ax = plt.subplots()
    ax.imshow(slide_image.transpose(1, 2, 0))
    rects = []
    for tile_info in tile_info_list:
        xy = (
            (tile_info["tile_x"] - origin_offset[1]) / downscale_factor,
            (tile_info["tile_y"] - origin_offset[0]) / downscale_factor,
        )
        rects.append(patches.Rectangle(xy, tile_size, tile_size))
    pc = collections.PatchCollection(
        rects, match_original=True, alpha=0.5, edgecolor="black"
    )
    pc.set_array(np.array([100] * len(tile_info_list)))
    ax.add_collection(pc)
    fig.savefig(output_path)
    plt.close(fig)


def is_already_processed(output_tiles_dir) -> bool:
    """Resume support: a slide directory with tiles + a non-empty csv is
    done (reference ``is_already_processed:221-234``)."""
    import pandas as pd

    output_tiles_dir = Path(output_tiles_dir)
    if not output_tiles_dir.exists():
        return False
    if len(list(output_tiles_dir.glob("*.png"))) == 0:
        return False
    try:
        df = pd.read_csv(output_tiles_dir / "dataset.csv")
    except Exception:
        return False
    return len(df) > 0


def process_slide(
    sample: Dict[str, Any],
    level: int,
    margin: int,
    tile_size: int,
    foreground_threshold: Optional[float],
    occupancy_threshold: float,
    output_dir: Path,
    thumbnail_dir: Path,
    tile_progress: bool = False,
    strict_parity: bool = False,
) -> Path:
    """Tile one slide end-to-end, writing PNGs + csv ledgers
    (reference ``process_slide:237-354``). ``strict_parity``: see
    :func:`generate_tiles`."""
    output_dir, thumbnail_dir = Path(output_dir), Path(thumbnail_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    thumbnail_dir.mkdir(parents=True, exist_ok=True)
    slide_metadata: Dict[str, Any] = sample.get("metadata", {})
    keys_to_save = (
        "slide_id", "tile_id", "image", "label", "tile_x", "tile_y", "occupancy",
    )
    metadata_keys = tuple("slide_" + key for key in slide_metadata)
    csv_columns = (*keys_to_save, *metadata_keys)

    slide_id: str = sample["slide_id"]
    rel_slide_dir = Path(slide_id)
    output_tiles_dir = output_dir / rel_slide_dir
    logging.info(f">>> Slide dir {output_tiles_dir}")
    if is_already_processed(output_tiles_dir):
        logging.info(f">>> Skipping {output_tiles_dir} - already processed")
        return output_tiles_dir

    output_tiles_dir.mkdir(parents=True, exist_ok=True)
    dataset_csv_path = output_tiles_dir / "dataset.csv"
    failed_tiles_csv_path = output_tiles_dir / "failed_tiles.csv"
    n_failed_tiles = 0

    with dataset_csv_path.open("w") as dataset_csv_file, failed_tiles_csv_path.open(
        "w"
    ) as failed_tiles_file:
        dataset_csv_file.write(",".join(csv_columns) + "\n")
        failed_tiles_file.write("tile_id\n")

        slide_image_path = Path(sample["image"])
        logging.info(f"Loading slide {slide_id} ...\nFile: {slide_image_path}")
        save_thumbnail(
            slide_image_path, thumbnail_dir / (slide_image_path.name + "_original.png")
        )

        loader = LoadROId(
            level=level, margin=margin, foreground_threshold=foreground_threshold
        )
        sample = loader(dict(sample))

        save_image(
            sample["image"], thumbnail_dir / (slide_image_path.name + "_roi.png")
        )

        logging.info(f"Tiling slide {slide_id} ...")
        image_tiles, rel_tile_locations, occupancies, _ = generate_tiles(
            sample["image"],
            tile_size,
            sample["foreground_threshold"],
            occupancy_threshold,
            strict_parity=strict_parity,
        )
        # tile locations: level coords -> level-0 coords; origin is (y, x)
        # while locations are (x, y) (reference process_slide:314-318)
        tile_locations = (
            sample["scale"] * rel_tile_locations + np.asarray(sample["origin"])[::-1]
        ).astype(int)
        n_tiles = image_tiles.shape[0]
        logging.info(f"{n_tiles} tiles found")

        tile_info_list = []
        for i in range(n_tiles):
            try:
                tile_info = get_tile_info(
                    sample, occupancies[i], tile_locations[i], rel_slide_dir
                )
                tile_info_list.append(tile_info)
                save_image(image_tiles[i], output_dir / tile_info["image"])
                dataset_csv_file.write(
                    format_csv_row(tile_info, keys_to_save, metadata_keys) + "\n"
                )
            except Exception as e:
                n_failed_tiles += 1
                descriptor = get_tile_descriptor(tile_locations[i])
                failed_tiles_file.write(descriptor + "\n")
                traceback.print_exc()
                warnings.warn(
                    f"An error occurred while saving tile "
                    f"{get_tile_id(slide_id, tile_locations[i])}: {e}"
                )

    visualize_tile_locations(
        sample,
        thumbnail_dir / (slide_image_path.name + "_roi_tiles.png"),
        tile_info_list,
        tile_size,
        origin_offset=sample["origin"],
    )
    if n_failed_tiles > 0:
        logging.warning(f"{slide_id} is incomplete. {n_failed_tiles} tiles failed.")
    logging.info(f"Finished processing slide {slide_id}")
    return output_tiles_dir


def merge_dataset_csv_files(dataset_dir: Path) -> Path:
    """All ``*/dataset.csv`` -> one ``dataset.csv``
    (reference ``merge_dataset_csv_files:357-374``)."""
    dataset_dir = Path(dataset_dir)
    full_csv = dataset_dir / "dataset.csv"
    with full_csv.open("w") as full_csv_file:
        first_file = True
        for slide_csv in sorted(dataset_dir.glob("*/dataset.csv")):
            logging.info(f"Merging slide {slide_csv}")
            content = slide_csv.read_text()
            if not first_file:
                content = content[content.index("\n") + 1 :]
            full_csv_file.write(content)
            first_file = False
    return full_csv


def main(
    slides: Sequence[Dict[str, Any]],
    root_output_dir: Union[str, Path],
    level: int,
    tile_size: int,
    margin: int,
    foreground_threshold: Optional[float],
    occupancy_threshold: float,
    parallel: bool = False,
    overwrite: bool = False,
    n_slides: Optional[int] = None,
) -> None:
    """Process a list of slide sample dicts into a tiles dataset
    (reference ``main:377-437``); resume-by-skip unless ``overwrite``."""
    dataset = list(slides)[:n_slides]
    for sample in dataset:
        image_path = Path(sample["image"])
        assert image_path.exists(), f"{image_path} doesn't exist"

    output_dir = Path(root_output_dir)
    logging.info(
        f"Creating dataset of level-{level} {tile_size}x{tile_size} tiles at: {output_dir}"
    )
    if overwrite and output_dir.exists():
        shutil.rmtree(output_dir)
    output_dir.mkdir(parents=True, exist_ok=not overwrite)
    thumbnail_dir = output_dir / "thumbnails"
    thumbnail_dir.mkdir(exist_ok=True)

    func = functools.partial(
        process_slide,
        level=level,
        margin=margin,
        tile_size=tile_size,
        foreground_threshold=foreground_threshold,
        occupancy_threshold=occupancy_threshold,
        output_dir=output_dir,
        thumbnail_dir=thumbnail_dir,
        tile_progress=not parallel,
    )
    if parallel:
        import multiprocessing

        with multiprocessing.Pool() as pool:
            list(pool.imap_unordered(func, dataset))
    else:
        list(map(func, dataset))

    logging.info("Merging slide files in a single file")
    merge_dataset_csv_files(output_dir)
