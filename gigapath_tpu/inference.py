"""Batch inference driver over cached slide-feature files.

Parity with reference ``docker/workspace/prov-gigapath/inference.py``: load a
trained classification checkpoint, iterate ``*_features.pt`` files (or orbax
feature dirs), softmax-classify, write a csv of ``slide_id`` /
``predicted_label`` / ``confidence`` and print the label distribution +
mean-confidence stats (``run_inference:37-79``).
"""

from __future__ import annotations

import argparse
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.obs import (
    CompileWatchdog,
    Heartbeat,
    console,
    get_ledger,
    get_run_log,
    span,
)


def load_model(
    model_path: str,
    input_dim: int = 1536,
    latent_dim: int = 768,
    feat_layer: str = "11",
    n_classes: int = 2,
    model_arch: str = "gigapath_slide_enc12l768d",
    **kwargs,
):
    """Build the classification head and load a checkpoint
    (reference ``load_model:18-34``)."""
    from gigapath_tpu.finetune.predict import _load_params_into_model
    from gigapath_tpu.models.classification_head import get_model

    model, params = get_model(
        input_dim=input_dim,
        latent_dim=latent_dim,
        feat_layer=feat_layer,
        n_classes=n_classes,
        model_arch=model_arch,
        dtype=jnp.bfloat16,
        **kwargs,
    )
    if model_path:
        params = _load_params_into_model(model_path, params)
    return model, params


def _load_features(path: str):
    """-> (features [N, D], coords [N, 2] or None)."""

    def to_np(t):
        return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)

    if path.endswith(".pt"):
        import torch

        t = torch.load(path, map_location="cpu", weights_only=False)
        if isinstance(t, dict):
            feats = t.get("features", t.get("tile_embeds"))
            assert feats is not None, f"{path}: no 'features'/'tile_embeds' key"
            coords = t.get("coords")
            return to_np(feats), None if coords is None else to_np(coords)
        return to_np(t), None
    from gigapath_tpu.utils.checkpoint import restore_checkpoint

    state = restore_checkpoint(path)
    if isinstance(state, dict):
        return np.asarray(state["features"]), state.get("coords")
    return np.asarray(state), None


def run_inference(
    model,
    params,
    feature_dir: str,
    output_file: str,
):
    """Classify every ``*_features.pt`` in ``feature_dir``
    (reference ``run_inference:37-79``)."""
    import pandas as pd

    feature_files = sorted(glob.glob(os.path.join(feature_dir, "*_features.pt")))
    if not feature_files:
        console(f"No feature files found in {feature_dir}")
        return None

    runlog = get_run_log(
        "inference", out_dir=os.path.dirname(os.path.abspath(output_file)),
        config={"feature_dir": feature_dir, "output_file": output_file,
                "n_slides": len(feature_files)},
    )

    @jax.jit
    def forward(params, embeds, coords):
        return model.apply({"params": params}, embeds, coords, deterministic=True)

    # variable-length slides -> one compile per distinct N; the watchdog
    # turns that invisible first-slide pause into compile events and the
    # ledger records each new shape's compiled cost/memory profile
    ledger = get_ledger(runlog)
    watchdog = CompileWatchdog("inference.forward", runlog, ledger=ledger)
    instrumented_forward = watchdog.wrap(forward)

    results = []
    warned = False
    try:
        with Heartbeat(runlog, name="inference") as heartbeat:
            for idx, path in enumerate(feature_files):
                # fenced span (GL008): dur_s covers load + dispatch +
                # device execution for this slide
                with span("slide", runlog, fence=True) as sp:
                    feats, coords = _load_features(path)
                    feats = feats[None]  # [1, N, D]
                    if coords is None:
                        if not warned:
                            runlog.echo(
                                "Warning: feature files carry no coords; using zeros "
                                "(positional signal collapses to one grid cell)"
                            )
                            warned = True
                        coords = np.zeros((feats.shape[1], 2), np.float32)
                    coords = np.asarray(coords, np.float32)[None]
                    logits = np.asarray(
                        sp.fence(instrumented_forward(
                            params, jnp.asarray(feats), jnp.asarray(coords)
                        )),
                        np.float32,
                    )
                probs = np.asarray(jax.nn.softmax(logits, axis=-1))[0]
                pred = int(probs.argmax())
                results.append(
                    {
                        "slide_id": os.path.basename(path).replace("_features.pt", ""),
                        "predicted_label": pred,
                        "confidence": float(probs[pred]),
                    }
                )
                runlog.step(
                    idx, wall_s=sp.dur_s, synced=True,
                    n_tiles=int(feats.shape[1]), predicted_label=pred,
                    confidence=float(probs[pred]),
                )
                heartbeat.beat(idx)
        results_df = pd.DataFrame(results)
        results_df.to_csv(output_file, index=False)
    except Exception as e:
        runlog.error("inference.run_inference", e)
        runlog.run_end(status="error")
        raise

    label_counts = {
        str(k): int(v)
        for k, v in results_df["predicted_label"].value_counts().items()
    }
    runlog.echo(f"Inference results saved to {output_file}")
    runlog.echo(f"Label distribution: {label_counts}")
    runlog.echo(f"Mean confidence: {results_df['confidence'].mean():.4f}")
    runlog.run_end(
        status="ok", n_slides=len(results), label_distribution=str(label_counts),
        mean_confidence=float(results_df["confidence"].mean()),
        compile_seconds_total=watchdog.compile_seconds_total(),
        ledger_path=ledger.path,
    )
    return results_df


def main(argv=None):
    parser = argparse.ArgumentParser(description="GigaPath model inference")
    parser.add_argument("--model_path", type=str, required=True)
    parser.add_argument("--feature_dir", type=str, required=True)
    parser.add_argument("--output_file", type=str, default="predictions.csv")
    parser.add_argument(
        "--batch_size", type=int, default=16,
        help="Accepted for reference-CLI compatibility (slides are "
        "variable-length; processed one at a time)",
    )
    parser.add_argument("--num_classes", type=int, default=2)
    parser.add_argument("--model_arch", type=str, default="gigapath_slide_enc12l768d")
    args = parser.parse_args(argv)
    model, params = load_model(
        args.model_path, n_classes=args.num_classes, model_arch=args.model_arch
    )
    return run_inference(model, params, args.feature_dir, args.output_file)


if __name__ == "__main__":
    main()
