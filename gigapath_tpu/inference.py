"""Batch inference driver over cached slide-feature files.

Parity with reference ``docker/workspace/prov-gigapath/inference.py``: load a
trained classification checkpoint, iterate ``*_features.pt`` files (or orbax
feature dirs), softmax-classify, write a csv of ``slide_id`` /
``predicted_label`` / ``confidence`` and print the label distribution +
mean-confidence stats (``run_inference:37-79``).

Two execution paths:

- **bucketed (default)**: slides route through the serving stack's
  shape-bucket ladder and request coalescer (:mod:`gigapath_tpu.serve`)
  — padded ``[batch_size, N_bucket, D]`` batches with key-padding
  masks, one AOT executable per bucket instead of one jit retrace per
  distinct tile count, and ``--batch_size`` actually batches (the
  reference accepted the flag and ignored it). Repeated slides are
  served from the content-hash embedding cache without a forward pass.
- **exact-shape** (``--no-buckets``): the original slide-at-a-time
  jit path — one compile per distinct N — kept as the fallback and the
  parity oracle the bucketed path is tested against.
"""

from __future__ import annotations

import argparse
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from gigapath_tpu.obs import (
    CompileWatchdog,
    Heartbeat,
    console,
    get_ledger,
    get_metrics,
    get_run_log,
    span,
)
from gigapath_tpu.obs.runlog import fail_run


def load_model(
    model_path: str,
    input_dim: int = 1536,
    latent_dim: int = 768,
    feat_layer: str = "11",
    n_classes: int = 2,
    model_arch: str = "gigapath_slide_enc12l768d",
    **kwargs,
):
    """Build the classification head and load a checkpoint
    (reference ``load_model:18-34``)."""
    from gigapath_tpu.finetune.predict import _load_params_into_model
    from gigapath_tpu.models.classification_head import get_model

    model, params = get_model(
        input_dim=input_dim,
        latent_dim=latent_dim,
        feat_layer=feat_layer,
        n_classes=n_classes,
        model_arch=model_arch,
        dtype=jnp.bfloat16,
        **kwargs,
    )
    if model_path:
        params = _load_params_into_model(model_path, params)
    return model, params


def _load_features(path: str):
    """-> (features [N, D], coords [N, 2] or None)."""

    def to_np(t):
        return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)

    if path.endswith(".pt"):
        import torch

        t = torch.load(path, map_location="cpu", weights_only=False)
        if isinstance(t, dict):
            feats = t.get("features", t.get("tile_embeds"))
            assert feats is not None, f"{path}: no 'features'/'tile_embeds' key"
            coords = t.get("coords")
            return to_np(feats), None if coords is None else to_np(coords)
        return to_np(t), None
    from gigapath_tpu.utils.checkpoint import restore_checkpoint

    state = restore_checkpoint(path)
    if isinstance(state, dict):
        return np.asarray(state["features"]), state.get("coords")
    return np.asarray(state), None


def _feature_stream(feature_files, prefetch: int, runlog):
    """Yield ``(idx, path, feats, coords)`` for every feature file.

    ``prefetch == 0``: plain synchronous loads (the historical driver).
    ``prefetch > 0``: a loader thread runs ahead through the dist
    boundary's bounded :class:`~gigapath_tpu.dist.boundary.MemoryChannel`
    — at most ``prefetch`` slides in flight (credit-based, so a slow
    device backpressures the loader onto the obs bus instead of into
    unbounded host memory), IO overlapped with dispatch either way.
    """
    if prefetch <= 0:
        for idx, path in enumerate(feature_files):
            feats, coords = _load_features(path)
            yield idx, path, feats, coords
        return

    import threading

    from gigapath_tpu.dist.boundary import (
        BoundaryConfig,
        EmbeddingChunk,
        MemoryChannel,
    )

    channel = MemoryChannel(BoundaryConfig(capacity=int(prefetch)),
                            runlog=runlog, name="inference.prefetch")
    failure: list = []

    def load():
        try:
            for idx, path in enumerate(feature_files):
                feats, coords = _load_features(path)
                feats = np.asarray(feats, np.float32)
                # digest=False: an intra-process handoff cannot corrupt,
                # and sha256 over a 10^5-tile slide would tax the hot
                # path the prefetch exists to speed up
                channel.send(EmbeddingChunk.build(
                    os.path.basename(path), idx, 0, feats.shape[0], feats,
                    coords=None if coords is None
                    else np.asarray(coords, np.float32),
                    producer="loader", digest=False,
                ))
        except BaseException as e:  # surfaced on the consuming thread
            failure.append(e)
        finally:
            channel.close()

    loader = threading.Thread(target=load, name="inference-prefetch",
                              daemon=True)
    loader.start()
    served = 0
    try:
        while served < len(feature_files):
            chunk = channel.recv(timeout=1.0)
            if chunk is None:
                if failure:
                    raise failure[0]
                continue
            yield (chunk.chunk_id, feature_files[chunk.chunk_id],
                   chunk.payload, chunk.coords)
            channel.ack(chunk.seq)
            served += 1
        if failure:
            raise failure[0]
    finally:
        channel.close()
        loader.join(timeout=10)


def _coords_or_zeros(feats, coords, runlog, warned: list):
    """The ONE coords-defaulting policy for every inference path: None
    becomes zeros (positional signal collapses to one grid cell), with
    one warning per run (``warned`` is the shared mutable flag)."""
    if coords is None:
        if not warned:
            runlog.echo(
                "Warning: feature files carry no coords; using zeros "
                "(positional signal collapses to one grid cell)"
            )
            warned.append(True)
        coords = np.zeros((feats.shape[0], 2), np.float32)
    return np.asarray(coords, np.float32)


def _results_df(results, output_file, runlog, **run_end_fields):
    """Shared CSV + summary tail of both inference paths. A write
    failure (disk full, permissions) is contained like any other run
    failure: ``error`` event + terminal ``run_end(status='error')``, so
    the anomaly engine's error-triggered flight dump and obs_report's
    terminal-status accounting see it."""
    import pandas as pd

    results_df = pd.DataFrame(results)
    try:
        results_df.to_csv(output_file, index=False)
    except Exception as e:
        fail_run(runlog, "inference.results", e)
        raise
    label_counts = {
        str(k): int(v)
        for k, v in results_df["predicted_label"].value_counts().items()
    }
    runlog.echo(f"Inference results saved to {output_file}")
    runlog.echo(f"Label distribution: {label_counts}")
    runlog.echo(f"Mean confidence: {results_df['confidence'].mean():.4f}")
    runlog.run_end(
        status="ok", n_slides=len(results),
        label_distribution=str(label_counts),
        mean_confidence=float(results_df["confidence"].mean()),
        **run_end_fields,
    )
    return results_df


def _run_inference_bucketed(model, params, feature_files, output_file,
                            runlog, batch_size: int, prefetch: int = 0):
    """Bucketed path: the serving stack's ladder + coalescer + AOT
    executables + content-hash cache, driven synchronously.

    Submits stream one file at a time and full buckets dispatch
    immediately (``step()`` after every submit), so at most
    ``batch_size`` slides per bucket are resident at once — the memory
    shape of the old slide-at-a-time loop, times the batch the
    ``--batch_size`` flag always promised.
    """
    from gigapath_tpu.serve import ServeConfig, SlideService

    def forward(p, embeds, coords, pad_mask):
        return model.apply({"params": p}, embeds, coords,
                           pad_mask=pad_mask, deterministic=True)

    config = ServeConfig.from_env(
        max_batch=int(batch_size),
        # an offline batch driver has no latency bound: the serving
        # default (50 ms) would deadline-dispatch batch-of-1 whenever a
        # feature file takes longer than that to load. Full buckets
        # still dispatch eagerly; partials flush in the final drain().
        max_wait_s=float("inf"),
        feature_dim=int(getattr(model, "input_dim", 1536)),
    )
    identity = (
        f"{getattr(model, 'model_arch', type(model).__name__)}"
        f"|feat{getattr(model, 'feat_layer', '?')}"
        f"|cls{getattr(model, 'n_classes', '?')}"
    )
    service = SlideService(forward, params, config=config, runlog=runlog,
                           identity=identity, name="serve")
    results = []
    warned: list = []
    exact_forward = None  # lazily jitted; only oversized slides pay it
    try:
        with Heartbeat(runlog, name="inference") as heartbeat:
            futures = []
            for idx, path, feats, coords in _feature_stream(
                feature_files, prefetch, runlog
            ):
                slide_id = os.path.basename(path).replace("_features.pt", "")
                feats = np.asarray(feats, np.float32)
                coords = _coords_or_zeros(feats, coords, runlog, warned)
                if feats.shape[0] > service.ladder.rungs[-1]:
                    # larger than the ladder's top rung: submit() would
                    # refuse it and abort the run — serve THIS slide on
                    # the exact-shape fallback (one extra compile, like
                    # the old driver) and keep the batch going
                    runlog.echo(
                        f"Warning: {slide_id} has {feats.shape[0]} tiles, "
                        f"above the ladder's top rung "
                        f"{service.ladder.rungs[-1]}; serving it on the "
                        "exact-shape fallback (raise "
                        "GIGAPATH_SERVE_BUCKET_MAX to bucket it)"
                    )
                    from concurrent.futures import Future

                    if exact_forward is None:
                        exact_forward = jax.jit(
                            lambda p, e, c: model.apply(
                                {"params": p}, e, c, deterministic=True
                            )
                        )
                    logits = np.asarray(exact_forward(
                        params, jnp.asarray(feats[None]),
                        jnp.asarray(coords[None])
                    ), np.float32)[0]
                    fut: Future = Future()
                    fut.set_result(logits)
                    futures.append((slide_id, fut))
                else:
                    futures.append((slide_id, service.submit(
                        slide_id, feats, coords
                    )))
                while service.step():  # dispatch any filled buckets now
                    pass
                heartbeat.beat(idx)
            # flush the partial batches — one step() per beat, not one
            # opaque drain(): each flush can pay a fresh AOT compile
            # plus a full padded forward, and a beat-less multi-minute
            # drain would trip the stall detector on a healthy run
            drained = len(feature_files)
            while True:
                n = service.step(drain=True)
                if n == 0 and service.queue.pending() == 0:
                    break
                drained += 1
                heartbeat.beat(drained)
            for slide_id, fut in futures:
                logits = np.asarray(fut.result(), np.float32)
                probs = np.asarray(jax.nn.softmax(logits, axis=-1))
                pred = int(probs.argmax())
                results.append({
                    "slide_id": slide_id,
                    "predicted_label": pred,
                    "confidence": float(probs[pred]),
                })
    except Exception as e:
        fail_run(runlog, "inference.run_inference", e)
        raise
    finally:
        service.close()
    stats = service.stats()
    return _results_df(
        results, output_file, runlog,
        compile_seconds_total=stats["compile_seconds_total"],
        dispatches=stats["dispatches"],
        buckets_used=stats["buckets_used"],
        cache_hits=stats["cache"]["hits"],
        unexpected_retraces=stats["unexpected_retraces"],
        ledger_path=service.ledger.path,
    )


def _run_inference_streaming(model, params, feature_files, output_file,
                             runlog, chunk_tiles: int, prefetch: int = 0):
    """Streaming chunked-prefill path (``--stream``): every slide folds
    through chunk-shaped stage executables via the serve streaming
    submitter — slide-encoder attention temporaries stay O(chunk)
    regardless of tile count, and slides of EVERY length share the same
    compiled programs (the exact-shape path compiles per distinct N;
    the bucket path pads to a rung). ``--prefetch`` composes: the
    loader thread runs ahead through the bounded dist-boundary channel
    while resident slides fold. The bucketed and exact paths remain the
    fallbacks and the parity oracles."""
    from gigapath_tpu.serve.streaming import (
        head_streaming_submitter,
        streaming_head_logits,
    )

    submitter = head_streaming_submitter(
        model, params, chunk_tiles=chunk_tiles or None, runlog=runlog,
    )
    metrics = get_metrics(runlog)
    slide_walls = metrics.histogram("inference.slide_wall_s")
    results = []
    warned: list = []
    try:
        with Heartbeat(runlog, name="inference") as heartbeat:
            for idx, path, feats, coords in _feature_stream(
                feature_files, prefetch, runlog
            ):
                slide_id = os.path.basename(path).replace("_features.pt", "")
                feats = np.asarray(feats, np.float32)
                coords = _coords_or_zeros(feats, coords, runlog, warned)
                with span("slide", runlog, fence=True) as sp:
                    session = submitter.open(slide_id, feats.shape[0])
                    for i, (a, b) in enumerate(session.session.tile_bounds):
                        session.feed(i, feats[a:b], coords[a:b])
                    logits = sp.fence(streaming_head_logits(
                        model, params, session.result()
                    ))
                probs = np.asarray(jax.nn.softmax(
                    jnp.asarray(logits), axis=-1))[0]
                pred = int(probs.argmax())
                results.append({
                    "slide_id": slide_id,
                    "predicted_label": pred,
                    "confidence": float(probs[pred]),
                })
                runlog.step(
                    idx, wall_s=sp.dur_s, synced=True,
                    n_tiles=int(feats.shape[0]),
                    n_chunks=session.session.n_chunks,
                    predicted_label=pred, confidence=float(probs[pred]),
                )
                if sp.dur_s is not None:
                    slide_walls.observe(sp.dur_s)
                metrics.maybe_flush()
                heartbeat.beat(idx)
    except Exception as e:
        fail_run(runlog, "inference.run_inference", e)
        raise
    return _results_df(
        results, output_file, runlog,
        streamed_slides=submitter.served,
        chunk_tiles=submitter.chunk_tiles,
    )


def run_inference(
    model,
    params,
    feature_dir: str,
    output_file: str,
    *,
    use_buckets: bool = True,
    batch_size: int = 16,
    prefetch: int = 0,
    stream: bool = False,
    stream_chunk: int = 0,
):
    """Classify every ``*_features.pt`` in ``feature_dir``
    (reference ``run_inference:37-79``). ``use_buckets`` routes through
    the serving stack (module docstring); False is the exact-shape
    oracle path. ``prefetch > 0`` overlaps feature IO with dispatch
    through the dist boundary's bounded channel (at most that many
    slides in flight — backpressure instead of unbounded run-ahead)."""
    feature_files = sorted(glob.glob(os.path.join(feature_dir, "*_features.pt")))
    if not feature_files:
        console(f"No feature files found in {feature_dir}")
        return None

    runlog = get_run_log(
        "inference", out_dir=os.path.dirname(os.path.abspath(output_file)),
        config={"feature_dir": feature_dir, "output_file": output_file,
                "n_slides": len(feature_files), "buckets": bool(use_buckets),
                "batch_size": int(batch_size), "prefetch": int(prefetch),
                "stream": bool(stream)},
    )
    if stream:
        return _run_inference_streaming(
            model, params, feature_files, output_file, runlog,
            chunk_tiles=int(stream_chunk), prefetch=prefetch,
        )
    if use_buckets:
        return _run_inference_bucketed(
            model, params, feature_files, output_file, runlog, batch_size,
            prefetch=prefetch,
        )

    @jax.jit
    def forward(params, embeds, coords):
        return model.apply({"params": params}, embeds, coords, deterministic=True)

    # variable-length slides -> one compile per distinct N; the watchdog
    # turns that invisible first-slide pause into compile events and the
    # ledger records each new shape's compiled cost/memory profile
    ledger = get_ledger(runlog)
    watchdog = CompileWatchdog("inference.forward", runlog, ledger=ledger)
    instrumented_forward = watchdog.wrap(forward)
    # typed metrics (obs/metrics.py): per-slide wall histogram; the
    # final snapshot flushes inside run_end via the registry's closer
    metrics = get_metrics(runlog)
    slide_walls = metrics.histogram("inference.slide_wall_s")

    results = []
    warned: list = []
    try:
        with Heartbeat(runlog, name="inference") as heartbeat:
            for idx, path in enumerate(feature_files):
                # fenced span (GL008): dur_s covers load + dispatch +
                # device execution for this slide
                with span("slide", runlog, fence=True) as sp:
                    feats, coords = _load_features(path)
                    coords = _coords_or_zeros(feats, coords, runlog,
                                              warned)[None]
                    feats = feats[None]  # [1, N, D]
                    logits = np.asarray(
                        sp.fence(instrumented_forward(
                            params, jnp.asarray(feats), jnp.asarray(coords)
                        )),
                        np.float32,
                    )
                probs = np.asarray(jax.nn.softmax(logits, axis=-1))[0]
                pred = int(probs.argmax())
                results.append(
                    {
                        "slide_id": os.path.basename(path).replace("_features.pt", ""),
                        "predicted_label": pred,
                        "confidence": float(probs[pred]),
                    }
                )
                runlog.step(
                    idx, wall_s=sp.dur_s, synced=True,
                    n_tiles=int(feats.shape[1]), predicted_label=pred,
                    confidence=float(probs[pred]),
                )
                if sp.dur_s is not None:
                    slide_walls.observe(sp.dur_s)
                metrics.maybe_flush()
                heartbeat.beat(idx)
    except Exception as e:
        fail_run(runlog, "inference.run_inference", e)
        raise

    return _results_df(
        results, output_file, runlog,
        compile_seconds_total=watchdog.compile_seconds_total(),
        ledger_path=ledger.path,
    )


def main(argv=None):
    parser = argparse.ArgumentParser(description="GigaPath model inference")
    parser.add_argument("--model_path", type=str, required=True)
    parser.add_argument("--feature_dir", type=str, required=True)
    parser.add_argument("--output_file", type=str, default="predictions.csv")
    parser.add_argument(
        "--batch_size", type=int, default=16,
        help="Slides coalesced per padded bucket batch (the serving "
        "stack's max_batch; ignored under --no-buckets, where slides "
        "are processed one at a time)",
    )
    parser.add_argument(
        "--no-buckets", dest="no_buckets", action="store_true",
        help="Exact-shape fallback/oracle path: one jit compile per "
        "distinct tile count, no batching, no padding",
    )
    parser.add_argument(
        "--stream", action="store_true",
        help="Streaming chunked prefill: fold each slide through "
        "chunk-shaped stage executables (O(chunk) attention "
        "temporaries, one compiled program set for every slide "
        "length). Defaults ON when GIGAPATH_CHUNKED_PREFILL is set.",
    )
    parser.add_argument(
        "--stream-chunk", type=int, default=0,
        help="Tiles per streaming-prefill chunk (0 = the "
        "GIGAPATH_PREFILL_CHUNK host flag, default 2048)",
    )
    parser.add_argument(
        "--prefetch", type=int, default=0,
        help="Overlap feature-file IO with dispatch: a loader thread "
        "runs at most this many slides ahead through the dist "
        "boundary's bounded channel (0 = synchronous loads; bucketed "
        "path only)",
    )
    parser.add_argument("--num_classes", type=int, default=2)
    parser.add_argument("--model_arch", type=str, default="gigapath_slide_enc12l768d")
    args = parser.parse_args(argv)
    model, params = load_model(
        args.model_path, n_classes=args.num_classes, model_arch=args.model_arch
    )
    # GIGAPATH_CHUNKED_PREFILL makes streaming the default route (one
    # host-side snapshot, the PipelineFlags convention)
    from gigapath_tpu.ops.pallas_dilated import snapshot_flags

    stream = bool(args.stream or snapshot_flags().chunked_prefill)
    return run_inference(
        model, params, args.feature_dir, args.output_file,
        use_buckets=not args.no_buckets, batch_size=args.batch_size,
        prefetch=args.prefetch, stream=stream,
        stream_chunk=args.stream_chunk,
    )


if __name__ == "__main__":
    main()
