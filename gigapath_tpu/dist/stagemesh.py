"""Per-stage meshes and the declarative sharding-rule registry.

The disaggregated pipeline runs the two encoders as separate fleets, and
each fleet needs its own mesh geometry and parameter layout:

- the **tile encoder** is data-parallel over tiles (every device crunches
  its own tile batch; optional tensor parallelism over the ViT's hidden
  dim) — axes ``("data", "model")``;
- the **slide encoder** is sequence/model-sharded (the 10^5-10^6-token
  tile-embedding sequence is what must split) — axes
  ``("data", "seq", "model")``.

Instead of hand-wiring pjit in_shardings per call site, each stage's
layout is a *registry entry*: an ordered list of
``(param-path regex, PartitionSpec)`` rules resolved against the param
tree by :func:`match_partition_rules` (the pattern of SNIPPETS.md [1] —
first matching rule wins, scalars never partition, an uncovered param is
a loud error, not silent replication). Both fleets consume the same
registry, so "what crosses which axis" stays auditable in one place —
the same philosophy as ``parallel/sharding.py``'s ``_SEQ_COLLECTIVES``
table, lifted from collectives to layouts.

Mesh construction delegates to :func:`gigapath_tpu.parallel.mesh.make_mesh`
over each stage's axis subset; rules degrade gracefully when a mesh
lacks (or has size 1 on) an axis a spec names.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gigapath_tpu.parallel.mesh import make_mesh
from gigapath_tpu.parallel.sharding import _COLUMN_PARALLEL, _ROW_PARALLEL


def match_partition_rules(rules: Sequence[Tuple[str, P]], params):
    """PartitionSpec pytree from ordered ``(regex, spec)`` rules.

    Each leaf's ``/``-joined module path (``encoder/layers_0/fc1/kernel``)
    is matched with ``re.search``; the FIRST matching rule wins. Scalar
    (or 1-element) leaves never partition. A leaf matching no rule
    raises — a silent fall-through to replicated is exactly the bug
    class gigalint GL003 exists for, so the registry ends every stage's
    list with an explicit catch-all instead.
    """
    compiled = [(re.compile(rule), spec) for rule, spec in rules]

    def one(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for rx, spec in compiled:
            if rx.search(name) is not None:
                return spec
        raise ValueError(
            f"no partition rule matches param '{name}' "
            f"(shape {tuple(shape)}); add a rule (or an explicit "
            "catch-all) to the stage's registry entry"
        )

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [one(path, leaf) for path, leaf in flat]
    )


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One fleet's declarative geometry + layout."""

    name: str
    axes: Tuple[str, ...]
    rules: Tuple[Tuple[str, P], ...]
    description: str = ""


def _tp_rules(model_axis: str = "model") -> Tuple[Tuple[str, P], ...]:
    """The tensor-parallel kernel rules, derived from the SAME
    column/row-parallel name lists ``parallel/sharding.py`` maintains
    (and gigalint GL003 audits) — two spellings of one layout table, by
    construction."""
    col = "|".join(_COLUMN_PARALLEL)
    row = "|".join(_ROW_PARALLEL)
    return (
        (rf"(^|/)({col})/kernel$", P(None, model_axis)),
        (rf"(^|/)({row})/kernel$", P(model_axis, None)),
        # vmapped MoE experts carry a leading E axis (ops/moe/moe_layer)
        (r"(^|/)experts/", P("expert")),
        (r".*", P()),  # everything else (biases, norms, embeddings)
    )


_REGISTRY: Dict[str, StageSpec] = {}


def register_stage(spec: StageSpec) -> StageSpec:
    _REGISTRY[spec.name] = spec
    return spec


def get_stage(name: str) -> StageSpec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown stage '{name}' (registered: {stage_names()})"
        ) from None


def stage_names() -> List[str]:
    return sorted(_REGISTRY)


register_stage(StageSpec(
    name="tile_encoder",
    axes=("data", "model"),
    rules=_tp_rules(),
    description="ViT-G tile fleet: data-parallel over tiles, optional "
                "tensor parallelism over hidden/head dims",
))

register_stage(StageSpec(
    name="slide_encoder",
    axes=("data", "seq", "model"),
    rules=_tp_rules(),
    description="LongNet slide fleet: the tile-embedding sequence shards "
                "over seq (ring/chunked prefill), kernels over model",
))


def stage_mesh(name: str, n_devices: Optional[int] = None, *,
               devices=None,
               axis_sizes: Optional[Dict[str, int]] = None) -> Mesh:
    """Build one stage's mesh over (a subset of) the visible devices —
    the two-process-group dryrun gives each stage its own device slice
    via ``devices=``."""
    spec = get_stage(name)
    if axis_sizes is not None:
        unknown = set(axis_sizes) - set(spec.axes)
        if unknown:
            raise ValueError(
                f"stage '{name}' has axes {spec.axes}; axis_sizes names "
                f"{sorted(unknown)}"
            )
    return make_mesh(n_devices, axes=spec.axes, devices=devices,
                     axis_sizes=axis_sizes)


def _degrade(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh lacks (or has size 1 on) from a spec —
    the rules stay declarative, the mesh decides what is real."""
    live = {a for a in mesh.axis_names if mesh.shape[a] > 1}

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(e for e in entry if e in live)
            return kept if kept else None
        return entry if entry in live else None

    return P(*(keep(e) for e in spec))


def stage_param_shardings(name: str, params, mesh: Mesh):
    """NamedSharding pytree for one stage's params under its mesh (the
    registry rules, degraded to the mesh's live axes)."""
    spec = get_stage(name)
    specs = match_partition_rules(spec.rules, params)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, _degrade(s, mesh)), specs,
        is_leaf=lambda x: isinstance(x, P),
    )
