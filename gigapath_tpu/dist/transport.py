"""The real network transport of the cross-stage boundary: TCP sockets.

Same producer/consumer protocol as :class:`~gigapath_tpu.dist.boundary.
DirChannelProducer`/``DirChannelConsumer`` (credits, acks, seq dedup,
checksums, retransmit timer, one ``backpressure`` event per blocking
episode), over a wire instead of a shared directory — the DCN/RPC shape
ROADMAP item 4 called for, with the directory transport kept as the
dryrun stand-in. ``worker.py``/``pipeline.py`` pick the transport
through :func:`make_producer`/:func:`make_consumer`
(``GIGAPATH_DIST_TRANSPORT``) with zero changes to the fold path.

Wire format — length-prefixed frames with MANDATORY digests:

    ``b"GPF1" | body_len:u32 | sha256(body):32B | body``
    ``body = header_len:u32 | header_json | blob``

``header_json`` carries the frame type (``hello`` / ``hello_ack`` /
``chunk`` / ``ack``); a chunk frame's blob is the same npz byte layout
the directory transport writes, so the chunk's OWN sha256 checksum rides
inside the frame digest (frame digest = wire integrity, chunk checksum
= end-to-end integrity — a corrupt frame is dropped and counted, never
delivered).

Recovery properties:

- **handshake**: every (re)connection opens with ``hello`` carrying the
  run id + producer id; the consumer answers ``hello_ack`` with its ACK
  WATERMARK (the sorted seqs it considers durable). The producer drops
  those from its unacked set and replays exactly the rest — a reconnect
  retransmits the unacked chunk ids and nothing else, and a RESTARTED
  consumer (whose watermark is its checkpoint's, see
  ``pipeline.run_slide_consumer``) receives only post-watermark chunks;
- **reconnect**: capped exponential backoff with full jitter
  (``random.uniform(0, min(cap, base * 2**attempt))`` — the herd-safe
  schedule), endpoint re-read per attempt (a restarted consumer binds a
  fresh port and rewrites ``transport.json``);
- **deadlines everywhere**: every ``connect`` carries
  ``connect_timeout_s``, every blocking frame read a ``settimeout``,
  the consumer's event loop a ``select(timeout)`` — no recv without a
  deadline (gigalint GL015 enforces this even here, the one
  socket-sanctioned module);
- **chaos at the frame layer**: ``drop_conn@K`` (half the frame, then
  the socket dies), ``delay_frame@K[:S]``, ``corrupt_frame@K`` (bytes
  flipped after the digest was computed), ``reorder_frame@K`` — all
  injected host-side inside :meth:`TcpChannelProducer._transmit`, so a
  chaos run compiles the same programs as a clean one.

numpy + stdlib only (no jax import), like the rest of the protocol
layer — the transport can never retrace anything.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import random
import selectors
import socket
import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gigapath_tpu.dist.boundary import (
    BoundaryConfig,
    ChannelStats,
    EmbeddingChunk,
    LinkTelemetry,
    _emit_backpressure,
)
from gigapath_tpu.dist.membership import _read_json, atomic_write_json
from gigapath_tpu.obs.clock import ClockSample, LinkClock, emit_clock_sync

MAGIC = b"GPF1"
_PREFIX = struct.Struct("!4sI")      # magic, body length
_U32 = struct.Struct("!I")
_DIGEST_SIZE = 32
MAX_FRAME_BYTES = 1 << 30            # framing sanity bound
ENDPOINT_FILE = "transport.json"
_BACKOFF_BASE_S = 0.05


class FrameError(ValueError):
    """Unrecoverable framing damage (bad magic / absurd length): the
    stream position is lost, the connection must be torn down."""


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------

def encode_frame(header: dict, blob: bytes = b"") -> bytes:
    """One wire frame: length-prefixed, sha256-digested body."""
    header_json = json.dumps(header, sort_keys=True).encode()
    body = _U32.pack(len(header_json)) + header_json + blob
    return _PREFIX.pack(MAGIC, len(body)) + hashlib.sha256(body).digest() + body


def decode_body(body: bytes) -> Tuple[dict, bytes]:
    (header_len,) = _U32.unpack_from(body, 0)
    header = json.loads(body[_U32.size:_U32.size + header_len].decode())
    return header, body[_U32.size + header_len:]


def chunk_to_blob(chunk: EmbeddingChunk) -> bytes:
    """Same npz byte layout as the directory transport's ``_write`` —
    one serialization, two transports."""
    arrays = dict(
        slide_id=np.array(chunk.slide_id),
        chunk_id=np.array(chunk.chunk_id, np.int64),
        start=np.array(chunk.start, np.int64),
        stop=np.array(chunk.stop, np.int64),
        payload=chunk.payload,
        producer=np.array(chunk.producer),
        checksum=np.array(chunk.checksum),
        trace_id=np.array(chunk.trace_id),
        parent_span_id=np.array(chunk.parent_span_id),
    )
    if chunk.coords is not None:
        arrays["coords"] = chunk.coords
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def blob_to_chunk(blob: bytes) -> Optional[EmbeddingChunk]:
    try:
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            coords = z["coords"] if "coords" in z.files else None
            return EmbeddingChunk(
                slide_id=str(z["slide_id"]),
                chunk_id=int(z["chunk_id"]), start=int(z["start"]),
                stop=int(z["stop"]), payload=np.asarray(z["payload"]),
                coords=None if coords is None else np.asarray(coords),
                producer=str(z["producer"]),
                checksum=str(z["checksum"]),
                trace_id=str(z["trace_id"])
                if "trace_id" in z.files else "",
                parent_span_id=str(z["parent_span_id"])
                if "parent_span_id" in z.files else "",
            )
    except (OSError, ValueError, KeyError):
        return None


class FrameBuffer:
    """Incremental frame parser over a byte stream. ``feed`` appends
    received bytes; ``frames`` yields every complete, digest-verified
    ``(header, blob)``. Digest mismatches are counted and skipped (the
    length prefix sits OUTSIDE the digest, so framing survives a
    corrupted body); magic/length damage raises :class:`FrameError`."""

    def __init__(self):
        self._buf = bytearray()
        self.digest_errors = 0

    def feed(self, data: bytes) -> None:
        self._buf.extend(data)

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)

    def frames(self) -> List[Tuple[dict, bytes]]:
        out: List[Tuple[dict, bytes]] = []
        while True:
            if len(self._buf) < _PREFIX.size:
                return out
            magic, body_len = _PREFIX.unpack_from(self._buf, 0)
            if magic != MAGIC or body_len > MAX_FRAME_BYTES:
                raise FrameError(
                    f"misframed stream (magic={magic!r}, len={body_len})"
                )
            total = _PREFIX.size + _DIGEST_SIZE + body_len
            if len(self._buf) < total:
                return out
            digest = bytes(self._buf[_PREFIX.size:_PREFIX.size + _DIGEST_SIZE])
            body = bytes(self._buf[_PREFIX.size + _DIGEST_SIZE:total])
            del self._buf[:total]
            if hashlib.sha256(body).digest() != digest:
                self.digest_errors += 1
                continue  # the frame is droppable; framing is intact
            try:
                out.append(decode_body(body))
            except (ValueError, KeyError, UnicodeDecodeError):
                self.digest_errors += 1


# ---------------------------------------------------------------------------
# endpoint discovery
# ---------------------------------------------------------------------------

def endpoint_path(root: str) -> str:
    return os.path.join(root, ENDPOINT_FILE)


def read_endpoint(root: str) -> Optional[Tuple[str, int]]:
    doc = _read_json(endpoint_path(root))
    if not doc or "port" not in doc:
        return None
    return str(doc.get("host", "127.0.0.1")), int(doc["port"])


def _metrics_counters(runlog):
    """The dist transport's three registry counters (a NullRunLog — or
    metrics off — yields no-op instruments)."""
    from gigapath_tpu.obs.metrics import get_metrics

    m = get_metrics(runlog)
    return (m.counter("dist.reconnects"), m.counter("dist.frame_errors"),
            m.counter("dist.bytes_sent"))


# ---------------------------------------------------------------------------
# consumer (the accepting side — the slide stage binds, workers dial in)
# ---------------------------------------------------------------------------

class TcpChannelConsumer:
    """The slide stage's receiving half over TCP: binds an ephemeral
    loopback port, publishes it to ``<root>/transport.json`` (atomic),
    and fans in every producer connection through one single-threaded
    ``selectors`` loop — no reader threads, no hand-rolled queues.

    ``delivered`` seeds the dedup AND ack-watermark sets for a restarted
    consumer: the handshake tells reconnecting producers these seqs are
    durable, so they replay only the rest."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 runlog=None, name: str = "tcp",
                 delivered: Optional[Sequence[int]] = None,
                 host: str = "127.0.0.1", run_id: str = ""):
        self.cfg = config or BoundaryConfig()
        self.root = root
        self.name = name
        self.run_id = run_id
        self._runlog = runlog
        self.stats = ChannelStats()
        (self._c_reconnects, self._c_frame_errors,
         self._c_bytes) = _metrics_counters(runlog)
        self._delivered: set = set(
            int(s) for s in delivered) if delivered else set()
        self._acked: set = set(self._delivered)
        self._ready: List[EmbeddingChunk] = []  # parsed, undelivered
        self._conns: Dict[socket.socket, dict] = {}
        self._seq_conn: Dict[int, socket.socket] = {}
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, 0))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listener, selectors.EVENT_READ)
        os.makedirs(root, exist_ok=True)
        atomic_write_json(endpoint_path(root), {
            "host": host, "port": self._listener.getsockname()[1],
            "pid": os.getpid(), "run": run_id,
        })
        self._closed = False

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    # -- the event loop ----------------------------------------------------
    def _drop_conn(self, sock: socket.socket, *, torn: bool) -> None:
        state = self._conns.pop(sock, None)
        if torn or (state and state["buf"].pending_bytes):
            # a half-received frame died with the connection
            self.stats.frame_errors += 1
            self._c_frame_errors.inc()
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _send_frame(self, sock: socket.socket, header: dict,
                    blob: bytes = b"") -> bool:
        """Outbound ack/handshake frame. The socket lives non-blocking
        for the read loop, but a send must not tear a frame on
        transient buffer pressure (sendall on a non-blocking socket can
        raise BlockingIOError after a PARTIAL write): flip to a
        deadline-bounded blocking send, restore after. Only a peer
        stuck past the deadline — not a full buffer — drops the
        connection."""
        try:
            sock.settimeout(self.cfg.connect_timeout_s)
            sock.sendall(encode_frame(header, blob))
            return True
        except OSError:
            self._drop_conn(sock, torn=False)
            return False
        finally:
            try:
                sock.setblocking(False)
            except OSError:
                pass  # already dropped/closed

    def _handle_frame(self, sock: socket.socket, header: dict,
                      blob: bytes) -> Optional[EmbeddingChunk]:
        kind = header.get("type")
        if kind == "hello":
            self._conns[sock]["producer"] = str(header.get("producer", "?"))
            # the ack watermark: what THIS consumer considers durable —
            # a reconnecting producer replays exactly the complement
            reply = {
                "type": "hello_ack", "run": self.run_id,
                "acked": sorted(self._acked),
            }
            if "t_send" in header:
                # clock alignment (obs/clock.py): echo the producer's
                # send stamp and add this clock's receive/reply stamps —
                # the producer completes the four-timestamp sample when
                # the reply lands and re-estimates the link offset on
                # EVERY (re)connect (a restarted peer is a fresh
                # monotonic origin)
                now = time.monotonic()
                reply["t_send"] = header["t_send"]
                reply["t_recv"] = now
                reply["t_reply"] = now
            self._send_frame(sock, reply)
            return None
        if kind == "ack":
            return None  # producers ack nothing; ignore
        if kind != "chunk":
            self.stats.frame_errors += 1
            self._c_frame_errors.inc()
            return None
        chunk = blob_to_chunk(blob)
        if chunk is None:
            self.stats.frame_errors += 1
            self._c_frame_errors.inc()
            return None
        if chunk.seq in self._delivered:
            self.stats.duplicates += 1
            if chunk.seq in self._acked:
                # the producer missed the ack (e.g. its conn died before
                # the ack frame landed): re-ack so it stops replaying
                self._send_frame(sock, {"type": "ack", "seq": chunk.seq})
            return None
        # cross-process transports must digest end-to-end: an empty
        # chunk checksum is rejected like the directory consumer does
        if not chunk.checksum or not chunk.verify():
            self.stats.corrupt += 1
            return None
        self._delivered.add(chunk.seq)
        self._seq_conn[chunk.seq] = sock
        self.stats.delivered += 1
        return chunk

    def _pump(self, timeout: float) -> None:
        """One bounded select pass: accept, read, parse. Parsed chunks
        land in ``self._ready`` (a same-thread list, drained by
        ``recv``)."""
        for key, _ in self._sel.select(timeout=max(timeout, 0.0)):
            sock = key.fileobj
            if sock is self._listener:
                try:
                    conn, _ = self._listener.accept()
                except OSError:
                    continue
                conn.setblocking(False)
                self._conns[conn] = {"buf": FrameBuffer(), "producer": ""}
                self._sel.register(conn, selectors.EVENT_READ)
                continue
            state = self._conns.get(sock)
            if state is None:
                continue
            try:
                data = sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                self._drop_conn(sock, torn=False)
                continue
            if not data:
                # peer EOF: only a non-empty parse buffer means a frame
                # died with the connection — a clean close (worker done,
                # SIGKILL between frames) is not wire corruption
                self._drop_conn(sock, torn=False)
                continue
            buf = state["buf"]
            buf.feed(data)
            before = buf.digest_errors
            try:
                frames = buf.frames()
            except FrameError:
                self._drop_conn(sock, torn=True)
                continue
            if buf.digest_errors > before:
                n = buf.digest_errors - before
                self.stats.frame_errors += n
                self._c_frame_errors.inc(n)
            for header, blob in frames:
                chunk = self._handle_frame(sock, header, blob)
                if chunk is not None:
                    self._ready.append(chunk)

    # -- the channel surface ------------------------------------------------
    def recv(self, timeout: Optional[float] = None) -> Optional[EmbeddingChunk]:
        """Next new, verified chunk (any producer), or None on timeout —
        the same contract as ``DirChannelConsumer.recv``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._ready:
                return self._ready.pop(0)
            if self._closed:
                return None
            wait = self.cfg.poll_s
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait < 0:
                    return None
            self._pump(wait)
            if self._ready:
                return self._ready.pop(0)
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def ack(self, seq: int) -> None:
        """Ack ``seq`` toward the producer that delivered it (falling
        back to every live connection — an ack is idempotent and a
        reconnected producer learns the watermark from the handshake
        anyway)."""
        seq = int(seq)
        self._acked.add(seq)
        self.stats.acked += 1
        sock = self._seq_conn.pop(seq, None)
        if sock is not None and sock in self._conns:
            if self._send_frame(sock, {"type": "ack", "seq": seq}):
                return
        for other in list(self._conns):
            self._send_frame(other, {"type": "ack", "seq": seq})

    def acked_seqs(self) -> List[int]:
        return sorted(self._acked)

    def close(self) -> None:
        self._closed = True
        for sock in list(self._conns):
            self._drop_conn(sock, torn=False)
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._sel.close()


# ---------------------------------------------------------------------------
# producer (one per tile worker — dials the consumer, replays on reconnect)
# ---------------------------------------------------------------------------

class TcpChannelProducer:
    """One tile worker's sending half over TCP. Connection management is
    LAZY and self-healing: ``send``/``pump_retransmits`` (re)connect as
    needed with capped-exponential-backoff + full-jitter, and every
    (re)handshake reconciles the unacked set against the consumer's ack
    watermark, then replays exactly the still-unacked chunks."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 producer: str = "", runlog=None, chaos=None,
                 name: str = "tcp", run_id: str = ""):
        self.cfg = config or BoundaryConfig()
        self.root = root
        self.producer = producer
        self.name = name
        self.run_id = run_id
        self._runlog = runlog
        self._chaos = chaos
        self.stats = ChannelStats()
        (self._c_reconnects, self._c_frame_errors,
         self._c_bytes) = _metrics_counters(runlog)
        self.telemetry = LinkTelemetry(runlog, f"{name}.{producer or 'p'}")
        self.clock = LinkClock(f"{name}.{producer or 'p'}")
        self._sock: Optional[socket.socket] = None
        self._buf = FrameBuffer()           # the ack/handshake stream
        self._ever_connected = False
        self._replay_on_watermark = False
        self._sent_at: Dict[int, float] = {}
        self._chunks: Dict[int, EmbeddingChunk] = {}
        self._frame_idx = 0                 # data-frame index for chaos
        self._reorder_held: Optional[bytes] = None
        self._episode_seq: Optional[int] = None

    # -- connection management ----------------------------------------------
    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._buf = FrameBuffer()

    def _connect_once(self) -> bool:
        """One connect attempt: dial, send ``hello``, and mark the
        stream as awaiting the consumer's ``hello_ack``. The handshake
        reply is processed ASYNCHRONOUSLY by :meth:`_drain_acks` (the
        consumer serves handshakes from its single recv loop — a
        producer blocking here for the reply would couple its send path
        to the consumer's poll cadence)."""
        addr = read_endpoint(self.root)
        if addr is None:
            return False
        try:
            sock = socket.create_connection(
                addr, timeout=self.cfg.connect_timeout_s
            )
        except OSError:
            return False
        was_reconnect = self._ever_connected
        self._close_sock()
        self._sock = sock
        # replay is gated on the watermark: a RECONNECT (or a first
        # connect that follows lost offline writes) must re-send the
        # unacked complement once the consumer tells us what is durable.
        # A clean first connect replays nothing — no spurious dups.
        self._replay_on_watermark = was_reconnect or bool(self._sent_at)
        if self._replay_on_watermark:
            # re-stamp so the retransmit timer defers to the imminent
            # watermark replay (it stays the fallback if the reply is
            # lost with yet another connection death)
            now = time.monotonic()
            for seq in self._sent_at:
                self._sent_at[seq] = now
        # every (re)connect re-estimates the link clock: the peer may be
        # a restarted process with a brand-new monotonic origin
        self.clock.resync()
        self._raw_send(encode_frame({
            "type": "hello", "run": self.run_id,
            "producer": self.producer,
            "t_send": time.monotonic(),
        }))
        if self._sock is None:  # the hello send itself failed
            return False
        self._ever_connected = True
        if was_reconnect:
            self.stats.reconnects += 1
            self._c_reconnects.inc()
            if self._runlog is not None:
                self._runlog.event(
                    "recovery", action="reconnect", channel=self.name,
                    producer=self.producer,
                    unacked=len(self._sent_at),
                )
        return True

    def _on_watermark(self, acked: Sequence[int]) -> None:
        """Process the handshake reply: reconcile the unacked set
        against the consumer's ack watermark, then replay exactly the
        still-unacked chunks — and nothing else."""
        for seq in acked:
            if self._sent_at.pop(int(seq), None) is not None:
                self._chunks.pop(int(seq), None)
                self.stats.acked += 1
        if not self._replay_on_watermark:
            return
        self._replay_on_watermark = False
        for seq in sorted(self._sent_at):
            chunk = self._chunks.get(seq)
            if chunk is None:
                continue
            self._transmit(chunk)
            self._sent_at[seq] = time.monotonic()
            self.stats.retransmits += 1
            self.telemetry.on_retransmit()

    def _ensure_connected(self,
                          deadline: Optional[float] = None) -> bool:
        """Reconnect loop: capped exponential backoff with FULL jitter
        (every waiter picks uniform-random inside the cap, so a fleet of
        workers reconnecting to a restarted consumer cannot stampede in
        lockstep)."""
        if self._sock is not None:
            return True
        attempt = 0
        while True:
            if self._connect_once():
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            cap = min(self.cfg.backoff_s, _BACKOFF_BASE_S * (2 ** attempt))
            delay = random.uniform(0, cap)
            if deadline is not None:
                delay = min(delay, max(deadline - time.monotonic(), 0))
            time.sleep(delay)
            attempt += 1

    # -- wire ----------------------------------------------------------------
    def _raw_send(self, frame: bytes) -> None:
        if self._sock is None:
            return  # lost write: stays unacked, reconnect+replay heals it
        try:
            self._sock.settimeout(self.cfg.connect_timeout_s)
            self._sock.sendall(frame)
            self.stats.bytes_sent += len(frame)
            self._c_bytes.inc(len(frame))
            self.telemetry.on_send(len(frame))
        except OSError:
            self._close_sock()

    def _transmit(self, chunk: EmbeddingChunk) -> None:
        """Serialize + send one chunk frame, with the frame-layer chaos
        injectors applied here — host-side, inside the transport, so
        chaos runs compile the same programs as clean runs."""
        frame = encode_frame(
            {"type": "chunk", "seq": chunk.seq, "producer": self.producer},
            chunk_to_blob(chunk),
        )
        idx = self._frame_idx
        self._frame_idx += 1
        chaos = self._chaos
        if chaos:
            delay = chaos.delay_frame(idx)
            if delay:
                time.sleep(delay)
            if chaos.corrupts_frame(idx):
                # flip bytes INSIDE the body, after the digest was
                # computed: framing survives, the digest check must not
                corrupted = bytearray(frame)
                body_at = _PREFIX.size + _DIGEST_SIZE
                for off in range(body_at + 8, min(body_at + 24, len(corrupted))):
                    corrupted[off] ^= 0xFF
                frame = bytes(corrupted)
            if chaos.drops_conn(idx):
                # a torn write: half the frame lands, then the wire dies
                half = frame[: len(frame) // 2]
                if self._sock is not None:
                    try:
                        self._sock.settimeout(self.cfg.connect_timeout_s)
                        self._sock.sendall(half)
                        self.stats.bytes_sent += len(half)
                        self._c_bytes.inc(len(half))
                        self.telemetry.on_send(len(half))
                    except OSError:
                        pass
                self._close_sock()
                self.stats.dropped += 1
                return
            if chaos.reorders_frame(idx):
                self._reorder_held = frame
                return
        self._raw_send(frame)
        if chaos and self._reorder_held is not None:
            held, self._reorder_held = self._reorder_held, None
            self._raw_send(held)

    def _drain_acks(self) -> None:
        """Non-blocking sweep of the consumer->producer stream (acks)."""
        if self._sock is None:
            return
        while True:
            try:
                self._sock.settimeout(0.0)
                data = self._sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError, socket.timeout):
                return
            except OSError:
                self._close_sock()
                return
            if not data:
                self._close_sock()
                return
            self._buf.feed(data)
            try:
                frames = self._buf.frames()
            except FrameError:
                self._close_sock()
                return
            for header, _ in frames:
                if header.get("type") == "ack":
                    seq = int(header.get("seq", -1))
                    if self._sent_at.pop(seq, None) is not None:
                        self._chunks.pop(seq, None)
                        self.stats.acked += 1
                elif header.get("type") == "hello_ack":
                    self._fold_clock_sample(header)
                    self._on_watermark(header.get("acked", []))

    def _fold_clock_sample(self, header: dict) -> None:
        """Complete the four-timestamp sample the ``hello`` opened: the
        ``hello_ack`` echoes ``t_send`` and carries the consumer's
        ``t_recv``/``t_reply``; the ack stamp is taken here, when the
        reply surfaces from the drain. One ``clock_sync`` event per
        folded sample."""
        if "t_send" not in header:
            return  # pre-clock peer: no sample, offset stays 0
        try:
            sample = ClockSample(
                t_send=float(header["t_send"]),
                t_recv=float(header["t_recv"]),
                t_reply=float(header["t_reply"]),
                t_ack=time.monotonic(),
            )
        except (KeyError, TypeError, ValueError):
            return  # malformed stamps: drop the sample, never the link
        est = self.clock.update(sample)
        emit_clock_sync(self._runlog, self.clock, est)

    def _update_depth(self) -> None:
        self.telemetry.set_depth(
            unacked=len(self._sent_at), capacity=self.cfg.capacity,
            oldest_sent_at=min(self._sent_at.values())
            if self._sent_at else None,
        )

    # -- the channel surface --------------------------------------------------
    def credits(self) -> int:
        self._drain_acks()
        self._update_depth()
        return max(self.cfg.capacity - len(self._sent_at), 0)

    def unacked_seqs(self) -> List[int]:
        self._drain_acks()
        return sorted(self._sent_at)

    def send(self, chunk: EmbeddingChunk,
             timeout: Optional[float] = None) -> None:
        """Blocks (polling) while every credit is in flight — identical
        credit/backpressure semantics to the other transports, with the
        connection managed underneath."""
        deadline = None if timeout is None else time.monotonic() + timeout
        self._ensure_connected(deadline)
        blocked_at = None
        while self.credits() <= 0:
            if blocked_at is None:
                blocked_at = time.monotonic()
                if self._episode_seq != chunk.seq:
                    self._episode_seq = chunk.seq
                    self.stats.backpressure_events += 1
                    _emit_backpressure(
                        self._runlog, channel=self.name, seq=chunk.seq,
                        queue_depth=len(self._sent_at),
                        capacity=self.cfg.capacity,
                    )
            if deadline is not None and time.monotonic() >= deadline:
                blocked = time.monotonic() - blocked_at
                self.stats.blocked_s += blocked
                self.telemetry.on_blocked(blocked)
                raise TimeoutError(
                    f"{self.name}: no credit within {timeout}s "
                    f"(seq {chunk.seq})"
                )
            time.sleep(self.cfg.poll_s)
        if blocked_at is not None:
            blocked = time.monotonic() - blocked_at
            self.stats.blocked_s += blocked
            self.telemetry.on_blocked(blocked)
        self._sent_at[chunk.seq] = time.monotonic()
        self._chunks[chunk.seq] = chunk
        self.stats.sent += 1
        if self._chaos is not None and self._chaos.drops_chunk(chunk.seq):
            self.stats.dropped += 1
            return
        self._transmit(chunk)
        if self._chaos is not None and self._chaos.dups_chunk(chunk.seq):
            self._transmit(chunk)

    def pump_retransmits(self, now: Optional[float] = None) -> int:
        """Re-send unacked chunks past the timer; a dead connection is
        re-established first (its handshake-watermark replay covers
        every unacked chunk the moment the ``hello_ack`` arrives, and
        the timer below stays the fallback)."""
        self._drain_acks()
        self._update_depth()
        if self._sock is None and self._sent_at:
            # ONE connect attempt per pump: the caller's poll loop is
            # the backoff here, and a worker must keep renewing its
            # lease between attempts — a blocking reconnect loop inside
            # the pump would read as a dead worker (the send path keeps
            # the jittered backoff, bounded by its own timeout)
            if not self._connect_once():
                return 0
            self._drain_acks()  # the watermark reply may already be in
            return len(self._sent_at)
        now = time.monotonic() if now is None else now
        n = 0
        for seq, sent_at in list(self._sent_at.items()):
            if now - sent_at >= self.cfg.retransmit_s:
                chunk = self._chunks.get(seq)
                if chunk is None:
                    continue
                self._transmit(chunk)
                self._sent_at[seq] = now
                self.stats.retransmits += 1
                self.telemetry.on_retransmit()
                n += 1
        return n

    def close(self) -> None:
        self._close_sock()


# ---------------------------------------------------------------------------
# transport selection (the worker/pipeline seam)
# ---------------------------------------------------------------------------

TRANSPORTS = ("dir", "tcp")


def transport_name(explicit: Optional[str] = None) -> str:
    """Resolve the cross-process transport: the plan document's value
    wins (every process sees the same choice), else the
    ``GIGAPATH_DIST_TRANSPORT`` env snapshot (host-side, read at
    construction), else the directory dryrun stand-in."""
    name = (explicit or os.environ.get("GIGAPATH_DIST_TRANSPORT", "")
            or "dir").strip().lower()
    if name not in TRANSPORTS:
        raise ValueError(
            f"GIGAPATH_DIST_TRANSPORT={name!r}: known transports "
            f"{TRANSPORTS}"
        )
    return name


def make_producer(root: str, config: Optional[BoundaryConfig] = None, *,
                  producer: str = "", runlog=None, chaos=None,
                  transport: Optional[str] = None, run_id: str = ""):
    """The producing half of the selected transport — the one seam
    ``worker.py`` calls, so switching transports changes zero lines of
    the produce/fold path."""
    name = transport_name(transport)
    if name == "tcp":
        return TcpChannelProducer(root, config, producer=producer,
                                  runlog=runlog, chaos=chaos, run_id=run_id)
    from gigapath_tpu.dist.boundary import DirChannelProducer

    return DirChannelProducer(root, config, producer=producer,
                              runlog=runlog, chaos=chaos)


def make_consumer(root: str, config: Optional[BoundaryConfig] = None, *,
                  runlog=None, transport: Optional[str] = None,
                  delivered: Optional[Sequence[int]] = None,
                  run_id: str = ""):
    """The consuming half of the selected transport (``pipeline.py``'s
    seam). ``delivered`` is the restarted consumer's checkpoint
    watermark."""
    name = transport_name(transport)
    if name == "tcp":
        return TcpChannelConsumer(root, config, runlog=runlog,
                                  delivered=delivered, run_id=run_id)
    from gigapath_tpu.dist.boundary import DirChannelConsumer

    return DirChannelConsumer(root, config, runlog=runlog,
                              delivered=delivered)
