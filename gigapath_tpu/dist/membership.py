"""Lease-based worker liveness and elastic degradation.

A cross-stage pipeline cannot ask a dead worker whether it is dead; it
can only notice the silence. Every worker of either stage holds a
*lease* — a small JSON file under ``<root>/members/`` it re-writes
(atomic tmp+rename) every ``lease_s / 3`` while alive. The consumer side
(:class:`Membership`) polls the lease directory; a lease past its expiry
is a lost worker:

1. a schema'd ``worker_lost`` event lands on the obs bus (the anomaly
   engine's ``worker_lost`` detector reacts with a flight dump — the
   post-mortem context for *why* the fleet shrank);
2. the coordinator computes the lost worker's UNACKED chunk ids (its
   assignment minus what the boundary channel has delivered) and
   re-assigns them across the survivors via the same deterministic
   :func:`~gigapath_tpu.dist.boundary.assign_chunks` plan, emitting a
   ``recovery`` event (``action="reassign"``);
3. survivors poll ``<root>/reassign/`` and pick up the ranges addressed
   to them — the slide completes with bit-parity to the clean run,
   because chunk ids (and therefore the assembled bytes) never depended
   on who produced them.

Files, not sockets, because the dryrun milestone is two process groups
on ONE machine (ROADMAP item 4) and a shared directory is the transport
both already have; the lease/reassign protocol itself is
transport-agnostic. numpy-free, jax-free, stdlib only.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, List, Optional, Sequence

from gigapath_tpu.obs.runlog import env_number

DEFAULT_LEASE_S = 5.0


def lease_seconds() -> float:
    """``GIGAPATH_DIST_LEASE_S`` (host-side, read at construction)."""
    return env_number("GIGAPATH_DIST_LEASE_S", DEFAULT_LEASE_S)


def _members_dir(root: str) -> str:
    return os.path.join(root, "members")


def _reassign_dir(root: str) -> str:
    return os.path.join(root, "reassign")


def atomic_write_json(path: str, doc: dict, *, indent=None,
                      sort_keys: bool = False) -> str:
    """The dist layer's ONE atomic JSON write (tmp + ``os.replace`` —
    a reader never sees a torn document, a SIGKILL mid-write leaves
    only a tmp file nobody scans). Leases, reassignments and the plan
    document all go through here."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=indent, sort_keys=sort_keys)
    os.replace(tmp, path)
    return path


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None  # racing rename / torn read: next poll sees it


class WorkerLease:
    """One worker's liveness claim. ``renew()`` is cheap enough to call
    every loop iteration — it only rewrites the file once a third of the
    lease has burned down."""

    def __init__(self, root: str, worker_id: str, *, stage: str = "tile",
                 lease_s: Optional[float] = None):
        self.root = root
        self.worker_id = worker_id
        self.stage = stage
        self.lease_s = lease_seconds() if lease_s is None else float(lease_s)
        self.path = os.path.join(_members_dir(root), f"lease-{worker_id}.json")
        os.makedirs(_members_dir(root), exist_ok=True)
        self._renewed_at = 0.0
        self._seq = 0

    def register(self, now: Optional[float] = None) -> None:
        self._write(time.time() if now is None else now)

    def renew(self, now: Optional[float] = None) -> bool:
        """Rewrite the lease if a third of it has elapsed; True when a
        write happened."""
        now = time.time() if now is None else now
        if now - self._renewed_at < self.lease_s / 3.0:
            return False
        self._write(now)
        return True

    def _write(self, now: float) -> None:
        self._seq += 1
        atomic_write_json(self.path, {
            "worker": self.worker_id, "stage": self.stage,
            "renewed": now, "expires": now + self.lease_s,
            "pid": os.getpid(), "seq": self._seq,
        })
        self._renewed_at = now

    def retire(self) -> None:
        """Clean exit: remove the lease so the coordinator never counts
        an orderly shutdown as a loss."""
        try:
            os.unlink(self.path)
        except OSError:
            pass


def read_lease(root: str, worker_id: str) -> Optional[dict]:
    """One lease document, raw (no expiry judgment) — the restarted
    consumer reads its predecessor's stale lease for ``consumer_lost``
    post-mortem context (pid, last renewal)."""
    return _read_json(os.path.join(_members_dir(root),
                                   f"lease-{worker_id}.json"))


class Membership:
    """The consumer/coordinator's view of the worker fleet."""

    def __init__(self, root: str, *, runlog=None):
        self.root = root
        self._runlog = runlog
        self._lost: set = set()   # workers already reported lost
        os.makedirs(_members_dir(root), exist_ok=True)
        os.makedirs(_reassign_dir(root), exist_ok=True)

    def _leases(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for path in glob.glob(os.path.join(_members_dir(self.root),
                                           "lease-*.json")):
            doc = _read_json(path)
            if doc and doc.get("worker"):
                out[str(doc["worker"])] = doc
        return out

    def alive(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        return sorted(
            w for w, doc in self._leases().items()
            if float(doc.get("expires", 0)) > now and w not in self._lost
        )

    def poll_lost(self, now: Optional[float] = None) -> List[str]:
        """Workers whose lease expired since the last poll. Each loss is
        reported ONCE: a ``worker_lost`` event (new EVENT_KIND; the
        anomaly engine fires its ``worker_lost`` detector on it) with the
        expiry context a post-mortem needs."""
        now = time.time() if now is None else now
        newly_lost: List[str] = []
        for worker, doc in sorted(self._leases().items()):
            expires = float(doc.get("expires", 0))
            if expires > now or worker in self._lost:
                continue
            self._lost.add(worker)
            newly_lost.append(worker)
            if self._runlog is not None:
                self._runlog.event(
                    "worker_lost", worker=worker,
                    stage=doc.get("stage"),
                    expired_by_s=round(now - expires, 3),
                    last_renew=doc.get("renewed"), pid=doc.get("pid"),
                )
                self._runlog.echo(
                    f"[dist] worker_lost: {worker} (stage "
                    f"{doc.get('stage')}, lease expired "
                    f"{now - expires:.2f}s ago)"
                )
        return newly_lost

    def report_lost(self, worker: str, *, reason: str = "process_exit",
                    **info) -> bool:
        """Mark a worker lost from DIRECT evidence (the orchestrator
        watched its OS process die) instead of waiting out the lease —
        faster detection when the process handle is at hand, and the
        ONLY detection for a worker that died before its first
        ``register()`` (no lease file ever existed for the expiry path
        to notice). Same once-per-worker contract and ``worker_lost``
        event as :meth:`poll_lost`. Returns False when already lost."""
        if worker in self._lost:
            return False
        self._lost.add(worker)
        if self._runlog is not None:
            self._runlog.event("worker_lost", worker=worker,
                               reason=reason, **info)
            self._runlog.echo(f"[dist] worker_lost: {worker} ({reason})")
        return True

    def lost(self) -> List[str]:
        return sorted(self._lost)


# ---------------------------------------------------------------------------
# reassignment
# ---------------------------------------------------------------------------

def write_reassignment(root: str, *, lost_worker: str,
                       assignments: Dict[str, Sequence[int]],
                       runlog=None) -> str:
    """Publish a reassignment of a lost worker's unacked chunk ids to
    the survivors (one JSON file under ``<root>/reassign/``, atomic) and
    emit the ``recovery`` event (``action="reassign"``) the acceptance
    asserts on."""
    os.makedirs(_reassign_dir(root), exist_ok=True)
    n = len(glob.glob(os.path.join(_reassign_dir(root), "reassign-*.json")))
    path = os.path.join(_reassign_dir(root), f"reassign-{n:04d}.json")
    doc = {
        "lost": lost_worker,
        "assignments": {w: sorted(int(c) for c in cs)
                        for w, cs in assignments.items()},
    }
    atomic_write_json(path, doc)
    if runlog is not None:
        total = sum(len(cs) for cs in assignments.values())
        runlog.recovery(
            action="reassign", worker=lost_worker, chunks=total,
            survivors=sorted(assignments), path=path,
        )
        runlog.echo(
            f"[dist] reassign: {total} unacked chunk(s) of {lost_worker} "
            f"-> {sorted(assignments)}"
        )
    return path


def reassignments_for(root: str, worker_id: str,
                      seen: Optional[set] = None) -> List[int]:
    """Chunk ids newly re-assigned TO ``worker_id``. ``seen`` (mutated)
    tracks processed reassignment files across calls so each file is
    honored once per worker."""
    out: List[int] = []
    for path in sorted(glob.glob(os.path.join(_reassign_dir(root),
                                              "reassign-*.json"))):
        name = os.path.basename(path)
        if seen is not None:
            if name in seen:
                continue
            doc = _read_json(path)
            if doc is None:
                continue  # torn read: retry next poll, don't mark seen
            seen.add(name)
        else:
            doc = _read_json(path)
            if doc is None:
                continue
        out.extend(int(c) for c in
                   (doc.get("assignments") or {}).get(worker_id, []))
    return sorted(set(out))
