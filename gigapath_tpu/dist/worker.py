"""The tile-encoder worker process of the disaggregated dryrun.

One worker = one OS process (``python -m gigapath_tpu.dist.worker``)
holding a lease, producing its assigned chunks of one slide's tile
embeddings through the directory boundary channel, and polling for
ranges re-assigned to it when a peer dies. The loop per iteration:

1. renew the lease (a dead worker is one that stops doing this);
2. produce the next pending chunk: load the chunk's tiles (the dryrun's
   deterministic synthetic loader — any worker can load any tile range,
   exactly like the production feature store), encode, ``send`` (which
   blocks on credits — backpressure propagates into this loop, never
   into unbounded memory);
3. pump retransmits for unacked chunks older than the timer;
4. pick up chunks re-assigned to this worker by the coordinator;
5. exit when the consumer publishes DONE (or the deadline passes).

Chaos (``GIGAPATH_CHAOS``, parsed ONCE host-side at worker start like
every injector): ``kill_worker@K`` hard-kills THIS worker (SIGKILL — no
goodbye, the lease just stops renewing) after K produced chunks;
``slow_worker@K[:S]`` sleeps S seconds before producing chunk K
(``K='*'`` = every chunk — the straggler whose skew the per-rank span
table must surface); ``drop_chunk@K`` / ``dup_chunk@K`` act inside the
channel's send.

Chunk production order matters to nobody downstream: the consumer
either assembles by tile range (dense mode) or folds at the
deterministic chunk-id frontier (``plan.chunked_prefill`` streaming
mode, ISSUE 12) — so retransmits, reassignment and interleaved
production from a multi-worker fleet all yield the identical slide
embedding, bit-exact.

The dryrun encoder is numpy (a fixed seeded projection + tanh): bitwise
deterministic across processes, imports in milliseconds, and keeps the
protocol layer provably free of traced code. The REAL quantized tile
encoder (ROADMAP item 3, ``gigapath_tpu/quant/``) drops in behind the
same ``encode`` seam when the plan says ``encoder: "quant_vit"`` — see
:func:`make_encoder`: the registry ViT arch with the quantized-Dense
tier, params deterministic from the plan's ``encoder_seed``, placed per
the ``tile_encoder`` entry of :mod:`gigapath_tpu.dist.stagemesh`, with
the kill/recover bit-exactness contract unchanged (re-encoding a chunk
is the same jitted program on the same machine).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from gigapath_tpu.dist.boundary import (
    BoundaryConfig,
    EmbeddingChunk,
    assign_chunks,
    plan_chunks,
)
from gigapath_tpu.dist.membership import (
    WorkerLease,
    atomic_write_json,
    reassignments_for,
)
from gigapath_tpu.dist.transport import make_producer
from gigapath_tpu.resilience.chaos import get_chaos

DONE_MARKER = "DONE"


def load_plan(root: str) -> dict:
    with open(os.path.join(root, "plan.json"), encoding="utf-8") as fh:
        return json.load(fh)


def write_plan(root: str, plan: dict) -> str:
    os.makedirs(root, exist_ok=True)
    return atomic_write_json(os.path.join(root, "plan.json"), plan,
                             indent=1, sort_keys=True)


# ---------------------------------------------------------------------------
# the dryrun's deterministic tile loader + encoder
# ---------------------------------------------------------------------------

def chunk_tiles(plan: dict, start: int, stop: int):
    """Synthetic tile features + coords for one tile range, a pure
    function of (tile_seed, tile index) — the dryrun twin of a feature
    store any worker can read any range from."""
    rng = np.random.default_rng([int(plan["tile_seed"]), int(start)])
    n = stop - start
    feats = rng.standard_normal((n, int(plan["dim_in"])),
                                dtype=np.float32)
    coords = rng.uniform(0, 25000, (n, 2)).astype(np.float32)
    return feats, coords


def encoder_weights(plan: dict) -> np.ndarray:
    rng = np.random.default_rng(int(plan["encoder_seed"]))
    w = rng.standard_normal((int(plan["dim_in"]), int(plan["dim_out"])),
                            dtype=np.float32)
    return w / np.sqrt(np.float32(plan["dim_in"]))


def encode_chunk(plan: dict, weights: np.ndarray, start: int, stop: int):
    """feats [n, Din] -> embeds [n, Dout], bitwise-deterministic given
    the plan (same numpy, same machine — the dryrun's parity anchor)."""
    feats, coords = chunk_tiles(plan, start, stop)
    return np.tanh(feats @ weights, dtype=np.float32), coords


def chunk_images(plan: dict, start: int, stop: int):
    """Synthetic tile IMAGES + coords for one tile range — the real-
    encoder twin of :func:`chunk_tiles`, a pure function of
    (tile_seed, tile index) so retransmits, reassignment and interleaved
    multi-worker production stay bit-exact."""
    rng = np.random.default_rng([int(plan["tile_seed"]), int(start)])
    n = stop - start
    img = int(plan.get("img_size", 32))
    imgs = rng.standard_normal((n, img, img, 3)).astype(np.float32)
    coords = rng.uniform(0, 25000, (n, 2)).astype(np.float32)
    return imgs, coords


def make_encoder(plan: dict):
    """The ``encode(start, stop) -> (embeds, coords)`` seam.

    ``plan["encoder"]`` selects the implementation behind the UNCHANGED
    surface: ``"dryrun"`` (default) is the seeded numpy projection;
    ``"quant_vit"`` is the REAL quantized ViT tile encoder (ROADMAP
    item 3 meeting item 4) — the registry tile arch with
    ``plan["quant"]``'s quantized-Dense tier, params deterministic from
    ``encoder_seed``, placed through the ``tile_encoder`` entry of the
    stage-sharding registry (a 1-device stage mesh in the dryrun — the
    same declarative path a sharded fleet consumes), one jitted forward
    per worker process. Produced embeddings round through the shared
    bf16 helper so every producer of tile embeddings — this worker, the
    dense pipeline entry, the streaming entry — feeds the slide stage
    bit-identical inputs. jax imports stay inside the quant_vit arm:
    the default dryrun worker remains numpy-only and starts in
    milliseconds."""
    encoder = plan.get("encoder", "dryrun")
    if encoder == "dryrun":
        weights = encoder_weights(plan)
        return lambda start, stop: encode_chunk(plan, weights, start, stop)
    if encoder != "quant_vit":
        # a typo'd encoder name must never silently run the dryrun
        # projection and look healthy (the get_chaos/normalize_mode
        # loud-typo discipline)
        raise ValueError(
            f"unknown plan encoder '{encoder}' (known: dryrun, quant_vit)"
        )

    import jax
    import jax.numpy as jnp

    from gigapath_tpu.dist.stagemesh import stage_mesh, stage_param_shardings
    from gigapath_tpu.models.tile_encoder import init_params
    from gigapath_tpu.quant.qtensor import bf16_round_trip, normalize_mode
    from gigapath_tpu.utils.registry import create_model_from_registry

    mode = normalize_mode(plan.get("quant", "int8"))
    model = create_model_from_registry(
        plan.get("tile_arch", "vit_tile_enc_test"),
        img_size=int(plan.get("img_size", 32)),
        embed_dim=int(plan["dim_out"]),
        quant=mode,
    )
    params = init_params(
        model, rng=jax.random.PRNGKey(int(plan["encoder_seed"]))
    )
    mesh = stage_mesh("tile_encoder", devices=jax.devices()[:1])
    params = jax.device_put(
        params, stage_param_shardings("tile_encoder", params, mesh)
    )
    forward = jax.jit(lambda p, x: model.apply({"params": p}, x))
    # warm EVERY chunk shape NOW, before the caller registers its
    # lease: the compiles must never land inside the lease window (a
    # worker paying its first compile mid-slide would look exactly like
    # a dead worker to the membership layer). plan_chunks emits at most
    # two shapes — the full chunk and a ragged tail.
    chunk = int(plan.get("chunk_tiles", 8))
    img = int(plan.get("img_size", 32))
    tail = int(plan["n_tiles"]) % chunk if plan.get("n_tiles") else 0
    for n in {chunk} | ({tail} if tail else set()):
        forward(params, jnp.zeros((n, img, img, 3), jnp.float32)
                ).block_until_ready()

    def encode(start: int, stop: int):
        imgs, coords = chunk_images(plan, start, stop)
        embeds = np.asarray(forward(params, jnp.asarray(imgs)), np.float32)
        return bf16_round_trip(embeds), coords

    return encode


# ---------------------------------------------------------------------------
# the worker loop
# ---------------------------------------------------------------------------

def run_tile_worker(root: str, worker_id: str, *,
                    deadline_s: float = 120.0, runlog=None) -> dict:
    """Produce this worker's share (initial assignment + anything
    re-assigned to it) until the consumer publishes DONE. Returns the
    channel stats (also folded into the worker's ``run_end``)."""
    plan = load_plan(root)
    cfg = BoundaryConfig.from_env(
        capacity=plan.get("credits"), chunk_tiles=plan.get("chunk_tiles"),
        retransmit_s=plan.get("retransmit_s"), poll_s=plan.get("poll_s"),
    )
    own_log = runlog is None
    if own_log:
        from gigapath_tpu.obs.runlog import get_run_log

        # run_start=False: the manifest would import jax for its version
        # probe — a tile worker is numpy-only and must start in
        # milliseconds, so it emits its own minimal manifest instead
        runlog = get_run_log(f"dist-{worker_id}", out_dir=root,
                             echo=False, run_start=False)
        runlog.event("run_start", driver=f"dist-{worker_id}",
                     pid=os.getpid(), worker=worker_id,
                     slide=plan.get("slide_id"))
    # chaos parses AFTER the log exists: a typo'd spec is an error event
    # + raise, never a silently clean chaos run
    chaos = get_chaos(runlog)
    workers = sorted(plan["workers"])
    rank = workers.index(worker_id) if worker_id in workers else -1
    chunks = plan_chunks(int(plan["n_tiles"]), cfg.chunk_tiles)
    by_id = {cid: (start, stop) for cid, start, stop in chunks}
    mine: List[int] = assign_chunks(
        [c[0] for c in chunks], workers,
    ).get(worker_id, [])

    # build (and, for the quant_vit encoder, jit-warm) the encoder
    # BEFORE registering the lease: the expensive one-time setup must
    # not eat into the first lease window — a worker importing jax is
    # not a dead worker
    encode = make_encoder(plan)
    lease = WorkerLease(root, worker_id, stage="tile",
                        lease_s=plan.get("lease_s"))
    lease.register()
    # the transport seam: dir (the dryrun stand-in) or tcp (the real
    # wire), chosen by the plan / GIGAPATH_DIST_TRANSPORT — nothing
    # below this line changes with the transport
    producer = make_producer(root, cfg, producer=worker_id,
                             runlog=runlog, chaos=chaos,
                             transport=plan.get("transport"),
                             run_id=getattr(runlog, "run_id", ""))
    from gigapath_tpu.obs.reqtrace import get_tracer
    from gigapath_tpu.obs.spans import span

    # the fleet trace context: the slide's trace id was minted at PLAN
    # time, so this worker's encode/send/backpressure spans land in the
    # same causal tree as the consumer's fold spans with no coordination
    ctx = get_tracer(runlog).context(
        str(plan.get("trace_id", "")), actor=worker_id,
        name=str(plan.get("slide_id", "")),
    )

    pending: List[int] = list(mine)
    seen_reassign: set = set()
    produced = 0
    done_path = os.path.join(root, DONE_MARKER)
    t_deadline = time.monotonic() + deadline_s
    status = "ok"
    try:
        while time.monotonic() < t_deadline:
            lease.renew()
            if pending:
                cid = pending.pop(0)
                start, stop = by_id[cid]
                sent = False
                # the per-chunk span carries the WORKER index as its
                # rank (two process groups on one host share jax
                # process index 0): obs_report's per-rank straggler
                # table keys on exactly this tag
                with span("dist.chunk", runlog, rank=rank, chunk=cid,
                          tiles=stop - start, worker=worker_id,
                          trace=ctx):
                    with span("dist.encode", runlog, rank=rank, chunk=cid,
                              worker=worker_id, trace=ctx):
                        if chaos:
                            # inside the span: injected slowness models
                            # slow COMPUTE, and the straggler table (and
                            # the fleet critical path) must see it
                            slow = chaos.slow_worker(cid)
                            if slow:
                                time.sleep(slow)
                        embeds, coords = encode(start, stop)
                    chunk = EmbeddingChunk.build(
                        plan["slide_id"], cid, start, stop, embeds,
                        coords=coords, producer=worker_id,
                        trace_id=ctx.trace_id,
                        # the producer's send-span id is STRUCTURAL, so
                        # it can ride the header before the span closes:
                        # the consumer's deliver span parents on it
                        parent_span_id=ctx.span_id_for("send", chunk=cid),
                    )
                    # a credit-blocked send must not starve the lease:
                    # bound each wait well under the lease window and
                    # renew between attempts — backpressure is healthy,
                    # being declared dead because of it is not. Pump
                    # retransmits between attempts too: at low credit a
                    # DROPPED earlier write can be the very thing
                    # holding every credit, and only a re-send frees it
                    blocked0 = producer.stats.blocked_s
                    t_send0 = time.monotonic()
                    while True:
                        lease.renew()
                        try:
                            producer.send(chunk,
                                          timeout=lease.lease_s / 4.0)
                            sent = True
                            break
                        except TimeoutError:
                            if os.path.exists(done_path):
                                # the run is over (consumer finished or
                                # failed): nobody will ack this credit
                                # back — drain out instead of spinning
                                # to our own deadline
                                break
                            if time.monotonic() >= t_deadline:
                                raise
                            producer.pump_retransmits()
                    if sent:
                        # split the send wall into credit-blocked wait
                        # vs the actual transmit: two adjacent trace
                        # spans, so the fleet critical path can tell
                        # backpressure from wire time. Manual add_span
                        # (not span()): the split is known only after
                        # the fact, from the producer's blocked_s delta
                        t_send1 = time.monotonic()
                        blocked = max(
                            producer.stats.blocked_s - blocked0, 0.0)
                        blocked = min(blocked, t_send1 - t_send0)
                        if blocked > 0:
                            ctx.add_span("backpressure_wait", t_send0,
                                         t_send0 + blocked, chunk=cid)
                        ctx.add_span("send", t_send0 + blocked, t_send1,
                                     chunk=cid)
                if not sent:
                    break  # DONE appeared while credit-blocked
                produced += 1
                if chaos:
                    chaos.maybe_kill_worker(produced)
                continue
            if os.path.exists(done_path):
                break
            producer.pump_retransmits()
            for cid in reassignments_for(root, worker_id, seen_reassign):
                if cid in by_id and cid not in pending:
                    pending.append(cid)
            time.sleep(cfg.poll_s)
        else:
            status = "deadline"
    except BaseException:
        status = "error"
        raise
    finally:
        # retire ONLY on a clean exit: a worker dying on an exception
        # (or its deadline) must leave its lease to EXPIRE, so the
        # coordinator counts it lost and reassigns its chunks — deleting
        # the lease here would dress every crash up as an orderly
        # shutdown and strand the slide
        if status == "ok":
            lease.retire()
        if own_log:
            runlog.event("run_end", status=status, worker=worker_id,
                         produced=produced, **producer.stats.as_dict())
            runlog.close()
    return {**producer.stats.as_dict(), "status": status}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="dist dryrun tile worker (module docstring)"
    )
    ap.add_argument("--root", required=True, help="shared pipeline workdir")
    ap.add_argument("--worker", required=True, help="worker id (e.g. w0)")
    ap.add_argument("--deadline-s", type=float, default=120.0)
    args = ap.parse_args(argv)
    stats = run_tile_worker(args.root, args.worker,
                            deadline_s=args.deadline_s)
    # a deadlined worker did NOT complete its share: exit nonzero so the
    # orchestrator's process-exit probe (and any supervisor) sees a
    # failure, not a clean drain
    return 0 if stats.get("status") == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
