"""The slide-stage consumer + the two-process-group dryrun orchestrator.

:func:`run_slide_consumer` is the receiving fleet's loop: drain the
boundary channel, ack + assemble each chunk, poll worker leases, and on
a loss re-assign the dead worker's unacked chunk ids across survivors
(the elastic-degradation half of the recovery contract). When the plan's
every chunk is assembled it runs the slide-encoder forward over the
dense ``[n_tiles, D]`` sequence — jitted once, watched for retraces —
and publishes DONE so the workers drain out.

:func:`run_disaggregated` is the one-call dryrun: write the plan, spawn
one OS process per tile worker (``python -m gigapath_tpu.dist.worker``,
optionally with per-worker ``GIGAPATH_CHAOS`` — that is how the
acceptance kills exactly one), run the consumer in the calling process,
join the fleet. All processes share a ``GIGAPATH_OBS_RUN_ID`` so their
per-process JSONL files merge in ``scripts/obs_report.py`` (worker span
ranks feed the per-rank straggler table).

Bit-parity invariant (the acceptance): the assembled sequence is a pure
function of the plan — chunk ids, tile ranges and the deterministic
encoder never depend on which worker produced what — so a run that
loses a worker mid-slide yields the clean run's slide embedding
BIT-exact, with the recovery visible as ``worker_lost`` +
``recovery action="reassign"`` events rather than as different numbers.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from gigapath_tpu.dist.boundary import (
    BoundaryConfig,
    ChunkTracker,
    SlideAssembler,
    assign_chunks,
    atomic_touch,
    plan_chunks,
)
from gigapath_tpu.dist.membership import (
    Membership,
    WorkerLease,
    read_lease,
    write_reassignment,
)
from gigapath_tpu.dist.transport import make_consumer
from gigapath_tpu.dist.worker import DONE_MARKER, load_plan, write_plan
from gigapath_tpu.resilience.chaos import get_chaos

RESULT_FILE = "result.npz"
CONSUMER_CKPT_DIR = "consumer-ckpt"

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def default_plan(*, slide_id: str = "slide0", n_tiles: int = 64,
                 dim_in: int = 16, dim_out: int = 8, chunk_tiles: int = 8,
                 workers: Optional[List[str]] = None, tile_seed: int = 0,
                 encoder_seed: int = 7, lease_s: float = 1.0,
                 credits: int = 4, retransmit_s: float = 0.5,
                 poll_s: float = 0.02,
                 chunked_prefill: bool = False,
                 transport: Optional[str] = None,
                 consumer_ckpt_every: Optional[int] = None,
                 encoder: Optional[str] = None,
                 quant: Optional[str] = None,
                 img_size: Optional[int] = None) -> dict:
    """The dryrun's plan document (written to ``<root>/plan.json``,
    read by every process — the shared deterministic truth).
    ``chunked_prefill`` puts the consumer in streaming mode: chunks fold
    into the slide encoder on arrival instead of assembling the dense
    sequence (the plan carries the mode so every process agrees).
    ``transport`` picks the boundary transport (``dir``/``tcp``; None =
    the ``GIGAPATH_DIST_TRANSPORT`` snapshot) and
    ``consumer_ckpt_every`` the consumer's checkpoint cadence in
    delivered chunks (None = the ``GIGAPATH_DIST_CONSUMER_CKPT_EVERY``
    snapshot; 0 = off) — in the plan so every process, restarted
    consumers included, agrees."""
    plan = dict(
        slide_id=slide_id, n_tiles=int(n_tiles), dim_in=int(dim_in),
        dim_out=int(dim_out), chunk_tiles=int(chunk_tiles),
        workers=sorted(workers or ["w0", "w1"]), tile_seed=int(tile_seed),
        encoder_seed=int(encoder_seed), lease_s=float(lease_s),
        credits=int(credits), retransmit_s=float(retransmit_s),
        poll_s=float(poll_s), chunked_prefill=bool(chunked_prefill),
        # the fleet-wide trace id, minted HERE at plan time: every
        # process reads it from plan.json, so producer and consumer
        # spans join one causal tree with zero coordination
        # (obs/reqtrace.py TraceContext)
        trace_id=f"tr-{slide_id}-{os.urandom(4).hex()}",
    )
    if transport is not None:
        plan["transport"] = str(transport)
    if consumer_ckpt_every is not None:
        plan["consumer_ckpt_every"] = int(consumer_ckpt_every)
    if encoder is not None:
        # "dryrun" (numpy projection) or "quant_vit" (the REAL quantized
        # tile encoder behind worker.make_encoder's seam); in the plan so
        # every worker — restarted or reassigned — builds the same one
        plan["encoder"] = str(encoder)
    if quant is not None:
        plan["quant"] = str(quant)
    if img_size is not None:
        plan["img_size"] = int(img_size)
    return plan


def _default_streaming_forward():
    """The dryrun slide stage in CHUNKED-PREFILL form: the same tiny
    encoder + classifier params as :func:`_default_forward` (same stage
    mesh placement), but consumed through a
    :class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`
    so the consumer folds ``EmbeddingChunk``s on arrival instead of
    assembling the dense ``[n_tiles, D]`` sequence first. Returns
    ``build(dim_in) -> (open_session(n_tiles, chunk_tiles), head_fn)``;
    ``head_fn`` maps the session's per-layer embeds to the same logits
    the dense forward emits (the parity/bit-exactness surface)."""
    import jax

    from gigapath_tpu.dist.stagemesh import stage_mesh, stage_param_shardings
    from gigapath_tpu.models.classification_head import get_model
    from gigapath_tpu.models.streaming_encoder import StreamingEncoderSession
    from gigapath_tpu.serve.streaming import streaming_head_logits
    from gigapath_tpu.utils.registry import create_model_from_registry

    def build(dim_in: int):
        model, params = get_model(
            input_dim=dim_in, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        mesh = stage_mesh("slide_encoder", devices=jax.devices()[:1])
        params = jax.device_put(
            params, stage_param_shardings("slide_encoder", params, mesh)
        )
        inner = create_model_from_registry(
            "gigapath_slide_enc_tiny", in_chans=dim_in, global_pool=False,
            dtype=None,
        )

        def open_session(n_tiles: int, chunk_tiles: int, runlog=None):
            # runlog -> per-stage CompileWatchdogs inside the session:
            # streaming recovery must never hide a retrace, same as the
            # dense consumer's watched forward
            return StreamingEncoderSession(
                inner, params["slide_encoder"], n_tiles,
                chunk_tiles=chunk_tiles, all_layer_embed=True,
                runlog=runlog,
            )

        def head(embeds):
            # the ONE classifier-tail implementation (serve/streaming.py)
            # keeps the dist parity surface and the serving path in
            # lockstep
            return streaming_head_logits(model, params, embeds)[0]

        return open_session, head

    return build


def _default_forward():
    """The dryrun slide stage: the tiny slide encoder + classifier head
    (the same arch the chaos/serve smokes pin), jitted once per shape,
    with params placed through the ``slide_encoder`` entry of the
    stage-sharding registry (a 1-device stage mesh here, so every rule
    degrades to replicated — the dryrun consumes the same declarative
    path a sharded fleet does, without changing a single byte)."""
    import jax

    from gigapath_tpu.dist.stagemesh import stage_mesh, stage_param_shardings
    from gigapath_tpu.models.classification_head import get_model

    def build(dim_in: int):
        model, params = get_model(
            input_dim=dim_in, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        mesh = stage_mesh("slide_encoder", devices=jax.devices()[:1])
        params = jax.device_put(
            params, stage_param_shardings("slide_encoder", params, mesh)
        )

        def forward(p, embeds, coords):
            return model.apply({"params": p}, embeds, coords,
                               deterministic=True)

        return jax.jit(forward), params

    return build


def _export_consumer_state(assembler, session) -> dict:
    """The consumer's durable fold state: the delivered-chunk watermark
    plus either the streaming session's frontier/partials or the dense
    assembly buffers — exactly what a restarted consumer needs for a
    BIT-exact resume."""
    state: dict = {
        "received": np.array(sorted(assembler.received), np.int64),
    }
    if session is not None:
        state["session"] = session.export_state()
    else:
        state["embeds"] = np.asarray(assembler.embeds)
        state["coords"] = np.asarray(assembler.coords)
    return state


def _restore_consumer_state(state: dict, assembler, session) -> List[int]:
    """Inverse of :func:`_export_consumer_state`; returns the restored
    watermark (sorted delivered chunk ids)."""
    received = [int(c) for c in np.asarray(state["received"]).tolist()]
    assembler.seed_received(received)
    if session is not None:
        session.restore_state(state["session"])
    else:
        assembler.embeds[...] = np.asarray(state["embeds"], np.float32)
        assembler.coords[...] = np.asarray(state["coords"], np.float32)
    return received


def run_slide_consumer(root: str, *, runlog=None,
                       forward_builder: Optional[Callable] = None,
                       streaming: Optional[bool] = None,
                       streaming_builder: Optional[Callable] = None,
                       deadline_s: float = 120.0,
                       worker_probe: Optional[Callable] = None,
                       ckpt_every: Optional[int] = None,
                       transport: Optional[str] = None) -> dict:
    """Assemble one slide from the channel, recovering from worker loss.

    ``streaming`` (default: the plan's ``chunked_prefill`` field, else
    the ``GIGAPATH_CHUNKED_PREFILL`` snapshot) switches the consumer to
    chunked prefill: each acked ``EmbeddingChunk`` folds into a
    :class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`
    the moment the fold frontier reaches it — arrival order, retransmits
    and reassignment all tolerated, with the fold sequence (and so the
    embedding, BIT-exact) a pure function of the deterministic chunk
    plan. The dense ``[n_tiles, D]`` sequence is never assembled in this
    mode (``assembled``/``coords`` come back None).

    ``worker_probe`` (optional): zero-arg callable returning
    ``{worker_id: exit_code_or_None}`` for workers whose OS processes
    this host can see — direct evidence of death that beats waiting out
    the lease, and the ONLY detection for a worker that died before its
    first lease registration (no lease file ever existed for the expiry
    path to notice). Cross-host consumers pass nothing and rely on
    leases alone.

    ``ckpt_every`` (plan ``consumer_ckpt_every`` /
    ``GIGAPATH_DIST_CONSUMER_CKPT_EVERY``; 0 = off): checkpoint the
    fold state every N delivered chunks through
    :class:`~gigapath_tpu.resilience.checkpoint.ResilientCheckpointer`'s
    atomic manifest discipline, and DEFER acks until the covering
    checkpoint commits — the ack watermark is the durable watermark, so
    a producer (or the reconnect handshake) replays exactly what a
    SIGKILLed consumer actually lost. A restart finds the checkpoint,
    emits ``consumer_lost`` + ``recovery action="consumer_resume"``,
    reloads the watermark, re-handshakes, receives only post-watermark
    chunks, and produces a BIT-exact slide embedding.

    Returns ``{"embedding", "assembled", "coords", "stats", "lost",
    "reassignments"}``; raises TimeoutError when the slide cannot
    complete within ``deadline_s`` (no silent partial slides)."""
    from gigapath_tpu.obs.runlog import env_number, get_run_log
    from gigapath_tpu.obs.watchdog import CompileWatchdog
    from gigapath_tpu.resilience.checkpoint import ResilientCheckpointer

    plan = load_plan(root)
    cfg = BoundaryConfig.from_env(
        capacity=plan.get("credits"), chunk_tiles=plan.get("chunk_tiles"),
        retransmit_s=plan.get("retransmit_s"), poll_s=plan.get("poll_s"),
    )
    own_log = runlog is None
    if own_log:
        runlog = get_run_log(
            "dist-consumer", out_dir=root,
            config={"slide": plan["slide_id"], "n_tiles": plan["n_tiles"],
                    "workers": plan["workers"],
                    "chunk_tiles": cfg.chunk_tiles},
        )
    chaos = get_chaos(runlog)
    if streaming is None:
        # one host-side read, the PipelineFlags convention: the plan
        # document wins (every process sees the same mode), the env
        # snapshot is the single-process default
        if "chunked_prefill" in plan:
            streaming = bool(plan["chunked_prefill"])
        else:
            from gigapath_tpu.ops.pallas_dilated import snapshot_flags

            streaming = snapshot_flags().chunked_prefill
    if ckpt_every is None:
        ckpt_every = plan.get("consumer_ckpt_every")
    if ckpt_every is None:
        ckpt_every = env_number("GIGAPATH_DIST_CONSUMER_CKPT_EVERY", 0)
    ckpt_every = int(ckpt_every)
    if ckpt_every > cfg.capacity:
        # acks are deferred to the checkpoint cadence: a cadence past
        # the credit window would park every producer at 0 credits while
        # the consumer waits for chunks that can no longer arrive
        raise ValueError(
            f"consumer_ckpt_every={ckpt_every} exceeds the credit "
            f"capacity {cfg.capacity}: the deferred-ack discipline "
            "would deadlock — lower the cadence or raise "
            "GIGAPATH_DIST_CREDITS"
        )
    checkpointer = (
        ResilientCheckpointer(os.path.join(root, CONSUMER_CKPT_DIR),
                              keep=2, runlog=runlog)
        if ckpt_every > 0 else None
    )
    restored_state = None
    prior = read_lease(root, "consumer")
    if checkpointer is not None and checkpointer.checkpoints():
        # a checkpoint exists before this consumer delivered anything:
        # a predecessor died mid-slide. The worker_lost-style event
        # first (with the stale lease as post-mortem context), then the
        # verified restore.
        prior = prior or {}
        runlog.event(
            "consumer_lost", stage="slide", reason="checkpoint_found",
            pid=prior.get("pid"), last_renew=prior.get("renewed"),
        )
        runlog.echo(
            "[dist] consumer_lost: predecessor left a mid-slide "
            f"checkpoint (pid {prior.get('pid')}); resuming"
        )
        restored_state = checkpointer.restore_latest(emit_resume=False)
    elif prior and prior.get("pid") != os.getpid():
        # no checkpoint, but a stale consumer lease: the predecessor
        # died before its first checkpoint ever committed (leases only
        # outlive a CRASH — clean exits retire them). Nothing to
        # restore — every chunk is still unacked at the producers — but
        # the death itself must not be invisible on the bus.
        runlog.event(
            "consumer_lost", stage="slide", reason="stale_lease",
            pid=prior.get("pid"), last_renew=prior.get("renewed"),
        )
        runlog.echo(
            "[dist] consumer_lost: predecessor died before its first "
            f"checkpoint (pid {prior.get('pid')}); starting fresh"
        )
    membership = Membership(root, runlog=runlog)
    lease = WorkerLease(root, "consumer", stage="slide",
                        lease_s=plan.get("lease_s"))
    lease.register()
    chunks = plan_chunks(int(plan["n_tiles"]), cfg.chunk_tiles)
    session = None
    head_fn = None
    if streaming:
        build = streaming_builder or _default_streaming_forward()
        open_session, head_fn = build(int(plan["dim_out"]))
        session = open_session(int(plan["n_tiles"]), cfg.chunk_tiles,
                               runlog=runlog)
        runlog.event("stream_open", slide=plan["slide_id"],
                     n_chunks=session.n_chunks,
                     chunk_tiles=cfg.chunk_tiles)
        # received-chunk bookkeeping only (recovery needs the set of
        # delivered chunk ids) — the dense buffers are exactly what
        # streaming mode exists to not allocate
        assembler = ChunkTracker()
    else:
        assembler = SlideAssembler(int(plan["n_tiles"]), int(plan["dim_out"]))
    # anytime-peek cadence (ISSUE 19): GIGAPATH_DRIFT_PEEK_EVERY read
    # ONCE here — the consumer loop never touches the environment
    from gigapath_tpu.obs.drift import cosine, stream_peek_every

    peek_every = stream_peek_every() if session is not None else 0
    last_peek = 0
    prev_peek: Optional[np.ndarray] = None
    assembler.expect([c[0] for c in chunks])
    watermark: List[int] = []
    if restored_state is not None:
        state, ckpt_step = restored_state
        watermark = _restore_consumer_state(state, assembler, session)
        runlog.recovery(
            action="consumer_resume", step=ckpt_step,
            chunks=len(watermark),
            missing=len(assembler.missing()),
        )
        runlog.echo(
            f"[dist] consumer_resume: watermark {len(watermark)} "
            f"chunk(s), {len(assembler.missing())} still missing"
        )
    # the transport seam (dir / tcp, one protocol): a restarted
    # consumer seeds its dedup + ack watermark from the checkpoint, so
    # the reconnect handshake replays only post-watermark chunks
    consumer = make_consumer(root, cfg, runlog=runlog,
                             transport=transport or plan.get("transport"),
                             delivered=watermark,
                             run_id=getattr(runlog, "run_id", ""))
    from gigapath_tpu.obs.reqtrace import get_tracer
    from gigapath_tpu.obs.spans import span

    # the consumer's half of the fleet trace (same plan-minted trace id
    # as every worker): deliver/fold/checkpoint/finalize spans, plus the
    # recovery gap as an EXPLICIT annotated span — detection to first
    # replayed chunk readable straight off the merged timeline
    ctx = get_tracer(runlog).context(
        str(plan.get("trace_id", "")), actor="consumer",
        name=str(plan.get("slide_id", "")),
    )
    # open recovery gap: (t_detect, action, who, closing chunk-id set —
    # None = the next delivered chunk closes it)
    gap_open: Optional[tuple] = None
    if restored_state is not None:
        gap_open = (time.monotonic(), "consumer_resume", "consumer", None)

    # who currently owns which chunk (updated by reassignments): the
    # coordinator's view of the SAME deterministic assignment the
    # workers computed for themselves
    owners: Dict[str, set] = {
        w: set(cids)
        for w, cids in assign_chunks([c[0] for c in chunks],
                                     plan["workers"]).items()
    }
    reassignments = 0
    pending_acks: List[int] = []
    delivered_here = 0  # chunks THIS process delivered (chaos cadence)
    deadline = time.monotonic() + deadline_s
    status = "ok"

    def _commit(final: bool = False) -> None:
        """Checkpoint the fold state, THEN flush the deferred acks: an
        ack is a promise the chunk is durable, so it must never precede
        the checkpoint that makes it so. With checkpointing off, acks
        are immediate and this only flushes."""
        if checkpointer is not None and (pending_acks or final):
            # chunk= the covered watermark: discriminates the structural
            # span id per commit (checkpoints repeat; spans must not
            # dedup into one)
            with span("dist.checkpoint", runlog, trace=ctx,
                      chunk=len(assembler.received)):
                checkpointer.save(
                    len(assembler.received),
                    _export_consumer_state(assembler, session),
                )
        while pending_acks:
            consumer.ack(pending_acks.pop(0))

    try:
        while not assembler.complete():
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"slide '{plan['slide_id']}' incomplete after "
                    f"{deadline_s}s: missing chunks {assembler.missing()}"
                )
            lease.renew()
            # the lease directory also carries the consumer's OWN lease
            # (and a crashed predecessor's stale one): only tile workers
            # of the plan are reassignment subjects
            newly_lost = [w for w in membership.poll_lost()
                          if w in plan["workers"]]
            if worker_probe is not None:
                for w, rc in worker_probe().items():
                    if rc is None or rc == 0:
                        continue  # still running / clean exit
                    if membership.report_lost(
                        w, reason="process_exit", stage="tile",
                        exit_code=rc,
                    ):
                        newly_lost.append(w)
            for lost in newly_lost:
                pending = sorted(
                    owners.get(lost, set()) - assembler.received
                )
                owners.pop(lost, None)
                survivors = [w for w in plan["workers"]
                             if w not in membership.lost()]
                if not pending:
                    continue
                if not survivors:
                    raise RuntimeError(
                        f"worker {lost} died holding chunks {pending} "
                        "and no survivors remain"
                    )
                new_owners = assign_chunks(pending, survivors)
                for w, cids in new_owners.items():
                    owners.setdefault(w, set()).update(cids)
                write_reassignment(root, lost_worker=lost,
                                   assignments=new_owners, runlog=runlog)
                reassignments += 1
                # the recovery gap opens at DETECTION and closes at the
                # first replayed chunk of the reassigned set — see the
                # delivery path below
                gap_open = (time.monotonic(), "reassign", lost,
                            set(pending))
            chunk = consumer.recv(timeout=cfg.poll_s * 5)
            if chunk is None:
                continue
            t_arrived = time.monotonic()
            if not assembler.add(chunk):
                # belt under the transport's dedup suspenders: already
                # held (and, with a checkpoint, already durable) — ack
                # so the producer's credit comes home
                consumer.ack(chunk.seq)
                continue
            # the cross-process causal link: the chunk header carries the
            # producer's structural send-span id, so this deliver span
            # parents on it and the fleet merger draws the flow arrow
            ctx.add_span("deliver", t_arrived, time.monotonic(),
                         chunk=chunk.chunk_id,
                         parent=chunk.parent_span_id or None,
                         producer=chunk.producer)
            if gap_open is not None and (gap_open[3] is None
                                         or chunk.chunk_id in gap_open[3]):
                # first replayed chunk after a recovery: close the gap
                # as one explicit annotated span on the timeline
                ctx.add_span("recovery_gap", gap_open[0], t_arrived,
                             chunk=chunk.chunk_id, action=gap_open[1],
                             worker=gap_open[2])
                gap_open = None
            if session is not None:
                # fold on arrival: the session frontier-buffers
                # out-of-order deliveries, so the executed fold order —
                # and the embedding, bit-exact — is the plan's, not the
                # network's. This overlaps stage-1 production with
                # stage-2 folding; by completion only the final layers
                # remain.
                with span("dist.fold", runlog, trace=ctx,
                          chunk=chunk.chunk_id):
                    frontier = session.feed(chunk.chunk_id, chunk.payload,
                                            chunk.coords)
                if (peek_every > 0 and frontier > last_peek
                        and frontier < session.n_chunks
                        and frontier % peek_every == 0
                        and hasattr(session, "peek")):
                    # provisional embedding off the running partials —
                    # same anytime surface serve/streaming.py exposes,
                    # here mid-recovery-capable: the peek reads only
                    # folded state, so replayed chunks never skew it
                    with span("dist.peek", runlog, trace=ctx,
                              fence=True, chunk=chunk.chunk_id) as sp:
                        emb_dev = session.peek()[-1]
                        sp.fence(emb_dev)
                    emb = np.asarray(emb_dev, np.float32).reshape(-1)
                    cos_prev = (cosine(emb, prev_peek)
                                if prev_peek is not None else None)
                    prev_peek = emb
                    last_peek = frontier
                    runlog.event(
                        "stream_peek", slide=plan["slide_id"],
                        frontier=frontier, n_chunks=session.n_chunks,
                        frac=round(frontier / session.n_chunks, 4),
                        cos_prev=(round(cos_prev, 6)
                                  if cos_prev is not None else None),
                        lse_spread=(round(session.lse_spread(), 4)
                                    if hasattr(session, "lse_spread")
                                    else None),
                        wall_s=(round(sp.dur_s, 4)
                                if sp.dur_s is not None else None),
                    )
            delivered_here += 1
            if chaos:
                # the consumer-crash injection point: AFTER the fold,
                # BEFORE any checkpoint/ack — what dies here is exactly
                # the state only a checkpoint brings back
                chaos.maybe_kill_consumer(delivered_here)
            if checkpointer is None:
                consumer.ack(chunk.seq)
            else:
                pending_acks.append(chunk.seq)
                if len(pending_acks) >= ckpt_every:
                    _commit()

        _commit(final=True)
        with span("dist.finalize", runlog, trace=ctx):
            if session is not None:
                embedding = head_fn(session.finalize())
                runlog.event("stream_finalize", slide=plan["slide_id"],
                             n_chunks=session.n_chunks)
            else:
                # the dense slide forward: jitted once, retraces
                # watched — recovery must never show up as a recompile
                build = forward_builder or _default_forward()
                forward, params = build(int(plan["dim_out"]))
                watchdog = CompileWatchdog("dist.slide_forward", runlog)
                instrumented = watchdog.wrap(forward)
                embedding = np.asarray(
                    instrumented(params, assembler.embeds[None],
                                 assembler.coords[None]),
                    np.float32,
                )[0]
    except BaseException:
        status = "error"
        raise
    finally:
        # DONE even on failure: stranded workers must drain, not spin
        # out their whole deadline. (A SIGKILLed consumer never reaches
        # here — no DONE — so the fleet keeps producing for the
        # restarted consumer.)
        atomic_touch(os.path.join(root, DONE_MARKER))
        if status == "ok":
            lease.retire()
        close = getattr(consumer, "close", None)
        if close is not None:
            close()
        if own_log:
            runlog.run_end(
                status=status, slide=plan["slide_id"],
                lost=membership.lost(), reassignments=reassignments,
                **consumer.stats.as_dict(),
            )
    return {
        "embedding": embedding,
        "assembled": None if session is not None else assembler.embeds,
        "coords": None if session is not None else assembler.coords,
        "stats": consumer.stats.as_dict(),
        "lost": membership.lost(),
        "reassignments": reassignments,
        "streaming": session is not None,
    }


def spawn_worker(root: str, worker_id: str, *,
                 chaos: Optional[str] = None, run_id: Optional[str] = None,
                 deadline_s: float = 120.0) -> subprocess.Popen:
    """One tile-worker OS process. ``chaos`` lands in THAT worker's
    ``GIGAPATH_CHAOS`` only — how the acceptance kills/slows exactly
    one member of the fleet."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("GIGAPATH_CHAOS", None)
    if chaos:
        env["GIGAPATH_CHAOS"] = chaos
    if run_id:
        env["GIGAPATH_OBS_RUN_ID"] = run_id
    return subprocess.Popen(
        [sys.executable, "-m", "gigapath_tpu.dist.worker",
         "--root", root, "--worker", worker_id,
         "--deadline-s", str(deadline_s)],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def spawn_consumer(root: str, *, chaos: Optional[str] = None,
                   run_id: Optional[str] = None,
                   deadline_s: float = 120.0) -> subprocess.Popen:
    """The slide consumer as ITS OWN OS process (``python -m
    gigapath_tpu.dist.pipeline``) — the shape the consumer-crash
    acceptance needs: SIGKILLable, restartable, resuming from its
    checkpoint. ``chaos`` lands in that process's ``GIGAPATH_CHAOS``
    only (``kill_consumer@K``)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("GIGAPATH_CHAOS", None)
    if chaos:
        env["GIGAPATH_CHAOS"] = chaos
    if run_id:
        env["GIGAPATH_OBS_RUN_ID"] = run_id
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "gigapath_tpu.dist.pipeline",
         "--root", root, "--deadline-s", str(deadline_s)],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def load_result(root: str) -> dict:
    """The subprocess consumer's published result
    (``<root>/result.npz``, atomic write)."""
    with np.load(os.path.join(root, RESULT_FILE),
                 allow_pickle=False) as z:
        return {"embedding": np.asarray(z["embedding"]),
                "streaming": bool(z["streaming"])}


def run_disaggregated(root: str, *, plan: Optional[dict] = None,
                      worker_chaos: Optional[Dict[str, str]] = None,
                      runlog=None, deadline_s: float = 120.0,
                      run_id: Optional[str] = None,
                      consumer_chaos: Optional[str] = None,
                      consumer_restarts: int = 1) -> dict:
    """The dryrun: plan -> worker fleet (real processes) -> consumer.

    ``worker_chaos`` maps worker id -> ``GIGAPATH_CHAOS`` spec for that
    worker's process. Returns the consumer result plus worker exit
    codes.

    ``consumer_chaos`` (e.g. ``"kill_consumer@5"``) moves the consumer
    into its OWN process too; when that process dies nonzero the
    orchestrator restarts it (chaos-free) up to ``consumer_restarts``
    times — the restarted consumer resumes from its checkpoint
    watermark. The result then carries ``consumer_exit_codes``."""
    plan = plan or default_plan()
    write_plan(root, plan)
    worker_chaos = worker_chaos or {}
    procs = {
        w: spawn_worker(root, w, chaos=worker_chaos.get(w), run_id=run_id,
                        deadline_s=deadline_s)
        for w in plan["workers"]
    }
    consumer_exits: List[int] = []
    try:
        if consumer_chaos is None:
            result = run_slide_consumer(
                root, runlog=runlog, deadline_s=deadline_s,
                # the orchestrator holds the process handles: report a
                # nonzero exit the moment it happens instead of waiting
                # out the lease (and catch workers that died before
                # their first lease registration)
                worker_probe=lambda: {w: p.poll() for w, p in procs.items()},
            )
        else:
            proc = spawn_consumer(root, chaos=consumer_chaos,
                                  run_id=run_id, deadline_s=deadline_s)
            consumer_exits.append(proc.wait())
            while consumer_exits[-1] != 0 and \
                    len(consumer_exits) <= consumer_restarts:
                proc = spawn_consumer(root, run_id=run_id,
                                      deadline_s=deadline_s)
                consumer_exits.append(proc.wait())
            if consumer_exits[-1] != 0:
                raise RuntimeError(
                    f"consumer never completed: exit codes "
                    f"{consumer_exits}"
                )
            result = load_result(root)
            result.update(assembled=None, coords=None, stats=None,
                          lost=None, reassignments=None)
    finally:
        exit_codes: Dict[str, Optional[int]] = {}
        for w, proc in procs.items():
            try:
                exit_codes[w] = proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                exit_codes[w] = proc.wait()
    result["worker_exit_codes"] = exit_codes
    if consumer_exits:
        result["consumer_exit_codes"] = consumer_exits
    return result


def main(argv=None) -> int:
    """``python -m gigapath_tpu.dist.pipeline`` — the slide consumer as
    a standalone process (the SIGKILLable half of the consumer-crash
    acceptance). Publishes its result atomically to
    ``<root>/result.npz`` so the orchestrator reads it across the
    process boundary."""
    ap = argparse.ArgumentParser(
        description="dist slide-stage consumer (module docstring)"
    )
    ap.add_argument("--root", required=True, help="shared pipeline workdir")
    ap.add_argument("--deadline-s", type=float, default=120.0)
    args = ap.parse_args(argv)
    result = run_slide_consumer(args.root, deadline_s=args.deadline_s)
    tmp = os.path.join(args.root, f"{RESULT_FILE}.tmp-{os.getpid()}")
    with open(tmp, "wb") as fh:
        np.savez(fh, embedding=np.asarray(result["embedding"], np.float32),
                 streaming=np.bool_(result["streaming"]))
    os.replace(tmp, os.path.join(args.root, RESULT_FILE))
    return 0


if __name__ == "__main__":
    sys.exit(main())
