"""The slide-stage consumer + the two-process-group dryrun orchestrator.

:func:`run_slide_consumer` is the receiving fleet's loop: drain the
boundary channel, ack + assemble each chunk, poll worker leases, and on
a loss re-assign the dead worker's unacked chunk ids across survivors
(the elastic-degradation half of the recovery contract). When the plan's
every chunk is assembled it runs the slide-encoder forward over the
dense ``[n_tiles, D]`` sequence — jitted once, watched for retraces —
and publishes DONE so the workers drain out.

:func:`run_disaggregated` is the one-call dryrun: write the plan, spawn
one OS process per tile worker (``python -m gigapath_tpu.dist.worker``,
optionally with per-worker ``GIGAPATH_CHAOS`` — that is how the
acceptance kills exactly one), run the consumer in the calling process,
join the fleet. All processes share a ``GIGAPATH_OBS_RUN_ID`` so their
per-process JSONL files merge in ``scripts/obs_report.py`` (worker span
ranks feed the per-rank straggler table).

Bit-parity invariant (the acceptance): the assembled sequence is a pure
function of the plan — chunk ids, tile ranges and the deterministic
encoder never depend on which worker produced what — so a run that
loses a worker mid-slide yields the clean run's slide embedding
BIT-exact, with the recovery visible as ``worker_lost`` +
``recovery action="reassign"`` events rather than as different numbers.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from gigapath_tpu.dist.boundary import (
    BoundaryConfig,
    ChunkTracker,
    DirChannelConsumer,
    SlideAssembler,
    assign_chunks,
    atomic_touch,
    plan_chunks,
)
from gigapath_tpu.dist.membership import Membership, write_reassignment
from gigapath_tpu.dist.worker import DONE_MARKER, load_plan, write_plan

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def default_plan(*, slide_id: str = "slide0", n_tiles: int = 64,
                 dim_in: int = 16, dim_out: int = 8, chunk_tiles: int = 8,
                 workers: Optional[List[str]] = None, tile_seed: int = 0,
                 encoder_seed: int = 7, lease_s: float = 1.0,
                 credits: int = 4, retransmit_s: float = 0.5,
                 poll_s: float = 0.02,
                 chunked_prefill: bool = False) -> dict:
    """The dryrun's plan document (written to ``<root>/plan.json``,
    read by every process — the shared deterministic truth).
    ``chunked_prefill`` puts the consumer in streaming mode: chunks fold
    into the slide encoder on arrival instead of assembling the dense
    sequence (the plan carries the mode so every process agrees)."""
    return dict(
        slide_id=slide_id, n_tiles=int(n_tiles), dim_in=int(dim_in),
        dim_out=int(dim_out), chunk_tiles=int(chunk_tiles),
        workers=sorted(workers or ["w0", "w1"]), tile_seed=int(tile_seed),
        encoder_seed=int(encoder_seed), lease_s=float(lease_s),
        credits=int(credits), retransmit_s=float(retransmit_s),
        poll_s=float(poll_s), chunked_prefill=bool(chunked_prefill),
    )


def _default_streaming_forward():
    """The dryrun slide stage in CHUNKED-PREFILL form: the same tiny
    encoder + classifier params as :func:`_default_forward` (same stage
    mesh placement), but consumed through a
    :class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`
    so the consumer folds ``EmbeddingChunk``s on arrival instead of
    assembling the dense ``[n_tiles, D]`` sequence first. Returns
    ``build(dim_in) -> (open_session(n_tiles, chunk_tiles), head_fn)``;
    ``head_fn`` maps the session's per-layer embeds to the same logits
    the dense forward emits (the parity/bit-exactness surface)."""
    import jax

    from gigapath_tpu.dist.stagemesh import stage_mesh, stage_param_shardings
    from gigapath_tpu.models.classification_head import get_model
    from gigapath_tpu.models.streaming_encoder import StreamingEncoderSession
    from gigapath_tpu.serve.streaming import streaming_head_logits
    from gigapath_tpu.utils.registry import create_model_from_registry

    def build(dim_in: int):
        model, params = get_model(
            input_dim=dim_in, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        mesh = stage_mesh("slide_encoder", devices=jax.devices()[:1])
        params = jax.device_put(
            params, stage_param_shardings("slide_encoder", params, mesh)
        )
        inner = create_model_from_registry(
            "gigapath_slide_enc_tiny", in_chans=dim_in, global_pool=False,
            dtype=None,
        )

        def open_session(n_tiles: int, chunk_tiles: int, runlog=None):
            # runlog -> per-stage CompileWatchdogs inside the session:
            # streaming recovery must never hide a retrace, same as the
            # dense consumer's watched forward
            return StreamingEncoderSession(
                inner, params["slide_encoder"], n_tiles,
                chunk_tiles=chunk_tiles, all_layer_embed=True,
                runlog=runlog,
            )

        def head(embeds):
            # the ONE classifier-tail implementation (serve/streaming.py)
            # keeps the dist parity surface and the serving path in
            # lockstep
            return streaming_head_logits(model, params, embeds)[0]

        return open_session, head

    return build


def _default_forward():
    """The dryrun slide stage: the tiny slide encoder + classifier head
    (the same arch the chaos/serve smokes pin), jitted once per shape,
    with params placed through the ``slide_encoder`` entry of the
    stage-sharding registry (a 1-device stage mesh here, so every rule
    degrades to replicated — the dryrun consumes the same declarative
    path a sharded fleet does, without changing a single byte)."""
    import jax

    from gigapath_tpu.dist.stagemesh import stage_mesh, stage_param_shardings
    from gigapath_tpu.models.classification_head import get_model

    def build(dim_in: int):
        model, params = get_model(
            input_dim=dim_in, latent_dim=32, feat_layer="1", n_classes=2,
            model_arch="gigapath_slide_enc_tiny", dtype=None,
        )
        mesh = stage_mesh("slide_encoder", devices=jax.devices()[:1])
        params = jax.device_put(
            params, stage_param_shardings("slide_encoder", params, mesh)
        )

        def forward(p, embeds, coords):
            return model.apply({"params": p}, embeds, coords,
                               deterministic=True)

        return jax.jit(forward), params

    return build


def run_slide_consumer(root: str, *, runlog=None,
                       forward_builder: Optional[Callable] = None,
                       streaming: Optional[bool] = None,
                       streaming_builder: Optional[Callable] = None,
                       deadline_s: float = 120.0,
                       worker_probe: Optional[Callable] = None) -> dict:
    """Assemble one slide from the channel, recovering from worker loss.

    ``streaming`` (default: the plan's ``chunked_prefill`` field, else
    the ``GIGAPATH_CHUNKED_PREFILL`` snapshot) switches the consumer to
    chunked prefill: each acked ``EmbeddingChunk`` folds into a
    :class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`
    the moment the fold frontier reaches it — arrival order, retransmits
    and reassignment all tolerated, with the fold sequence (and so the
    embedding, BIT-exact) a pure function of the deterministic chunk
    plan. The dense ``[n_tiles, D]`` sequence is never assembled in this
    mode (``assembled``/``coords`` come back None).

    ``worker_probe`` (optional): zero-arg callable returning
    ``{worker_id: exit_code_or_None}`` for workers whose OS processes
    this host can see — direct evidence of death that beats waiting out
    the lease, and the ONLY detection for a worker that died before its
    first lease registration (no lease file ever existed for the expiry
    path to notice). Cross-host consumers pass nothing and rely on
    leases alone.

    Returns ``{"embedding", "assembled", "coords", "stats", "lost",
    "reassignments"}``; raises TimeoutError when the slide cannot
    complete within ``deadline_s`` (no silent partial slides)."""
    from gigapath_tpu.obs.runlog import get_run_log
    from gigapath_tpu.obs.watchdog import CompileWatchdog

    plan = load_plan(root)
    cfg = BoundaryConfig.from_env(
        capacity=plan.get("credits"), chunk_tiles=plan.get("chunk_tiles"),
        retransmit_s=plan.get("retransmit_s"), poll_s=plan.get("poll_s"),
    )
    own_log = runlog is None
    if own_log:
        runlog = get_run_log(
            "dist-consumer", out_dir=root,
            config={"slide": plan["slide_id"], "n_tiles": plan["n_tiles"],
                    "workers": plan["workers"],
                    "chunk_tiles": cfg.chunk_tiles},
        )
    if streaming is None:
        # one host-side read, the PipelineFlags convention: the plan
        # document wins (every process sees the same mode), the env
        # snapshot is the single-process default
        if "chunked_prefill" in plan:
            streaming = bool(plan["chunked_prefill"])
        else:
            from gigapath_tpu.ops.pallas_dilated import snapshot_flags

            streaming = snapshot_flags().chunked_prefill
    consumer = DirChannelConsumer(root, cfg, runlog=runlog)
    membership = Membership(root, runlog=runlog)
    chunks = plan_chunks(int(plan["n_tiles"]), cfg.chunk_tiles)
    session = None
    head_fn = None
    if streaming:
        build = streaming_builder or _default_streaming_forward()
        open_session, head_fn = build(int(plan["dim_out"]))
        session = open_session(int(plan["n_tiles"]), cfg.chunk_tiles,
                               runlog=runlog)
        runlog.event("stream_open", slide=plan["slide_id"],
                     n_chunks=session.n_chunks,
                     chunk_tiles=cfg.chunk_tiles)
        # received-chunk bookkeeping only (recovery needs the set of
        # delivered chunk ids) — the dense buffers are exactly what
        # streaming mode exists to not allocate
        assembler = ChunkTracker()
    else:
        assembler = SlideAssembler(int(plan["n_tiles"]), int(plan["dim_out"]))
    assembler.expect([c[0] for c in chunks])

    # who currently owns which chunk (updated by reassignments): the
    # coordinator's view of the SAME deterministic assignment the
    # workers computed for themselves
    owners: Dict[str, set] = {
        w: set(cids)
        for w, cids in assign_chunks([c[0] for c in chunks],
                                     plan["workers"]).items()
    }
    reassignments = 0
    deadline = time.monotonic() + deadline_s
    status = "ok"
    try:
        while not assembler.complete():
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"slide '{plan['slide_id']}' incomplete after "
                    f"{deadline_s}s: missing chunks {assembler.missing()}"
                )
            newly_lost = membership.poll_lost()
            if worker_probe is not None:
                for w, rc in worker_probe().items():
                    if rc is None or rc == 0:
                        continue  # still running / clean exit
                    if membership.report_lost(
                        w, reason="process_exit", stage="tile",
                        exit_code=rc,
                    ):
                        newly_lost.append(w)
            for lost in newly_lost:
                pending = sorted(
                    owners.get(lost, set()) - assembler.received
                )
                owners.pop(lost, None)
                survivors = [w for w in plan["workers"]
                             if w not in membership.lost()]
                if not pending:
                    continue
                if not survivors:
                    raise RuntimeError(
                        f"worker {lost} died holding chunks {pending} "
                        "and no survivors remain"
                    )
                new_owners = assign_chunks(pending, survivors)
                for w, cids in new_owners.items():
                    owners.setdefault(w, set()).update(cids)
                write_reassignment(root, lost_worker=lost,
                                   assignments=new_owners, runlog=runlog)
                reassignments += 1
            chunk = consumer.recv(timeout=cfg.poll_s * 5)
            if chunk is None:
                continue
            consumer.ack(chunk.seq)
            if assembler.add(chunk) and session is not None:
                # fold on arrival: the session frontier-buffers
                # out-of-order deliveries, so the executed fold order —
                # and the embedding, bit-exact — is the plan's, not the
                # network's. This overlaps stage-1 production with
                # stage-2 folding; by completion only the final layers
                # remain.
                session.feed(chunk.chunk_id, chunk.payload, chunk.coords)

        if session is not None:
            embedding = head_fn(session.finalize())
            runlog.event("stream_finalize", slide=plan["slide_id"],
                         n_chunks=session.n_chunks)
        else:
            # the dense slide forward: jitted once, retraces watched —
            # recovery must never show up as a recompile
            build = forward_builder or _default_forward()
            forward, params = build(int(plan["dim_out"]))
            watchdog = CompileWatchdog("dist.slide_forward", runlog)
            instrumented = watchdog.wrap(forward)
            embedding = np.asarray(
                instrumented(params, assembler.embeds[None],
                             assembler.coords[None]),
                np.float32,
            )[0]
    except BaseException:
        status = "error"
        raise
    finally:
        # DONE even on failure: stranded workers must drain, not spin
        # out their whole deadline
        atomic_touch(os.path.join(root, DONE_MARKER))
        if own_log:
            runlog.run_end(
                status=status, slide=plan["slide_id"],
                lost=membership.lost(), reassignments=reassignments,
                **consumer.stats.as_dict(),
            )
    return {
        "embedding": embedding,
        "assembled": None if session is not None else assembler.embeds,
        "coords": None if session is not None else assembler.coords,
        "stats": consumer.stats.as_dict(),
        "lost": membership.lost(),
        "reassignments": reassignments,
        "streaming": session is not None,
    }


def spawn_worker(root: str, worker_id: str, *,
                 chaos: Optional[str] = None, run_id: Optional[str] = None,
                 deadline_s: float = 120.0) -> subprocess.Popen:
    """One tile-worker OS process. ``chaos`` lands in THAT worker's
    ``GIGAPATH_CHAOS`` only — how the acceptance kills/slows exactly
    one member of the fleet."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("GIGAPATH_CHAOS", None)
    if chaos:
        env["GIGAPATH_CHAOS"] = chaos
    if run_id:
        env["GIGAPATH_OBS_RUN_ID"] = run_id
    return subprocess.Popen(
        [sys.executable, "-m", "gigapath_tpu.dist.worker",
         "--root", root, "--worker", worker_id,
         "--deadline-s", str(deadline_s)],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def run_disaggregated(root: str, *, plan: Optional[dict] = None,
                      worker_chaos: Optional[Dict[str, str]] = None,
                      runlog=None, deadline_s: float = 120.0,
                      run_id: Optional[str] = None) -> dict:
    """The dryrun: plan -> worker fleet (real processes) -> consumer.

    ``worker_chaos`` maps worker id -> ``GIGAPATH_CHAOS`` spec for that
    worker's process. Returns the consumer result plus worker exit
    codes."""
    plan = plan or default_plan()
    write_plan(root, plan)
    worker_chaos = worker_chaos or {}
    procs = {
        w: spawn_worker(root, w, chaos=worker_chaos.get(w), run_id=run_id,
                        deadline_s=deadline_s)
        for w in plan["workers"]
    }
    try:
        result = run_slide_consumer(
            root, runlog=runlog, deadline_s=deadline_s,
            # the orchestrator holds the process handles: report a
            # nonzero exit the moment it happens instead of waiting out
            # the lease (and catch workers that died before their first
            # lease registration)
            worker_probe=lambda: {w: p.poll() for w, p in procs.items()},
        )
    finally:
        exit_codes: Dict[str, Optional[int]] = {}
        for w, proc in procs.items():
            try:
                exit_codes[w] = proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                exit_codes[w] = proc.wait()
    result["worker_exit_codes"] = exit_codes
    return result
