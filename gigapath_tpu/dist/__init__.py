"""Disaggregated two-stage pipeline: the fault-tolerant cross-stage
boundary (ROADMAP item 4's multichip-dryrun milestone).

The production shape is two fleets at wildly different scales — a
1.13B-param ViT-G tile encoder fanning out over 10^5-10^6 tiles per
slide, streaming embeddings into a LongNet slide encoder (PAPER.md §0)
— joined not by one monolithic program but by a *boundary* that must
survive the failure modes a single pjit never sees: a dead tile worker,
a straggler, a dropped or duplicated chunk, a consumer that falls
behind.

- :mod:`gigapath_tpu.dist.boundary` — the bounded, credit-based
  embedding channel between the stages: per-chunk sequence numbers +
  content checksums, producer blocks (and emits a schema'd
  ``backpressure`` event) when consumer credits run out, consumer acks
  chunks so unacked chunks are requeued on failure, duplicates and
  out-of-order arrivals are deduped by seq;
- :mod:`gigapath_tpu.dist.membership` — lease-based worker liveness
  (heartbeat renew + expiry -> ``worker_lost`` anomaly) and elastic
  degradation: a lost tile worker's unacked tile range is re-assigned
  across survivors via the same deterministic chunk plan, so the slide
  completes with bit-parity to the clean run;
- :mod:`gigapath_tpu.dist.stagemesh` — per-stage mesh construction over
  ``parallel/mesh.py``'s axes plus a declarative sharding-rule registry
  (the ``match_partition_rules`` pattern) keyed per stage, consumed by
  both fleets;
- :mod:`gigapath_tpu.dist.transport` — the REAL network transport
  (TCP): length-prefixed sha256-digested frames, reconnect with capped
  exponential backoff + full jitter, a handshake carrying the
  consumer's ack watermark so a reconnect replays exactly the unacked
  chunk ids, and the frame-layer chaos injectors (``drop_conn`` /
  ``delay_frame`` / ``corrupt_frame`` / ``reorder_frame``); selected by
  ``GIGAPATH_DIST_TRANSPORT`` through ``make_producer``/
  ``make_consumer`` with zero changes to the fold path;
- :mod:`gigapath_tpu.dist.worker` / :mod:`gigapath_tpu.dist.pipeline` —
  the runnable dryrun harness: real tile-worker *processes* and the
  slide-stage consumer (its own SIGKILLable process when needed, with
  checkpointed fold state and bit-exact resume —
  ``GIGAPATH_DIST_CONSUMER_CKPT_EVERY``), provable on one machine (two
  process groups on CPU), chaos-injectable via the ``GIGAPATH_CHAOS``
  ``kill_worker`` / ``kill_consumer`` / ``slow_worker`` /
  ``drop_chunk`` / ``dup_chunk`` injectors.

Everything protocol-level (boundary, membership, the chunk plan) is
numpy + stdlib only — no jax import — so a tile worker process starts
in milliseconds and the transport can never retrace anything.
``scripts/dist_smoke.py`` is the one-command two-process recovery
checklist.
"""

from gigapath_tpu.dist.boundary import (  # noqa: F401
    BoundaryConfig,
    DirChannelConsumer,
    DirChannelProducer,
    EmbeddingChunk,
    MemoryChannel,
    SlideAssembler,
    assign_chunks,
    chunk_checksum,
    plan_chunks,
)
from gigapath_tpu.dist.membership import (  # noqa: F401
    Membership,
    WorkerLease,
    write_reassignment,
)
from gigapath_tpu.dist.transport import (  # noqa: F401
    TcpChannelConsumer,
    TcpChannelProducer,
    make_consumer,
    make_producer,
    transport_name,
)
