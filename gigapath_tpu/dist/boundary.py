"""The cross-stage embedding channel: bounded, credit-based, checksummed.

One slide's tile embeddings flow from the tile-encoder stage to the
slide-encoder stage as *chunks* — contiguous tile ranges cut by the
deterministic :func:`plan_chunks` plan. The channel gives that flow the
four properties a cross-host boundary needs and a monolithic pjit gets
for free:

- **bounded**: the producer holds at most ``capacity`` unacked chunks in
  flight (credit-based flow control). When credits hit zero the producer
  BLOCKS — and emits one schema'd ``backpressure`` event per blocking
  episode (queue depth, credits, capacity) so a consumer falling behind
  is visible on the obs bus, not an OOM an hour later;
- **checksummed**: every chunk carries a sha256 over its header and
  payload bytes; a corrupt arrival is counted and discarded (the
  producer-side retransmit timer heals it), never assembled;
- **acked**: the consumer acks each delivered seq; producer credits are
  acked-based, so unacked chunks are exactly the set a recovery has to
  requeue (:mod:`gigapath_tpu.dist.membership` re-assigns a lost
  worker's unacked range across survivors);
- **deduped**: sequence numbers are the chunk ids of the deterministic
  plan — stable across retransmits AND across re-assignment — so a
  duplicate (a ``dup_chunk`` injection, a retransmit racing its ack, a
  survivor re-producing a chunk the dead worker's last write also
  landed) is dropped by seq and the assembled slide is bit-identical to
  the clean run's.

Two transports, one protocol: :class:`MemoryChannel` (in-process,
``threading.Condition`` — the serving/inference prefetch path and the
unit tests) and the :class:`DirChannelProducer`/:class:`DirChannelConsumer`
pair (a shared directory with atomic tmp+rename writes — the two-process
dryrun harness; DCN/RPC transports slot in behind the same surface).
numpy + stdlib only; nothing here can touch a traced program, so the
channel can add no retraces (pinned by tests/test_dist.py).
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import os
import threading
import time
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

from gigapath_tpu.obs.locktrace import make_condition

import numpy as np

from gigapath_tpu.obs.runlog import env_number


@dataclasses.dataclass(frozen=True)
class BoundaryConfig:
    """Channel knobs, snapshotted host-side at construction.

    ``from_env`` reads the ``GIGAPATH_DIST_*`` flags ONCE (the
    ``get_run_log`` discipline — never at trace time; README flag
    table)."""

    capacity: int = 8          # credits: max unacked chunks in flight
    chunk_tiles: int = 512     # tiles per chunk in the deterministic plan
    poll_s: float = 0.02       # producer block / consumer scan cadence
    retransmit_s: float = 2.0  # unacked-for-longer gets re-sent
    # TCP transport (dist/transport.py) only
    connect_timeout_s: float = 5.0  # per-connect AND per-frame deadline
    backoff_s: float = 2.0          # reconnect backoff cap (full jitter)

    @classmethod
    def from_env(cls, **overrides) -> "BoundaryConfig":
        fields = dict(
            capacity=int(env_number("GIGAPATH_DIST_CREDITS", cls.capacity)),
            chunk_tiles=int(env_number("GIGAPATH_DIST_CHUNK_TILES",
                                       cls.chunk_tiles)),
            poll_s=env_number("GIGAPATH_DIST_POLL_S", cls.poll_s),
            retransmit_s=env_number("GIGAPATH_DIST_RETRANSMIT_S",
                                    cls.retransmit_s),
            connect_timeout_s=env_number("GIGAPATH_DIST_CONNECT_TIMEOUT_S",
                                         cls.connect_timeout_s),
            backoff_s=env_number("GIGAPATH_DIST_BACKOFF_S", cls.backoff_s),
        )
        fields.update({k: v for k, v in overrides.items() if v is not None})
        if fields["capacity"] < 1:
            raise ValueError(f"capacity must be >= 1, got {fields['capacity']}")
        return cls(**fields)


# ---------------------------------------------------------------------------
# the deterministic chunk plan
# ---------------------------------------------------------------------------

def plan_chunks(n_tiles: int, chunk_tiles: int) -> List[Tuple[int, int, int]]:
    """``[(chunk_id, start, stop), ...]`` covering ``[0, n_tiles)`` in
    order. Chunk ids double as the channel's sequence numbers: they are
    a pure function of the slide geometry, so a survivor re-producing a
    lost worker's chunk emits the SAME seq the original would have —
    dedup and bit-parity both hang off this determinism."""
    if n_tiles < 1 or chunk_tiles < 1:
        raise ValueError(f"need n_tiles/chunk_tiles >= 1, got "
                         f"{n_tiles}/{chunk_tiles}")
    return [
        (cid, start, min(start + chunk_tiles, n_tiles))
        for cid, start in enumerate(range(0, n_tiles, chunk_tiles))
    ]


def assign_chunks(chunk_ids: Sequence[int],
                  workers: Sequence[str]) -> Dict[str, List[int]]:
    """Deterministic round-robin of chunk ids over SORTED worker ids —
    the one assignment function, used both for the initial shard and for
    re-assigning a lost worker's unacked chunks across survivors (same
    inputs -> same plan on every host, no coordination round needed)."""
    if not workers:
        raise ValueError("assign_chunks: no workers")
    ordered = sorted(workers)
    out: Dict[str, List[int]] = {w: [] for w in ordered}
    for i, cid in enumerate(sorted(chunk_ids)):
        out[ordered[i % len(ordered)]].append(cid)
    return out


# ---------------------------------------------------------------------------
# chunks
# ---------------------------------------------------------------------------

def chunk_checksum(slide_id: str, chunk_id: int, start: int, stop: int,
                   payload: np.ndarray,
                   coords: Optional[np.ndarray]) -> str:
    """sha256 over the header and the exact payload bytes. The header is
    inside the digest so a chunk whose payload survived but whose tile
    range was mangled still fails verification."""
    h = hashlib.sha256()
    h.update(f"{slide_id}|{chunk_id}|{start}|{stop}|".encode())
    h.update(np.ascontiguousarray(payload).tobytes())
    if coords is not None:
        h.update(np.ascontiguousarray(coords).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class EmbeddingChunk:
    """One contiguous tile range of one slide's embeddings in flight.

    ``seq == chunk_id`` (see :func:`plan_chunks`); ``producer`` is
    provenance for the report, never protocol state."""

    slide_id: str
    chunk_id: int
    start: int
    stop: int
    payload: np.ndarray                    # [stop-start, D] float32
    coords: Optional[np.ndarray] = None    # [stop-start, 2] float32
    producer: str = ""
    checksum: str = ""

    @property
    def seq(self) -> int:
        return self.chunk_id

    @classmethod
    def build(cls, slide_id: str, chunk_id: int, start: int, stop: int,
              payload: np.ndarray, coords: Optional[np.ndarray] = None,
              producer: str = "", digest: bool = True) -> "EmbeddingChunk":
        """``digest=False`` skips the sha256 (checksum stays ``""``) —
        ONLY for intra-process channels, where the handoff is a memory
        reference that cannot corrupt and hashing hundreds of MB per
        slide would tax the hot path for nothing. Cross-process
        transports must digest: the directory consumer rejects an
        empty checksum outright."""
        payload = np.asarray(payload, np.float32)
        if coords is not None:
            coords = np.asarray(coords, np.float32)
        if payload.shape[0] != stop - start:
            raise ValueError(
                f"chunk {chunk_id}: payload rows {payload.shape[0]} != "
                f"tile range [{start}, {stop})"
            )
        return cls(
            slide_id=slide_id, chunk_id=int(chunk_id), start=int(start),
            stop=int(stop), payload=payload, coords=coords,
            producer=producer,
            checksum=chunk_checksum(slide_id, chunk_id, start, stop,
                                    payload, coords) if digest else "",
        )

    def verify(self) -> bool:
        return self.checksum == chunk_checksum(
            self.slide_id, self.chunk_id, self.start, self.stop,
            self.payload, self.coords,
        )


@dataclasses.dataclass
class ChannelStats:
    """Host-side protocol counters, rendered by ``obs_report.py``'s
    ``== dist ==`` section and asserted by the smoke/tests."""

    sent: int = 0
    delivered: int = 0
    acked: int = 0
    duplicates: int = 0      # arrivals dropped by seq dedup
    corrupt: int = 0         # arrivals failing checksum verification
    retransmits: int = 0     # unacked chunks re-sent after the timer
    dropped: int = 0         # sends swallowed by chaos injection
    backpressure_events: int = 0
    blocked_s: float = 0.0   # total producer wall spent credit-blocked
    # TCP transport only (dist/transport.py); zero on the other two
    reconnects: int = 0      # connections re-established after the first
    frame_errors: int = 0    # torn/corrupt/misframed wire frames dropped
    bytes_sent: int = 0      # frame bytes pushed onto the wire

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _emit_backpressure(runlog, *, channel: str, seq: int, queue_depth: int,
                       capacity: int) -> None:
    """One schema'd ``backpressure`` event per producer blocking episode
    (runlog optional — bare-channel users stay silent)."""
    if runlog is not None:
        runlog.event(  # gigarace: calls RunLog.event
            "backpressure", channel=channel, seq=seq, credits=0,
            queue_depth=queue_depth, capacity=capacity,
        )


# ---------------------------------------------------------------------------
# in-process transport (threads)
# ---------------------------------------------------------------------------

class MemoryChannel:
    """Intra-process producer/consumer pair over one bounded buffer.

    The transport behind the inference prefetch wiring and the
    backpressure unit tests: ``send`` blocks while ``capacity`` chunks
    are unacked, ``recv`` dedups by seq, ``ack`` returns the credit.
    """

    def __init__(self, config: Optional[BoundaryConfig] = None, *,
                 runlog=None, name: str = "memory"):
        self.cfg = config or BoundaryConfig()
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self.stats = ChannelStats()
        self._cond = make_condition("gigapath_tpu.dist.boundary.MemoryChannel._cond")
        self._queue: List[EmbeddingChunk] = []
        self._unacked: Dict[int, EmbeddingChunk] = {}
        self._delivered: set = set()
        self._closed = False
        self._episode_seq: Optional[int] = None  # backpressure dedup

    # -- producer side ----------------------------------------------------
    def send(self, chunk: EmbeddingChunk,
             timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            blocked_at = None
            while len(self._unacked) >= self.cfg.capacity and not self._closed:
                if blocked_at is None:
                    blocked_at = time.monotonic()
                    if self._episode_seq != chunk.seq:
                        # one event per blocking EPISODE: a caller
                        # retrying a timed-out send of the same seq is
                        # the same episode, not a new one
                        self._episode_seq = chunk.seq
                        self.stats.backpressure_events += 1
                        _emit_backpressure(
                            self._runlog, channel=self.name, seq=chunk.seq,
                            queue_depth=len(self._unacked),
                            capacity=self.cfg.capacity,
                        )
                wait = self.cfg.poll_s
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        self.stats.blocked_s += time.monotonic() - blocked_at
                        raise TimeoutError(
                            f"{self.name}: no credit within {timeout}s "
                            f"(seq {chunk.seq})"
                        )
                self._cond.wait(timeout=wait)
            if blocked_at is not None:
                self.stats.blocked_s += time.monotonic() - blocked_at
            if self._closed:
                raise RuntimeError(f"{self.name}: channel closed")
            self._unacked[chunk.seq] = chunk
            self._queue.append(chunk)
            self.stats.sent += 1
            self._cond.notify_all()

    def unacked_seqs(self) -> List[int]:
        with self._cond:
            return sorted(self._unacked)

    # -- consumer side ----------------------------------------------------
    def recv(self, timeout: Optional[float] = None) -> Optional[EmbeddingChunk]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                while self._queue:
                    chunk = self._queue.pop(0)
                    if chunk.seq in self._delivered:
                        self.stats.duplicates += 1
                        continue
                    # digest-less chunks (build(digest=False)) are the
                    # sanctioned intra-process fast path: the handoff
                    # is a memory reference, there is nothing to verify
                    if chunk.checksum and not chunk.verify():
                        self.stats.corrupt += 1
                        continue
                    self._delivered.add(chunk.seq)
                    self.stats.delivered += 1
                    return chunk
                if self._closed:
                    return None
                wait = self.cfg.poll_s
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return None
                self._cond.wait(timeout=wait)

    def ack(self, seq: int) -> None:
        with self._cond:
            if self._unacked.pop(seq, None) is not None:
                self.stats.acked += 1
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# cross-process transport (shared directory)
# ---------------------------------------------------------------------------
#
# Layout under <root>/channel/:
#   chunk-<seq:06d>-<nonce>.npz   one send (atomic tmp+rename; the nonce
#                                 keeps retransmits/dups from colliding)
#   ack-<seq:06d>                 consumer ack marker (empty file)
#
# The producer's credit view is acked-based (a chunk file it wrote whose
# ack marker exists frees its credit); the consumer's dedup view is an
# in-memory seq set. Atomic renames mean a reader never sees a partial
# chunk; SIGKILL mid-write leaves only a tmp file nobody scans.

def _atomic_write_npz(path: str, **arrays) -> None:
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)


def atomic_touch(path: str) -> str:
    """Atomically materialize an empty marker file (ack markers, the
    pipeline's DONE flag): tmp + rename, so a scanner never races a
    half-created entry."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8"):
        pass
    os.replace(tmp, path)
    return path


class DirChannelProducer:
    """One tile worker's sending half of the directory channel."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 producer: str = "", runlog=None, chaos=None,
                 name: str = "dir"):
        self.cfg = config or BoundaryConfig()
        self.dir = os.path.join(root, "channel")
        os.makedirs(self.dir, exist_ok=True)
        self.producer = producer
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self._chaos = chaos
        self.stats = ChannelStats()
        self._sent_at: Dict[int, float] = {}      # seq -> last send time
        self._chunks: Dict[int, EmbeddingChunk] = {}  # unacked payloads
        self._nonce = 0
        self._episode_seq: Optional[int] = None   # backpressure dedup

    # -- protocol ---------------------------------------------------------
    def _ack_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"ack-{seq:06d}")

    def _refresh_acks(self) -> None:
        for seq in list(self._sent_at):
            if os.path.exists(self._ack_path(seq)):
                self._sent_at.pop(seq, None)
                self._chunks.pop(seq, None)
                self.stats.acked += 1

    def _write(self, chunk: EmbeddingChunk) -> None:
        self._nonce += 1
        path = os.path.join(
            self.dir,
            f"chunk-{chunk.seq:06d}-{self.producer or 'p'}-{self._nonce}.npz",
        )
        arrays = dict(
            slide_id=np.array(chunk.slide_id),
            chunk_id=np.array(chunk.chunk_id, np.int64),
            start=np.array(chunk.start, np.int64),
            stop=np.array(chunk.stop, np.int64),
            payload=chunk.payload,
            producer=np.array(chunk.producer or self.producer),
            checksum=np.array(chunk.checksum),
        )
        if chunk.coords is not None:
            arrays["coords"] = chunk.coords
        _atomic_write_npz(path, **arrays)

    def credits(self) -> int:
        self._refresh_acks()
        return max(self.cfg.capacity - len(self._sent_at), 0)

    def unacked_seqs(self) -> List[int]:
        self._refresh_acks()
        return sorted(self._sent_at)

    def send(self, chunk: EmbeddingChunk,
             timeout: Optional[float] = None) -> None:
        """Blocks (polling) while every credit is in flight; the chaos
        injectors hook here — a ``drop_chunk`` swallows THIS write but
        still registers the seq as sent-unacked (the retransmit timer
        heals it, exactly like a lost network write), a ``dup_chunk``
        writes twice (the consumer's dedup absorbs the twin)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        blocked_at = None
        while self.credits() <= 0:
            if blocked_at is None:
                blocked_at = time.monotonic()
                if self._episode_seq != chunk.seq:
                    # one event per blocking episode, even when the
                    # caller retries a timed-out send of the same seq
                    # (the worker's lease-renewing retry loop does)
                    self._episode_seq = chunk.seq
                    self.stats.backpressure_events += 1
                    _emit_backpressure(
                        self._runlog, channel=self.name, seq=chunk.seq,
                        queue_depth=len(self._sent_at),
                        capacity=self.cfg.capacity,
                    )
            if deadline is not None and time.monotonic() >= deadline:
                self.stats.blocked_s += time.monotonic() - blocked_at
                raise TimeoutError(
                    f"{self.name}: no credit within {timeout}s "
                    f"(seq {chunk.seq})"
                )
            time.sleep(self.cfg.poll_s)
        if blocked_at is not None:
            self.stats.blocked_s += time.monotonic() - blocked_at
        self._sent_at[chunk.seq] = time.monotonic()
        self._chunks[chunk.seq] = chunk
        self.stats.sent += 1
        if self._chaos is not None and self._chaos.drops_chunk(chunk.seq):
            self.stats.dropped += 1
            return
        self._write(chunk)
        if self._chaos is not None and self._chaos.dups_chunk(chunk.seq):
            self._write(chunk)

    def pump_retransmits(self, now: Optional[float] = None) -> int:
        """Re-send every chunk unacked for longer than ``retransmit_s``.
        Returns the number re-sent. Safe against duplicates: seqs dedup
        at the consumer."""
        now = time.monotonic() if now is None else now
        self._refresh_acks()
        n = 0
        for seq, sent_at in list(self._sent_at.items()):
            if now - sent_at >= self.cfg.retransmit_s:
                chunk = self._chunks.get(seq)
                if chunk is None:
                    continue
                self._write(chunk)
                self._sent_at[seq] = now
                self.stats.retransmits += 1
                n += 1
        return n


class DirChannelConsumer:
    """The slide stage's receiving half of the directory channel (one
    consumer drains every producer's chunks — the fan-in point)."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 runlog=None, name: str = "dir",
                 delivered: Optional[Sequence[int]] = None):
        """``delivered``: seqs a RESTARTED consumer already holds (its
        checkpoint watermark) — seeded into the dedup set so retransmits
        of pre-crash chunks are absorbed, not re-assembled."""
        self.cfg = config or BoundaryConfig()
        self.dir = os.path.join(root, "channel")
        os.makedirs(self.dir, exist_ok=True)
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self.stats = ChannelStats()
        self._delivered: set = set(
            int(s) for s in delivered) if delivered else set()
        # seqs this consumer considers DURABLE: the seeded watermark plus
        # every ack it issued itself. Only these may be re-acked on a
        # duplicate — a delivered-but-deferred-ack seq must NOT be (the
        # deferred-ack discipline: an ack is a durability promise)
        self._acked: set = set(self._delivered)

    def _load(self, path: str) -> Optional[EmbeddingChunk]:
        try:
            with np.load(path, allow_pickle=False) as z:
                coords = z["coords"] if "coords" in z.files else None
                return EmbeddingChunk(
                    slide_id=str(z["slide_id"]),
                    chunk_id=int(z["chunk_id"]), start=int(z["start"]),
                    stop=int(z["stop"]), payload=np.asarray(z["payload"]),
                    coords=None if coords is None else np.asarray(coords),
                    producer=str(z["producer"]),
                    checksum=str(z["checksum"]),
                )
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            # a torn archive can only be a racing writer's tmp that
            # slipped in; re-scan next poll, never delete blind
            return None

    def recv(self, timeout: Optional[float] = None) -> Optional[EmbeddingChunk]:
        """Next new, verified chunk (any producer), or None on timeout.
        Processed files are deleted; duplicate seqs and corrupt payloads
        are counted and dropped."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            for path in sorted(glob.glob(os.path.join(self.dir, "chunk-*.npz"))):
                name = os.path.basename(path)
                try:
                    seq = int(name.split("-")[1])
                except (IndexError, ValueError):
                    continue
                if seq in self._delivered:
                    self.stats.duplicates += 1
                    _unlink_quiet(path)
                    if seq in self._acked:
                        # re-ack (idempotent marker): a RESTARTED
                        # consumer's seeded watermark may cover seqs
                        # whose deferred ack died with the predecessor
                        # between checkpoint and flush — swallowing the
                        # retransmit without acking would pin the
                        # producer's credit forever. ONLY durable seqs:
                        # acking a deferred-ack duplicate would promise
                        # durability a crash can still revoke
                        atomic_touch(os.path.join(self.dir,
                                                  f"ack-{seq:06d}"))
                    continue
                chunk = self._load(path)
                if chunk is None:
                    continue
                _unlink_quiet(path)
                if not chunk.verify():
                    self.stats.corrupt += 1
                    continue
                self._delivered.add(seq)
                self.stats.delivered += 1
                return chunk
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(self.cfg.poll_s)

    def ack(self, seq: int) -> None:
        atomic_touch(os.path.join(self.dir, f"ack-{seq:06d}"))
        self._acked.add(int(seq))
        self.stats.acked += 1


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------

class ChunkTracker:
    """Delivery-set bookkeeping (expect / dedup-add / received /
    missing / complete) — the recovery-critical half every consumer
    needs regardless of what it does with the payloads. The streaming
    (chunked-prefill) consumer uses it bare: the session, not a dense
    array, holds the slide."""

    def __init__(self):
        self._have: set = set()
        self._expected: Optional[set] = None

    def expect(self, chunk_ids: Sequence[int]) -> None:
        self._expected = set(int(c) for c in chunk_ids)

    def seed_received(self, chunk_ids: Sequence[int]) -> None:
        """Mark chunks already held (a restarted consumer's checkpoint
        watermark) so their retransmits dedup instead of re-folding."""
        self._have.update(int(c) for c in chunk_ids)

    def add(self, chunk: EmbeddingChunk) -> bool:
        """Record one delivery; returns False for a chunk id already
        seen (belt under the channel's dedup suspenders)."""
        if chunk.chunk_id in self._have:
            return False
        self._have.add(chunk.chunk_id)
        return True

    @property
    def received(self) -> set:
        return set(self._have)

    def missing(self) -> List[int]:
        if self._expected is None:
            return []
        return sorted(self._expected - self._have)

    def complete(self) -> bool:
        return self._expected is not None and not self.missing()


class SlideAssembler(ChunkTracker):
    """Chunks -> the dense ``[n_tiles, D]`` tile-embedding sequence.

    Placement is by the chunk's tile range, so arrival order and the
    identity of the producing worker are irrelevant to the assembled
    bytes — the bit-parity half of the recovery contract."""

    def __init__(self, n_tiles: int, dim: int, *, coords_dim: int = 2):
        super().__init__()
        self.n_tiles = int(n_tiles)
        self.embeds = np.zeros((n_tiles, dim), np.float32)
        self.coords = np.zeros((n_tiles, coords_dim), np.float32)

    def add(self, chunk: EmbeddingChunk) -> bool:
        """Place one chunk (tracker dedup first)."""
        if not super().add(chunk):
            return False
        self.embeds[chunk.start:chunk.stop] = chunk.payload
        if chunk.coords is not None:
            self.coords[chunk.start:chunk.stop] = chunk.coords
        return True
