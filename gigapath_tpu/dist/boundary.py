"""The cross-stage embedding channel: bounded, credit-based, checksummed.

One slide's tile embeddings flow from the tile-encoder stage to the
slide-encoder stage as *chunks* — contiguous tile ranges cut by the
deterministic :func:`plan_chunks` plan. The channel gives that flow the
four properties a cross-host boundary needs and a monolithic pjit gets
for free:

- **bounded**: the producer holds at most ``capacity`` unacked chunks in
  flight (credit-based flow control). When credits hit zero the producer
  BLOCKS — and emits one schema'd ``backpressure`` event per blocking
  episode (queue depth, credits, capacity) so a consumer falling behind
  is visible on the obs bus, not an OOM an hour later;
- **checksummed**: every chunk carries a sha256 over its header and
  payload bytes; a corrupt arrival is counted and discarded (the
  producer-side retransmit timer heals it), never assembled;
- **acked**: the consumer acks each delivered seq; producer credits are
  acked-based, so unacked chunks are exactly the set a recovery has to
  requeue (:mod:`gigapath_tpu.dist.membership` re-assigns a lost
  worker's unacked range across survivors);
- **deduped**: sequence numbers are the chunk ids of the deterministic
  plan — stable across retransmits AND across re-assignment — so a
  duplicate (a ``dup_chunk`` injection, a retransmit racing its ack, a
  survivor re-producing a chunk the dead worker's last write also
  landed) is dropped by seq and the assembled slide is bit-identical to
  the clean run's.

Two transports, one protocol: :class:`MemoryChannel` (in-process,
``threading.Condition`` — the serving/inference prefetch path and the
unit tests) and the :class:`DirChannelProducer`/:class:`DirChannelConsumer`
pair (a shared directory with atomic tmp+rename writes — the two-process
dryrun harness; DCN/RPC transports slot in behind the same surface).
numpy + stdlib only; nothing here can touch a traced program, so the
channel can add no retraces (pinned by tests/test_dist.py).
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import threading
import time
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

from gigapath_tpu.obs.locktrace import make_condition

import numpy as np

from gigapath_tpu.obs.clock import (ClockSample, LinkClock, emit_clock_sync)
from gigapath_tpu.obs.metrics import get_metrics
from gigapath_tpu.obs.runlog import env_number


@dataclasses.dataclass(frozen=True)
class BoundaryConfig:
    """Channel knobs, snapshotted host-side at construction.

    ``from_env`` reads the ``GIGAPATH_DIST_*`` flags ONCE (the
    ``get_run_log`` discipline — never at trace time; README flag
    table)."""

    capacity: int = 8          # credits: max unacked chunks in flight
    chunk_tiles: int = 512     # tiles per chunk in the deterministic plan
    poll_s: float = 0.02       # producer block / consumer scan cadence
    retransmit_s: float = 2.0  # unacked-for-longer gets re-sent
    # TCP transport (dist/transport.py) only
    connect_timeout_s: float = 5.0  # per-connect AND per-frame deadline
    backoff_s: float = 2.0          # reconnect backoff cap (full jitter)

    @classmethod
    def from_env(cls, **overrides) -> "BoundaryConfig":
        fields = dict(
            capacity=int(env_number("GIGAPATH_DIST_CREDITS", cls.capacity)),
            chunk_tiles=int(env_number("GIGAPATH_DIST_CHUNK_TILES",
                                       cls.chunk_tiles)),
            poll_s=env_number("GIGAPATH_DIST_POLL_S", cls.poll_s),
            retransmit_s=env_number("GIGAPATH_DIST_RETRANSMIT_S",
                                    cls.retransmit_s),
            connect_timeout_s=env_number("GIGAPATH_DIST_CONNECT_TIMEOUT_S",
                                         cls.connect_timeout_s),
            backoff_s=env_number("GIGAPATH_DIST_BACKOFF_S", cls.backoff_s),
        )
        fields.update({k: v for k, v in overrides.items() if v is not None})
        if fields["capacity"] < 1:
            raise ValueError(f"capacity must be >= 1, got {fields['capacity']}")
        return cls(**fields)


# ---------------------------------------------------------------------------
# the deterministic chunk plan
# ---------------------------------------------------------------------------

def plan_chunks(n_tiles: int, chunk_tiles: int) -> List[Tuple[int, int, int]]:
    """``[(chunk_id, start, stop), ...]`` covering ``[0, n_tiles)`` in
    order. Chunk ids double as the channel's sequence numbers: they are
    a pure function of the slide geometry, so a survivor re-producing a
    lost worker's chunk emits the SAME seq the original would have —
    dedup and bit-parity both hang off this determinism."""
    if n_tiles < 1 or chunk_tiles < 1:
        raise ValueError(f"need n_tiles/chunk_tiles >= 1, got "
                         f"{n_tiles}/{chunk_tiles}")
    return [
        (cid, start, min(start + chunk_tiles, n_tiles))
        for cid, start in enumerate(range(0, n_tiles, chunk_tiles))
    ]


def assign_chunks(chunk_ids: Sequence[int],
                  workers: Sequence[str]) -> Dict[str, List[int]]:
    """Deterministic round-robin of chunk ids over SORTED worker ids —
    the one assignment function, used both for the initial shard and for
    re-assigning a lost worker's unacked chunks across survivors (same
    inputs -> same plan on every host, no coordination round needed)."""
    if not workers:
        raise ValueError("assign_chunks: no workers")
    ordered = sorted(workers)
    out: Dict[str, List[int]] = {w: [] for w in ordered}
    for i, cid in enumerate(sorted(chunk_ids)):
        out[ordered[i % len(ordered)]].append(cid)
    return out


# ---------------------------------------------------------------------------
# chunks
# ---------------------------------------------------------------------------

def chunk_checksum(slide_id: str, chunk_id: int, start: int, stop: int,
                   payload: np.ndarray,
                   coords: Optional[np.ndarray]) -> str:
    """sha256 over the header and the exact payload bytes. The header is
    inside the digest so a chunk whose payload survived but whose tile
    range was mangled still fails verification."""
    h = hashlib.sha256()
    h.update(f"{slide_id}|{chunk_id}|{start}|{stop}|".encode())
    h.update(np.ascontiguousarray(payload).tobytes())
    if coords is not None:
        h.update(np.ascontiguousarray(coords).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class EmbeddingChunk:
    """One contiguous tile range of one slide's embeddings in flight.

    ``seq == chunk_id`` (see :func:`plan_chunks`); ``producer`` is
    provenance for the report, never protocol state. ``trace_id`` /
    ``parent_span_id`` carry the fleet trace context across the boundary
    (:mod:`gigapath_tpu.obs.reqtrace`): the parent is the producer's
    structural ``send`` span id, computed at build time, so the
    consumer's ``deliver`` span joins the causal tree without any
    side-channel. Like ``producer``, they stay OUTSIDE the checksum —
    observability fields must never change the assembled bytes'
    verification."""

    slide_id: str
    chunk_id: int
    start: int
    stop: int
    payload: np.ndarray                    # [stop-start, D] float32
    coords: Optional[np.ndarray] = None    # [stop-start, 2] float32
    producer: str = ""
    checksum: str = ""
    trace_id: str = ""
    parent_span_id: str = ""

    @property
    def seq(self) -> int:
        return self.chunk_id

    @classmethod
    def build(cls, slide_id: str, chunk_id: int, start: int, stop: int,
              payload: np.ndarray, coords: Optional[np.ndarray] = None,
              producer: str = "", digest: bool = True,
              trace_id: str = "",
              parent_span_id: str = "") -> "EmbeddingChunk":
        """``digest=False`` skips the sha256 (checksum stays ``""``) —
        ONLY for intra-process channels, where the handoff is a memory
        reference that cannot corrupt and hashing hundreds of MB per
        slide would tax the hot path for nothing. Cross-process
        transports must digest: the directory consumer rejects an
        empty checksum outright."""
        payload = np.asarray(payload, np.float32)
        if coords is not None:
            coords = np.asarray(coords, np.float32)
        if payload.shape[0] != stop - start:
            raise ValueError(
                f"chunk {chunk_id}: payload rows {payload.shape[0]} != "
                f"tile range [{start}, {stop})"
            )
        return cls(
            slide_id=slide_id, chunk_id=int(chunk_id), start=int(start),
            stop=int(stop), payload=payload, coords=coords,
            producer=producer,
            checksum=chunk_checksum(slide_id, chunk_id, start, stop,
                                    payload, coords) if digest else "",
            trace_id=trace_id, parent_span_id=parent_span_id,
        )

    def verify(self) -> bool:
        return self.checksum == chunk_checksum(
            self.slide_id, self.chunk_id, self.start, self.stop,
            self.payload, self.coords,
        )


@dataclasses.dataclass
class ChannelStats:
    """Host-side protocol counters, rendered by ``obs_report.py``'s
    ``== dist ==`` section and asserted by the smoke/tests."""

    sent: int = 0
    delivered: int = 0
    acked: int = 0
    duplicates: int = 0      # arrivals dropped by seq dedup
    corrupt: int = 0         # arrivals failing checksum verification
    retransmits: int = 0     # unacked chunks re-sent after the timer
    dropped: int = 0         # sends swallowed by chaos injection
    backpressure_events: int = 0
    blocked_s: float = 0.0   # total producer wall spent credit-blocked
    # TCP transport only (dist/transport.py); zero on the other two
    reconnects: int = 0      # connections re-established after the first
    frame_errors: int = 0    # torn/corrupt/misframed wire frames dropped
    bytes_sent: int = 0      # frame bytes pushed onto the wire

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _emit_backpressure(runlog, *, channel: str, seq: int, queue_depth: int,
                       capacity: int) -> None:
    """One schema'd ``backpressure`` event per producer blocking episode
    (runlog optional — bare-channel users stay silent)."""
    if runlog is not None:
        runlog.event(  # gigarace: calls RunLog.event
            "backpressure", channel=channel, seq=seq, credits=0,
            queue_depth=queue_depth, capacity=capacity,
        )


class LinkTelemetry:
    """Per-(producer, consumer)-link labeled instruments
    (:mod:`gigapath_tpu.obs.metrics`): the channel-health view the fleet
    report's ``== fleet ==`` per-link table renders. One instance per
    producing half of a cross-process channel; the link label is
    ``{transport}.{producer}`` (the consumer side is the single fan-in
    point, so the producer id identifies the link).

    Instruments (all ``dist.link.{link}.*``):

    - ``credits_in_flight`` (gauge) — credits currently consumed;
    - ``unacked_depth``     (gauge) — sent-unacked chunks;
    - ``ack_lag_chunks``    (gauge) — chunks past the ack watermark
      (for this protocol: the unacked set's size);
    - ``ack_lag_s``         (gauge) — age of the OLDEST unacked chunk
      (how long the watermark has been stuck);
    - ``backpressure_s``    (counter) — producer wall spent credit-blocked;
    - ``retransmits``       (counter) — timer-driven re-sends;
    - ``bytes``             (counter) — payload/frame bytes pushed.

    Built on :func:`~gigapath_tpu.obs.metrics.get_metrics`, so with obs
    (or metrics) off every instrument is the shared null — zero
    overhead, no locks. The final ``metrics`` snapshot rides the
    registry's existing closer flush."""

    def __init__(self, runlog, link: str):
        registry = get_metrics(runlog)
        self.link = link
        prefix = f"dist.link.{link}"
        self.credits_in_flight = registry.gauge(f"{prefix}.credits_in_flight")
        self.unacked_depth = registry.gauge(f"{prefix}.unacked_depth")
        self.ack_lag_chunks = registry.gauge(f"{prefix}.ack_lag_chunks")
        self.ack_lag_s = registry.gauge(f"{prefix}.ack_lag_s")
        self.backpressure_s = registry.counter(f"{prefix}.backpressure_s")
        self.retransmits = registry.counter(f"{prefix}.retransmits")
        self.bytes = registry.counter(f"{prefix}.bytes")

    def on_send(self, nbytes: int) -> None:
        self.bytes.inc(max(int(nbytes), 0))

    def on_blocked(self, seconds: float) -> None:
        self.backpressure_s.inc(max(float(seconds), 0.0))

    def on_retransmit(self, n: int = 1) -> None:
        self.retransmits.inc(n)

    def set_depth(self, *, unacked: int, capacity: int,
                  oldest_sent_at: Optional[float],
                  now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.credits_in_flight.set(min(unacked, capacity))
        self.unacked_depth.set(unacked)
        self.ack_lag_chunks.set(unacked)
        self.ack_lag_s.set(0.0 if oldest_sent_at is None
                           else max(now - oldest_sent_at, 0.0))


def chunk_nbytes(chunk: EmbeddingChunk) -> int:
    """Payload bytes a send pushes across the link (the dir transport's
    byte accounting; the TCP transport counts real frame bytes)."""
    n = int(chunk.payload.nbytes)
    if chunk.coords is not None:
        n += int(chunk.coords.nbytes)
    return n


# ---------------------------------------------------------------------------
# in-process transport (threads)
# ---------------------------------------------------------------------------

class MemoryChannel:
    """Intra-process producer/consumer pair over one bounded buffer.

    The transport behind the inference prefetch wiring and the
    backpressure unit tests: ``send`` blocks while ``capacity`` chunks
    are unacked, ``recv`` dedups by seq, ``ack`` returns the credit.
    """

    def __init__(self, config: Optional[BoundaryConfig] = None, *,
                 runlog=None, name: str = "memory"):
        self.cfg = config or BoundaryConfig()
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self.stats = ChannelStats()
        self._cond = make_condition("gigapath_tpu.dist.boundary.MemoryChannel._cond")
        self._queue: List[EmbeddingChunk] = []
        self._unacked: Dict[int, EmbeddingChunk] = {}
        self._delivered: set = set()
        self._closed = False
        self._episode_seq: Optional[int] = None  # backpressure dedup

    # -- producer side ----------------------------------------------------
    def send(self, chunk: EmbeddingChunk,
             timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            blocked_at = None
            while len(self._unacked) >= self.cfg.capacity and not self._closed:
                if blocked_at is None:
                    blocked_at = time.monotonic()
                    if self._episode_seq != chunk.seq:
                        # one event per blocking EPISODE: a caller
                        # retrying a timed-out send of the same seq is
                        # the same episode, not a new one
                        self._episode_seq = chunk.seq
                        self.stats.backpressure_events += 1
                        _emit_backpressure(
                            self._runlog, channel=self.name, seq=chunk.seq,
                            queue_depth=len(self._unacked),
                            capacity=self.cfg.capacity,
                        )
                wait = self.cfg.poll_s
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        self.stats.blocked_s += time.monotonic() - blocked_at
                        raise TimeoutError(
                            f"{self.name}: no credit within {timeout}s "
                            f"(seq {chunk.seq})"
                        )
                self._cond.wait(timeout=wait)
            if blocked_at is not None:
                self.stats.blocked_s += time.monotonic() - blocked_at
            if self._closed:
                raise RuntimeError(f"{self.name}: channel closed")
            self._unacked[chunk.seq] = chunk
            self._queue.append(chunk)
            self.stats.sent += 1
            self._cond.notify_all()

    def unacked_seqs(self) -> List[int]:
        with self._cond:
            return sorted(self._unacked)

    # -- consumer side ----------------------------------------------------
    def recv(self, timeout: Optional[float] = None) -> Optional[EmbeddingChunk]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                while self._queue:
                    chunk = self._queue.pop(0)
                    if chunk.seq in self._delivered:
                        self.stats.duplicates += 1
                        continue
                    # digest-less chunks (build(digest=False)) are the
                    # sanctioned intra-process fast path: the handoff
                    # is a memory reference, there is nothing to verify
                    if chunk.checksum and not chunk.verify():
                        self.stats.corrupt += 1
                        continue
                    self._delivered.add(chunk.seq)
                    self.stats.delivered += 1
                    return chunk
                if self._closed:
                    return None
                wait = self.cfg.poll_s
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return None
                self._cond.wait(timeout=wait)

    def ack(self, seq: int) -> None:
        with self._cond:
            if self._unacked.pop(seq, None) is not None:
                self.stats.acked += 1
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# ---------------------------------------------------------------------------
# cross-process transport (shared directory)
# ---------------------------------------------------------------------------
#
# Layout under <root>/channel/:
#   chunk-<seq:06d>-<nonce>.npz   one send (atomic tmp+rename; the nonce
#                                 keeps retransmits/dups from colliding)
#   ack-<seq:06d>                 consumer ack marker (empty file)
#
# The producer's credit view is acked-based (a chunk file it wrote whose
# ack marker exists frees its credit); the consumer's dedup view is an
# in-memory seq set. Atomic renames mean a reader never sees a partial
# chunk; SIGKILL mid-write leaves only a tmp file nobody scans.

def _atomic_write_npz(path: str, **arrays) -> None:
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)


def atomic_touch(path: str) -> str:
    """Atomically materialize an empty marker file (ack markers, the
    pipeline's DONE flag): tmp + rename, so a scanner never races a
    half-created entry."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8"):
        pass
    os.replace(tmp, path)
    return path


class DirChannelProducer:
    """One tile worker's sending half of the directory channel."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 producer: str = "", runlog=None, chaos=None,
                 name: str = "dir"):
        self.cfg = config or BoundaryConfig()
        self.dir = os.path.join(root, "channel")
        os.makedirs(self.dir, exist_ok=True)
        self.producer = producer
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self._chaos = chaos
        self.stats = ChannelStats()
        self._sent_at: Dict[int, float] = {}      # seq -> last send time
        self._chunks: Dict[int, EmbeddingChunk] = {}  # unacked payloads
        self._nonce = 0
        self._episode_seq: Optional[int] = None   # backpressure dedup
        self.telemetry = LinkTelemetry(
            runlog, f"{name}.{producer or 'p'}")
        # clock alignment (obs/clock.py): one ping/pong file exchange per
        # producer lifetime — the dir transport is same-machine (shared
        # monotonic clock), so a single sample documents offset ~= 0 with
        # an honest poll-cadence uncertainty bound
        self.clock = LinkClock(f"{name}.{producer or 'p'}")
        self._clock_ping: Optional[Tuple[str, float]] = None
        self._send_clock_ping()

    # -- clock alignment --------------------------------------------------
    def _send_clock_ping(self) -> None:
        tag = self.producer or "p"
        path = os.path.join(self.dir, f"clock-ping-{tag}-{os.getpid()}.json")
        t_send = time.monotonic()
        try:
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"link": self.clock.link, "t_send": t_send}, fh)
            os.replace(tmp, path)
        except OSError:
            return  # clock sync is best-effort; the channel must not care
        self._clock_ping = (path, t_send)

    def _poll_clock(self) -> None:
        """Complete an outstanding ping if the consumer answered: fold
        the four-timestamp sample, emit one ``clock_sync`` event, clean
        both files up."""
        if self._clock_ping is None:
            return
        path, t_send = self._clock_ping
        pong = path.replace("clock-ping-", "clock-pong-")
        if not os.path.exists(pong):
            return
        t_ack = time.monotonic()
        try:
            with open(pong, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            sample = ClockSample(t_send=t_send, t_recv=float(doc["t_recv"]),
                                 t_reply=float(doc["t_reply"]), t_ack=t_ack)
        except (OSError, ValueError, KeyError):
            return  # torn pong: re-read next poll
        est = self.clock.update(sample)
        emit_clock_sync(self._runlog, self.clock, est)
        self._clock_ping = None
        _unlink_quiet(path)
        _unlink_quiet(pong)

    # -- protocol ---------------------------------------------------------
    def _ack_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"ack-{seq:06d}")

    def _refresh_acks(self) -> None:
        self._poll_clock()
        for seq in list(self._sent_at):
            if os.path.exists(self._ack_path(seq)):
                self._sent_at.pop(seq, None)
                self._chunks.pop(seq, None)
                self.stats.acked += 1
        self.telemetry.set_depth(
            unacked=len(self._sent_at), capacity=self.cfg.capacity,
            oldest_sent_at=min(self._sent_at.values())
            if self._sent_at else None,
        )

    def _write(self, chunk: EmbeddingChunk) -> None:
        self._nonce += 1
        path = os.path.join(
            self.dir,
            f"chunk-{chunk.seq:06d}-{self.producer or 'p'}-{self._nonce}.npz",
        )
        arrays = dict(
            slide_id=np.array(chunk.slide_id),
            chunk_id=np.array(chunk.chunk_id, np.int64),
            start=np.array(chunk.start, np.int64),
            stop=np.array(chunk.stop, np.int64),
            payload=chunk.payload,
            producer=np.array(chunk.producer or self.producer),
            checksum=np.array(chunk.checksum),
            trace_id=np.array(chunk.trace_id),
            parent_span_id=np.array(chunk.parent_span_id),
        )
        if chunk.coords is not None:
            arrays["coords"] = chunk.coords
        _atomic_write_npz(path, **arrays)

    def credits(self) -> int:
        self._refresh_acks()
        return max(self.cfg.capacity - len(self._sent_at), 0)

    def unacked_seqs(self) -> List[int]:
        self._refresh_acks()
        return sorted(self._sent_at)

    def send(self, chunk: EmbeddingChunk,
             timeout: Optional[float] = None) -> None:
        """Blocks (polling) while every credit is in flight; the chaos
        injectors hook here — a ``drop_chunk`` swallows THIS write but
        still registers the seq as sent-unacked (the retransmit timer
        heals it, exactly like a lost network write), a ``dup_chunk``
        writes twice (the consumer's dedup absorbs the twin)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        blocked_at = None
        while self.credits() <= 0:
            if blocked_at is None:
                blocked_at = time.monotonic()
                if self._episode_seq != chunk.seq:
                    # one event per blocking episode, even when the
                    # caller retries a timed-out send of the same seq
                    # (the worker's lease-renewing retry loop does)
                    self._episode_seq = chunk.seq
                    self.stats.backpressure_events += 1
                    _emit_backpressure(
                        self._runlog, channel=self.name, seq=chunk.seq,
                        queue_depth=len(self._sent_at),
                        capacity=self.cfg.capacity,
                    )
            if deadline is not None and time.monotonic() >= deadline:
                blocked = time.monotonic() - blocked_at
                self.stats.blocked_s += blocked
                self.telemetry.on_blocked(blocked)
                raise TimeoutError(
                    f"{self.name}: no credit within {timeout}s "
                    f"(seq {chunk.seq})"
                )
            time.sleep(self.cfg.poll_s)
        if blocked_at is not None:
            blocked = time.monotonic() - blocked_at
            self.stats.blocked_s += blocked
            self.telemetry.on_blocked(blocked)
        self._sent_at[chunk.seq] = time.monotonic()
        self._chunks[chunk.seq] = chunk
        self.stats.sent += 1
        if self._chaos is not None and self._chaos.drops_chunk(chunk.seq):
            self.stats.dropped += 1
            return
        self._write(chunk)
        self.telemetry.on_send(chunk_nbytes(chunk))
        if self._chaos is not None and self._chaos.dups_chunk(chunk.seq):
            self._write(chunk)
            self.telemetry.on_send(chunk_nbytes(chunk))

    def pump_retransmits(self, now: Optional[float] = None) -> int:
        """Re-send every chunk unacked for longer than ``retransmit_s``.
        Returns the number re-sent. Safe against duplicates: seqs dedup
        at the consumer."""
        now = time.monotonic() if now is None else now
        self._refresh_acks()
        n = 0
        for seq, sent_at in list(self._sent_at.items()):
            if now - sent_at >= self.cfg.retransmit_s:
                chunk = self._chunks.get(seq)
                if chunk is None:
                    continue
                self._write(chunk)
                self._sent_at[seq] = now
                self.stats.retransmits += 1
                self.telemetry.on_retransmit()
                self.telemetry.on_send(chunk_nbytes(chunk))
                n += 1
        return n


class DirChannelConsumer:
    """The slide stage's receiving half of the directory channel (one
    consumer drains every producer's chunks — the fan-in point)."""

    def __init__(self, root: str, config: Optional[BoundaryConfig] = None, *,
                 runlog=None, name: str = "dir",
                 delivered: Optional[Sequence[int]] = None):
        """``delivered``: seqs a RESTARTED consumer already holds (its
        checkpoint watermark) — seeded into the dedup set so retransmits
        of pre-crash chunks are absorbed, not re-assembled."""
        self.cfg = config or BoundaryConfig()
        self.dir = os.path.join(root, "channel")
        os.makedirs(self.dir, exist_ok=True)
        self.name = name
        self._runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self.stats = ChannelStats()
        self._delivered: set = set(
            int(s) for s in delivered) if delivered else set()
        # seqs this consumer considers DURABLE: the seeded watermark plus
        # every ack it issued itself. Only these may be re-acked on a
        # duplicate — a delivered-but-deferred-ack seq must NOT be (the
        # deferred-ack discipline: an ack is a durability promise)
        self._acked: set = set(self._delivered)

    def _load(self, path: str) -> Optional[EmbeddingChunk]:
        try:
            with np.load(path, allow_pickle=False) as z:
                coords = z["coords"] if "coords" in z.files else None
                return EmbeddingChunk(
                    slide_id=str(z["slide_id"]),
                    chunk_id=int(z["chunk_id"]), start=int(z["start"]),
                    stop=int(z["stop"]), payload=np.asarray(z["payload"]),
                    coords=None if coords is None else np.asarray(coords),
                    producer=str(z["producer"]),
                    checksum=str(z["checksum"]),
                    trace_id=str(z["trace_id"])
                    if "trace_id" in z.files else "",
                    parent_span_id=str(z["parent_span_id"])
                    if "parent_span_id" in z.files else "",
                )
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            # a torn archive can only be a racing writer's tmp that
            # slipped in; re-scan next poll, never delete blind
            return None

    def recv(self, timeout: Optional[float] = None) -> Optional[EmbeddingChunk]:
        """Next new, verified chunk (any producer), or None on timeout.
        Processed files are deleted; duplicate seqs and corrupt payloads
        are counted and dropped."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._answer_clock_pings()
            for path in sorted(glob.glob(os.path.join(self.dir, "chunk-*.npz"))):
                name = os.path.basename(path)
                try:
                    seq = int(name.split("-")[1])
                except (IndexError, ValueError):
                    continue
                if seq in self._delivered:
                    self.stats.duplicates += 1
                    _unlink_quiet(path)
                    if seq in self._acked:
                        # re-ack (idempotent marker): a RESTARTED
                        # consumer's seeded watermark may cover seqs
                        # whose deferred ack died with the predecessor
                        # between checkpoint and flush — swallowing the
                        # retransmit without acking would pin the
                        # producer's credit forever. ONLY durable seqs:
                        # acking a deferred-ack duplicate would promise
                        # durability a crash can still revoke
                        atomic_touch(os.path.join(self.dir,
                                                  f"ack-{seq:06d}"))
                    continue
                chunk = self._load(path)
                if chunk is None:
                    continue
                _unlink_quiet(path)
                if not chunk.verify():
                    self.stats.corrupt += 1
                    continue
                self._delivered.add(seq)
                self.stats.delivered += 1
                return chunk
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(self.cfg.poll_s)

    def _answer_clock_pings(self) -> None:
        """Answer outstanding clock pings (obs/clock.py's dir-transport
        half): stamp this process's monotonic clock into an atomic pong
        the pinging producer completes its sample from. Idempotent — an
        already-answered ping is skipped."""
        for path in glob.glob(os.path.join(self.dir, "clock-ping-*.json")):
            pong = path.replace("clock-ping-", "clock-pong-")
            if os.path.exists(pong):
                continue
            now = time.monotonic()
            try:
                tmp = f"{pong}.tmp-{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"t_recv": now, "t_reply": now}, fh)
                os.replace(tmp, pong)
            except OSError:
                continue  # best-effort: the producer just re-polls

    def ack(self, seq: int) -> None:
        atomic_touch(os.path.join(self.dir, f"ack-{seq:06d}"))
        self._acked.add(int(seq))
        self.stats.acked += 1


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------

class ChunkTracker:
    """Delivery-set bookkeeping (expect / dedup-add / received /
    missing / complete) — the recovery-critical half every consumer
    needs regardless of what it does with the payloads. The streaming
    (chunked-prefill) consumer uses it bare: the session, not a dense
    array, holds the slide."""

    def __init__(self):
        self._have: set = set()
        self._expected: Optional[set] = None

    def expect(self, chunk_ids: Sequence[int]) -> None:
        self._expected = set(int(c) for c in chunk_ids)

    def seed_received(self, chunk_ids: Sequence[int]) -> None:
        """Mark chunks already held (a restarted consumer's checkpoint
        watermark) so their retransmits dedup instead of re-folding."""
        self._have.update(int(c) for c in chunk_ids)

    def add(self, chunk: EmbeddingChunk) -> bool:
        """Record one delivery; returns False for a chunk id already
        seen (belt under the channel's dedup suspenders)."""
        if chunk.chunk_id in self._have:
            return False
        self._have.add(chunk.chunk_id)
        return True

    @property
    def received(self) -> set:
        return set(self._have)

    def missing(self) -> List[int]:
        if self._expected is None:
            return []
        return sorted(self._expected - self._have)

    def complete(self) -> bool:
        return self._expected is not None and not self.missing()


class SlideAssembler(ChunkTracker):
    """Chunks -> the dense ``[n_tiles, D]`` tile-embedding sequence.

    Placement is by the chunk's tile range, so arrival order and the
    identity of the producing worker are irrelevant to the assembled
    bytes — the bit-parity half of the recovery contract."""

    def __init__(self, n_tiles: int, dim: int, *, coords_dim: int = 2):
        super().__init__()
        self.n_tiles = int(n_tiles)
        self.embeds = np.zeros((n_tiles, dim), np.float32)
        self.coords = np.zeros((n_tiles, coords_dim), np.float32)

    def add(self, chunk: EmbeddingChunk) -> bool:
        """Place one chunk (tracker dedup first)."""
        if not super().add(chunk):
            return False
        self.embeds[chunk.start:chunk.stop] = chunk.payload
        if chunk.coords is not None:
            self.coords[chunk.start:chunk.stop] = chunk.coords
        return True
