"""Request queue with same-bucket coalescing (continuous batching).

The serving loop's scheduling policy lives here, decoupled from both the
transport (threads submit, one worker drains) and the executor (the AOT
cache). Requests land in per-bucket FIFO lanes; a batch dispatches as
soon as either

- its bucket has ``max_batch`` pending slides (a FULL batch — the
  throughput case), or
- the bucket's OLDEST request has waited ``max_wait_s`` (the latency
  case: a lone odd-sized slide must not wait for company that never
  comes).

``pop_ready`` is pull-based and takes an explicit ``now`` so the policy
is deterministic under test (no hidden clock reads in assertions);
callers in production pass nothing and get the monotonic clock. The
queue never touches jax — it moves numpy references and futures.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from gigapath_tpu.obs.locktrace import make_condition

import numpy as np


class SlideRequest:
    """One slide awaiting a forward pass."""

    __slots__ = ("slide_id", "feats", "coords", "n_tiles", "bucket_n",
                 "cache_key", "future", "t_submit", "t_dispatch", "trace")

    def __init__(self, slide_id: str, feats: np.ndarray,
                 coords: Optional[np.ndarray], bucket_n: int,
                 cache_key: Optional[str] = None,
                 t_submit: Optional[float] = None):
        self.slide_id = slide_id
        self.feats = feats
        self.coords = coords
        self.n_tiles = int(np.asarray(feats).shape[0])
        self.bucket_n = int(bucket_n)
        self.cache_key = cache_key
        self.future: Future = Future()
        self.t_submit = time.monotonic() if t_submit is None else t_submit
        self.t_dispatch: Optional[float] = None
        # end-to-end request trace (obs/reqtrace.py), attached by the
        # service at enqueue; None for bare-queue users and when obs is
        # off (the trace rides the request through the worker handoff)
        self.trace = None

    def wait_s(self, now: Optional[float] = None) -> float:
        end = self.t_dispatch if self.t_dispatch is not None else (
            time.monotonic() if now is None else now
        )
        return max(end - self.t_submit, 0.0)


class RequestQueue:
    """Per-bucket FIFO lanes + the fill-or-deadline dispatch policy."""

    def __init__(self, max_batch: int = 8, max_wait_s: float = 0.05,
                 capacity_for: Optional[Callable[[int], int]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        # per-bucket batch capacity (<= max_batch); the service passes
        # its token-budget clamp so big buckets fill (and dispatch) at
        # smaller batch sizes than small ones
        self._capacity_for = capacity_for
        self._lanes: Dict[int, List[SlideRequest]] = {}
        # incremental padded-token depth: the load-shed check runs on
        # EVERY submit precisely when the queue is deepest, so summing
        # the lanes there would make overloaded submits O(queue depth)
        self._pending_tokens = 0
        self._cond = make_condition("gigapath_tpu.serve.queue.RequestQueue._cond")

    def capacity(self, bucket_n: int) -> int:
        if self._capacity_for is None:
            return self.max_batch
        return max(1, min(self.max_batch, int(self._capacity_for(bucket_n))))

    # -- producer side ----------------------------------------------------
    def submit(self, req: SlideRequest) -> None:
        with self._cond:
            self._lanes.setdefault(req.bucket_n, []).append(req)
            self._pending_tokens += req.bucket_n
            self._cond.notify_all()

    # -- consumer side ----------------------------------------------------
    def pending(self) -> int:
        with self._cond:
            return sum(len(lane) for lane in self._lanes.values())

    def pending_from_signal(self) -> Optional[int]:
        """Pending count for the SIGTERM drain callback: the signal may
        have interrupted a thread INSIDE a ``with self._cond:`` region,
        so a blocking acquire here self-deadlocks the shutdown —
        try-acquire and report None on contention (GL020 discipline)."""
        if not self._cond.acquire(timeout=0.2):
            return None
        try:
            return sum(len(lane) for lane in self._lanes.values())
        finally:
            self._cond.release()

    def pending_tokens(self) -> int:
        """Total PADDED tiles queued (each request costs its bucket's
        rung, not its raw tile count — padded tiles are what the device
        will actually materialize). The load-shedding budget
        (``serve/health.py``) is denominated in these. O(1): kept
        incrementally by ``submit``/``pop_ready``."""
        with self._cond:
            return self._pending_tokens

    def _oldest_head_locked(self) -> Optional[SlideRequest]:
        heads = [lane[0] for lane in self._lanes.values() if lane]
        return min(heads, key=lambda r: r.t_submit) if heads else None

    def next_deadline_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the oldest pending request's deadline expires
        (<= 0 means a batch is already dispatchable on the deadline
        rule); None when the queue is idle."""
        now = time.monotonic() if now is None else now
        with self._cond:
            head = self._oldest_head_locked()
        if head is None:
            return None
        return (head.t_submit + self.max_wait_s) - now

    def pop_ready(self, now: Optional[float] = None,
                  drain: bool = False) -> List[SlideRequest]:
        """One dispatchable same-bucket batch (possibly empty).

        Priority: the bucket holding the overall-oldest request once its
        deadline has PASSED (an expired head must never be starved by
        full lanes elsewhere — sustained hot-bucket traffic would defer
        it forever, and the displaced full lane dispatches on the very
        next poll), else a FULL bucket (the one whose head has waited
        longest among the full ones), else — only under ``drain`` —
        whatever bucket holds the oldest head.
        """
        now = time.monotonic() if now is None else now
        with self._cond:
            pick: Optional[SlideRequest] = None
            head = self._oldest_head_locked()
            if head is not None and (
                drain or now - head.t_submit >= self.max_wait_s
            ):
                pick = head
            else:
                full = [
                    lane[0] for lane in self._lanes.values()
                    if len(lane) >= self.capacity(lane[0].bucket_n)
                ]
                if full:
                    pick = min(full, key=lambda r: r.t_submit)
            if pick is None:
                return []
            cap = self.capacity(pick.bucket_n)
            lane = self._lanes[pick.bucket_n]
            batch, rest = lane[:cap], lane[cap:]
            if rest:
                self._lanes[pick.bucket_n] = rest
            else:
                del self._lanes[pick.bucket_n]
            self._pending_tokens -= pick.bucket_n * len(batch)
        for req in batch:
            req.t_dispatch = now
        return batch

    def wait_for_work(self, timeout: Optional[float] = None,
                      now: Optional[float] = None) -> None:
        """Block until work might be dispatchable — the worker's parking
        spot between polls. Returns immediately only when a batch is
        ready NOW (a full lane, or an expired deadline); a pending but
        not-yet-dispatchable request parks like an empty queue, waiting
        for a new submit or the caller's deadline-bounded timeout
        (returning early on it would busy-spin the worker for the whole
        ``max_wait_s`` window). Spurious wakeups are fine, the worker
        re-polls ``pop_ready``."""
        now = time.monotonic() if now is None else now
        with self._cond:
            for lane in self._lanes.values():
                if lane and len(lane) >= self.capacity(lane[0].bucket_n):
                    return
            head = self._oldest_head_locked()
            if head is not None and now - head.t_submit >= self.max_wait_s:
                return
            self._cond.wait(timeout=timeout)
