"""Geometric shape-bucket ladder for slide serving.

Slides are ragged — 10^5..10^6 tiles at production scale (PAPER.md §0),
anything from a biopsy fragment to a full resection in practice — and a
jitted forward compiles once per distinct shape. Serving therefore maps
every tile count onto a SMALL FIXED SET of padded ``[B, N_bucket, D]``
shapes: a geometric ladder (each rung ``growth``× the previous, aligned
to the TPU-friendly 128 multiple the slide encoder already pads to
internally) bounds the executable count at O(log N_max) while capping
padding waste at ``growth``× worst case. The key-padding mask rides next
to the padded arrays, and the slide encoder's exact suffix-pad masking
(tests/test_pad_masking.py) makes the padded forward bit-for-bit
trustworthy: bucketed logits match exact-shape logits at 1e-5
(tests/test_serve.py's parity suite).

The batch dimension is bucketed too: :func:`assemble_batch` always pads
a coalesced batch to the queue's fixed capacity with fully-masked dummy
rows, so a partially-filled dispatch reuses the full-batch executable
instead of compiling a second one per occupancy level. Rows are
independent in the slide encoder (attention never crosses the batch
axis), so dummy rows cannot perturb real rows; their outputs are
discarded at scatter time.

Host-side numpy only — nothing here is jit-reachable.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class BucketLadder:
    """Geometric ladder of padded tile counts.

    ``rungs[0] = align_up(n_min)``; each later rung is the previous rung
    times ``growth``, aligned up to ``align``, strictly increasing, until
    ``n_max`` is covered. ``bucket_for(n)`` returns the smallest rung
    >= n (so a slide whose tile count lands exactly ON a rung pays zero
    padding).
    """

    def __init__(self, n_min: int = 1024, growth: float = 2.0,
                 n_max: int = 1 << 20, align: int = 128):
        if n_min < 1:
            raise ValueError(f"n_min must be >= 1, got {n_min}")
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        if n_max < n_min:
            raise ValueError(f"n_max {n_max} < n_min {n_min}")
        self.align = int(align)
        rungs: List[int] = []
        rung = self._align_up(n_min)
        while True:
            rungs.append(rung)
            if rung >= n_max:
                break
            nxt = self._align_up(int(np.ceil(rung * growth)))
            rung = max(nxt, rung + self.align)  # strictly increasing
        self._rungs: Tuple[int, ...] = tuple(rungs)

    def _align_up(self, n: int) -> int:
        return -(-int(n) // self.align) * self.align

    @property
    def rungs(self) -> Tuple[int, ...]:
        return self._rungs

    def __len__(self) -> int:
        return len(self._rungs)

    def bucket_for(self, n_tiles: int) -> int:
        """Smallest rung >= ``n_tiles``."""
        if n_tiles < 1:
            raise ValueError(f"n_tiles must be >= 1, got {n_tiles}")
        for rung in self._rungs:
            if rung >= n_tiles:
                return rung
        raise ValueError(
            f"slide of {n_tiles} tiles exceeds the ladder's top rung "
            f"{self._rungs[-1]} (raise n_max, or serve it on the "
            "exact-shape fallback path)"
        )


def pad_slide(feats: np.ndarray, coords: Optional[np.ndarray],
              bucket_n: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad one slide ``([N, D], [N, 2] or None)`` to
    ``([bucket_n, D], [bucket_n, 2], mask [bucket_n])``.

    Mask convention: True = VALID tile (the collate convention,
    data/collate.py — the slide encoder inverts it internally). Pad rows
    are zeros; coords default to zeros when the feature file carries
    none (positional signal collapses to one grid cell — the caller
    warns, as inference.py always has).
    """
    feats = np.asarray(feats)
    if feats.ndim != 2:
        raise ValueError(f"feats must be [N, D], got shape {feats.shape}")
    n, d = feats.shape
    if n > bucket_n:
        raise ValueError(f"slide of {n} tiles does not fit bucket {bucket_n}")
    out = np.zeros((bucket_n, d), feats.dtype)
    out[:n] = feats
    c = np.zeros((bucket_n, 2), np.float32)
    if coords is not None:
        c[:n] = np.asarray(coords, np.float32)
    mask = np.zeros((bucket_n,), bool)
    mask[:n] = True
    return out, c, mask


def assemble_batch(
    slides: Sequence[Tuple[np.ndarray, Optional[np.ndarray]]],
    bucket_n: int,
    capacity: int,
    feature_dim: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stack ``slides`` (each ``(feats [N_i, D], coords or None)``) into
    one fixed-shape batch ``(embeds [capacity, bucket_n, D],
    coords [capacity, bucket_n, 2], mask [capacity, bucket_n])``.

    Rows beyond ``len(slides)`` are dummy rows: all-zero, all-masked
    (only their always-valid cls token attends, to itself) — present so
    every dispatch of this bucket shares ONE executable shape regardless
    of how full the batch is.
    """
    if not slides and feature_dim is None:
        raise ValueError("empty batch needs an explicit feature_dim")
    if len(slides) > capacity:
        raise ValueError(f"{len(slides)} slides exceed capacity {capacity}")
    d = feature_dim if feature_dim is not None else np.asarray(slides[0][0]).shape[1]
    embeds = np.zeros((capacity, bucket_n, d), np.float32)
    coords = np.zeros((capacity, bucket_n, 2), np.float32)
    mask = np.zeros((capacity, bucket_n), bool)
    for i, (f, c) in enumerate(slides):
        embeds[i], coords[i], mask[i] = pad_slide(f, c, bucket_n)
    return embeds, coords, mask
