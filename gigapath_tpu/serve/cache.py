"""Content-hash embedding cache: re-queried slides never recompute.

Downstream tasks re-query the same slides constantly (every probe,
finetune eval, report regeneration hits the same cohort), and a slide's
embedding is a pure function of its tile features + coords + model
identity. The cache key is therefore a sha256 over the exact feature
bytes — not the slide id, which is a filename convention two pipelines
can disagree on; renaming a file must not fake a miss, and two different
slides sharing an id must not collide.

Byte-budgeted LRU: entries are numpy pytrees (logits, embeddings);
eviction is size-aware (a 1M-tile slide's layer stack and a biopsy's
logits are not the same weight). Thread-safe — submitters probe it
concurrently from request threads while the dispatch worker fills it.
Host memory only; no jax anywhere.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional

from gigapath_tpu.obs.locktrace import make_lock

import numpy as np


def content_key(feats: np.ndarray, coords: Optional[np.ndarray] = None,
                extra: str = "") -> str:
    """sha256 over the slide's exact content: feature bytes, coord
    bytes, shapes/dtypes, plus ``extra`` (the model identity — same
    features through two checkpoints are two cache lines)."""
    h = hashlib.sha256()
    for arr in (feats, coords):
        if arr is None:
            h.update(b"none|")
            continue
        arr = np.ascontiguousarray(arr)
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
        h.update(b"|")
    h.update(extra.encode())
    return h.hexdigest()


def _nbytes(value: Any) -> int:
    if isinstance(value, dict):
        return sum(_nbytes(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_nbytes(v) for v in value)
    return int(getattr(value, "nbytes", 64))  # scalars: bookkeeping floor


class EmbeddingCache:
    """Byte-budgeted, thread-safe LRU over content keys."""

    def __init__(self, budget_bytes: int = 256 << 20):
        self.budget_bytes = int(budget_bytes)
        self._entries: "OrderedDict[str, Any]" = OrderedDict()
        self._sizes: Dict[str, int] = {}
        self._lock = make_lock("gigapath_tpu.serve.cache.EmbeddingCache._lock")
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return self._entries[key]
            self.misses += 1
            return None

    def put(self, key: str, value: Any) -> bool:
        """Insert (refreshing recency on re-insert). Returns False when
        the value alone exceeds the whole budget — such a value is
        served but never cached (caching it would evict everything for
        one line that LRU would drop first anyway)."""
        size = _nbytes(value)
        if size > self.budget_bytes:
            return False
        with self._lock:
            if key in self._entries:
                self.bytes -= self._sizes[key]
                del self._entries[key]
            while self._entries and self.bytes + size > self.budget_bytes:
                old_key, _ = self._entries.popitem(last=False)
                self.bytes -= self._sizes.pop(old_key)
                self.evictions += 1
            self._entries[key] = value
            self._sizes[key] = size
            self.bytes += size
        return True

    def stats(self) -> Dict[str, float]:
        with self._lock:
            requests = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": (self.hits / requests) if requests else 0.0,
            }
