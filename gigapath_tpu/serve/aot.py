"""Per-bucket AOT executable cache with persisted compiled artifacts.

The serving invariant this module owns: **an executable per bucket,
compiled at most once per process, and ideally zero times** — a warm
restart loads the persisted artifact instead of retracing (ROADMAP item
1's cold-start acceptance). Three tiers, checked in order:

1. **in-memory**: the executable already built this process;
2. **artifact**: a persisted ``jax.experimental.serialize_executable``
   payload under ``artifact_dir``, keyed by an environment fingerprint
   (jax version, backend, input signature, caller identity, the
   kernel-tier ``PipelineFlags`` snapshot — quant tier included — AND
   the ACTIVE plan-registry state — the verified entries digest plus
   the bucket's resolved plan, ``gigapath_tpu/plan/``) so a stale
   artifact from another jax build, model shape, kernel tier or
   registry state can never be executed — any mismatch or load failure
   falls through to a fresh compile, and any plan-registry edit
   re-fingerprints every bucket (the compiled forward bakes in plans
   for every geometry key its trace resolved, which no bucket-level
   check can enumerate — over-invalidation is a recompile, staleness
   would be wrong dispatch);
3. **compile**: ``jit(forward, donate_argnums=(1, 2)).lower(...).compile()``
   over ``jax.ShapeDtypeStruct`` inputs (no dummy arrays are ever
   materialized), then persisted best-effort for the next process.

Params ride as a runtime argument (only their shapes are baked in), so
one artifact serves every checkpoint of the same architecture. The
per-request buffers — embeds and coords — are MARKED donated; params
and the key-padding mask are not (params are reused every call, the
mask is noise-sized). Donation only materializes when an output can
alias the ``[B, N, D]`` input (embedding-shaped outputs); for a
logits-shaped forward XLA finds no aliasable output and ignores it,
logging one harmless "donated buffers were not usable" warning per
bucket compile — expected, not a defect.

Observability: compiles are filed with the serving
:class:`~gigapath_tpu.obs.watchdog.CompileWatchdog` through its
``is_new``/``record`` surface, with this cache's :meth:`_cache_size`
standing in for the jit cache (AOT compiles never touch the jit call
cache, so the watchdog's usual probe would be blind here) — cache
growth on an already-seen bucket is flagged as an unexpected retrace
exactly like a jit-cache retrace would be. The perf ledger adopts the
already-compiled executable (``adopt_compiled``: cost/memory analysis
off the existing artifact, fingerprint from one extra trace, ZERO extra
XLA compiles — pinned by tests/test_serve.py's XLA-layer compile
counts).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Tuple

ARTIFACT_SCHEMA_VERSION = 1


def _param_signature(params: Any) -> str:
    """Stable signature over a param pytree's leaf shapes/dtypes — the
    facts an executable bakes in (values ride at call time)."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    h = hashlib.sha256()
    h.update(str(len(leaves)).encode())
    for leaf in leaves:
        h.update(str(getattr(leaf, "shape", ())).encode())
        h.update(str(getattr(leaf, "dtype", "")).encode())
    return h.hexdigest()[:16]


class AotExecutableCache:
    """Bucketed AOT executables for ``forward(params, embeds, coords,
    pad_mask)`` (embeds ``[B, N, D]`` f32, coords ``[B, N, 2]`` f32,
    mask ``[B, N]`` bool, True = valid)."""

    def __init__(self, forward: Callable, params: Any, *,
                 feature_dim: int, artifact_dir: Optional[str] = None,
                 identity: str = "", name: str = "serve.forward",
                 runlog=None, watchdog=None, ledger=None,
                 donate: bool = True):
        import jax

        from gigapath_tpu.obs.runlog import NullRunLog

        self.name = name
        self.params = params
        self.feature_dim = int(feature_dim)
        self.artifact_dir = artifact_dir
        self.identity = identity
        self.runlog = runlog if runlog is not None else NullRunLog()
        self.watchdog = watchdog
        self.ledger = ledger
        self._forward = forward
        self._jit = jax.jit(
            forward, donate_argnums=(1, 2) if donate else ()
        )
        self._param_sig = _param_signature(params)
        # the FULL kernel-tier flag snapshot participates in the
        # artifact identity: a forward built under one tier (quant,
        # ring, stream fusion, ...) must never be satisfied by a
        # persisted executable of another. The code signature usually
        # catches this too, but an untraceable forward degrades to
        # shapes-only — the flag fingerprint is the belt under that
        # suspender, and a NamedTuple repr covers every current and
        # future field without hand-picking. One host-side snapshot at
        # construction, the PipelineFlags convention.
        from gigapath_tpu.ops.pallas_dilated import snapshot_flags

        self._flags_sig = repr(snapshot_flags())
        self._code_sig: Optional[str] = None  # lazy; see _code_signature
        self._executables: Dict[Tuple[int, int], Callable] = {}
        # provenance per key: "compiled" | "artifact"
        self.sources: Dict[Tuple[int, int], str] = {}
        self.compile_seconds: Dict[Tuple[int, int], float] = {}
        if self.watchdog is not None:
            # the watchdog's cache-size probe points HERE: AOT compiles
            # bypass the jit call cache, so compiled-executable count is
            # the honest retrace signal for the serving path
            self.watchdog.attach(self)

    # -- watchdog cache-size surface (mirrors jitted fn._cache_size) ------
    def _cache_size(self) -> int:
        return sum(1 for s in self.sources.values() if s == "compiled")

    @property
    def compiled_count(self) -> int:
        return self._cache_size()

    @property
    def loaded_count(self) -> int:
        return sum(1 for s in self.sources.values() if s == "artifact")

    # -- shapes -----------------------------------------------------------
    def _abstract_inputs(self, capacity: int, bucket_n: int):
        import jax
        import jax.numpy as jnp

        sds = jax.ShapeDtypeStruct
        return (
            sds((capacity, bucket_n, self.feature_dim), jnp.float32),
            sds((capacity, bucket_n, 2), jnp.float32),
            sds((capacity, bucket_n), jnp.bool_),
        )

    # -- artifact persistence ---------------------------------------------
    def _code_signature(self) -> str:
        """Identity for the forward's CODE, not just its shapes: the
        jaxpr at one canonical shape ``[1, 128, D]`` (128 = the
        encoder's pad quantum; the shape is fixed so every process of
        the same code computes the same signature regardless of which
        bucket it serves first). A model-code fix that keeps the arch
        name and param shapes — e.g. a masking correction — changes the
        jaxpr and therefore invalidates persisted artifacts, where a
        shapes-only fingerprint would silently serve pre-fix outputs on
        every warm restart. One abstract trace per process, ZERO XLA
        compiles (the compile-count pins stay intact); an untraceable
        forward degrades to the shapes-only fingerprint with a warning."""
        if self._code_sig is None:
            import jax

            try:
                jaxpr = jax.make_jaxpr(self._forward)(
                    self.params, *self._abstract_inputs(1, 128)
                )
                self._code_sig = hashlib.sha256(
                    str(jaxpr).encode()
                ).hexdigest()[:16]
            except Exception as e:
                self.runlog.echo(
                    f"[serve] forward not abstractly traceable at the "
                    f"canonical shape ({type(e).__name__}: {e}); artifact "
                    "fingerprints fall back to shapes-only (stale CODE "
                    "will not be detected)"
                )
                self._code_sig = "no-code-sig"
        return self._code_sig

    def _plan_signature(self, capacity: int, bucket_n: int) -> str:
        """The ACTIVE execution-plan state, as it stands right now: the
        verified registry's entries digest combined with this bucket's
        own resolved plan (:func:`gigapath_tpu.plan.resolve_plan`, which
        re-stats the registry file, so an edit is seen immediately).
        The WHOLE registry digest — not just this bucket's key — because
        the compiled forward resolves plans for every geometry key its
        trace encounters (the model's inner ``dilated_attention`` calls
        resolve their own q/k/v-shaped keys, which no bucket-level
        caller can enumerate). Folding this into the fingerprint means a
        registry edit can never load a stale-plan executable: every
        artifact of the old registry state stops matching and the bucket
        recompiles under the new one — over-invalidation costs a
        recompile, staleness would cost wrong dispatch. Resolution
        failure degrades to a constant (shapes/flags still protect the
        artifact)."""
        try:
            from gigapath_tpu.plan import plan_registry_signature, resolve_plan

            resolved = resolve_plan(
                self.name, self._abstract_inputs(capacity, bucket_n)
            )
            return f"{plan_registry_signature()}|{resolved!r}"
        except Exception as e:
            self.runlog.echo(
                f"[serve] plan resolution failed for bucket "
                f"{capacity}x{bucket_n} ({type(e).__name__}: {e}); "
                "artifact identity falls back to the flag snapshot"
            )
            return "no-plan-sig"

    def _fingerprint(self, capacity: int, bucket_n: int) -> str:
        import jax

        h = hashlib.sha256()
        for part in (
            str(ARTIFACT_SCHEMA_VERSION), jax.__version__,
            jax.default_backend(), self.identity, self._param_sig,
            self._code_signature(), self._flags_sig,
            self._plan_signature(capacity, bucket_n),
            f"{capacity}x{bucket_n}x{self.feature_dim}",
        ):
            h.update(part.encode())
            h.update(b"|")
        return h.hexdigest()[:16]

    def artifact_path(self, capacity: int, bucket_n: int) -> Optional[str]:
        if not self.artifact_dir:
            return None
        return os.path.join(
            self.artifact_dir,
            f"{self.name}-{capacity}x{bucket_n}"
            f"-{self._fingerprint(capacity, bucket_n)}.aot",
        )

    def _try_load(self, path: Optional[str], capacity: int,
                  bucket_n: int) -> Optional[Callable]:
        """Deserialize a persisted executable; None on ANY mismatch or
        failure (a stale artifact must fall through to a compile, never
        crash or mis-execute)."""
        if path is None or not os.path.exists(path):
            return None
        import jax
        from jax.experimental import serialize_executable

        try:
            with open(path, "rb") as fh:
                doc = pickle.load(fh)
            meta = doc["meta"]
            if (
                meta["v"] != ARTIFACT_SCHEMA_VERSION
                or meta["jax_version"] != jax.__version__
                or meta["backend"] != jax.default_backend()
                or meta["fingerprint"] != self._fingerprint(capacity, bucket_n)
            ):
                return None
            return serialize_executable.deserialize_and_load(
                doc["payload"], doc["in_tree"], doc["out_tree"]
            )
        except Exception as e:
            self.runlog.echo(
                f"[serve] artifact load failed for bucket "
                f"{capacity}x{bucket_n} ({type(e).__name__}: {e}); "
                "recompiling"
            )
            return None

    def _persist(self, path: Optional[str], compiled, capacity: int,
                 bucket_n: int) -> None:
        """Best-effort: serving must not depend on a writable disk."""
        if path is None:
            return
        import jax
        from jax.experimental import serialize_executable

        try:
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            doc = {
                "meta": {
                    "v": ARTIFACT_SCHEMA_VERSION,
                    "jax_version": jax.__version__,
                    "backend": jax.default_backend(),
                    "fingerprint": self._fingerprint(capacity, bucket_n),
                    "name": self.name,
                    "shape": [capacity, bucket_n, self.feature_dim],
                },
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(doc, fh)
            os.replace(tmp, path)  # atomic: a killed write leaves no torn artifact
        except Exception as e:
            self.runlog.echo(
                f"[serve] artifact persist failed for bucket "
                f"{capacity}x{bucket_n} ({type(e).__name__}: {e}); "
                "serving continues uncached"
            )

    # -- the three-tier lookup --------------------------------------------
    def executable(self, capacity: int, bucket_n: int) -> Callable:
        """The executable for ``[capacity, bucket_n, feature_dim]``
        batches: in-memory, else artifact load, else compile+persist."""
        key = (int(capacity), int(bucket_n))
        exe = self._executables.get(key)
        if exe is not None:
            return exe

        path = self.artifact_path(*key)
        loaded = self._try_load(path, *key)
        if loaded is not None:
            self._executables[key] = loaded
            self.sources[key] = "artifact"
            if self.watchdog is not None:
                self.watchdog.mark_preloaded(key)
            self.runlog.echo(
                f"[serve] bucket {key[0]}x{key[1]}: loaded persisted "
                f"executable ({os.path.basename(path)}) — no compile"
            )
            return loaded

        import jax

        abstract = self._abstract_inputs(*key)
        t0 = time.time()
        compiled = self._jit.lower(self.params, *abstract).compile()
        seconds = time.time() - t0
        self._executables[key] = compiled
        self.sources[key] = "compiled"
        self.compile_seconds[key] = seconds
        if self.watchdog is not None:
            # files the compile event; cache growth on a seen key would
            # be flagged as an unexpected retrace
            self.watchdog.record(key, seconds)
        if self.ledger is not None:
            self.ledger.adopt_compiled(
                self.name, key, compiled, self._forward,
                self.params, *abstract,
            )
        self._persist(path, compiled, *key)
        return compiled

    def __call__(self, embeds, coords, mask):
        """Dispatch one assembled batch; shapes pick the executable."""
        key = (int(embeds.shape[0]), int(embeds.shape[1]))
        known = key in self._executables
        exe = self.executable(*key)
        if self.watchdog is not None and known:
            # steady dispatch on an already-materialized executable;
            # first sights were filed by executable() (compile) or
            # mark_preloaded (artifact load)
            self.watchdog.record(key, None)
        return exe(self.params, embeds, coords, mask)
