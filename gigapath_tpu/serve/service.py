"""The slide-embedding service: queue -> bucket -> AOT executable ->
content-hash cache, wired through the obs bus.

``SlideService`` is the orchestration layer ROADMAP item 1 asked for:
requests (slide feature arrays) arrive from any thread via
:meth:`submit` and resolve as futures; a single dispatch worker
coalesces them into same-bucket batches (:mod:`gigapath_tpu.serve.queue`),
pads them onto the bucket ladder (:mod:`gigapath_tpu.serve.buckets`),
runs the per-bucket AOT executable (:mod:`gigapath_tpu.serve.aot` —
compiled once per bucket, loaded from a persisted artifact on warm
restarts), and banks every result in the content-hash cache
(:mod:`gigapath_tpu.serve.cache`) so re-queried slides short-circuit the
encoder entirely. Identical slides in flight coalesce onto ONE pending
forward (the second submitter gets the same future), so a thundering
herd on a hot slide costs one dispatch.

Observability rides the existing bus for free: a ``RunLog`` (the
driver's, or the service's own), a ``CompileWatchdog`` whose cache-size
probe points at the AOT cache (zero-mid-serve-retrace is a pinned
invariant, not a hope), the perf ledger adopting each compiled
executable at zero extra compiles, a ``Heartbeat`` thread making a hung
dispatch visible, and the anomaly engine's detectors (dispatch walls
ride ``step`` events keyed by bucket, so its spike baselines are
per-bucket). Serving-specific telemetry lands as schema'd
``serve_dispatch`` / ``cache_hit`` events that
``scripts/obs_report.py``'s ``== serving ==`` section folds into batch
occupancy, queue-wait and hit-rate tables.

Since PR 9 the service is also *measured* (:mod:`gigapath_tpu.obs.metrics`
/ :mod:`gigapath_tpu.obs.reqtrace`): queue-wait, dispatch and
end-to-end latency land in exponential-bucket histograms (periodic +
final ``metrics`` events; Prometheus textfile via
``GIGAPATH_METRICS_TEXTFILE``), every request carries a
``RequestTrace`` with a stable ``trace_id`` whose
``submit -> queue -> dispatch[forward, cache_store]`` spans export as
Perfetto-loadable Chrome-trace JSON at ``run_end``, and an optional
latency SLO (``GIGAPATH_SERVE_SLO_TARGET_S``) tracks multi-window
error-budget burn — a sustained p99 breach emits ONE ``slo`` event that
the anomaly engine's ``slo_burn`` detector turns into a flight dump +
profiler capture. All of it is host-side bookkeeping around the
dispatch boundary: obs off means no registry, no tracer, no SLO — and
the compiled programs are byte-identical either way (pinned).

All ``GIGAPATH_SERVE_*`` flags are host-side, read ONCE at
:meth:`ServeConfig.from_env` (service construction) — never at trace
time (GL001-clean by construction; README flag table).

Sync usage (drivers, tests)::

    svc = SlideService(forward, params, config=ServeConfig(max_batch=4))
    futs = [svc.submit(sid, feats, coords) for ...]
    svc.drain()                   # dispatch everything on THIS thread
    results = [f.result() for f in futs]
    svc.close()

Async usage (the smoke's concurrent submitters)::

    with SlideService(...) as svc:        # starts the worker thread
        fut = svc.submit(sid, feats, coords)
        logits = fut.result(timeout=60)
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, List, Optional

from gigapath_tpu.obs.locktrace import make_lock

import numpy as np

from gigapath_tpu.obs import (
    CompileWatchdog,
    Heartbeat,
    NullSloTracker,
    SloTracker,
    get_ledger,
    get_metrics,
    get_run_log,
    get_tracer,
    span,
)
from gigapath_tpu.resilience.chaos import ChaosError, get_chaos
from gigapath_tpu.serve.aot import AotExecutableCache
from gigapath_tpu.serve.buckets import BucketLadder, assemble_batch
from gigapath_tpu.serve.cache import EmbeddingCache, content_key
from gigapath_tpu.serve.health import (
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    LoadSheddedError,
)
from gigapath_tpu.serve.queue import RequestQueue, SlideRequest


@dataclass(frozen=True)
class ServeConfig:
    """Serving policy knobs (all host-side; env via :meth:`from_env`)."""

    max_batch: int = 8          # batch capacity per dispatch
    max_wait_s: float = 0.05    # latency bound: oldest request's deadline
    # memory bound: capacity x bucket_n never exceeds this many padded
    # tiles, so a big-bucket dispatch is capped below max_batch (the
    # default equals the exact path's worst single slide, 2^20 tiles —
    # padding the batch axis must not multiply peak memory past what
    # the old slide-at-a-time driver already materialized)
    batch_tokens: int = 1 << 20
    cache_budget_mb: float = 256.0
    artifact_dir: Optional[str] = None  # persisted executables; None = off
    bucket_min: int = 1024
    bucket_growth: float = 2.0
    bucket_max: int = 1 << 20
    bucket_align: int = 128     # rung alignment (the encoder's internal pad)
    feature_dim: int = 1536
    # self-healing policies (serve/health.py); 0 = policy off
    shed_tokens: int = 0        # load-shed submits past this queued-token depth
    deadline_s: float = 0.0     # per-request deadline (fail expired at dispatch)
    breaker_failures: int = 0   # consecutive failures that open a bucket breaker
    breaker_cooldown_s: float = 30.0  # open -> half-open probe delay
    # latency SLO (obs/metrics.py SloTracker); target 0 = SLO off. The
    # windows/min_events are config-only (tests and smokes shrink them
    # via explicit ServeConfig overrides): at most `slo_budget` of
    # requests may exceed `slo_target_s` end-to-end, and a burn rate
    # >= `slo_burn_threshold` on BOTH windows emits the `slo` event the
    # anomaly engine's slo_burn detector reacts to
    slo_target_s: float = 0.0
    slo_budget: float = 0.01
    slo_burn_threshold: float = 2.0
    slo_short_window_s: float = 60.0
    slo_long_window_s: float = 300.0
    slo_min_events: int = 8

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        """Read the ``GIGAPATH_SERVE_*`` env surface ONCE (host-side, at
        service construction — the obs layer's flag discipline).
        Explicit keyword overrides win over env over defaults."""
        from gigapath_tpu.obs.runlog import env_number

        base = cls(
            max_batch=int(env_number("GIGAPATH_SERVE_MAX_BATCH",
                                     cls.max_batch)),
            max_wait_s=env_number("GIGAPATH_SERVE_MAX_WAIT_S",
                                  cls.max_wait_s),
            batch_tokens=int(env_number("GIGAPATH_SERVE_BATCH_TOKENS",
                                        cls.batch_tokens)),
            cache_budget_mb=env_number("GIGAPATH_SERVE_CACHE_MB",
                                       cls.cache_budget_mb),
            artifact_dir=os.environ.get("GIGAPATH_SERVE_ARTIFACT_DIR")
            or None,
            bucket_min=int(env_number("GIGAPATH_SERVE_BUCKET_MIN",
                                      cls.bucket_min)),
            bucket_growth=env_number("GIGAPATH_SERVE_BUCKET_GROWTH",
                                     cls.bucket_growth),
            bucket_max=int(env_number("GIGAPATH_SERVE_BUCKET_MAX",
                                      cls.bucket_max)),
            bucket_align=int(env_number("GIGAPATH_SERVE_BUCKET_ALIGN",
                                        cls.bucket_align)),
            shed_tokens=int(env_number("GIGAPATH_SERVE_SHED_TOKENS",
                                       cls.shed_tokens)),
            deadline_s=env_number("GIGAPATH_SERVE_DEADLINE_S",
                                  cls.deadline_s),
            breaker_failures=int(env_number(
                "GIGAPATH_SERVE_BREAKER_FAILURES", cls.breaker_failures)),
            breaker_cooldown_s=env_number(
                "GIGAPATH_SERVE_BREAKER_COOLDOWN_S", cls.breaker_cooldown_s),
            slo_target_s=env_number("GIGAPATH_SERVE_SLO_TARGET_S",
                                    cls.slo_target_s),
            slo_budget=env_number("GIGAPATH_SERVE_SLO_BUDGET",
                                  cls.slo_budget),
            slo_burn_threshold=env_number("GIGAPATH_SERVE_SLO_BURN",
                                          cls.slo_burn_threshold),
        )
        return replace(base, **overrides) if overrides else base


def _tree_np(value: Any) -> Any:
    """Whole output pytree onto the host, one transfer per leaf (slicing
    device arrays per row would dispatch an eager XLA op per slide —
    the zero-extra-compile pin in tests/test_serve.py would catch it)."""
    if isinstance(value, dict):
        return {k: _tree_np(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_tree_np(v) for v in value)
    return np.asarray(value)


def _to_host(value: Any, row: int) -> Any:
    """Row ``row`` of a HOST (numpy) batched output pytree — COPIED out
    of the batch buffer. ``value[row]`` alone is a view whose ``.base``
    is the whole ``[capacity, bucket_n, ...]`` batch (dummy rows
    included), so caching it would pin up to capacity× the bytes the
    cache accounts for. The copy is read-only: the same array backs the
    requester's future AND the cache line, so a consumer mutating its
    result would silently corrupt every later cache hit — mutation
    fails loudly instead (``.copy()`` it on the consumer side)."""
    if isinstance(value, dict):
        return {k: _to_host(v, row) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(_to_host(v, row) for v in value)
    out = np.array(value[row])
    out.setflags(write=False)
    return out


class SlideService:
    """See module docstring. ``forward(params, embeds, coords,
    pad_mask) -> pytree`` must be jit-compatible with a leading batch
    axis on every output leaf (rows independent across the batch)."""

    def __init__(self, forward: Callable, params: Any, *,
                 config: Optional[ServeConfig] = None,
                 out_dir: Optional[str] = None, runlog=None,
                 identity: str = "", name: str = "serve"):
        self.config = config or ServeConfig.from_env()
        self.identity = identity
        self._owns_runlog = runlog is None
        if runlog is None:
            runlog = get_run_log(
                name, out_dir=out_dir,
                config={
                    "max_batch": self.config.max_batch,
                    "max_wait_s": self.config.max_wait_s,
                    "cache_budget_mb": self.config.cache_budget_mb,
                    "artifact_dir": self.config.artifact_dir,
                    "buckets": f"{self.config.bucket_min}..x"
                               f"{self.config.bucket_growth:g}..",
                    "identity": identity,
                },
            )
        self.runlog = runlog  # gigarace: type gigapath_tpu.obs.runlog.RunLog
        self.ladder = BucketLadder(
            n_min=self.config.bucket_min, growth=self.config.bucket_growth,
            n_max=self.config.bucket_max, align=self.config.bucket_align,
        )
        self.queue = RequestQueue(
            max_batch=self.config.max_batch,
            max_wait_s=self.config.max_wait_s,
            capacity_for=self.capacity_for,
        )
        self.cache = EmbeddingCache(
            budget_bytes=int(self.config.cache_budget_mb * (1 << 20))
        )
        self.ledger = get_ledger(runlog)
        self.watchdog = CompileWatchdog(f"{name}.forward", runlog,
                                        ledger=self.ledger)
        self.aot = AotExecutableCache(
            forward, params, feature_dim=self.config.feature_dim,
            artifact_dir=self.config.artifact_dir, identity=identity,
            name=f"{name}.forward", runlog=runlog,
            watchdog=self.watchdog, ledger=self.ledger,
        )
        self.heartbeat = Heartbeat(runlog, name=name)
        # typed metrics + end-to-end request tracing (obs/metrics.py,
        # obs/reqtrace.py): attach-once per runlog — a driver-owned
        # runlog shares ONE registry/collector with the service — and
        # both are true no-ops against a NullRunLog (obs off). The
        # instruments are resolved here once so the dispatch hot path
        # pays a bisect + scalar updates, not name lookups
        self.metrics = get_metrics(runlog)  # gigarace: type gigapath_tpu.obs.metrics.MetricsRegistry
        self.tracer = get_tracer(runlog)  # gigarace: type gigapath_tpu.obs.reqtrace.TraceCollector
        self._m_submits = self.metrics.counter("serve.submits")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._m_hits = self.metrics.counter("serve.cache_hits")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._m_joins = self.metrics.counter("serve.inflight_joins")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._m_shed = self.metrics.counter("serve.shed")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._m_dispatches = self.metrics.counter("serve.dispatches")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._m_slides = self.metrics.counter("serve.slides")  # gigarace: type gigapath_tpu.obs.metrics.Counter
        self._g_queued_tokens = self.metrics.gauge("serve.queued_tokens")  # gigarace: type gigapath_tpu.obs.metrics.Gauge
        self._h_queue_wait = self.metrics.histogram("serve.queue_wait_s")  # gigarace: type gigapath_tpu.obs.metrics.Histogram
        self._h_dispatch = self.metrics.histogram("serve.dispatch_s")  # gigarace: type gigapath_tpu.obs.metrics.Histogram
        self._h_e2e = self.metrics.histogram("serve.e2e_s")  # gigarace: type gigapath_tpu.obs.metrics.Histogram
        # latency SLO: multi-window error-budget burn feeding the
        # anomaly engine's slo_burn detector via `slo` events; the
        # terminal status rides the runlog's closers so clean runs still
        # render an `== slo ==` section
        if (self.config.slo_target_s > 0
                and getattr(runlog, "path", None) is not None):
            self.slo = SloTracker(
                self.config.slo_target_s,
                budget=self.config.slo_budget,
                short_window_s=self.config.slo_short_window_s,
                long_window_s=self.config.slo_long_window_s,
                burn_threshold=self.config.slo_burn_threshold,
                min_events=self.config.slo_min_events,
                runlog=runlog, name=name,
            )
            runlog.add_closer(self.slo.emit_status)
        else:
            self.slo = NullSloTracker()
        # self-healing (serve/health.py): breaker state, chaos injection
        # (GIGAPATH_CHAOS read once here, host-side — NullChaos when
        # unset), the graceful-drain flag the SIGTERM chain flips
        self.breaker = (
            CircuitBreaker(self.config.breaker_failures,
                           self.config.breaker_cooldown_s)
            if self.config.breaker_failures > 0 else None
        )
        self.chaos = get_chaos()
        self._draining = False
        self._sigterm_cb = None
        self._pending: Dict[str, SlideRequest] = {}  # in-flight by content
        self._lock = make_lock("gigapath_tpu.serve.service.SlideService._lock")
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._closed = False
        self.dispatch_count = 0
        self.slides_served = 0
        self.inflight_joins = 0
        self.shed_count = 0
        self.deadline_failures = 0
        self.bisections = 0
        self.poisoned_requests = 0
        self.per_bucket_dispatches: Dict[int, int] = {}

    def capacity_for(self, bucket_n: int) -> int:
        """Per-bucket batch capacity: ``max_batch`` clamped so one
        dispatch never pads more than ``batch_tokens`` tiles — a
        131k-tile bucket batches fewer slides than a 1k one instead of
        multiplying peak memory by the full batch axis."""
        return max(1, min(self.config.max_batch,
                          self.config.batch_tokens // max(1, int(bucket_n))))

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "SlideService":
        if self._worker is None:
            self._stop.clear()
            self.heartbeat.start()
            self._worker = threading.Thread(
                target=self._run, daemon=True, name="serve-dispatch"
            )
            self._worker.start()
            self._arm_signal_drain()
        return self

    def _arm_signal_drain(self) -> None:
        """Graceful SIGTERM drain for worker-mode services: the chained
        handler (obs/flight.py — the GL011-sanctioned signal site) flips
        the draining flag (new submits rejected) and CLAIMS the
        shutdown, so the worker finishes the queued batches and the
        owner exits via close() instead of dying mid-dispatch with
        in-flight futures stranded."""
        if self._sigterm_cb is not None:
            return

        def _drain(signum) -> bool:
            if self._draining or self._closed:  # gigalint: waive GL019 -- signal context cannot block on the lock; a stale read only re-runs the drain claim, which is idempotent
                # already draining (or dead): a REPEAT SIGTERM is the
                # operator escalating past a drain that isn't finishing
                # (hung dispatch) — don't re-claim graceful, let the
                # chain proceed to the prior disposition (process death)
                return False
            self._draining = True
            # signal-safe obs: the handler may have interrupted a thread
            # INSIDE runlog.event() holding its write lock — the
            # *_from_signal paths try-acquire and drop on contention
            # instead of self-deadlocking the shutdown
            # try-acquire count (None on contention): the blocking
            # queue.pending() here was gigarace GL020's first real catch
            # — the signal can interrupt a thread holding the queue cond
            pending = self.queue.pending_from_signal()
            self.runlog.event_from_signal(
                "recovery", action="drain", signal=int(signum),
                pending=pending,
            )
            self.runlog.echo_from_signal(
                "[serve] SIGTERM: draining — new submits rejected, "
                f"{pending if pending is not None else '?'} request(s) "
                "still dispatching"
            )
            return True  # graceful claim: don't re-raise process death

        from gigapath_tpu.obs.flight import register_signal_callback

        if register_signal_callback(_drain):
            self._sigterm_cb = _drain

    def __enter__(self) -> "SlideService":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(status="error" if exc_type else "ok")

    # -- request side -----------------------------------------------------
    def submit(self, slide_id: str, feats: np.ndarray,
               coords: Optional[np.ndarray] = None):
        """Enqueue one slide; returns a ``Future`` resolving to the
        forward output's row for this slide (host numpy pytree).
        Cache hits and in-flight duplicates resolve without a forward
        pass (``cache_hit`` event either way)."""
        if self._closed:  # gigalint: waive GL019 -- racy fast-path reject; re-checked under the lock before the request is enqueued
            raise RuntimeError("SlideService is closed")
        feats = np.asarray(feats, np.float32)
        if feats.ndim != 2:
            raise ValueError(f"feats must be [N, D], got {feats.shape}")
        if feats.shape[1] != self.config.feature_dim:
            raise ValueError(
                f"feature dim {feats.shape[1]} != configured "
                f"{self.config.feature_dim}"
            )
        if self._draining:
            raise RuntimeError(
                "SlideService is draining (SIGTERM received): queued "
                "requests will finish, new submits are rejected"
            )
        bucket_n = self.ladder.bucket_for(feats.shape[0])
        key = content_key(feats, coords, extra=self.identity)
        # request trace + submit counter: t_sub is ALSO the request's
        # queue-wait origin (one clock read, one origin — the trace's
        # queue span and the queue_wait_s histogram must agree)
        t_sub = time.monotonic()
        tr = self.tracer.start(slide_id, now=t_sub,
                               n_tiles=int(feats.shape[0]))
        self._m_submits.inc()
        # cache probe, pending probe and enqueue are ONE atomic section:
        # probing the cache outside the lock would let a dispatch finish
        # in the gap (cache.put + _pending.pop) and this request re-run
        # a full forward for bytes already sitting in the cache
        with self._lock:
            if self._closed:
                # re-checked under the lock: a submit racing close()
                # past the unlocked check above must not enqueue onto a
                # service whose orphan sweep already ran (its future
                # would never resolve)
                raise RuntimeError("SlideService is closed")
            pending = self._pending.get(key)
            if pending is not None:
                # identical content already awaiting dispatch: join it
                # (probed BEFORE the cache so a join never counts as a
                # cache miss in the stats the hit-rate trend is fed by)
                self.inflight_joins += 1
                self._m_joins.inc()
                tr.add_span("submit", t_sub, time.monotonic(),
                            bucket=bucket_n, outcome="inflight_join")
                tr.finish(status="inflight_join")
                self.runlog.event(
                    "cache_hit", slide_id=slide_id, key=key[:16],
                    n_tiles=int(feats.shape[0]), inflight=True,
                )
                return pending.future
            cached = self.cache.get(key)
            if cached is not None:
                from concurrent.futures import Future

                fut: Future = Future()
                fut.set_result(cached)
                self._m_hits.inc()
                tr.add_span("submit", t_sub, time.monotonic(),
                            bucket=bucket_n, outcome="cache_hit")
                tr.finish(status="cache_hit")
                self.runlog.event(
                    "cache_hit", slide_id=slide_id, key=key[:16],
                    n_tiles=int(feats.shape[0]), inflight=False,
                )
                return fut
            if self.config.shed_tokens > 0:
                # load shedding: back-pressure at the door, checked AFTER
                # the cache/pending probes — a hit or an in-flight join
                # adds zero padded tokens to the queue and zero device
                # time, and the hot repeated-slide traffic the cache
                # exists for is exactly what an earlier check would shed.
                # The budget is in PADDED tiles (what the device will
                # materialize); the rejected future fails immediately so
                # the caller can retry elsewhere instead of waiting on a
                # queue that cannot keep up
                depth = self.queue.pending_tokens()
                if depth + bucket_n > self.config.shed_tokens:
                    self.shed_count += 1
                    self._m_shed.inc()
                    tr.add_span("submit", t_sub, time.monotonic(),
                                bucket=bucket_n, outcome="shed")
                    tr.finish(status="shed")
                    self.runlog.event(
                        "recovery", action="shed", slide_id=slide_id,
                        bucket=bucket_n, queued_tokens=depth,
                        budget=self.config.shed_tokens,
                    )
                    from concurrent.futures import Future

                    fut = Future()
                    fut.set_exception(LoadSheddedError(
                        f"queue depth {depth} + {bucket_n} padded tiles "
                        f"exceeds GIGAPATH_SERVE_SHED_TOKENS="
                        f"{self.config.shed_tokens}"
                    ))
                    return fut
            req = SlideRequest(
                slide_id, feats, coords, bucket_n=bucket_n, cache_key=key,
                t_submit=t_sub,
            )
            req.trace = tr
            self._pending[key] = req
        # the submit span closes BEFORE the request becomes visible to
        # the dispatch worker: a RequestTrace is single-owner (submitter,
        # then worker — the queue's existing handoff), so the queue span
        # the worker opens at tr.t_last must find the submit span closed
        tr.add_span("submit", t_sub, time.monotonic(), bucket=bucket_n,
                    outcome="enqueued")
        self.queue.submit(req)
        self._g_queued_tokens.set(self.queue.pending_tokens())
        return req.future

    # -- dispatch side ----------------------------------------------------
    def step(self, *, drain: bool = False,
             now: Optional[float] = None) -> int:
        """Process at most ONE ready batch on the calling thread;
        returns the number of slides served. Drivers in sync mode call
        this in a loop; the worker thread calls it forever.

        Self-healing order per batch: expired deadlines fail first (no
        device time for answers nobody awaits), then the bucket's
        circuit breaker gets a say (open -> fail fast; half-open -> this
        batch is the probe), then the dispatch runs with poisoned-batch
        bisection — one bad slide fails ONE future, the rest of the
        batch still returns parity-correct results."""
        batch = self.queue.pop_ready(now=now, drain=drain)
        if not batch:
            return 0
        bucket_n = batch[0].bucket_n
        if self.config.deadline_s > 0:
            live = []
            for req in batch:
                if req.wait_s() > self.config.deadline_s:
                    self.deadline_failures += 1
                    self.runlog.event(
                        "recovery", action="deadline",
                        slide_id=req.slide_id, bucket=bucket_n,
                        waited_s=round(req.wait_s(), 6),
                        deadline_s=self.config.deadline_s,
                    )
                    self._fail_requests([req], DeadlineExceededError(
                        f"{req.slide_id}: waited {req.wait_s():.3f}s > "
                        f"deadline {self.config.deadline_s}s"
                    ))
                else:
                    live.append(req)
            batch = live
            if not batch:
                return 0
        if self.breaker is not None:
            verdict = self.breaker.admit(bucket_n)
            if verdict == "reject":
                self.runlog.event(
                    "recovery", action="breaker_shed", bucket=bucket_n,
                    slides=len(batch), state=self.breaker.state(bucket_n),
                )
                self._fail_requests(batch, BreakerOpenError(
                    f"bucket {bucket_n}: circuit breaker open"
                ))
                return 0
            if verdict == "probe":
                self.runlog.event(
                    "recovery", action="breaker_probe", bucket=bucket_n,
                    slides=len(batch),
                )
        had_failure = [False]
        served = self._dispatch_with_bisection(batch, had_failure)
        if self.breaker is not None:
            transition = (
                self.breaker.record_failure(bucket_n) if had_failure[0]
                else self.breaker.record_success(bucket_n)
            )
            if transition == "open":
                self.runlog.event(
                    "recovery", action="breaker_open", bucket=bucket_n,
                    cooldown_s=self.config.breaker_cooldown_s,
                )
                self.runlog.echo(
                    f"[serve] circuit breaker OPEN for bucket {bucket_n} "
                    f"(cooldown {self.config.breaker_cooldown_s:g}s)"
                )
            elif transition == "close":
                self.runlog.event(
                    "recovery", action="breaker_close", bucket=bucket_n,
                )
        return served

    def _fail_requests(self, reqs: List[SlideRequest],
                       err: Exception) -> None:
        """Fail futures + drop their in-flight pending entries (waiters
        must hear, joiners must not latch onto a dead dispatch)."""
        with self._lock:
            for req in reqs:
                if req.cache_key is not None:
                    self._pending.pop(req.cache_key, None)
        for req in reqs:
            if req.future.done():
                continue  # already resolved (bisection partial): not ours
            if req.trace is not None:
                req.trace.finish(status=type(err).__name__)
            # a failed request is a spent unit of error budget: a
            # deadline/breaker/poison storm produces no successful
            # latencies, and an SLO fed only by successes would read a
            # 100%-failing service as healthy
            self.slo.observe_failure()
            req.future.set_exception(err)

    def _dispatch_with_bisection(self, batch: List[SlideRequest],
                                 had_failure: List[bool]) -> int:
        """Dispatch; on failure, bisect so one poisoned slide fails ONE
        future instead of the whole batch. Halves re-dispatch at the
        same bucket shape (batches always pad to full capacity), so
        bisection adds ZERO compiles — only extra forward passes, and
        only on the failure path."""
        try:
            return self._dispatch(batch)
        except Exception as e:
            self.runlog.error("serve.dispatch", e)
            had_failure[0] = True
            if len(batch) == 1:
                req = batch[0]
                self.poisoned_requests += 1
                self.runlog.event(
                    "recovery", action="poisoned_request",
                    slide_id=req.slide_id, bucket=req.bucket_n,
                    error=f"{type(e).__name__}: {e}",
                )
                self._fail_requests(batch, e)
                return 0
            self.bisections += 1
            self.runlog.event(
                "recovery", action="bisect", bucket=batch[0].bucket_n,
                slides=len(batch),
            )
            mid = len(batch) // 2
            return (
                self._dispatch_with_bisection(batch[:mid], had_failure)
                + self._dispatch_with_bisection(batch[mid:], had_failure)
            )

    def _dispatch(self, batch: List[SlideRequest]) -> int:
        """One assembled forward for one same-bucket batch (the PR-7
        dispatch body, factored out so bisection can re-enter it)."""
        bucket_n = batch[0].bucket_n
        capacity = self.capacity_for(bucket_n)
        if self.chaos:
            poisoned = self.chaos.poisoned([r.slide_id for r in batch])
            if poisoned is not None:
                raise ChaosError(f"chaos: poisoned slide {poisoned}")
        t_d0 = time.monotonic()
        with span("serve.dispatch", self.runlog, fence=True,
                  bucket=bucket_n, slides=len(batch)) as sp:
            if self.chaos:
                # chaos slow_dispatch: a host-side sleep INSIDE the
                # dispatch span, so the injected slowness lands exactly
                # where the latency telemetry (dispatch histogram, e2e,
                # SLO burn) must see it — the compiled program untouched
                slow_s = self.chaos.slow_dispatch(self.dispatch_count)
                if slow_s:
                    time.sleep(slow_s)
            embeds, coords, mask = assemble_batch(
                [(r.feats, r.coords) for r in batch], bucket_n, capacity,
                feature_dim=self.config.feature_dim,
            )
            t_fwd0 = time.monotonic()
            out = self.aot(embeds, coords, mask)
            sp.fence(out)
        # the span's fence (block_until_ready) ran at exit, so THIS is
        # the moment device execution finished — the forward span's end
        t_fwd1 = time.monotonic()
        # host-side conversion and scatter stay INSIDE the poisoned-
        # batch containment: a MemoryError copying rows out of a big
        # batch must fail these futures too, not strand their waiters
        out = _tree_np(out)
        source = self.aot.sources.get((capacity, bucket_n), "?")
        for i, req in enumerate(batch):
            result = _to_host(out, i)
            t_c0 = time.monotonic()
            if req.cache_key is not None:
                self.cache.put(req.cache_key, result)
                with self._lock:
                    self._pending.pop(req.cache_key, None)
            t_c1 = time.monotonic()
            # bisection can re-enter this loop with requests that were
            # ALREADY resolved before a partial failure (e.g. a
            # MemoryError in _to_host halfway through the scatter): the
            # first resolution owns the telemetry — a re-dispatch must
            # not double-observe e2e/SLO or append spans past the
            # trace's frozen end
            first_resolution = not req.future.done()
            if first_resolution:
                req.future.set_result(result)
            t_res = time.monotonic()
            if first_resolution:
                # per-request latency telemetry: the trace's spans, the
                # histograms, and the SLO tracker all read the SAME
                # clocks (t_submit from submit(), t_dispatch from
                # pop_ready)
                t_disp = (req.t_dispatch if req.t_dispatch is not None
                          else t_d0)
                tr = req.trace
                if tr is not None:
                    tr.add_span("queue", tr.t_last, t_disp, bucket=bucket_n)
                    tr.add_span("dispatch", t_disp, t_res, bucket=bucket_n,
                                slides=len(batch), capacity=capacity,
                                source=source)
                    tr.add_span("forward", t_fwd0, t_fwd1, bucket=bucket_n,
                                batch=len(batch))
                    if req.cache_key is not None:
                        tr.add_span("cache_store", t_c0, t_c1)
                    tr.finish(t_res)
                self._h_queue_wait.observe(req.wait_s())
                e2e = max(t_res - req.t_submit, 0.0)
                self._h_e2e.observe(e2e)
                self.slo.observe(e2e)
        self.dispatch_count += 1
        self.slides_served += len(batch)
        self._m_dispatches.inc()
        self._m_slides.inc(len(batch))
        if sp.dur_s is not None:
            self._h_dispatch.observe(sp.dur_s)
        self._g_queued_tokens.set(self.queue.pending_tokens())
        self.per_bucket_dispatches[bucket_n] = (
            self.per_bucket_dispatches.get(bucket_n, 0) + 1
        )
        waits = [round(r.wait_s(), 6) for r in batch]
        self.runlog.event(
            "serve_dispatch", bucket=bucket_n, slides=len(batch),
            capacity=capacity, occupancy=round(len(batch) / capacity, 4),
            queue_wait_s=waits, wall_s=sp.dur_s, source=source,
        )
        # dispatch walls also ride step events so the anomaly engine's
        # per-bucket spike/dip baselines cover serving for free
        self.runlog.step(
            self.dispatch_count, wall_s=sp.dur_s, synced=True,
            bucket=str(bucket_n), slides=len(batch),
        )
        self.heartbeat.beat(self.dispatch_count)
        self.metrics.maybe_flush()
        return len(batch)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                if self.step():
                    continue
                deadline = self.queue.next_deadline_s()
                timeout = 0.05 if deadline is None else max(
                    min(deadline, 0.05), 0.001
                )
                self.queue.wait_for_work(timeout=timeout)
            except Exception as e:  # a poisoned batch must not kill serving
                self.runlog.error("serve.dispatch", e)

    def drain(self) -> int:
        """Dispatch everything still queued on the CALLING thread (sync
        mode / shutdown flush); returns slides served."""
        served = 0
        while True:
            n = self.step(drain=True)
            if n == 0 and self.queue.pending() == 0:
                return served
            served += n

    # -- summaries --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        cache = self.cache.stats()
        with self._lock:
            # the submit-side counters are lock-guarded (N submitter
            # threads); the dispatch-side ones are worker-thread-owned
            inflight_joins = self.inflight_joins
            shed_count = self.shed_count
        return {
            "dispatches": self.dispatch_count,
            "slides_served": self.slides_served,
            "inflight_joins": inflight_joins,
            "shed": shed_count,
            "deadline_failures": self.deadline_failures,
            "bisections": self.bisections,
            "poisoned_requests": self.poisoned_requests,
            "breaker_trips": self.breaker.trips if self.breaker else 0,
            "slo_violations": self.slo.violations,
            "slo_burn_entries": self.slo.burn_entries,
            "buckets_used": len(self.per_bucket_dispatches),
            "per_bucket_dispatches": {
                str(k): v
                for k, v in sorted(self.per_bucket_dispatches.items())
            },
            "compiled_executables": self.aot.compiled_count,
            "loaded_executables": self.aot.loaded_count,
            "unexpected_retraces": len(self.watchdog.unexpected_retraces),
            "compile_seconds_total": self.watchdog.compile_seconds_total(),
            "cache": cache,
        }

    def close(self, status: str = "ok") -> None:
        if self._closed:  # gigalint: waive GL019 -- racy idempotence fast-path; the flag is flipped under the lock below and a duplicate close() is harmless
            return
        if self._sigterm_cb is not None:
            from gigapath_tpu.obs.flight import unregister_signal_callback

            unregister_signal_callback(self._sigterm_cb)
            self._sigterm_cb = None
        if self._worker is not None:
            self._stop.set()
            # join until the worker is DEAD, not a fixed grace:
            # proceeding into drain() while the worker is mid-step()
            # would put two threads inside the AOT cache / watchdog /
            # dispatch counters, which are single-dispatch-thread by
            # design. A flagship compile can exceed any fixed grace;
            # the worker always exits after its current batch (_stop
            # is set and queue waits are <= 50 ms), and a truly hung
            # forward is the stall detector's job — echoed here so the
            # wait is visible either way.
            waited = 0.0
            while True:
                self._worker.join(timeout=10.0)
                if not self._worker.is_alive():
                    break
                waited += 10.0
                self.runlog.echo(
                    "[serve] close(): dispatch worker still mid-batch "
                    f"after {waited:.0f}s; waiting"
                )
            self._worker = None
        try:
            self.drain()
        finally:
            self.heartbeat.stop()
            # _closed flips INSIDE the same locked section as the orphan
            # sweep, so no submit can slip between the two; orphaned
            # futures (submitters gone, service closing) fail loudly
            # rather than hang their waiters forever
            with self._lock:
                self._closed = True
                orphans = list(self._pending.values())
                self._pending.clear()
            for req in orphans:
                if not req.future.done():
                    req.future.set_exception(
                        RuntimeError("SlideService closed before dispatch")
                    )
            if self._owns_runlog:
                self.runlog.run_end(status=status, **{
                    k: v for k, v in self.stats().items()
                    if not isinstance(v, dict)
                })
