"""Serving-side streaming chunked prefill: the submit path that folds.

The bucketed :class:`~gigapath_tpu.serve.service.SlideService` pads each
slide to a ladder rung and runs one dense AOT forward — correct, but the
whole tile-embedding sequence must exist before dispatch, and every new
slide length rides a slide-sized executable. The streaming submitter is
the other operating point: a slide opens a
:class:`~gigapath_tpu.models.streaming_encoder.StreamingEncoderSession`,
tile-embedding chunks (``EmbeddingChunk``s from the dist boundary, a
prefetch loader, or the tile-encoder fleet) fold into the encoder AS
THEY ARRIVE — stage-1 production overlapped with stage-2 folding end to
end — and the only compiled programs are CHUNK-shaped stage executables,
shared by every slide regardless of length. The dense service remains
the fallback and the parity oracle.

Obs wiring: one ``stream_open`` / ``stream_result`` event pair per
slide (chunk counts, fold counts, wall), so ``obs_report.py`` sees
streaming serves next to bucketed ones. Out-of-order and duplicate
chunk delivery are absorbed by the session's deterministic fold
frontier (bit-parity per the dist boundary's contract).

Fleet tracing (ISSUE 17): ``open(..., trace=ctx)`` threads a
:class:`~gigapath_tpu.obs.reqtrace.TraceContext` so each fold and the
finalize land as ``fold`` / ``finalize`` spans in the slide's
cross-process causal tree. Duplicate deliveries dedup on the context's
structural span id, so a replayed chunk cannot fork the tree.

Model health (ISSUE 19): with ``GIGAPATH_DRIFT_PEEK_EVERY=N`` (or an
explicit ``peek_every``), the session takes a provisional embedding off
the running partials every N folded chunks
(``StreamingEncoderSession.peek()``) and emits one ``stream_peek``
event per peek (frontier, cosine vs the previous peek, layer-0 branch
LSE spread); ``result()`` scores every peek against the finalized
embedding — the anytime-confidence surface — observing each cosine
into the ``serve.stream_confidence`` histogram and folding the final
embedding into the submitter's :class:`~gigapath_tpu.obs.drift.
DriftSentinel` when one is attached.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from gigapath_tpu.models.streaming_encoder import (
    StreamingEncoderSession,
    embeds_to_outputs,
    prefill_chunk_tiles,
)
from gigapath_tpu.obs.drift import cosine, stream_peek_every

# cosine-confidence ladder: 0.05-wide linear rungs over (0, 1] — the
# default exponential latency ladder would dump every confidence into
# two buckets
CONFIDENCE_BOUNDS = [i / 20 for i in range(1, 21)]


class StreamingSlideSession:
    """One slide's streaming serve: feed chunks, then ``result()``.

    ``feed`` accepts ``EmbeddingChunk``-shaped objects (``chunk_id`` /
    ``payload`` / ``coords``) or explicit ``(idx, embeds, coords)``;
    ``result()`` returns the ``layer_{i}_embed`` / ``last_layer_embed``
    dict of ``pipeline.run_inference_with_slide_encoder`` (the oracle
    surface the parity tests pin)."""

    def __init__(self, submitter: "StreamingSubmitter", slide_id: str,
                 n_tiles: int, trace=None):
        self.submitter = submitter
        self.slide_id = slide_id
        self.trace = trace
        self.session = StreamingEncoderSession(
            submitter.model, submitter.params, int(n_tiles),
            chunk_tiles=submitter.chunk_tiles, all_layer_embed=True,
        )
        self._t_open = time.monotonic()
        self._outputs: Optional[Dict[str, np.ndarray]] = None
        self._peeks: List[Tuple[int, np.ndarray]] = []
        self._last_peek = 0
        if submitter.runlog is not None:
            submitter.runlog.event(
                "stream_open", slide=slide_id, n_tiles=int(n_tiles),
                n_chunks=self.session.n_chunks,
                chunk_tiles=submitter.chunk_tiles,
            )

    def feed(self, chunk, embeds=None, coords=None) -> int:
        """Fold one chunk (any arrival order). Returns the fold
        frontier — how many chunks are folded so far."""
        if embeds is None:
            cid, embeds, coords = chunk.chunk_id, chunk.payload, chunk.coords
            parent = getattr(chunk, "parent_span_id", "") or None
        else:
            cid, parent = int(chunk), None
        t0 = time.monotonic()
        frontier = self.session.feed(cid, embeds, coords)
        if self.trace is not None:
            self.trace.add_span("fold", t0, time.monotonic(), chunk=cid,
                                parent=parent)
        every = self.submitter.peek_every
        if (every > 0 and frontier > self._last_peek
                and frontier < self.session.n_chunks
                and frontier % every == 0):
            self._peek(frontier)
        return frontier

    def _peek(self, frontier: int) -> None:
        """One anytime read: provisional last-layer embedding off the
        running partials + the ``stream_peek`` event (cosine vs the
        previous peek, layer-0 branch LSE spread)."""
        t0 = time.monotonic()
        emb = np.asarray(
            self.session.peek()[-1], np.float32
        ).reshape(-1)
        cos_prev = cosine(emb, self._peeks[-1][1]) if self._peeks else None
        self._peeks.append((frontier, emb))
        self._last_peek = frontier
        if self.submitter.runlog is not None:
            self.submitter.runlog.event(
                "stream_peek", slide=self.slide_id, frontier=frontier,
                n_chunks=self.session.n_chunks,
                frac=round(frontier / self.session.n_chunks, 4),
                cos_prev=(round(cos_prev, 6) if cos_prev is not None
                          else None),
                lse_spread=round(self.session.lse_spread(), 4),
                wall_s=round(time.monotonic() - t0, 4),
            )

    def pending(self) -> List[int]:
        return self.session.pending()

    def result(self) -> Dict[str, np.ndarray]:
        if self._outputs is None:
            t0 = time.monotonic()
            self._outputs = embeds_to_outputs(self.session.finalize())
            if self.trace is not None:
                self.trace.add_span("finalize", t0, time.monotonic())
            self.submitter.served += 1
            final = np.asarray(
                self._outputs["last_layer_embed"], np.float32
            ).reshape(-1)
            # provisional-vs-final convergence: each peek's cosine to
            # the finalized embedding, observed into the shared
            # serve.stream_confidence histogram
            confidences = [
                round(cosine(emb, final), 6) for _, emb in self._peeks
            ]
            hist = self.submitter.confidence_hist
            if hist is not None:
                for c in confidences:
                    hist.observe(c)
            if self.submitter.runlog is not None:
                self.submitter.runlog.event(
                    "stream_result", slide=self.slide_id,
                    n_chunks=self.session.n_chunks,
                    peeks=len(confidences),
                    confidence_first=(
                        confidences[0] if confidences else None
                    ),
                    confidence_last=(
                        confidences[-1] if confidences else None
                    ),
                    wall_s=round(time.monotonic() - self._t_open, 4),
                )
            # the served embedding feeds the drift sentinel LAST: an
            # alarming transition's flight dump then carries this
            # slide's stream_peek/stream_result context
            if self.submitter.drift is not None:
                self.submitter.drift.observe(final)
        return self._outputs


class StreamingSubmitter:
    """Streaming-prefill front end over one ``(model, params)`` pair.

    ``open(slide_id, n_tiles)`` starts a slide; the per-chunk stage
    executables (embed / qkv / fold / post-attention) are keyed on chunk
    shape inside jax's jit cache, so slides of any length share them.
    ``chunk_tiles`` defaults to the ``GIGAPATH_PREFILL_CHUNK`` host
    flag."""

    def __init__(self, model, params, *, chunk_tiles: Optional[int] = None,
                 runlog=None, name: str = "serve.stream",
                 drift=None, peek_every: Optional[int] = None,
                 metrics=None):
        """``drift``: optional :class:`~gigapath_tpu.obs.drift.
        DriftSentinel` every finalized embedding folds into.
        ``peek_every``: anytime-peek cadence in folded chunks (defaults
        to the ``GIGAPATH_DRIFT_PEEK_EVERY`` host flag, snapshotted
        here at construction; 0 = off). ``metrics``: optional registry
        for the ``serve.stream_confidence`` histogram."""
        self.model = model
        self.params = params
        self.chunk_tiles = int(chunk_tiles or prefill_chunk_tiles())
        self.runlog = runlog
        self.name = name
        self.served = 0
        self.drift = drift
        self.peek_every = int(peek_every if peek_every is not None
                              else stream_peek_every())
        self.confidence_hist = None
        if metrics is not None:
            self.confidence_hist = metrics.histogram(
                "serve.stream_confidence", bounds=CONFIDENCE_BOUNDS
            )

    def open(self, slide_id: str, n_tiles: int,
             trace=None) -> StreamingSlideSession:
        return StreamingSlideSession(self, slide_id, n_tiles, trace=trace)

    def stream_slide(self, slide_id: str, chunks, n_tiles: int,
                     trace=None) -> Dict[str, np.ndarray]:
        """Convenience: open + feed an iterable/channel of chunks +
        result, folding each chunk the moment the iterable yields it
        (a blocking channel ``recv`` loop overlaps production with the
        folds for free)."""
        session = self.open(slide_id, n_tiles, trace=trace)
        for chunk in chunks:
            session.feed(chunk)
        return session.result()


def streaming_head_logits(head_model, params, embeds) -> np.ndarray:
    """Classifier tail of ``ClassificationHead`` over a streaming
    session's per-layer embeds (feature-axis concat of the selected
    layers + the linear classifier — per-slide [B, D] vectors, nothing
    chunked left to stream). ``embeds``: the session's embed list or
    its ``result()`` dict."""
    from gigapath_tpu.models.classification_head import parse_feat_layer

    if isinstance(embeds, dict):
        n = sum(1 for key in embeds if key.startswith("layer_"))
        embeds = [embeds[f"layer_{i}_embed"] for i in range(n)]
    layers = parse_feat_layer(head_model.feat_layer)
    h = jnp.concatenate(
        [jnp.asarray(embeds[i]) for i in layers], axis=-1
    )
    p = params["classifier"]
    dtype = h.dtype
    logits = h.reshape(-1, h.shape[-1]) @ p["kernel"].astype(dtype)
    logits = logits + p["bias"].astype(dtype)
    return np.asarray(logits, np.float32)


def head_streaming_submitter(head_model, params, *,
                             chunk_tiles: Optional[int] = None,
                             runlog=None) -> StreamingSubmitter:
    """A :class:`StreamingSubmitter` for a ``ClassificationHead``: the
    inner slide encoder streams; callers apply
    :func:`streaming_head_logits` to each session's layer embeds."""
    from gigapath_tpu.utils.registry import create_model_from_registry

    inner = create_model_from_registry(
        head_model.model_arch, in_chans=head_model.input_dim,
        global_pool=head_model.global_pool, dtype=head_model.dtype,
        **(head_model.slide_kwargs or {}),
    )
    return StreamingSubmitter(
        inner, params["slide_encoder"], chunk_tiles=chunk_tiles,
        runlog=runlog,
    )
