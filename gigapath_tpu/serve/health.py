"""Serving self-healing policies: load shedding, deadlines, circuit
breaking (the request-facing half of the PR-8 resilience layer).

A serving process facing heavy traffic fails in ways a batch driver
never sees: a queue that grows without bound until the host OOMs, a
request that waits forever behind a hot bucket, one bucket whose
executable (or data) is persistently broken taking every caller down
with it. The policies here keep each failure contained:

- **load shedding** (``GIGAPATH_SERVE_SHED_TOKENS``; the check lives in
  ``SlideService.submit``, after the cache/pending probes): a submit
  that would push the queue's pending PADDED-token depth past the
  budget is rejected immediately (:class:`LoadSheddedError` on the
  future) — back-pressure at the door instead of an OOM an hour later;
- **per-request deadlines** (``GIGAPATH_SERVE_DEADLINE_S``): a request
  that already waited past its deadline when its batch dispatches fails
  with :class:`DeadlineExceededError` instead of burning device time on
  an answer nobody is still waiting for;
- **circuit breaker** (:class:`CircuitBreaker` via
  ``GIGAPATH_SERVE_BREAKER_FAILURES`` /
  ``GIGAPATH_SERVE_BREAKER_COOLDOWN_S``): per-bucket; N consecutive
  failed dispatches OPEN the breaker (new batches for that bucket
  fail fast with :class:`BreakerOpenError`), after the cooldown ONE
  half-open probe batch is admitted — success closes the breaker,
  failure re-opens it.

All policy state is host-side and per-bucket; every trip/close/shed
emits a ``recovery`` event through the service's runlog (rendered by
``scripts/obs_report.py``'s ``== recovery ==``). Clocks are injectable
(``now=``) so tests are deterministic, like ``serve/queue.py``.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class LoadSheddedError(RuntimeError):
    """Rejected at submit: queue depth exceeded the token budget."""


class DeadlineExceededError(RuntimeError):
    """Expired before dispatch: queue wait exceeded the deadline."""


class BreakerOpenError(RuntimeError):
    """Fail-fast: this bucket's circuit breaker is open."""


class CircuitBreaker:
    """Per-bucket closed -> open -> half-open state machine."""

    def __init__(self, failures: int = 3, cooldown_s: float = 30.0):
        self.failures = max(int(failures), 1)
        self.cooldown_s = float(cooldown_s)
        # bucket -> {"state", "consecutive", "opened_at", "probing"}
        self._buckets: Dict[int, dict] = {}
        self.trips = 0

    def _entry(self, bucket: int) -> dict:
        return self._buckets.setdefault(bucket, {
            "state": "closed", "consecutive": 0,
            "opened_at": 0.0, "probing": False,
        })

    def state(self, bucket: int) -> str:
        return self._entry(bucket)["state"]

    def admit(self, bucket: int, now: Optional[float] = None) -> str:
        """``"dispatch"`` (closed), ``"probe"`` (half-open: this batch is
        THE probe), or ``"reject"`` (open, or a probe already in
        flight)."""
        now = time.monotonic() if now is None else now
        entry = self._entry(bucket)
        if entry["state"] == "closed":
            return "dispatch"
        if entry["state"] == "open":
            if now - entry["opened_at"] >= self.cooldown_s:
                entry["state"] = "half_open"
                entry["probing"] = True
                return "probe"
            return "reject"
        # half_open: one probe at a time
        if entry["probing"]:
            return "reject"
        entry["probing"] = True
        return "probe"

    def record_success(self, bucket: int) -> Optional[str]:
        """Returns ``"close"`` when a half-open probe just closed the
        breaker, else None."""
        entry = self._entry(bucket)
        entry["consecutive"] = 0
        if entry["state"] != "closed":
            entry["state"] = "closed"
            entry["probing"] = False
            return "close"
        return None

    def record_failure(self, bucket: int,
                       now: Optional[float] = None) -> Optional[str]:
        """Returns ``"open"`` when this failure tripped (or re-tripped)
        the breaker, else None."""
        now = time.monotonic() if now is None else now
        entry = self._entry(bucket)
        entry["consecutive"] += 1
        if entry["state"] == "half_open":
            # the probe failed: straight back to open, fresh cooldown
            entry["state"] = "open"
            entry["opened_at"] = now
            entry["probing"] = False
            self.trips += 1
            return "open"
        if entry["state"] == "closed" and entry["consecutive"] >= self.failures:
            entry["state"] = "open"
            entry["opened_at"] = now
            self.trips += 1
            return "open"
        return None
