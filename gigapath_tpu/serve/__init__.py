"""Slide-embedding serving stack (ROADMAP item 1).

- :mod:`gigapath_tpu.serve.buckets` — geometric shape-bucket ladder +
  padded-batch assembly with key-padding masks;
- :mod:`gigapath_tpu.serve.aot` — per-bucket AOT executables (donated
  request buffers, persisted compiled artifacts: warm cold-start loads
  instead of retracing);
- :mod:`gigapath_tpu.serve.queue` — same-bucket request coalescing under
  a fill-or-deadline (continuous batching) policy;
- :mod:`gigapath_tpu.serve.cache` — content-hash embedding LRU with a
  byte budget (re-queried slides never recompute);
- :mod:`gigapath_tpu.serve.health` — self-healing policies (PR-8):
  token-budget load shedding, per-request deadlines, per-bucket circuit
  breakers with half-open probes;
- :mod:`gigapath_tpu.serve.streaming` — streaming chunked prefill
  submit path (ISSUE 12): per-slide sessions fold `EmbeddingChunk`s on
  arrival through chunk-shaped stage executables shared by every slide
  length; the bucketed dense service below stays the fallback/oracle;
- :mod:`gigapath_tpu.serve.service` — the orchestration loop, wired
  through the obs bus (runlog, watchdog, heartbeat, ledger, anomaly
  engine; ``serve_dispatch`` / ``cache_hit`` / ``recovery`` events),
  with poisoned-batch bisection and a graceful SIGTERM drain chained
  through :mod:`gigapath_tpu.obs.flight`.

Smoke: ``python scripts/serve_smoke.py``; tier-1:
``tests/test_serve.py``; knobs: the ``GIGAPATH_SERVE_*`` rows of the
README flag table (all host-side, read once at service construction).
"""

from gigapath_tpu.serve.aot import AotExecutableCache
from gigapath_tpu.serve.buckets import BucketLadder, assemble_batch, pad_slide
from gigapath_tpu.serve.cache import EmbeddingCache, content_key
from gigapath_tpu.serve.health import (
    BreakerOpenError,
    CircuitBreaker,
    DeadlineExceededError,
    LoadSheddedError,
)
from gigapath_tpu.serve.queue import RequestQueue, SlideRequest
from gigapath_tpu.serve.service import ServeConfig, SlideService
from gigapath_tpu.serve.streaming import (
    StreamingSlideSession,
    StreamingSubmitter,
)

__all__ = [
    "AotExecutableCache",
    "BreakerOpenError",
    "BucketLadder",
    "CircuitBreaker",
    "DeadlineExceededError",
    "EmbeddingCache",
    "LoadSheddedError",
    "RequestQueue",
    "ServeConfig",
    "SlideRequest",
    "SlideService",
    "StreamingSlideSession",
    "StreamingSubmitter",
    "assemble_batch",
    "content_key",
    "pad_slide",
]
