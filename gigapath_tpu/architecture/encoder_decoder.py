"""Seq2seq wrapper: Encoder + Decoder under one module.

Parity with reference ``torchscale/architecture/encoder_decoder.py:10-61``.
``share_all_embeddings`` maps both vocab embeddings onto one table by tying
the decoder's embed/output to the encoder's ``embed_tokens`` (flax shares by
passing the same module instance).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
from flax import linen as nn

from gigapath_tpu.architecture.config import EncoderDecoderConfig
from gigapath_tpu.architecture.decoder import Decoder
from gigapath_tpu.architecture.encoder import Encoder


class EncoderDecoder(nn.Module):
    args: EncoderDecoderConfig
    dtype: Any = None

    def setup(self):
        args = self.args
        if args.share_all_embeddings:
            args.share_decoder_input_output_embed = True
        self.encoder = Encoder(args=args, is_encoder_decoder=True, dtype=self.dtype)
        self.decoder = Decoder(args=args, is_encoder_decoder=True, dtype=self.dtype)

    def __call__(
        self,
        src_tokens: Optional[jnp.ndarray] = None,
        prev_output_tokens: Optional[jnp.ndarray] = None,
        *,
        encoder_token_embeddings: Optional[jnp.ndarray] = None,
        decoder_token_embeddings: Optional[jnp.ndarray] = None,
        return_all_hiddens: bool = False,
        features_only: bool = False,
        deterministic: bool = True,
    ) -> Dict[str, Any]:
        encoder_out = self.encoder(
            src_tokens,
            token_embeddings=encoder_token_embeddings,
            return_all_hiddens=return_all_hiddens,
            features_only=True,
            deterministic=deterministic,
        )
        return self.decoder(
            prev_output_tokens,
            token_embeddings=decoder_token_embeddings,
            encoder_out=encoder_out,
            features_only=features_only,
            return_all_hiddens=return_all_hiddens,
            deterministic=deterministic,
        )
