"""RetNet decoder (retention network).

Parity with reference ``torchscale/architecture/retnet.py``: RMS-normed
decoder blocks of MultiScaleRetention + GLU feed-forward (``DecoderLayer:71``),
embedding scale, chunk padding for chunkwise-recurrent mode
(``RetNetDecoder.forward:344-349``), final RMSNorm and output projection with
optional embedding sharing. Relative-position constants come from
:func:`gigapath_tpu.ops.multiscale_retention.retnet_rel_pos` — computed from
static sequence lengths, so jit folds them.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.architecture.config import RetNetConfig
from gigapath_tpu.ops.droppath import DropPath
from gigapath_tpu.ops.feedforward import GLU
from gigapath_tpu.ops.multiscale_retention import MultiScaleRetention, retnet_rel_pos
from gigapath_tpu.ops.norms import RMSNorm


class RetNetDecoderLayer(nn.Module):
    """Retention + GLU block (reference ``retnet.py:71-196``)."""

    args: RetNetConfig
    depth: int
    is_moe_layer: bool = False
    dtype: Any = None

    @property
    def alpha(self) -> float:
        if self.args.deepnorm:
            return math.pow(2.0 * self.args.decoder_layers, 0.25)
        return 1.0

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        rel_pos,
        chunkwise_recurrent: bool = False,
        decode: bool = False,
        deterministic: bool = True,
    ):
        args = self.args
        norm = lambda name: RMSNorm(  # noqa: E731
            args.decoder_embed_dim, eps=args.layernorm_eps, dtype=self.dtype, name=name
        )
        if args.drop_path_rate > 0:
            prob = float(
                np.linspace(0, args.drop_path_rate, args.decoder_layers)[self.depth]
            )
            drop_path = DropPath(prob)
        else:
            drop_path = None
        dropout = nn.Dropout(args.dropout)

        residual = x
        if args.decoder_normalize_before:
            x = norm("retention_layer_norm")(x)
        x = MultiScaleRetention(
            embed_dim=args.decoder_embed_dim,
            value_dim=args.decoder_value_embed_dim,
            num_heads=args.decoder_retention_heads,
            layernorm_eps=args.layernorm_eps,
            dtype=self.dtype,
            name="retention",
        )(x, rel_pos, chunkwise_recurrent=chunkwise_recurrent, decode=decode)
        x = dropout(x, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.decoder_normalize_before:
            x = norm("retention_layer_norm")(x)

        residual = x
        if args.decoder_normalize_before:
            x = norm("final_layer_norm")(x)
        if not self.is_moe_layer:
            x = GLU(
                embed_dim=args.decoder_embed_dim,
                ffn_dim=args.decoder_ffn_embed_dim,
                activation_fn=args.activation_fn,
                dropout=args.dropout,
                activation_dropout=args.activation_dropout,
                dtype=self.dtype,
                name="ffn",
            )(x, deterministic=deterministic)
            l_aux = None
        else:
            from gigapath_tpu.ops.moe.moe_layer import MOELayer

            x, l_aux = MOELayer.from_config(
                args, prefix="decoder", dtype=self.dtype, name="moe_layer"
            )(x, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.decoder_normalize_before:
            x = norm("final_layer_norm")(x)
        return x, l_aux


class RetNetDecoder(nn.Module):
    """RetNet stack returning ``(x, {"inner_states", "l_aux", "attn"})``
    (reference ``RetNetDecoder:199-391``).

    Modes: default parallel; ``chunkwise_recurrent`` from the config (input
    padded to a chunk multiple and sliced back); ``decode=True`` +
    ``mutable=["cache"]`` for O(1)-state stepwise generation.
    """

    args: RetNetConfig
    dtype: Any = None

    @nn.compact
    def __call__(
        self,
        prev_output_tokens: Optional[jnp.ndarray] = None,
        *,
        token_embeddings: Optional[jnp.ndarray] = None,
        features_only: bool = False,
        return_all_hiddens: bool = False,
        decode: bool = False,
        decode_position: int = 0,
        deterministic: bool = True,
    ) -> Dict[str, Any]:
        args = self.args
        assert prev_output_tokens is not None or token_embeddings is not None

        embed_tokens = None
        if args.vocab_size > 0:
            embed_tokens = nn.Embed(
                args.vocab_size,
                args.decoder_embed_dim,
                dtype=self.dtype,
                name="embed_tokens",
            )
        if token_embeddings is None:
            token_embeddings = embed_tokens(prev_output_tokens)

        embed_scale = (
            1.0 if args.no_scale_embedding else math.sqrt(args.decoder_embed_dim)
        )
        x = embed_scale * token_embeddings
        if args.layernorm_embedding:
            x = RMSNorm(
                args.decoder_embed_dim,
                eps=args.layernorm_eps,
                dtype=self.dtype,
                name="layernorm_embedding",
            )(x)
        x = nn.Dropout(args.dropout)(x, deterministic=deterministic)

        T = x.shape[1]
        chunkwise = args.chunkwise_recurrent and not decode
        if chunkwise and T % args.recurrent_chunk_size != 0:
            pad = args.recurrent_chunk_size - T % args.recurrent_chunk_size
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        slen = x.shape[1]

        rel_pos = retnet_rel_pos(
            # recurrent mode positions at decode_position (1-indexed length)
            decode_position + 1 if decode else slen,
            args.decoder_embed_dim,
            args.decoder_retention_heads,
            activate_recurrent=decode,
            chunkwise_recurrent=chunkwise,
            recurrent_chunk_size=args.recurrent_chunk_size,
        )

        inner_states = [x]
        l_aux = []
        moe_freq = args.moe_freq
        for i in range(args.decoder_layers):
            is_moe_layer = moe_freq != 0 and (i + 1) % moe_freq == 0
            x, l_aux_i = RetNetDecoderLayer(
                args=args,
                depth=i,
                is_moe_layer=is_moe_layer,
                dtype=self.dtype,
                name=f"layers_{i}",
            )(
                x,
                rel_pos,
                chunkwise_recurrent=chunkwise,
                decode=decode,
                deterministic=deterministic,
            )
            l_aux.append(l_aux_i)
            inner_states.append(x)

        if chunkwise and slen != T:
            x = x[:, :T]

        if args.decoder_normalize_before:
            x = RMSNorm(
                args.decoder_embed_dim,
                eps=args.layernorm_eps,
                dtype=self.dtype,
                name="layer_norm",
            )(x)

        if not features_only and not args.no_output_layer and args.vocab_size > 0:
            if args.share_decoder_input_output_embed:
                x = embed_tokens.attend(x)
            else:
                x = nn.Dense(
                    args.vocab_size,
                    use_bias=False,
                    dtype=self.dtype,
                    kernel_init=nn.initializers.normal(args.decoder_embed_dim**-0.5),
                    name="output_projection",
                )(x)

        return {
            "decoder_out": x,
            "inner_states": inner_states if return_all_hiddens else [x],
            "l_aux": l_aux,
            "attn": None,
        }
