"""Architecture configs (Encoder / Decoder / EncoderDecoder / RetNet).

Parity with reference ``torchscale/architecture/config.py``: the same field
surface and the same ``postprocessing()`` invariants (deepnorm vs subln
exclusivity, xmoe implications). Two deliberate fixes over the reference:

- stringified ``segment_length`` / ``dilated_ratio`` are parsed with
  ``ast.literal_eval`` instead of ``eval`` (the reference ``eval()``s user
  strings, ``config.py:71-73``);
- configs are dataclasses with ``override()`` and ``asdict`` support rather
  than kwargs-bags, so unknown keys fail loudly unless passed through
  ``extras``.
"""

from __future__ import annotations

import ast
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

IntList = Union[None, str, List[int]]


def _parse_int_list(value: IntList) -> Optional[List[int]]:
    if value is None or value == "":
        return None
    if isinstance(value, str):
        parsed = ast.literal_eval(value)
    else:
        parsed = value
    return [int(x) for x in parsed]


@dataclass
class _MoEFieldsMixin:
    moe_freq: int = 0
    moe_top1_expert: bool = False
    moe_expert_count: int = 0
    moe_gating_use_fp32: bool = True
    moe_eval_capacity_token_fraction: float = 0.25
    moe_second_expert_policy: str = "random"
    moe_normalize_gate_prob_before_dropping: bool = False
    use_xmoe: bool = False


def _shared_postprocess(cfg) -> None:
    cfg.segment_length = _parse_int_list(getattr(cfg, "segment_length", None))
    cfg.dilated_ratio = _parse_int_list(getattr(cfg, "dilated_ratio", None))
    if cfg.deepnorm:
        cfg.subln = False
        if hasattr(cfg, "encoder_normalize_before"):
            cfg.encoder_normalize_before = False
        if hasattr(cfg, "decoder_normalize_before"):
            cfg.decoder_normalize_before = False
    if cfg.subln:
        cfg.deepnorm = False
        if hasattr(cfg, "encoder_normalize_before"):
            cfg.encoder_normalize_before = True
        if hasattr(cfg, "decoder_normalize_before"):
            cfg.decoder_normalize_before = True
    if cfg.use_xmoe:
        cfg.moe_normalize_gate_prob_before_dropping = True
        cfg.moe_second_expert_policy = "random"
        assert cfg.moe_freq > 0 and cfg.moe_expert_count > 0


class _ConfigBase:
    def override(self, args: Any) -> None:
        """Overwrite fields from an argparse-like namespace (non-None only)."""
        for f in dataclasses.fields(self):
            value = getattr(args, f.name, None)
            if value is not None:
                setattr(self, f.name, value)
        self.postprocessing()

    def asdict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "_ConfigBase":
        names = {f.name for f in dataclasses.fields(cls)}
        known = {k: v for k, v in d.items() if k in names}
        extras = {k: v for k, v in d.items() if k not in names}
        cfg = cls(**known)
        # parity with the reference kwargs-bag: unknown keys (e.g. the dead
        # 'block_shift' in the LongNet registry) are tolerated but recorded
        cfg.extras.update(extras)
        return cfg


@dataclass
class EncoderConfig(_ConfigBase, _MoEFieldsMixin):
    encoder_embed_dim: int = 768
    encoder_attention_heads: int = 12
    encoder_ffn_embed_dim: int = 3072
    encoder_layers: int = 12
    encoder_normalize_before: bool = True
    normalize_output: bool = True
    activation_fn: str = "gelu"
    dropout: float = 0.0
    drop_path_rate: float = 0.0
    attention_dropout: float = 0.0
    activation_dropout: float = 0.0
    no_scale_embedding: bool = True
    layernorm_embedding: bool = False
    rel_pos_buckets: int = 0
    max_rel_pos: int = 0
    deepnorm: bool = False
    subln: bool = True
    bert_init: bool = False
    multiway: bool = False
    share_encoder_input_output_embed: bool = False
    max_source_positions: int = 1024
    no_output_layer: bool = False
    layernorm_eps: float = 1e-5
    vocab_size: int = -1
    img_size: int = 224
    patch_size: int = 16
    in_chans: int = 3
    checkpoint_activations: bool = False
    fsdp: bool = False
    ddp_rank: int = 0
    xpos_rel_pos: bool = False
    xpos_scale_base: int = 512
    flash_attention: bool = False
    segment_length: IntList = None
    dilated_ratio: IntList = None
    seq_parallel: bool = False
    extras: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.postprocessing()

    def postprocessing(self):
        _shared_postprocess(self)


@dataclass
class DecoderConfig(_ConfigBase, _MoEFieldsMixin):
    decoder_embed_dim: int = 768
    decoder_attention_heads: int = 12
    decoder_ffn_embed_dim: int = 3072
    decoder_layers: int = 12
    decoder_normalize_before: bool = True
    activation_fn: str = "gelu"
    dropout: float = 0.0
    drop_path_rate: float = 0.0
    attention_dropout: float = 0.0
    activation_dropout: float = 0.0
    no_scale_embedding: bool = True
    layernorm_embedding: bool = False
    rel_pos_buckets: int = 0
    max_rel_pos: int = 0
    deepnorm: bool = False
    subln: bool = True
    bert_init: bool = False
    multiway: bool = False
    share_decoder_input_output_embed: bool = False
    max_target_positions: int = 1024
    no_output_layer: bool = False
    layernorm_eps: float = 1e-5
    vocab_size: int = -1
    checkpoint_activations: bool = False
    fsdp: bool = False
    ddp_rank: int = 0
    xpos_rel_pos: bool = False
    xpos_scale_base: int = 512
    flash_attention: bool = False
    segment_length: IntList = None
    dilated_ratio: IntList = None
    seq_parallel: bool = False
    extras: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.postprocessing()

    def postprocessing(self):
        _shared_postprocess(self)


@dataclass
class EncoderDecoderConfig(_ConfigBase, _MoEFieldsMixin):
    encoder_embed_dim: int = 768
    encoder_attention_heads: int = 12
    encoder_ffn_embed_dim: int = 3072
    encoder_layers: int = 12
    encoder_normalize_before: bool = True
    normalize_output: bool = True
    decoder_embed_dim: int = 768
    decoder_attention_heads: int = 12
    decoder_ffn_embed_dim: int = 3072
    decoder_layers: int = 12
    decoder_normalize_before: bool = True
    activation_fn: str = "gelu"
    dropout: float = 0.0
    drop_path_rate: float = 0.0
    attention_dropout: float = 0.0
    activation_dropout: float = 0.0
    no_scale_embedding: bool = True
    layernorm_embedding: bool = False
    rel_pos_buckets: int = 0
    max_rel_pos: int = 0
    deepnorm: bool = False
    subln: bool = True
    bert_init: bool = False
    multiway: bool = False
    share_all_embeddings: bool = False
    share_decoder_input_output_embed: bool = False
    max_source_positions: int = 1024
    max_target_positions: int = 1024
    no_output_layer: bool = False
    layernorm_eps: float = 1e-5
    vocab_size: int = -1
    checkpoint_activations: bool = False
    fsdp: bool = False
    ddp_rank: int = 0
    xpos_rel_pos: bool = False
    xpos_scale_base: int = 512
    flash_attention: bool = False
    segment_length: IntList = None
    dilated_ratio: IntList = None
    seq_parallel: bool = False
    extras: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.postprocessing()

    def postprocessing(self):
        _shared_postprocess(self)


@dataclass
class RetNetConfig(_ConfigBase, _MoEFieldsMixin):
    decoder_embed_dim: int = 768
    decoder_value_embed_dim: int = 1280
    decoder_retention_heads: int = 3
    decoder_ffn_embed_dim: int = 1280
    decoder_layers: int = 12
    decoder_normalize_before: bool = True
    activation_fn: str = "gelu"
    dropout: float = 0.0
    drop_path_rate: float = 0.0
    activation_dropout: float = 0.0
    no_scale_embedding: bool = True
    layernorm_embedding: bool = False
    rel_pos_buckets: int = 0
    max_rel_pos: int = 0
    deepnorm: bool = False
    subln: bool = True
    multiway: bool = False
    share_decoder_input_output_embed: bool = False
    max_target_positions: int = 1024
    no_output_layer: bool = False
    layernorm_eps: float = 1e-6
    chunkwise_recurrent: bool = False
    recurrent_chunk_size: int = 512
    vocab_size: int = -1
    checkpoint_activations: bool = False
    fsdp: bool = False
    ddp_rank: int = 0
    xpos_rel_pos: bool = False
    xpos_scale_base: int = 512
    extras: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.postprocessing()

    def postprocessing(self):
        if self.deepnorm:
            self.subln = False
            self.decoder_normalize_before = False
        if self.subln:
            self.deepnorm = False
            self.decoder_normalize_before = True
        if self.use_xmoe:
            self.moe_normalize_gate_prob_before_dropping = True
            self.moe_second_expert_policy = "random"
            assert self.moe_freq > 0 and self.moe_expert_count > 0
