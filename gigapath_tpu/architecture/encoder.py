"""Transformer encoder stack (pre/post-LN, DropPath, deepnorm, MoE hooks).

Parity with reference ``torchscale/architecture/encoder.py``: EncoderLayer is
self-attn + FFN-or-MoE with sub-LN/deepnorm variants and per-depth DropPath;
Encoder assembles the stack with embed scaling, optional text embedding /
output projection, relative position bias, and per-layer activation
checkpointing. TPU mapping:

- fairscale ``checkpoint_wrapper`` -> ``flax.linen.remat`` per layer;
- fairscale FSDP ``wrap`` -> parameter sharding is annotated at the pjit
  level (:mod:`gigapath_tpu.parallel.sharding`), no module wrapper needed;
- apex FusedLayerNorm -> ``nn.LayerNorm`` (XLA fuses it);
- the sub-LN / deepnorm post-init weight scaling is a param-tree transform
  (:func:`gigapath_tpu.architecture.init.apply_init_scaling`) applied by the
  factories, since flax init is functional.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.architecture.config import EncoderConfig
from gigapath_tpu.ops.attention import MultiheadAttention
from gigapath_tpu.ops.droppath import DropPath
from gigapath_tpu.ops.feedforward import FeedForwardNetwork
from gigapath_tpu.ops.relative_position_bias import RelativePositionBias


class EncoderLayer(nn.Module):
    """One encoder block. ``build_self_attention`` is the subclass hook the
    LongNet layer overrides to swap in dilated attention (parity with
    reference ``EncoderLayer.build_self_attention:102``)."""

    args: EncoderConfig
    depth: int
    is_moe_layer: bool = False
    is_encoder_decoder: bool = False
    dtype: Any = None

    def build_self_attention(self) -> nn.Module:
        return MultiheadAttention(
            embed_dim=self.args.encoder_embed_dim,
            num_heads=self.args.encoder_attention_heads,
            dropout=self.args.attention_dropout,
            self_attention=True,
            subln=self.args.subln,
            layernorm_eps=self.args.layernorm_eps,
            xpos_rel_pos=self.args.xpos_rel_pos,
            xpos_scale_base=self.args.xpos_scale_base,
            multiway=self.args.multiway,
            dtype=self.dtype,
            name="self_attn",
        )

    @property
    def alpha(self) -> float:
        if not self.args.deepnorm:
            return 1.0
        if self.is_encoder_decoder:
            return (
                math.pow(
                    math.pow(self.args.encoder_layers, 4) * getattr(self.args, "decoder_layers", 1),
                    0.0625,
                )
                * 0.81
            )
        return math.pow(2.0 * self.args.encoder_layers, 0.25)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        encoder_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        rel_pos: Optional[jnp.ndarray] = None,
        multiway_split_position: int = -1,
        deterministic: bool = True,
    ):
        args = self.args
        split = multiway_split_position
        from gigapath_tpu.ops.multiway import maybe_multiway, multiway_layernorm

        def ln(name):
            fn = multiway_layernorm(
                args.multiway, name, epsilon=args.layernorm_eps, dtype=self.dtype
            )
            return lambda x: fn(x, split_position=split)
        if args.drop_path_rate > 0:
            prob = float(np.linspace(0, args.drop_path_rate, args.encoder_layers)[self.depth])
            drop_path = DropPath(prob)
        else:
            drop_path = None
        dropout = nn.Dropout(args.dropout)

        if attn_mask is not None:
            attn_mask = jnp.where(attn_mask.astype(bool), -1e8, 0.0)

        residual = x
        if args.encoder_normalize_before:
            x = ln("self_attn_layer_norm")(x)
        x = self.build_self_attention()(
            x,
            x,
            x,
            key_padding_mask=encoder_padding_mask,
            attn_mask=attn_mask,
            rel_pos=rel_pos,
            multiway_split_position=split,
            deterministic=deterministic,
        )
        x = dropout(x, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.encoder_normalize_before:
            x = ln("self_attn_layer_norm")(x)

        residual = x
        if args.encoder_normalize_before:
            x = ln("final_layer_norm")(x)
        if not self.is_moe_layer:
            make_ffn = lambda name: FeedForwardNetwork(  # noqa: E731
                embed_dim=args.encoder_embed_dim,
                ffn_dim=args.encoder_ffn_embed_dim,
                activation_fn=args.activation_fn,
                dropout=args.dropout,
                activation_dropout=args.activation_dropout,
                layernorm_eps=args.layernorm_eps,
                subln=args.subln,
                dtype=self.dtype,
                name=name,
            )
            x = maybe_multiway(args.multiway, make_ffn, "ffn")(
                x, deterministic, split_position=split
            )
            l_aux = None
        else:
            try:
                from gigapath_tpu.ops.moe.moe_layer import MOELayer
            except ImportError as e:  # pragma: no cover
                raise NotImplementedError(
                    "MoE layers require gigapath_tpu.ops.moe (not built yet)"
                ) from e
            # padding mask forwarded so padded tokens neither claim expert
            # capacity nor bias the balance loss (the reference drops it here)
            x, l_aux = MOELayer.from_config(
                args, prefix="encoder", dtype=self.dtype, name="moe_layer"
            )(x, encoder_padding_mask, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.encoder_normalize_before:
            x = ln("final_layer_norm")(x)
        return x, l_aux


class Encoder(nn.Module):
    """Encoder stack returning the reference's output dict
    (``encoder_out`` / ``encoder_states`` / ``l_aux`` ...,
    ``architecture/encoder.py:393-399``)."""

    args: EncoderConfig
    is_encoder_decoder: bool = False
    dtype: Any = None

    layer_cls = EncoderLayer  # subclass hook (LongNetEncoder overrides)

    def build_encoder_layer(self, depth: int, is_moe_layer: bool) -> nn.Module:
        cls = type(self).layer_cls
        if self.args.checkpoint_activations:
            # flax counts the module itself as arg 0; multiway_split_position
            # (arg 5) and deterministic (arg 6) are both static
            cls = nn.remat(cls, static_argnums=(5, 6))
        return cls(
            args=self.args,
            depth=depth,
            is_moe_layer=is_moe_layer,
            is_encoder_decoder=self.is_encoder_decoder,
            dtype=self.dtype,
            name=f"layers_{depth}",
        )

    @nn.compact
    def __call__(
        self,
        src_tokens: Optional[jnp.ndarray] = None,
        *,
        token_embeddings: Optional[jnp.ndarray] = None,
        encoder_padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        return_all_hiddens: bool = False,
        features_only: bool = False,
        multiway_split_position: int = -1,
        positions: Optional[jnp.ndarray] = None,
        embed_positions: Optional[Any] = None,
        deterministic: bool = True,
    ) -> Dict[str, Any]:
        args = self.args
        assert src_tokens is not None or token_embeddings is not None

        if token_embeddings is None:
            token_embeddings = nn.Embed(
                args.vocab_size,
                args.encoder_embed_dim,
                dtype=self.dtype,
                name="embed_tokens",
            )(src_tokens)

        # encoder_padding_mask stays None when absent (the reference
        # materializes a zeros mask; a traced all-False mask would push
        # DilatedAttention off the static Pallas path for every unmasked
        # call, so None is load-bearing here)

        embed_scale = 1.0 if args.no_scale_embedding else math.sqrt(args.encoder_embed_dim)
        x = embed = embed_scale * token_embeddings
        if embed_positions is not None:
            # positional module injected by the model layer (BEiT3 passes a
            # multiway pair of learned tables; reference encoder.py:347-349)
            x = x + embed_positions(x, positions, multiway_split_position)
        if args.layernorm_embedding:
            from gigapath_tpu.ops.multiway import multiway_layernorm

            x = multiway_layernorm(
                args.multiway,
                "layernorm_embedding",
                epsilon=args.layernorm_eps,
                dtype=self.dtype,
            )(x, split_position=multiway_split_position)
        x = nn.Dropout(args.dropout)(x, deterministic=deterministic)
        if encoder_padding_mask is not None:
            x = jnp.where(encoder_padding_mask[..., None], 0, x)

        rel_pos_bias = None
        if args.rel_pos_buckets > 0 and args.max_rel_pos > 0:
            rel_pos_bias = RelativePositionBias(
                num_buckets=args.rel_pos_buckets,
                max_distance=args.max_rel_pos,
                n_heads=args.encoder_attention_heads,
                name="relative_position",
            )(x.shape[0], x.shape[1], x.shape[1])

        encoder_states = []
        if return_all_hiddens:
            encoder_states.append(x)

        l_aux = []
        moe_freq = args.moe_freq
        for i in range(args.encoder_layers):
            is_moe_layer = moe_freq != 0 and (i + 1) % moe_freq == 0
            x, l_aux_i = self.build_encoder_layer(i, is_moe_layer)(
                x,
                encoder_padding_mask,
                attn_mask,
                rel_pos_bias,
                multiway_split_position,
                deterministic,
            )
            if return_all_hiddens:
                encoder_states.append(x)
            l_aux.append(l_aux_i)

        moe_losses = [l for l in l_aux if l is not None]
        if moe_losses:
            # surface the balance loss to training loops that only see the
            # model output (LongNetViT drops the dict): collect with
            # apply(..., mutable=["intermediates"]) and add
            # moe_aux_loss_weight * sum to the task loss
            self.sow("intermediates", "moe_l_aux", sum(moe_losses))

        if args.encoder_normalize_before and args.normalize_output:
            from gigapath_tpu.ops.multiway import multiway_layernorm

            x = multiway_layernorm(
                args.multiway,
                "layer_norm",
                epsilon=args.layernorm_eps,
                dtype=self.dtype,
            )(x, split_position=multiway_split_position)

        if not features_only and not args.no_output_layer and args.vocab_size > 0:
            x = nn.Dense(
                args.vocab_size,
                use_bias=False,
                dtype=self.dtype,
                kernel_init=nn.initializers.normal(args.encoder_embed_dim**-0.5),
                name="output_projection",
            )(x)

        return {
            "encoder_out": x,
            "encoder_embedding": embed,
            "encoder_padding_mask": encoder_padding_mask,
            "encoder_states": encoder_states,
            "l_aux": l_aux,
        }
