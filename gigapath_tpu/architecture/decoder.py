"""Transformer decoder stack (causal self-attn, cross-attn, MoE, KV cache).

Parity with reference ``torchscale/architecture/decoder.py``: DecoderLayer is
causal self-attention + optional encoder cross-attention + FFN-or-MoE with
pre/post-LN, deepnorm residual scaling and per-depth DropPath
(``decoder.py:23-207``); Decoder assembles embedding scale / positions /
layernorm-embedding, the layer stack, relative-position biases (self and
cross), and the output projection with optional input/output embedding
sharing (``decoder.py:210-481``). TPU mapping:

- the materialized ``-inf`` triangle (``decoder.py:434-441``) never exists:
  causal masking is a flag on the fused attention op (the reference builds
  it only when *not* using flash attention — the flag path here is the
  flash path made default);
- fairseq-style ``incremental_state`` dicts become the flax ``cache``
  collection: ``decode=True`` + ``mutable=["cache"]`` runs single-token
  steps against a static-shape KV cache
  (:class:`gigapath_tpu.ops.attention.MultiheadAttention`);
- fairscale checkpoint/FSDP wrapping -> ``nn.remat`` per layer + pjit
  sharding rules.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from gigapath_tpu.architecture.config import DecoderConfig
from gigapath_tpu.ops.attention import MultiheadAttention
from gigapath_tpu.ops.droppath import DropPath
from gigapath_tpu.ops.feedforward import FeedForwardNetwork
from gigapath_tpu.ops.relative_position_bias import RelativePositionBias


class DecoderLayer(nn.Module):
    """One decoder block (reference ``DecoderLayer:23``)."""

    args: DecoderConfig
    depth: int
    is_moe_layer: bool = False
    is_encoder_decoder: bool = False
    dtype: Any = None

    def build_self_attention(self) -> nn.Module:
        return MultiheadAttention(
            embed_dim=self.args.decoder_embed_dim,
            num_heads=self.args.decoder_attention_heads,
            dropout=self.args.attention_dropout,
            self_attention=True,
            subln=self.args.subln,
            layernorm_eps=self.args.layernorm_eps,
            xpos_rel_pos=self.args.xpos_rel_pos,
            xpos_scale_base=self.args.xpos_scale_base,
            dtype=self.dtype,
            name="self_attn",
        )

    def build_encoder_attention(self) -> nn.Module:
        return MultiheadAttention(
            embed_dim=self.args.decoder_embed_dim,
            num_heads=self.args.decoder_attention_heads,
            dropout=self.args.attention_dropout,
            self_attention=False,
            encoder_decoder_attention=True,
            subln=self.args.subln,
            layernorm_eps=self.args.layernorm_eps,
            dtype=self.dtype,
            name="encoder_attn",
        )

    @property
    def alpha(self) -> float:
        if not self.args.deepnorm:
            return 1.0
        if self.is_encoder_decoder:
            return math.pow(3.0 * self.args.decoder_layers, 0.25)
        return math.pow(2.0 * self.args.decoder_layers, 0.25)

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        encoder_out: Optional[jnp.ndarray] = None,
        encoder_padding_mask: Optional[jnp.ndarray] = None,
        self_attn_padding_mask: Optional[jnp.ndarray] = None,
        self_attn_rel_pos: Optional[jnp.ndarray] = None,
        cross_attn_rel_pos: Optional[jnp.ndarray] = None,
        decode: bool = False,
        deterministic: bool = True,
    ):
        args = self.args
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=args.layernorm_eps, dtype=self.dtype, name=name
        )
        if args.drop_path_rate > 0:
            prob = float(
                np.linspace(0, args.drop_path_rate, args.decoder_layers)[self.depth]
            )
            drop_path = DropPath(prob)
        else:
            drop_path = None
        dropout = nn.Dropout(args.dropout)

        residual = x
        if args.decoder_normalize_before:
            x = ln("self_attn_layer_norm")(x)
        x = self.build_self_attention()(
            x,
            x,
            x,
            key_padding_mask=self_attn_padding_mask,
            rel_pos=self_attn_rel_pos,
            is_causal=True,
            decode=decode,
            deterministic=deterministic,
        )
        x = dropout(x, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.decoder_normalize_before:
            x = ln("self_attn_layer_norm")(x)

        if self.is_encoder_decoder and encoder_out is not None:
            residual = x
            if args.decoder_normalize_before:
                x = ln("encoder_attn_layer_norm")(x)
            x = self.build_encoder_attention()(
                x,
                encoder_out,
                encoder_out,
                key_padding_mask=encoder_padding_mask,
                rel_pos=cross_attn_rel_pos,
                deterministic=deterministic,
            )
            x = dropout(x, deterministic=deterministic)
            if drop_path is not None:
                x = drop_path(x, deterministic=deterministic)
            x = residual * self.alpha + x
            if not args.decoder_normalize_before:
                x = ln("encoder_attn_layer_norm")(x)

        residual = x
        if args.decoder_normalize_before:
            x = ln("final_layer_norm")(x)
        if not self.is_moe_layer:
            x = FeedForwardNetwork(
                embed_dim=args.decoder_embed_dim,
                ffn_dim=args.decoder_ffn_embed_dim,
                activation_fn=args.activation_fn,
                dropout=args.dropout,
                activation_dropout=args.activation_dropout,
                layernorm_eps=args.layernorm_eps,
                subln=args.subln,
                dtype=self.dtype,
                name="ffn",
            )(x, deterministic=deterministic)
            l_aux = None
        else:
            from gigapath_tpu.ops.moe.moe_layer import MOELayer

            x, l_aux = MOELayer.from_config(
                args, prefix="decoder", dtype=self.dtype, name="moe_layer"
            )(x, self_attn_padding_mask, deterministic=deterministic)
        if drop_path is not None:
            x = drop_path(x, deterministic=deterministic)
        x = residual * self.alpha + x
        if not args.decoder_normalize_before:
            x = ln("final_layer_norm")(x)
        return x, l_aux


class Decoder(nn.Module):
    """Decoder stack returning ``(x, {"inner_states", "l_aux", "attn"})``
    (reference ``Decoder.forward:388-478``)."""

    args: DecoderConfig
    is_encoder_decoder: bool = False
    dtype: Any = None

    layer_cls = DecoderLayer  # subclass hook (LongNetDecoder overrides)

    def build_decoder_layer(self, depth: int, is_moe_layer: bool) -> nn.Module:
        cls = type(self).layer_cls
        if self.args.checkpoint_activations:
            # flax counts the module as arg 0 -> deterministic is arg 8
            cls = nn.remat(cls, static_argnums=(7, 8))
        return cls(
            args=self.args,
            depth=depth,
            is_moe_layer=is_moe_layer,
            is_encoder_decoder=self.is_encoder_decoder,
            dtype=self.dtype,
            name=f"layers_{depth}",
        )

    @nn.compact
    def __call__(
        self,
        prev_output_tokens: Optional[jnp.ndarray] = None,
        *,
        self_attn_padding_mask: Optional[jnp.ndarray] = None,
        encoder_out: Optional[Dict[str, Any]] = None,
        token_embeddings: Optional[jnp.ndarray] = None,
        features_only: bool = False,
        return_all_hiddens: bool = False,
        decode: bool = False,
        deterministic: bool = True,
    ) -> Dict[str, Any]:
        args = self.args
        assert prev_output_tokens is not None or token_embeddings is not None

        embed_tokens = None
        if args.vocab_size > 0:
            embed_tokens = nn.Embed(
                args.vocab_size,
                args.decoder_embed_dim,
                dtype=self.dtype,
                name="embed_tokens",
            )
        if token_embeddings is None:
            token_embeddings = embed_tokens(prev_output_tokens)

        embed_scale = (
            1.0 if args.no_scale_embedding else math.sqrt(args.decoder_embed_dim)
        )
        x = embed_scale * token_embeddings
        if args.layernorm_embedding:
            x = nn.LayerNorm(
                epsilon=args.layernorm_eps, dtype=self.dtype, name="layernorm_embedding"
            )(x)
        x = nn.Dropout(args.dropout)(x, deterministic=deterministic)

        B, slen = x.shape[:2]
        self_attn_rel_pos = None
        cross_attn_rel_pos = None
        if args.rel_pos_buckets > 0 and args.max_rel_pos > 0:
            self_attn_rel_pos = RelativePositionBias(
                num_buckets=args.rel_pos_buckets,
                max_distance=args.max_rel_pos,
                n_heads=args.decoder_attention_heads,
                bidirectional=False,
                name="self_attn_relative_position",
            )(B, slen, slen)
            if self.is_encoder_decoder and encoder_out is not None:
                klen = encoder_out["encoder_out"].shape[1]
                cross_attn_rel_pos = RelativePositionBias(
                    num_buckets=args.rel_pos_buckets,
                    max_distance=args.max_rel_pos,
                    n_heads=args.decoder_attention_heads,
                    bidirectional=False,
                    name="cross_attn_relative_position",
                )(B, slen, klen)

        inner_states = [x]
        l_aux = list(encoder_out.get("l_aux", [])) if encoder_out else []
        moe_freq = args.moe_freq
        for i in range(args.decoder_layers):
            is_moe_layer = moe_freq != 0 and (i + 1) % moe_freq == 0
            x, l_aux_i = self.build_decoder_layer(i, is_moe_layer)(
                x,
                encoder_out["encoder_out"] if encoder_out else None,
                encoder_out.get("encoder_padding_mask") if encoder_out else None,
                self_attn_padding_mask,
                self_attn_rel_pos,
                cross_attn_rel_pos,
                decode,
                deterministic,
            )
            l_aux.append(l_aux_i)
            inner_states.append(x)

        moe_losses = [l for l in l_aux if l is not None]
        if moe_losses:
            self.sow("intermediates", "moe_l_aux", sum(moe_losses))

        if args.decoder_normalize_before:
            x = nn.LayerNorm(
                epsilon=args.layernorm_eps, dtype=self.dtype, name="layer_norm"
            )(x)

        if not features_only and not args.no_output_layer and args.vocab_size > 0:
            if args.share_decoder_input_output_embed:
                x = embed_tokens.attend(x)
            else:
                x = nn.Dense(
                    args.vocab_size,
                    use_bias=False,
                    dtype=self.dtype,
                    kernel_init=nn.initializers.normal(
                        args.decoder_embed_dim**-0.5
                    ),
                    name="output_projection",
                )(x)

        return {
            "decoder_out": x,
            "inner_states": inner_states if return_all_hiddens else [x],
            "l_aux": l_aux,
            "attn": None,
        }
