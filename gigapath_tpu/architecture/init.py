"""Post-init parameter-tree transforms (BERT init, sub-LN/deepnorm scaling).

Flax initialization is functional, so the reference's in-place post-init
rescaling (``architecture/encoder.py:235-270``) becomes a pure function on
the param tree applied by the model factories:

- sub-LN: multiply ``fc1/fc2/out_proj/v_proj`` kernels by
  ``sqrt(log(2 * L))`` (encoder) / ``sqrt(log(3 * L_dec) * log(2 * L_enc) / 3)``
  (encoder-decoder);
- deepnorm: divide the same kernels by ``(8 * L) ** 0.25``.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax

_SCALED_LEAVES = ("fc1", "fc2", "out_proj", "v_proj")


def _scale_tree(params: Dict[str, Any], factor: float) -> Dict[str, Any]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def transform(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if any(n in _SCALED_LEAVES for n in names) and names[-1] == "kernel":
            return leaf * factor
        return leaf

    treedef = jax.tree_util.tree_structure(params)
    leaves = [transform(path, leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def subln_init_scale(num_layers: int, is_encoder_decoder: bool = False, decoder_layers: int = 0) -> float:
    if is_encoder_decoder:
        return math.sqrt(math.log(3 * decoder_layers) * math.log(2 * num_layers) / 3)
    return math.sqrt(math.log(num_layers * 2))


def deepnorm_init_scale(num_layers: int, is_encoder_decoder: bool = False, decoder_layers: int = 0) -> float:
    if is_encoder_decoder:
        return math.pow(math.pow(num_layers, 4) * decoder_layers, 0.0625) / 1.15
    return math.pow(8.0 * num_layers, 0.25)


def init_bert_params(
    params: Dict[str, Any], rng: "jax.Array", std: float = 0.02
) -> Dict[str, Any]:
    """BERT-style re-init on a flax param tree (reference
    ``architecture/utils.py:10-33``): every Dense/Embed kernel is redrawn
    from a truncated normal (std 0.02, +-2 std), biases keep zeros.
    Attention q/k/v kernels get the reference's extra ``1/sqrt(2)`` scale."""
    import jax.numpy as jnp
    from jax import random

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    keys = random.split(rng, len(flat))

    def redraw(path, leaf, key):
        names = [getattr(p, "key", str(p)) for p in path]
        if names[-1] in ("kernel", "embedding") and getattr(leaf, "ndim", 0) >= 2:
            scale = std
            if any(n in ("q_proj", "k_proj", "v_proj") for n in names):
                scale = std / math.sqrt(2)
            draw = random.truncated_normal(key, -2.0, 2.0, leaf.shape, jnp.float32)
            # the +-2-truncated unit normal has std 0.87962566; divide it out
            # so the delivered std is exactly `scale`
            return (draw * (scale / 0.87962566103423978)).astype(leaf.dtype)
        return leaf

    leaves = [redraw(path, leaf, k) for (path, leaf), k in zip(flat, keys)]
    return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), leaves)


def apply_init_scaling(
    params: Dict[str, Any],
    *,
    subln: bool,
    deepnorm: bool,
    num_layers: int,
    is_encoder_decoder: bool = False,
    decoder_layers: int = 0,
) -> Dict[str, Any]:
    """Apply the reference's post-init weight scaling to a flax param tree."""
    if subln:
        return _scale_tree(params, subln_init_scale(num_layers, is_encoder_decoder, decoder_layers))
    if deepnorm:
        return _scale_tree(params, 1.0 / deepnorm_init_scale(num_layers, is_encoder_decoder, decoder_layers))
    return params
