"""Post-init parameter-tree transforms (BERT init, sub-LN/deepnorm scaling).

Flax initialization is functional, so the reference's in-place post-init
rescaling (``architecture/encoder.py:235-270``) becomes a pure function on
the param tree applied by the model factories:

- sub-LN: multiply ``fc1/fc2/out_proj/v_proj`` kernels by
  ``sqrt(log(2 * L))`` (encoder) / ``sqrt(log(3 * L_dec) * log(2 * L_enc) / 3)``
  (encoder-decoder);
- deepnorm: divide the same kernels by ``(8 * L) ** 0.25``.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax

_SCALED_LEAVES = ("fc1", "fc2", "out_proj", "v_proj")


def _scale_tree(params: Dict[str, Any], factor: float) -> Dict[str, Any]:
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def transform(path, leaf):
        names = [getattr(p, "key", str(p)) for p in path]
        if any(n in _SCALED_LEAVES for n in names) and names[-1] == "kernel":
            return leaf * factor
        return leaf

    treedef = jax.tree_util.tree_structure(params)
    leaves = [transform(path, leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def subln_init_scale(num_layers: int, is_encoder_decoder: bool = False, decoder_layers: int = 0) -> float:
    if is_encoder_decoder:
        return math.sqrt(math.log(3 * decoder_layers) * math.log(2 * num_layers) / 3)
    return math.sqrt(math.log(num_layers * 2))


def deepnorm_init_scale(num_layers: int, is_encoder_decoder: bool = False, decoder_layers: int = 0) -> float:
    if is_encoder_decoder:
        return math.pow(math.pow(num_layers, 4) * decoder_layers, 0.0625) / 1.15
    return math.pow(8.0 * num_layers, 0.25)


def apply_init_scaling(
    params: Dict[str, Any],
    *,
    subln: bool,
    deepnorm: bool,
    num_layers: int,
    is_encoder_decoder: bool = False,
    decoder_layers: int = 0,
) -> Dict[str, Any]:
    """Apply the reference's post-init weight scaling to a flax param tree."""
    if subln:
        return _scale_tree(params, subln_init_scale(num_layers, is_encoder_decoder, decoder_layers))
    if deepnorm:
        return _scale_tree(params, 1.0 / deepnorm_init_scale(num_layers, is_encoder_decoder, decoder_layers))
    return params
