"""Device-time measurement that survives async/remote dispatch.

On this image the TPU is reached through a tunnel where
``block_until_ready`` returns before execution finishes and every host
fetch costs ~100 ms round-trip, so per-call wall timing is useless. The
robust recipe: run the op N times *inside one jitted fori_loop* with a
forced cross-iteration data dependency (so XLA cannot hoist the body), fetch
one scalar, and difference two loop counts to cancel the fixed round-trip
overhead.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def chained_seconds_per_iter(
    step: Callable[..., jnp.ndarray],
    x0: jnp.ndarray,
    *,
    args: Tuple = (),
    iters_low: int = 2,
    iters_high: int = 12,
    repeats: int = 2,
) -> Tuple[float, float]:
    """Median seconds/iter of ``step(carry, *args) -> carry``.

    Returns ``(sec_per_iter, overhead_sec)``. Pass model params and other
    large arrays via ``args`` — NOT by closing over them: closure constants
    get serialized into the (size-limited) remote-compile request.
    """

    def chain(x, extra, n):
        def body(_, carry):
            return step(carry, *extra)

        return jax.lax.fori_loop(0, n, body, x).sum()

    lo = jax.jit(lambda x, extra: chain(x, extra, iters_low))
    hi = jax.jit(lambda x, extra: chain(x, extra, iters_high))
    float(lo(x0, args))  # compile
    float(hi(x0, args))

    def timed(f):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(f(x0, args))
            best = min(best, time.perf_counter() - t0)  # gigalint: waive GL008 -- this IS the sanctioned fence: the float() scalar fetch syncs the chained fori_loop, and differencing two loop counts cancels the round-trip
        return best

    t_lo, t_hi = timed(lo), timed(hi)
    per_iter = (t_hi - t_lo) / (iters_high - iters_low)
    overhead = t_lo - iters_low * per_iter
    return max(per_iter, 1e-9), overhead
