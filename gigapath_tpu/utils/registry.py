"""Model registry: named architecture factories.

Replaces the reference's dependency on timm's global ``@register_model``
registry (``slide_encoder.py:255-270``) with a small explicit one.
"""

from __future__ import annotations

from typing import Callable, Dict, List

MODEL_REGISTRY: Dict[str, Callable] = {}


def register_model(fn: Callable) -> Callable:
    MODEL_REGISTRY[fn.__name__] = fn
    return fn


def create_model_from_registry(arch: str, **kwargs):
    if arch not in MODEL_REGISTRY:
        raise KeyError(f"unknown model arch {arch!r}; known: {sorted(MODEL_REGISTRY)}")
    return MODEL_REGISTRY[arch](**kwargs)


def list_models() -> List[str]:
    return sorted(MODEL_REGISTRY)
