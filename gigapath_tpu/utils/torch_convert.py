"""Torch-checkpoint -> flax param-tree conversion with non-strict reporting.

Counterpart of the reference's ``load_state_dict(strict=False)`` +
missing/unexpected key printout (``gigapath/slide_encoder.py:236-248``),
plus the actual tensor-layout translation a cross-framework load needs
(Linear kernels transpose, LayerNorm weight->scale).

torch is only needed to *read* ``.pth`` files (CPU); the converted tree is
pure numpy/jax and all model code is torch-free.
"""

from __future__ import annotations

import logging
import re
from typing import Any, Dict, List, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):
        return t.detach().cpu().float().numpy()
    return np.asarray(t)


def load_torch_state_dict(path: str) -> Dict[str, Any]:
    """Read a torch checkpoint file; unwraps the common ``{"model": ...}``."""
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "model" in state and all(
        hasattr(v, "shape") for v in state["model"].values()
    ):
        state = state["model"]
    if isinstance(state, dict) and "model_state_dict" in state:
        state = state["model_state_dict"]
    return state


def convert_torch_entry(key: str, value) -> Tuple[Tuple[str, ...], np.ndarray]:
    """Map one ``a.b.weight``-style torch key to a flax param path + array.

    Rules:
    - ``*.weight`` on a 2-D tensor -> ``(*, kernel)`` transposed (torch Linear
      stores [out, in], flax Dense [in, out]);
    - ``*.weight`` on a 1-D tensor -> ``(*, scale)`` (LayerNorm/RMSNorm);
    - ``*.weight`` on a 4-D tensor -> ``(*, kernel)`` in HWIO (conv patch
      embeds; torch stores OIHW);
    - ``*.bias`` -> ``(*, bias)``; everything else keeps its name
      (cls_token, pos_embed, ...).
    """
    parts = key.split(".")
    arr = _to_numpy(value)
    leaf = parts[-1]
    if leaf == "weight":
        if arr.ndim == 2:
            return tuple(parts[:-1] + ["kernel"]), arr.T
        if arr.ndim == 4:
            return tuple(parts[:-1] + ["kernel"]), arr.transpose(2, 3, 1, 0)
        return tuple(parts[:-1] + ["scale"]), arr
    if leaf == "bias":
        return tuple(parts[:-1] + ["bias"]), arr
    return tuple(parts), arr


def convert_state_dict(
    state_dict: Dict[str, Any], skip_prefixes: Tuple[str, ...] = ("pos_embed",)
) -> Dict[Tuple[str, ...], np.ndarray]:
    """Convert a full torch state dict to ``{flax path: array}``.

    ``pos_embed`` buffers are skipped by default: the TPU model computes
    sincos embeddings on the fly (:mod:`gigapath_tpu.ops.pos_embed`).
    """
    out = {}
    for key, value in state_dict.items():
        if any(key.startswith(p) for p in skip_prefixes):
            continue
        # torch ModuleList indexing `layers.0.` -> flax submodule `layers_0.`
        key = re.sub(r"\blayers\.(\d+)\b", r"layers_\1", key)
        # fairscale checkpoint_wrapper leaves a `_checkpoint_wrapped_module.`
        # segment in checkpoints saved with activation checkpointing on
        key = key.replace("_checkpoint_wrapped_module.", "")
        path, arr = convert_torch_entry(key, value)
        out[path] = arr
    return out


def _flatten(tree: Dict[str, Any], prefix=()) -> Dict[Tuple[str, ...], Any]:
    flat = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            flat.update(_flatten(v, prefix + (k,)))
        else:
            flat[prefix + (k,)] = v
    return flat


def _unflatten(flat: Dict[Tuple[str, ...], Any]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for path, v in flat.items():
        node = tree
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = v
    return tree


def merge_into_params(
    params: Dict[str, Any],
    converted: Dict[Tuple[str, ...], np.ndarray],
    *,
    strict: bool = False,
) -> Tuple[Dict[str, Any], List[str], List[str]]:
    """Non-strict merge: returns (new_params, missing_keys, unexpected_keys).

    Shape mismatches are treated as unexpected (reported, not loaded), which
    is the practical behavior of the reference's non-strict torch load.
    """
    flat = _flatten(params)
    missing = [".".join(p) for p in flat if p not in converted]
    unexpected = []
    new_flat = dict(flat)
    for path, arr in converted.items():
        if path not in flat:
            unexpected.append(".".join(path))
            continue
        if tuple(flat[path].shape) != tuple(arr.shape):
            unexpected.append(
                ".".join(path) + f" (shape {arr.shape} vs {tuple(flat[path].shape)})"
            )
            continue
        new_flat[path] = arr.astype(np.asarray(flat[path]).dtype)
    if strict and (missing or unexpected):
        raise ValueError(f"strict load failed; missing={missing}, unexpected={unexpected}")
    for k in missing:
        logger.warning("Missing %s", k)
    for k in unexpected:
        logger.warning("Unexpected %s", k)
    return _unflatten(new_flat), missing, unexpected
