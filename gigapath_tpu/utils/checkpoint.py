"""Checkpoint save/restore (Orbax).

Counterpart of the reference's ``torch.save(model.state_dict())`` checkpoints
(``finetune/training.py:207-214``, ``finetune/utils.py:348-350``) plus what
the reference lacks (VERDICT r1 #55): optimizer-state checkpoints and
kill-and-resume. Sharded arrays are handled natively by Orbax — on a mesh the
save/restore round-trips the sharding layout.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Dict[str, Any]) -> None:
    """Save a pytree state dict (e.g. {"params", "opt_state", "epoch"})."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    _checkpointer().save(path, state, force=True)


def restore_checkpoint(path: str, template: Optional[Dict[str, Any]] = None):
    """Restore a state dict; with ``template``, restores into its
    structure/dtypes (required for opt_state namedtuples)."""
    path = os.path.abspath(path)
    if template is not None:
        import orbax.checkpoint as ocp

        return _checkpointer().restore(
            path, restore_args=ocp.checkpoint_utils.construct_restore_args(template),
            item=template,
        )
    return _checkpointer().restore(path)


def checkpoint_exists(path: str) -> bool:
    return os.path.isdir(os.path.abspath(path))


class MonitorScore:
    """Best-score checkpoint monitor (reference ``Monitor_Score``,
    ``finetune/utils.py:327-350``): saves when the score improves."""

    def __init__(self):
        self.best_score = None

    def __call__(self, val_score: float, state: Dict[str, Any], ckpt_name: str) -> bool:
        if self.best_score is None or val_score > self.best_score:
            self.best_score = val_score
            save_checkpoint(ckpt_name, jax.device_get(state))
            return True
        return False
