"""Checkpoint save/restore (Orbax).

Counterpart of the reference's ``torch.save(model.state_dict())`` checkpoints
(``finetune/training.py:207-214``, ``finetune/utils.py:348-350``) plus what
the reference lacks (VERDICT r1 #55): optimizer-state checkpoints and
kill-and-resume. Sharded arrays are handled natively by Orbax — on a mesh the
save/restore round-trips the sharding layout.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Dict[str, Any]) -> None:
    """Save a pytree state dict (e.g. {"params", "opt_state", "epoch"})."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    _checkpointer().save(path, state, force=True)


def restore_checkpoint(path: str, template: Optional[Dict[str, Any]] = None):
    """Restore a state dict; with ``template``, restores into its
    structure/dtypes (required for opt_state namedtuples)."""
    path = os.path.abspath(path)
    if template is not None:
        import orbax.checkpoint as ocp

        return _checkpointer().restore(
            path, restore_args=ocp.checkpoint_utils.construct_restore_args(template),
            item=template,
        )
    return _checkpointer().restore(path)


def checkpoint_exists(path: str) -> bool:
    return os.path.isdir(os.path.abspath(path))


class MonitorScore:
    """Best-score checkpoint monitor (reference ``Monitor_Score``,
    ``finetune/utils.py:327-350``): saves when the score improves.

    The best score is persisted INSIDE the checkpoint state
    (``best_score`` key) AND in a tiny ``<ckpt>.best.json`` sidecar, so
    a resumed run re-arms the monitor instead of starting at None —
    without this, the first (possibly worse) epoch after a resume would
    overwrite the best checkpoint (PR-8 satellite;
    ``tests/test_resilience.py``). The sidecar is what
    :meth:`from_checkpoint` reads: re-arming is one small JSON read, not
    a full Orbax restore of the params pytree just to extract one
    scalar. The in-state copy stays as the durable fallback (older
    checkpoints, a lost sidecar)."""

    def __init__(self, best_score: Optional[float] = None):
        self.best_score = best_score

    @staticmethod
    def _sidecar(ckpt_name: str) -> str:
        return os.path.abspath(str(ckpt_name)) + ".best.json"

    @classmethod
    def from_checkpoint(cls, ckpt_name: str) -> "MonitorScore":
        """Re-arm from a previous run's best checkpoint: the sidecar
        first (O(1)), the checkpoint state as fallback (None — a fresh
        monitor — when both are missing, unreadable, or predate
        persistence)."""
        try:
            with open(cls._sidecar(ckpt_name), encoding="utf-8") as fh:
                return cls(float(json.load(fh)["best_score"]))
        except (OSError, ValueError, KeyError, TypeError):
            pass
        if not checkpoint_exists(ckpt_name):
            return cls()
        try:
            state = restore_checkpoint(ckpt_name)
            score = state.get("best_score") if isinstance(state, dict) else None
            return cls(None if score is None else float(np.asarray(score)))
        except Exception:
            return cls()

    def __call__(self, val_score: float, state: Dict[str, Any], ckpt_name: str) -> bool:
        if self.best_score is None or val_score > self.best_score:
            self.best_score = val_score
            state = dict(state)
            state["best_score"] = np.asarray(float(val_score))
            save_checkpoint(ckpt_name, jax.device_get(state))
            # atomic sidecar write AFTER the checkpoint lands: a crash
            # between the two leaves a stale sidecar pointing at the
            # previous best, never a best.json for a half-written save
            side = self._sidecar(ckpt_name)
            try:
                tmp = f"{side}.tmp-{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump({"best_score": float(val_score)}, fh)
                os.replace(tmp, side)
            except OSError:
                pass  # sidecar is an optimization; the state copy holds
            return True
        return False
