"""Profiling & telemetry hooks (thin shims over the obs layer).

Superset of the reference's instrumentation (SURVEY §5.1): the reference
records CPU wall-clock + CUDA events around each MoE all-to-all
(``xmoe/moe_layer.py:276-307``) and prints sec/it in the train loop; here
the implementations live in the obs subsystem and this module re-exports
the historical names:

- :func:`trace` / :func:`annotate` — ``jax.profiler`` passthroughs, now
  owned by :mod:`gigapath_tpu.obs.spans` (which also provides the
  nestable, event-emitting ``span`` context manager);
- :func:`compiled_flops` / :func:`compiled_memory` — XLA cost/memory
  analysis (the thop replacement), now owned by
  :mod:`gigapath_tpu.obs.ledger`, which additionally folds full
  ``compile_profile`` captures into the per-run perf ledger;
- :func:`collect_moe_metadata` surfaces the gating telemetry MoE layers
  sow (entropy, unused experts, balance fractions —
  ``xmoe/routing.py:53,72-87``) as a flat scalar dict — still defined
  here (it is host-side pytree flattening, not a compiled-artifact
  concern), shared with the in-graph ``gigapath_tpu.obs.telemetry`` twin.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

from gigapath_tpu.obs.ledger import (  # noqa: F401  (re-exported shims)
    compiled_flops,
    compiled_memory,
)
from gigapath_tpu.obs.spans import annotate, trace  # noqa: F401


def iter_moe_metadata(intermediates: Dict[str, Any]):
    """Yield ``("layer_path/metric", leaf)`` for every scalar sown under a
    ``moe_metadata`` collection. The ONE flattening shared by the host
    collector below and the in-graph ``gigapath_tpu.obs.telemetry``
    twin, so their key spaces cannot drift.

    Defensive on the edges (this feeds telemetry, it must never take a
    run down): empty intermediates -> nothing; a non-scalar leaf under
    ``moe_metadata`` (unexpected — gating stats are scalars by design) is
    skipped rather than silently reduced to a made-up number. The size
    check reads only the static shape, so it is trace-safe."""
    flat = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in flat:
        names = [getattr(p, "key", str(p)) for p in path]
        if "moe_metadata" in names:
            # path: (..., moe_metadata, <tuple idx>, <metric name>)
            metric = names[-1]
            layer = "/".join(n for n in names[: names.index("moe_metadata")])
            if int(np.prod(getattr(leaf, "shape", ()))) != 1:
                continue
            yield f"{layer}/{metric}", leaf


def collect_moe_metadata(intermediates: Dict[str, Any]) -> Dict[str, float]:
    """Flatten every sown ``moe_metadata`` dict into ``layer_path/metric``
    host floats. Collect with ``model.apply(..., mutable=["intermediates"])``."""
    return {
        key: float(np.asarray(leaf).reshape(()))
        for key, leaf in iter_moe_metadata(intermediates)
    }


def xla_op_totals(trace_dir: str) -> Dict[str, Dict[str, float]]:
    """Aggregate a captured xplane trace into per-op total microseconds.

    Returns ``{"ops": {...}, "async": {...}}`` — the 'XLA Ops' line (real
    per-op device time for THIS process; contention-independent) and the
    async line (overlap-capable DMA spans; double-counts overlap, use for
    orientation only). One implementation shared by the profile scripts.
    """
    import glob
    import os

    from jax.profiler import ProfileData

    traces = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    ops: Dict[str, float] = {}
    asyncs: Dict[str, float] = {}
    pd = ProfileData.from_file(traces[-1])
    for plane in pd.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "XLA Ops":
                for ev in line.events:
                    ops[ev.name] = ops.get(ev.name, 0.0) + ev.duration_ns / 1e3
            elif "Async" in line.name:
                for ev in line.events:
                    asyncs[ev.name] = asyncs.get(ev.name, 0.0) + ev.duration_ns / 1e3
    return {"ops": ops, "async": asyncs}
