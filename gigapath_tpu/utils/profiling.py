"""Profiling & telemetry hooks.

Superset of the reference's instrumentation (SURVEY §5.1): the reference
records CPU wall-clock + CUDA events around each MoE all-to-all
(``xmoe/moe_layer.py:276-307``) and prints sec/it in the train loop; here

- :func:`trace` wraps ``jax.profiler`` — one context manager captures a
  full XLA trace (collectives included, which covers the a2a timing the
  reference hand-rolls) viewable in TensorBoard/Perfetto;
- :func:`annotate` names host-side regions inside a trace;
- :func:`collect_moe_metadata` surfaces the gating telemetry MoE layers sow
  (entropy, unused experts, balance fractions — ``xmoe/routing.py:53,72-87``)
  as a flat scalar dict ready for ``log_writer``;
- :func:`compiled_flops` / :func:`compiled_memory` read XLA cost analysis
  (the thop replacement, reference ``finetune/training.py:14,53``).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture a device trace for the enclosed block:

    >>> with trace("/tmp/profile"):
    ...     step(params, batch)  # compiled work is recorded
    """
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host region inside a trace (``with annotate("collate"): ...``)."""
    return jax.profiler.TraceAnnotation(name)


def iter_moe_metadata(intermediates: Dict[str, Any]):
    """Yield ``("layer_path/metric", leaf)`` for every scalar sown under a
    ``moe_metadata`` collection. The ONE flattening shared by the host
    collector below and the in-graph ``gigapath_tpu.obs.telemetry``
    twin, so their key spaces cannot drift.

    Defensive on the edges (this feeds telemetry, it must never take a
    run down): empty intermediates -> nothing; a non-scalar leaf under
    ``moe_metadata`` (unexpected — gating stats are scalars by design) is
    skipped rather than silently reduced to a made-up number. The size
    check reads only the static shape, so it is trace-safe."""
    flat = jax.tree_util.tree_flatten_with_path(intermediates)[0]
    for path, leaf in flat:
        names = [getattr(p, "key", str(p)) for p in path]
        if "moe_metadata" in names:
            # path: (..., moe_metadata, <tuple idx>, <metric name>)
            metric = names[-1]
            layer = "/".join(n for n in names[: names.index("moe_metadata")])
            if int(np.prod(getattr(leaf, "shape", ()))) != 1:
                continue
            yield f"{layer}/{metric}", leaf


def collect_moe_metadata(intermediates: Dict[str, Any]) -> Dict[str, float]:
    """Flatten every sown ``moe_metadata`` dict into ``layer_path/metric``
    host floats. Collect with ``model.apply(..., mutable=["intermediates"])``."""
    return {
        key: float(np.asarray(leaf).reshape(()))
        for key, leaf in iter_moe_metadata(intermediates)
    }


def compiled_flops(fn, *args) -> Optional[float]:
    """FLOPs of the jitted computation, from XLA cost analysis."""
    try:
        analysis = jax.jit(fn).lower(*args).compile().cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return float(analysis.get("flops", float("nan")))
    except Exception:
        return None


def compiled_memory(fn, *args) -> Optional[Dict[str, float]]:
    """Peak/argument/output memory of the compiled computation (bytes)."""
    try:
        compiled = jax.jit(fn).lower(*args).compile()
        mem = compiled.memory_analysis()
        return {
            "temp_bytes": float(getattr(mem, "temp_size_in_bytes", float("nan"))),
            "argument_bytes": float(getattr(mem, "argument_size_in_bytes", float("nan"))),
            "output_bytes": float(getattr(mem, "output_size_in_bytes", float("nan"))),
        }
    except Exception:
        return None


def xla_op_totals(trace_dir: str) -> Dict[str, Dict[str, float]]:
    """Aggregate a captured xplane trace into per-op total microseconds.

    Returns ``{"ops": {...}, "async": {...}}`` — the 'XLA Ops' line (real
    per-op device time for THIS process; contention-independent) and the
    async line (overlap-capable DMA spans; double-counts overlap, use for
    orientation only). One implementation shared by the profile scripts.
    """
    import glob
    import os

    from jax.profiler import ProfileData

    traces = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    ops: Dict[str, float] = {}
    asyncs: Dict[str, float] = {}
    pd = ProfileData.from_file(traces[-1])
    for plane in pd.planes:
        if "TPU" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "XLA Ops":
                for ev in line.events:
                    ops[ev.name] = ops.get(ev.name, 0.0) + ev.duration_ns / 1e3
            elif "Async" in line.name:
                for ev in line.events:
                    asyncs[ev.name] = asyncs.get(ev.name, 0.0) + ev.duration_ns / 1e3
    return {"ops": ops, "async": asyncs}
