"""Fault-tolerance layer: deterministic chaos injection, hardened
checkpoints, in-graph non-finite guards (PR-8 tentpole).

The PR-6 anomaly engine *detects* and the flight recorder *records*;
this package *recovers* — and proves every recovery path against seeded
fault injection instead of luck:

- :mod:`gigapath_tpu.resilience.chaos` — ``GIGAPATH_CHAOS``-driven
  injectors (non-finite loss at step k, corrupted feature batch, loader
  failure/slowdown, SIGTERM at step k, corrupted checkpoint, poisoned
  serve request), parsed ONCE host-side at driver start
  (``get_chaos`` — the ``get_run_log`` discipline; GL001-clean);
- :mod:`gigapath_tpu.resilience.checkpoint` — ``ResilientCheckpointer``:
  atomic tmp-dir+rename saves, sha256 manifests verified on restore,
  keep-last-K rotation with a best pointer, full train-state snapshots,
  ``resume='auto'`` that falls back past corrupt checkpoints, and a
  SIGTERM-triggered emergency checkpoint chained through
  :mod:`gigapath_tpu.obs.flight`'s (single, GL011-sanctioned) handler;
- :mod:`gigapath_tpu.resilience.guard` — in-graph non-finite guard
  (``jnp.where`` zero-update skip-step; no retraces, byte-identical HLO
  when off) plus the host-side ``SkipStepMonitor`` that rolls back to
  the last checkpoint after M consecutive skips.

Recovery actions emit schema'd ``recovery`` events on the obs bus
(``scripts/obs_report.py`` renders them as ``== recovery ==``); obs off
constructs nothing. ``scripts/chaos_smoke.py`` is the one-command CPU
recovery checklist; ``tests/test_resilience.py`` pins the acceptance
(kill-and-resume bit-exact parity, corrupt-checkpoint fallback,
NaN-step skip, poisoned-serve-batch isolation).
"""

from gigapath_tpu.resilience.chaos import (
    ChaosError,
    ChaosInjector,
    NullChaos,
    get_chaos,
)
from gigapath_tpu.resilience.checkpoint import ResilientCheckpointer
from gigapath_tpu.resilience.guard import (
    SkipStepMonitor,
    guard_update,
    nonfinite_guard_enabled,
)

__all__ = [
    "ChaosError",
    "ChaosInjector",
    "NullChaos",
    "ResilientCheckpointer",
    "SkipStepMonitor",
    "get_chaos",
    "guard_update",
    "nonfinite_guard_enabled",
]
