"""Hardened checkpoints: atomic saves, verified restores, rotation,
emergency SIGTERM snapshots.

:mod:`gigapath_tpu.utils.checkpoint` serializes pytrees (Orbax); this
module makes those serializations survivable:

- **atomic**: every save lands in a ``.tmp-*`` directory and is renamed
  into place — a SIGKILL mid-write leaves a stale tmp dir, never a
  half-written "latest" checkpoint;
- **verified**: a ``manifest.json`` of per-file sha256 digests is
  written with each save and re-hashed on restore, so bit rot or a
  truncated copy is a detected failure, not silently-wrong weights;
- **rotated**: keep-last-K by step, with a ``best.json`` pointer that
  pins the best-scoring checkpoint outside the rotation window;
- **resumable**: :meth:`ResilientCheckpointer.restore_latest` (the
  ``--resume auto`` engine) scans newest-first and falls back past any
  corrupt/unreadable checkpoint, emitting an ``anomaly`` event
  (``detector="corrupt_checkpoint"``) per skip and a ``recovery``
  event (``action="resume"``) for the one it lands on;
- **preemption-safe**: :meth:`arm_sigterm_checkpoint` chains an
  emergency final save through :mod:`gigapath_tpu.obs.flight`'s single
  SIGTERM handler (the GL011-sanctioned ``signal.signal`` site), AFTER
  the flight dump and BEFORE process death.

Full train-state snapshots carry ``params`` / ``opt_state`` / ``step``
/ ``rng`` / the loader cursor / the ``MonitorScore`` best score —
everything kill-and-resume bit-exactness needs (pinned by
``tests/test_resilience.py``).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

from gigapath_tpu.resilience.chaos import NullChaos

_PREFIX = "ckpt-"
_STATE_SUBDIR = "state"
_MANIFEST = "manifest.json"
_BEST = "best.json"


def _sha256_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def _hash_tree(root: str) -> Dict[str, str]:
    """Relative path -> sha256 for every file under ``root`` (manifest
    excluded — it describes the tree, it is not part of it)."""
    out: Dict[str, str] = {}
    for dirpath, _, files in os.walk(root):
        for name in sorted(files):
            if dirpath == root and name == _MANIFEST:
                continue
            full = os.path.join(dirpath, name)
            out[os.path.relpath(full, root)] = _sha256_file(full)
    return out


class ResilientCheckpointer:
    """See module docstring. ``runlog=None`` emits nothing (obs off —
    the factories hand a ``NullRunLog`` whose events are no-ops)."""

    def __init__(self, directory: str, *, keep: int = 3, runlog=None,
                 chaos=None):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.keep = max(int(keep), 1)
        if runlog is None:
            from gigapath_tpu.obs.runlog import NullRunLog

            runlog = NullRunLog(driver="checkpoint", echo=False)
        self.runlog = runlog
        self.chaos = chaos if chaos is not None else NullChaos()
        self._sigterm_cb: Optional[Callable] = None

    # -- naming -----------------------------------------------------------
    def _name(self, step: int) -> str:
        return f"{_PREFIX}{int(step):08d}"

    def path_for(self, step: int) -> str:
        return os.path.join(self.dir, self._name(step))

    def checkpoints(self) -> List[Tuple[int, str]]:
        """[(step, path)] ascending by step; corrupt ones included —
        ``restore_latest`` verifies, listing does not."""
        out = []
        for name in os.listdir(self.dir):
            if not name.startswith(_PREFIX):
                continue
            try:
                step = int(name[len(_PREFIX):])
            except ValueError:
                continue
            full = os.path.join(self.dir, name)
            if os.path.isdir(full):
                out.append((step, full))
        return sorted(out)

    # -- save -------------------------------------------------------------
    def save(self, step: int, state: Dict[str, Any]) -> str:
        """Atomic verified save of a (host or device) state pytree."""
        import jax

        from gigapath_tpu.utils.checkpoint import save_checkpoint

        final = self.path_for(step)
        # a valid checkpoint for this exact step already on disk (a
        # SIGTERM emergency save racing the periodic save it just made):
        # keep it — the step's post-update state is deterministic, and
        # rmtree-before-rename here would destroy the only valid latest
        # checkpoint in the window before the new rename commits
        if os.path.isdir(final) and self.verify(final):
            return final
        tmp = os.path.join(self.dir, f".tmp-{self._name(step)}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        save_checkpoint(os.path.join(tmp, _STATE_SUBDIR),
                        jax.device_get(state))
        manifest = {
            "step": int(step),
            "files": _hash_tree(tmp),
        }
        with open(os.path.join(tmp, _MANIFEST), "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
        # the rename is the commit: readers either see the old world or
        # the complete new checkpoint, never a partial write (only a
        # corrupt/absent ``final`` ever gets replaced — see above)
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        self._rotate()
        return final

    def _rotate(self) -> None:
        """Keep the newest ``keep`` checkpoints plus the best-pinned one."""
        ckpts = self.checkpoints()
        if len(ckpts) <= self.keep:
            return
        best = self.best()
        pinned = best["name"] if best else None
        for step, path in ckpts[: len(ckpts) - self.keep]:
            if os.path.basename(path) == pinned:
                continue
            shutil.rmtree(path, ignore_errors=True)

    # -- best pointer -----------------------------------------------------
    def mark_best(self, step: int, score: float) -> None:
        tmp = os.path.join(self.dir, f".tmp-{_BEST}-{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"name": self._name(step), "score": float(score)}, fh)
        os.replace(tmp, os.path.join(self.dir, _BEST))

    def best(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, _BEST), encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    # -- verify / restore -------------------------------------------------
    def verify(self, path: str) -> bool:
        """Re-hash a checkpoint against its manifest. False on any
        missing/mismatched/extra-manifest condition — never raises."""
        try:
            with open(os.path.join(path, _MANIFEST), encoding="utf-8") as fh:
                manifest = json.load(fh)
            expected = manifest["files"]
        except (OSError, ValueError, KeyError):
            return False
        try:
            return _hash_tree(path) == expected
        except OSError:
            return False

    def restore(self, path: str, template: Optional[Dict[str, Any]] = None):
        import jax

        from gigapath_tpu.utils.checkpoint import restore_checkpoint

        state = restore_checkpoint(os.path.join(path, _STATE_SUBDIR), template)
        # device_put: numpy leaves and jax Arrays land in DIFFERENT pjit
        # cache entries, so feeding the restored (numpy) state straight
        # into the jitted step would retrace every shape once after a
        # resume — restored state must look exactly like live state
        return jax.device_put(state)

    def restore_latest(
        self, template: Optional[Dict[str, Any]] = None, *,
        emit_resume: bool = True,
    ) -> Optional[Tuple[Dict[str, Any], int]]:
        """The ``--resume auto`` scan: newest valid checkpoint wins; a
        corrupt one is skipped with an ``anomaly`` event and the scan
        falls back to the previous. None when nothing valid exists.
        ``emit_resume=False`` for callers that are not resuming (the
        guard's rollback reuses this scan and reports its OWN recovery
        action — a rollback must not be telemetried as a resume)."""
        candidates = list(reversed(self.checkpoints()))
        if candidates and self.chaos and self.chaos.corrupts_checkpoint():
            corrupted = self.chaos.corrupt_checkpoint(candidates[0][1])
            self.runlog.echo(
                f"[chaos] corrupted latest checkpoint file: {corrupted}"
            )
        fallbacks = 0
        for step, path in candidates:
            if not self.verify(path):
                self.runlog.event(
                    "anomaly", detector="corrupt_checkpoint", step=step,
                    path=path, value=None,
                )
                self.runlog.echo(
                    f"[resume] checkpoint {os.path.basename(path)} failed "
                    "manifest verification; falling back"
                )
                fallbacks += 1
                continue
            try:
                state = self.restore(path, template)
            except Exception as e:
                self.runlog.event(
                    "anomaly", detector="corrupt_checkpoint", step=step,
                    path=path, error=f"{type(e).__name__}: {e}",
                )
                fallbacks += 1
                continue
            if emit_resume:
                self.runlog.event(
                    "recovery", action="resume", step=step, path=path,
                    fallbacks=fallbacks,
                )
            return state, step
        return None

    # -- SIGTERM emergency checkpoint -------------------------------------
    def arm_sigterm_checkpoint(
        self, state_provider: Callable[[], Optional[Tuple[int, Dict[str, Any]]]]
    ) -> bool:
        """Chain a final checkpoint through the flight recorder's SIGTERM
        handler: ``state_provider() -> (step, state) | None`` supplies
        the last COMPLETED step's state (the driver updates it each
        step). Runs after the flight dump; the process still dies after
        (the supervisor's kill is honored — resumption is the next
        process's job)."""
        from gigapath_tpu.obs.flight import register_signal_callback

        def _emergency(signum) -> bool:
            try:
                provided = state_provider()
                if provided is not None:
                    step, state = provided
                    path = self.save(step, state)
                    # signal-safe obs: the handler may have interrupted
                    # the main thread INSIDE runlog.event() holding its
                    # write lock — the *_from_signal paths try-acquire
                    # and drop on contention instead of self-deadlocking
                    self.runlog.event_from_signal(
                        "recovery", action="emergency_checkpoint",
                        step=step, path=path, signal=int(signum),
                    )
                    self.runlog.echo_from_signal(
                        f"[sigterm] emergency checkpoint at step {step} "
                        f"-> {path}"
                    )
            except Exception:  # a failed save must not mask the signal
                pass
            return False  # not a graceful claim: the process dies next

        self._sigterm_cb = _emergency
        return register_signal_callback(_emergency)

    def disarm(self) -> None:
        if self._sigterm_cb is not None:
            from gigapath_tpu.obs.flight import unregister_signal_callback

            unregister_signal_callback(self._sigterm_cb)
            self._sigterm_cb = None
