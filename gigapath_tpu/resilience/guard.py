"""In-graph non-finite guard: skip the update, keep the run.

One NaN batch (a corrupt shard, an fp overflow at a loss spike) poisons
``params`` forever — every later step multiplies garbage. The guard
makes the optimizer step conditional INSIDE the jitted program:

    new_params, new_opt = ... ordinary update ...
    (params, opt_state), skipped = guard_update(
        loss, grads, old=(params, opt_state), new=(new_params, new_opt))

``skipped`` is a device scalar (1.0 = the update was dropped because
loss or the global grad norm went non-finite); the per-leaf
``jnp.where`` select adds no retraces (same program every call) and no
host syncs (drivers read ``skipped`` at their existing sync points).
The guard is a HOST-side construction choice: drivers build the
guarded step only when :func:`nonfinite_guard_enabled` says so
(``GIGAPATH_NONFINITE_GUARD``, read once at driver start), so the
guard-off program is byte-identical HLO to the unguarded one — pinned
in ``tests/test_resilience.py``.

The host half, :class:`SkipStepMonitor`, counts consecutive skips: each
skip emits a ``recovery`` event (``action="skip_step"``) and tags the
step event ``nonfinite=True`` (the anomaly engine's ``nonfinite_step``
detector fires on that); after M consecutive skips it answers
``"rollback"`` and the driver restores the last valid checkpoint —
a persistently non-finite regime means the params are already garbage
and skipping forward cannot save them.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple


def nonfinite_guard_enabled() -> bool:
    """``GIGAPATH_NONFINITE_GUARD`` (host-side, read once at driver
    start): unset -> ON; ``''``/``'0'``/``'false'``/``'no'`` -> OFF.
    Off means the driver builds the unguarded step — byte-identical
    HLO to the pre-guard program."""
    from gigapath_tpu.obs.runlog import env_on_by_default

    return env_on_by_default("GIGAPATH_NONFINITE_GUARD")


def rollback_after() -> int:
    """``GIGAPATH_GUARD_ROLLBACK_AFTER`` (host-side, read once): M
    consecutive skipped steps before the monitor orders a rollback to
    the last checkpoint (default 3; 0 disables rollback)."""
    from gigapath_tpu.obs.runlog import env_number

    return max(int(env_number("GIGAPATH_GUARD_ROLLBACK_AFTER", 3)), 0)


def guard_update(loss, grads, old: Any, new: Any) -> Tuple[Any, Any]:
    """In-graph: ``(state, skipped)`` where ``state = new`` when loss AND
    the global grad norm are finite, else ``old`` (leafwise
    ``jnp.where`` — the zero-update skip-step). Call INSIDE the jitted
    step; ``old``/``new`` are matching pytrees (params, opt_state)."""
    import jax
    import jax.numpy as jnp

    gnorm = jnp.sqrt(sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        for leaf in jax.tree_util.tree_leaves(grads)
    ))
    ok = jnp.isfinite(jnp.asarray(loss, jnp.float32)) & jnp.isfinite(gnorm)
    guarded = jax.tree_util.tree_map(
        lambda n, o: jnp.where(ok, n, o), new, old
    )
    return guarded, (1.0 - ok.astype(jnp.float32))


class SkipStepMonitor:
    """Host-side skip accounting + rollback policy (module docstring)."""

    def __init__(self, runlog, *, rollback_after_skips: Optional[int] = None):
        self.runlog = runlog
        self.rollback_after = (
            rollback_after() if rollback_after_skips is None
            else max(int(rollback_after_skips), 0)
        )
        self.skip_count = 0
        self.rollback_count = 0
        self._consecutive = 0
        # run length of the CURRENT non-finite regime as of the last
        # observed skip (survives the reset a rollback order performs):
        # drivers put it on the step event so the anomaly engine's
        # nonfinite_step detector can report it
        self.last_consecutive = 0

    def observe(self, step: int, skipped: float) -> Optional[str]:
        """Feed one step's ``skipped`` scalar (host float, read at the
        driver's sync point). Returns ``"rollback"`` when the driver
        should restore the last checkpoint, else None."""
        if float(skipped) < 0.5:
            self._consecutive = 0
            return None
        self.skip_count += 1
        self._consecutive += 1
        self.last_consecutive = self._consecutive
        self.runlog.event(
            "recovery", action="skip_step", step=int(step),
            consecutive=self._consecutive,
        )
        self.runlog.echo(
            f"[guard] non-finite loss/grad at step {step}: update "
            f"skipped ({self._consecutive} consecutive)"
        )
        if self.rollback_after and self._consecutive >= self.rollback_after:
            self._consecutive = 0
            return "rollback"
        return None

    def rollback_performed(self) -> None:
        """The driver restored a checkpoint for an ordered rollback —
        ``rollback_count`` counts PERFORMED rollbacks, not orders (an
        order with no checkpoint to restore must not inflate the
        ``run_end`` accounting)."""
        self.rollback_count += 1

    def rollback_unavailable(self, step: int) -> None:
        """An ordered rollback found no valid checkpoint (the default
        ``checkpoint_every=0`` run): loudly surfaced — the params are
        likely garbage and nothing can restore them — instead of the
        order dissolving into a silent no-op."""
        self.runlog.event(
            "recovery", action="rollback_unavailable", step=int(step),
        )
        self.runlog.echo(
            f"[guard] rollback ordered at step {step} but no valid "
            "checkpoint exists (checkpoint_every=0?): params may be "
            "unrecoverable, continuing with skip-steps only"
        )
