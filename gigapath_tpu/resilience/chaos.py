"""Deterministic, seeded fault injection (``GIGAPATH_CHAOS``).

Every recovery path in this repo is proven against *injected* faults,
never against luck: the resilience tests and ``scripts/chaos_smoke.py``
set ``GIGAPATH_CHAOS`` and assert the recovery, so a regression in the
skip-step guard or the resume scan fails deterministically on CPU.

Spec grammar — comma-separated tokens, parsed ONCE host-side at driver
start (:func:`get_chaos`, the ``get_run_log`` discipline; never read at
trace time — GL001-clean because no injector is trace-reachable):

- ``nan_loss@K``      — poison the step-``K`` feature batch with NaNs so
  the loss goes non-finite (drives the in-graph guard);
- ``corrupt_batch@K`` — overwrite the step-``K`` feature batch with huge
  garbage (the corrupted-shard case: loss blows up to inf);
- ``sigterm@K``       — deliver a real ``SIGTERM`` to this process after
  step ``K`` completes (the preempted-worker case; lands in
  :mod:`gigapath_tpu.obs.flight`'s chained handler);
- ``fail_loader@I``   — the dataset read of sample index ``I`` raises
  (``xN`` suffix = fail the first N attempts: ``fail_loader@2x3``);
- ``slow_loader@I:S`` — the read of sample index ``I`` sleeps S seconds;
- ``corrupt_ckpt``    — flip bytes in the LATEST checkpoint before a
  ``resume='auto'`` scan (drives the fallback-past-corruption path);
- ``poison@ID``       — serving: any dispatched batch containing slide
  ``ID`` raises (drives poisoned-batch bisection);
- ``slow_dispatch@K:S`` — serving: dispatch ``K`` sleeps S seconds
  host-side inside the dispatch span (``K = *`` slows EVERY dispatch —
  the forced-slow run that proves the SLO burn detector fires);
- ``kill_worker@K``   — dist: this tile worker SIGKILLs itself after
  producing K chunks (the hard-death case: no goodbye, the lease just
  stops renewing — drives lease expiry -> ``worker_lost`` ->
  reassignment in :mod:`gigapath_tpu.dist`);
- ``kill_consumer@K`` — dist: the slide-stage consumer SIGKILLs itself
  after K delivered chunks (the consumer-crash case: its streaming fold
  state is gone unless checkpointed — drives the ``consumer_lost`` ->
  ``recovery action="consumer_resume"`` path);
- ``slow_worker@K:S`` — dist: sleep S seconds before producing chunk
  ``K`` (``K = *`` slows EVERY chunk — the straggler whose skew the
  per-rank span table must surface);
- ``drop_chunk@K``    — dist: the boundary channel swallows the FIRST
  send of chunk seq ``K`` (the lost-write case; the producer's
  retransmit timer heals it);
- ``dup_chunk@K``     — dist: chunk seq ``K`` is sent twice (the
  consumer's seq dedup absorbs the twin);
- ``drop_conn@K``     — dist/tcp: the connection dies mid-frame at data
  frame ``K`` (half the frame bytes land, then the socket closes — the
  torn-write case; reconnect + handshake replay heals it);
- ``delay_frame@K[:S]`` — dist/tcp: sleep S seconds before sending data
  frame ``K`` (``K = *`` delays every frame);
- ``corrupt_frame@K`` — dist/tcp: flip bytes inside data frame ``K``'s
  body on the wire (the frame digest catches it; dropped + counted,
  the retransmit timer heals it);
- ``reorder_frame@K`` — dist/tcp: hold data frame ``K`` and send it
  AFTER the next frame (out-of-order delivery; seq dedup + the fold
  frontier absorb it);
- ``seed=N``          — seed for the deterministic corruption bytes.

All frame injectors act INSIDE the transport, host-side, at the frame
layer — so a chaos run compiles the same programs as a clean one.

All injection is host-side (batches are poisoned *before* they reach the
jitted step), so chaos can change no compiled program and add no
retraces.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Dict, List, Optional, Sequence


class ChaosError(RuntimeError):
    """An injected fault (loader failure, poisoned serve request)."""


class NullChaos:
    """Chaos off: falsy, every consult a no-op. Drivers guard their
    consults with ``if chaos:`` so the off path costs one truthiness
    check per step."""

    def __bool__(self) -> bool:
        return False

    def batch_fault(self, step: int) -> Optional[str]:
        return None

    def apply_batch_fault(self, kind: str, arr):
        return arr

    def maybe_sigterm(self, step: int) -> bool:
        return False

    def loader_fault(self, index: int) -> None:
        return None

    def corrupts_checkpoint(self) -> bool:
        return False

    def corrupt_checkpoint(self, path: str) -> Optional[str]:
        return None

    def poisoned(self, slide_ids: Sequence[str]) -> Optional[str]:
        return None

    def slow_dispatch(self, dispatch_index: int) -> float:
        return 0.0

    def maybe_kill_worker(self, produced: int) -> bool:
        return False

    def maybe_kill_consumer(self, delivered: int) -> bool:
        return False

    def slow_worker(self, chunk_index: int) -> float:
        return 0.0

    def drops_chunk(self, seq: int) -> bool:
        return False

    def dups_chunk(self, seq: int) -> bool:
        return False

    def drops_conn(self, frame_index: int) -> bool:
        return False

    def delay_frame(self, frame_index: int) -> float:
        return 0.0

    def corrupts_frame(self, frame_index: int) -> bool:
        return False

    def reorders_frame(self, frame_index: int) -> bool:
        return False


class ChaosInjector(NullChaos):
    """Parsed ``GIGAPATH_CHAOS`` spec. One instance per driver run."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed = 0
        self._nan_steps: set = set()
        self._corrupt_steps: set = set()
        self._sigterm_steps: set = set()
        self._fail_loader: Dict[int, int] = {}   # index -> remaining fails
        self._slow_loader: Dict[int, float] = {}  # index -> sleep seconds
        self._corrupt_ckpt = False
        self._ckpt_corrupted = False
        self._poison_ids: List[str] = []
        self._slow_dispatch: Dict[str, float] = {}  # index (or "*") -> s
        self._kill_worker_after: Optional[int] = None
        self._kill_consumer_after: Optional[int] = None
        self._slow_worker: Dict[str, float] = {}  # chunk (or "*") -> s
        self._drop_chunks: set = set()
        self._dup_chunks: set = set()
        self._drop_conns: set = set()         # data frame index, one-shot
        self._delay_frames: Dict[str, float] = {}  # frame (or "*") -> s
        self._corrupt_frames: set = set()     # data frame index, one-shot
        self._reorder_frames: set = set()     # data frame index, one-shot
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            self._parse(token)

    def __bool__(self) -> bool:
        return True

    def _parse(self, token: str) -> None:
        if token.startswith("seed="):
            self.seed = int(token.split("=", 1)[1])
            return
        kind, _, arg = token.partition("@")
        if kind == "nan_loss":
            self._nan_steps.add(int(arg))
        elif kind == "corrupt_batch":
            self._corrupt_steps.add(int(arg))
        elif kind == "sigterm":
            self._sigterm_steps.add(int(arg))
        elif kind == "fail_loader":
            idx, _, times = arg.partition("x")
            self._fail_loader[int(idx)] = int(times) if times else 1
        elif kind == "slow_loader":
            idx, _, secs = arg.partition(":")
            self._slow_loader[int(idx)] = float(secs) if secs else 1.0
        elif kind == "corrupt_ckpt":
            self._corrupt_ckpt = True
        elif kind == "poison":
            self._poison_ids.append(arg)
        elif kind == "slow_dispatch":
            idx, _, secs = arg.partition(":")
            self._slow_dispatch[idx or "*"] = float(secs) if secs else 1.0
        elif kind == "kill_worker":
            self._kill_worker_after = int(arg)
        elif kind == "kill_consumer":
            self._kill_consumer_after = int(arg)
        elif kind == "slow_worker":
            idx, _, secs = arg.partition(":")
            self._slow_worker[idx or "*"] = float(secs) if secs else 1.0
        elif kind == "drop_chunk":
            self._drop_chunks.add(int(arg))
        elif kind == "dup_chunk":
            self._dup_chunks.add(int(arg))
        elif kind == "drop_conn":
            self._drop_conns.add(int(arg))
        elif kind == "delay_frame":
            idx, _, secs = arg.partition(":")
            self._delay_frames[idx or "*"] = float(secs) if secs else 1.0
        elif kind == "corrupt_frame":
            self._corrupt_frames.add(int(arg))
        elif kind == "reorder_frame":
            self._reorder_frames.add(int(arg))
        else:
            raise ValueError(
                f"GIGAPATH_CHAOS: unknown injector {token!r} (known: "
                "nan_loss@K, corrupt_batch@K, sigterm@K, fail_loader@I[xN], "
                "slow_loader@I[:S], corrupt_ckpt, poison@ID, "
                "slow_dispatch@K[:S] (K='*' = all), kill_worker@K, "
                "kill_consumer@K, slow_worker@K[:S] (K='*' = all), "
                "drop_chunk@K, dup_chunk@K, drop_conn@K, "
                "delay_frame@K[:S] (K='*' = all), corrupt_frame@K, "
                "reorder_frame@K, seed=N)"
            )

    # -- batch faults (consulted by train loops, host-side) ---------------
    def batch_fault(self, step: int) -> Optional[str]:
        if step in self._nan_steps:
            return "nan"
        if step in self._corrupt_steps:
            return "corrupt"
        return None

    def apply_batch_fault(self, kind: str, arr):
        """Poisoned COPY of a host batch array: NaNs (non-finite loss) or
        huge garbage (corrupted shard — the loss blows up to inf)."""
        import numpy as np

        out = np.array(arr, np.float32)
        if kind == "nan":
            out.reshape(-1)[:: max(out.size // 8, 1)] = np.nan
        else:
            out.reshape(-1)[:: max(out.size // 8, 1)] = 1e30
        return out

    # -- preemption -------------------------------------------------------
    def maybe_sigterm(self, step: int) -> bool:
        """Deliver a REAL SIGTERM after step ``step`` — the handler chain
        (flight dump + registered emergency-checkpoint callbacks) runs at
        the next bytecode boundary of the main thread."""
        if step not in self._sigterm_steps:
            return False
        self._sigterm_steps.discard(step)  # one delivery per spec entry
        os.kill(os.getpid(), signal.SIGTERM)
        return True

    # -- loader faults (consulted by SlideDataset reads) ------------------
    def loader_fault(self, index: int) -> None:
        sleep_s = self._slow_loader.get(index)
        if sleep_s:
            time.sleep(sleep_s)
        remaining = self._fail_loader.get(index, 0)
        if remaining > 0:
            self._fail_loader[index] = remaining - 1
            raise ChaosError(f"chaos: injected loader failure at sample {index}")

    # -- checkpoint corruption -------------------------------------------
    def corrupts_checkpoint(self) -> bool:
        """One corruption per run: the resume scan consults this once."""
        if self._corrupt_ckpt and not self._ckpt_corrupted:
            self._ckpt_corrupted = True
            return True
        return False

    def corrupt_checkpoint(self, path: str) -> Optional[str]:
        return corrupt_checkpoint_dir(path, seed=self.seed)

    # -- serving poison ---------------------------------------------------
    def poisoned(self, slide_ids: Sequence[str]) -> Optional[str]:
        for sid in slide_ids:
            if sid in self._poison_ids:
                return sid
        return None

    def slow_dispatch(self, dispatch_index: int) -> float:
        """Seconds dispatch ``dispatch_index`` must sleep (0 = no
        injection). Host-side, slept by the service INSIDE its dispatch
        span — the compiled program is untouched, only the wall the
        latency telemetry measures."""
        return self._slow_dispatch.get(
            str(dispatch_index), self._slow_dispatch.get("*", 0.0)
        )

    # -- dist: cross-stage boundary faults (gigapath_tpu.dist) ------------
    def maybe_kill_worker(self, produced: int) -> bool:
        """SIGKILL THIS process once ``produced`` chunks have landed —
        the tile worker consults this after each send. SIGKILL, not
        SIGTERM: the hard-preemption case where no handler runs and the
        only signal the fleet gets is a lease that stops renewing."""
        if self._kill_worker_after is None or produced < self._kill_worker_after:
            return False
        self._kill_worker_after = None  # one death per spec entry
        os.kill(os.getpid(), signal.SIGKILL)
        return True  # unreachable after SIGKILL; keeps the surface honest

    def maybe_kill_consumer(self, delivered: int) -> bool:
        """SIGKILL THIS process once ``delivered`` chunks have been
        received — the slide-stage consumer consults this after each
        delivery. The consumer-side twin of :meth:`maybe_kill_worker`:
        no handler runs, the streaming fold state is simply gone, and
        only a checkpoint brings the slide back."""
        if (self._kill_consumer_after is None
                or delivered < self._kill_consumer_after):
            return False
        self._kill_consumer_after = None  # one death per spec entry
        os.kill(os.getpid(), signal.SIGKILL)
        return True  # unreachable after SIGKILL; keeps the surface honest

    def slow_worker(self, chunk_index: int) -> float:
        """Seconds to sleep before producing chunk ``chunk_index``
        (``'*'`` = every chunk — the deterministic straggler)."""
        return self._slow_worker.get(
            str(chunk_index), self._slow_worker.get("*", 0.0)
        )

    def drops_chunk(self, seq: int) -> bool:
        """True exactly ONCE per configured seq: the first send is
        swallowed by the transport, the retransmit goes through."""
        if seq in self._drop_chunks:
            self._drop_chunks.discard(seq)
            return True
        return False

    def dups_chunk(self, seq: int) -> bool:
        if seq in self._dup_chunks:
            self._dup_chunks.discard(seq)
            return True
        return False

    # -- dist: TCP frame-layer faults (gigapath_tpu.dist.transport) -------
    def drops_conn(self, frame_index: int) -> bool:
        """True exactly ONCE per configured data-frame index: the
        transport sends HALF the frame's bytes and closes the socket —
        a torn write plus a dead connection, healed by reconnect +
        handshake replay."""
        if frame_index in self._drop_conns:
            self._drop_conns.discard(frame_index)
            return True
        return False

    def delay_frame(self, frame_index: int) -> float:
        """Seconds to sleep before sending data frame ``frame_index``
        (``'*'`` = every frame)."""
        return self._delay_frames.get(
            str(frame_index), self._delay_frames.get("*", 0.0)
        )

    def corrupts_frame(self, frame_index: int) -> bool:
        """True exactly ONCE per configured data-frame index: bytes
        inside the frame body are flipped AFTER the digest was computed,
        so the receiver's sha256 check must catch and drop it."""
        if frame_index in self._corrupt_frames:
            self._corrupt_frames.discard(frame_index)
            return True
        return False

    def reorders_frame(self, frame_index: int) -> bool:
        """True exactly ONCE per configured data-frame index: the frame
        is held back and sent after its successor."""
        if frame_index in self._reorder_frames:
            self._reorder_frames.discard(frame_index)
            return True
        return False


def corrupt_checkpoint_dir(path: str, seed: int = 0) -> Optional[str]:
    """Deterministically flip bytes in the largest payload file under a
    checkpoint directory (manifest excluded — corruption the manifest
    must CATCH, not corruption of the manifest itself). Returns the
    corrupted file path, or None when nothing corruptible exists."""
    import numpy as np

    candidates = []
    for root, _, files in os.walk(path):
        for name in files:
            if name == "manifest.json":
                continue
            full = os.path.join(root, name)
            size = os.path.getsize(full)
            if size > 0:
                candidates.append((size, full))
    if not candidates:
        return None
    _, target = max(candidates)
    rng = np.random.default_rng(seed)
    with open(target, "r+b") as fh:
        data = bytearray(fh.read())
        for pos in rng.integers(0, len(data), size=min(16, len(data))):
            data[pos] ^= 0xFF
        fh.seek(0)
        fh.write(bytes(data))
    return target


def get_chaos(runlog=None):
    """Build the run's chaos injector from ``GIGAPATH_CHAOS``, read ONCE
    here, host-side, at driver start (never at trace time). Unset/empty
    -> :class:`NullChaos` (falsy; drivers skip every consult).

    A typo'd spec must be a LOUD failure, never a silently clean run —
    the whole point of a chaos run is the injection, and an injector
    that quietly didn't parse is a recovery path that quietly wasn't
    tested. Construction errors land as an ``error`` event on ``runlog``
    (when given) and the ValueError propagates to the caller."""
    spec = os.environ.get("GIGAPATH_CHAOS", "").strip()
    if not spec:
        return NullChaos()
    try:
        return ChaosInjector(spec)
    except ValueError as e:
        if runlog is not None:
            try:
                runlog.error("chaos_parse", e)
            except Exception:
                pass  # telemetry must not mask the parse error itself
        raise
