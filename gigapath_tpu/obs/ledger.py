"""Compiled-artifact perf ledger: machine-checkable performance
observability that needs no chip.

Rounds 5-6 landed kernel work whose on-chip validation is gated on the
axon tunnel; the signals that ARE deterministic without a device are the
compiled artifact's own numbers: XLA ``cost_analysis()`` FLOPs / bytes
accessed, ``memory_analysis()`` argument/output/temp/donated bytes, and
the traced program's shape — eqn counts by primitive (the same
transpose/slice/broadcast/reshape/pallas_call columns PERFORMANCE.md's
round-6 table tabulates by hand). This module captures those as
``compile_profile`` obs events and folds every profile of a run into one
canonical per-run ledger JSON, keyed by ``name|shape-signature``, that
``scripts/ledger_diff.py`` can diff across commits with per-metric
thresholds. Golden ledgers for the flagship shapes live in
``tests/goldens/`` and are pinned by a tier-1 test — the standing,
trace-level perf regression gate.

Capture paths:

- hooked through :class:`~gigapath_tpu.obs.watchdog.CompileWatchdog`
  (``ledger=`` arg): ``wrap()`` captures automatically on each new key,
  loops driving the ``is_new``/``record`` surface call
  ``watchdog.profile(key, fn, *args, **kwargs)``;
- standalone: :func:`capture_profile` / :meth:`PerfLedger.capture`.

Cost model: a FULL profile (cost+memory analysis) lowers AND compiles
the function once more through the AOT path — that does not touch the
jit call cache (no retrace is visible to ``fn._cache_size()``, pinned by
tests/test_obs.py) but it is one extra XLA compile. The ledger therefore
takes the full profile only for the FIRST signature seen per name (the
hot/flagship shape); later signatures get a fingerprint-only profile
(one extra trace, no compile). ``full=True`` on capture overrides.

``GIGAPATH_OBS=0``: :func:`get_ledger` returns a :class:`NullLedger`
(no events, no trace/lower/compile work, no file).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

LEDGER_SCHEMA_VERSION = 1

# Primitive columns every fingerprint reports explicitly (0 when absent):
# the glue-op classes PERFORMANCE.md's round-6 table tracks, the kernel
# count, and the sequence-parallel collectives (the ring-vs-gather
# signal: the ring path must show ppermute > 0 and zero full-segment
# all_gather of K/V — pinned by the golden ledger's dilated_ring_*
# entries). Other primitives appear under their own names as seen.
FINGERPRINT_COLUMNS = (
    "transpose", "slice", "broadcast_in_dim", "reshape", "pallas_call",
    "ppermute", "all_gather",
)


# ---------------------------------------------------------------------------
# jaxpr fingerprint
# ---------------------------------------------------------------------------

def _eqn_is_quant(eqn) -> bool:
    """Does an equation touch a low-precision (int8 / float8_*) aval?
    The ``quant`` fingerprint column: when the quantized kernel tier
    (gigapath_tpu/quant/, GIGAPATH_QUANT_TILE) is on, the traced
    program must SHOW low-precision operands — a tier flag that
    compiles the f32 program silently is exactly the regression this
    column pins, the same way ppermute/all_gather pin the ring tier."""
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        dtype = str(getattr(aval, "dtype", ""))
        if dtype == "int8" or dtype.startswith("float8"):
            return True
    return False


def _eqn_is_mask(eqn) -> bool:
    """Does an equation PRODUCE a dense square boolean mask — a bool
    aval whose two trailing dims are equal and > 1? The ``mask``
    fingerprint column: the jnp streaming fold materializes per-pair
    ``[.., C, C]`` segment/phase/validity masks (pure O(C^2) traffic),
    while the Pallas fold tier computes the same predicates in-kernel
    from iota comparisons and must show ZERO such eqns — the golden
    ledger pins both sides of that A/B, and a mask count creeping back
    into a kernel path is exactly the regression this column flags."""
    for var in eqn.outvars:
        aval = getattr(var, "aval", None)
        if str(getattr(aval, "dtype", "")) != "bool":
            continue
        shape = tuple(getattr(aval, "shape", ()) or ())
        if len(shape) >= 2 and shape[-1] == shape[-2] and shape[-1] > 1:
            return True
    return False


def _count_eqns(jaxpr, counts: Dict[str, int],
                qbox: Optional[List[int]] = None,
                mbox: Optional[List[int]] = None) -> None:
    """Recursive primitive histogram over a jaxpr and every sub-jaxpr
    (pjit bodies, custom_vjp calls, scan/cond branches, pallas_call).
    ``qbox``/``mbox`` (1-element lists) additionally accumulate the
    low-precision and square-bool-mask eqn counts for the ``quant`` /
    ``mask`` columns."""
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        if qbox is not None and _eqn_is_quant(eqn):
            qbox[0] += 1
        if mbox is not None and _eqn_is_mask(eqn):
            mbox[0] += 1
        for val in eqn.params.values():
            for item in val if isinstance(val, (list, tuple)) else (val,):
                sub = getattr(item, "jaxpr", None)
                if sub is not None:
                    # ClosedJaxpr has .jaxpr.eqns; Jaxpr has .eqns
                    _count_eqns(getattr(sub, "jaxpr", sub), counts, qbox,
                                mbox)
                elif hasattr(item, "eqns") and eqn.primitive.name != "pallas_call":
                    # a RAW Jaxpr param (shard_map bodies ride as one):
                    # without this arm the whole sharded program would
                    # fingerprint as a single opaque eqn. pallas_call
                    # kernel bodies stay opaque on purpose — the KERNEL
                    # COUNT is the round-6 column's signal; Mosaic
                    # kernel-internal ops are not XLA glue
                    _count_eqns(item, counts, qbox, mbox)


def jaxpr_fingerprint(fn, *args, **kwargs) -> Dict[str, Any]:
    """Eqn counts by primitive for ``fn(*args, **kwargs)``'s traced
    program: ``{"eqns_total": N, "quant": Q, "mask": M, "primitives":
    {name: count}}`` with the :data:`FINGERPRINT_COLUMNS` always
    present, ``quant`` the count of eqns touching int8/float8 avals
    (the quantized-tier op-mix pin) and ``mask`` the count of eqns
    producing dense square boolean masks (the streaming-fold
    mask-materialization pin) — neither is a primitive, so neither
    feeds ``eqns_total``. One extra trace, no compile. ``fn`` may be
    jitted or plain."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    counts: Dict[str, int] = {}
    qbox = [0]
    mbox = [0]
    _count_eqns(closed.jaxpr, counts, qbox, mbox)
    for col in FINGERPRINT_COLUMNS:
        counts.setdefault(col, 0)
    return {
        "eqns_total": int(sum(counts.values())),
        "quant": int(qbox[0]),
        "mask": int(mbox[0]),
        "primitives": {k: int(v) for k, v in sorted(counts.items())},
    }


# ---------------------------------------------------------------------------
# cost / memory analysis (the utils.profiling backends live HERE now)
# ---------------------------------------------------------------------------

def _compile_aot(fn, *args, **kwargs):
    """AOT lower+compile (jitting if needed). Does not touch the jit call
    cache, so watched functions see no retrace."""
    import jax

    lowered = getattr(fn, "lower", None)
    if lowered is None:
        fn = jax.jit(fn)
    return fn.lower(*args, **kwargs).compile()


def _finite(value) -> Optional[float]:
    """float(value) if finite, else None — NaN must never reach a ledger
    (it serializes as a non-RFC token and blinds ledger_diff's
    comparisons, which treat NaN deltas as in-tolerance)."""
    import math

    try:
        f = float(value)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def cost_analysis_of(compiled) -> Optional[Dict[str, Optional[float]]]:
    """``{"flops", "bytes_accessed"}`` from a compiled object's XLA cost
    analysis; None when the backend offers none; individual fields None
    when the backend reports them non-finite or not at all."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):
            analysis = analysis[0]
        return {
            "flops": _finite(analysis.get("flops")),
            "bytes_accessed": _finite(analysis.get("bytes accessed")),
        }
    except Exception:
        return None


def memory_analysis_of(compiled) -> Optional[Dict[str, Optional[float]]]:
    """Argument/output/temp/donated bytes plus a derived ``peak_bytes``
    (arguments + temporaries + non-aliased outputs — donated inputs alias
    their outputs, so ``donated_bytes`` is subtracted once). Fields the
    backend cannot report finitely are None, and so is the derived peak."""
    try:
        mem = compiled.memory_analysis()
        arg = _finite(getattr(mem, "argument_size_in_bytes", None))
        out = _finite(getattr(mem, "output_size_in_bytes", None))
        tmp = _finite(getattr(mem, "temp_size_in_bytes", None))
        donated = _finite(getattr(mem, "alias_size_in_bytes", 0.0))
        peak = None
        if None not in (arg, out, tmp):
            peak = arg + tmp + max(out - (donated or 0.0), 0.0)
        return {
            "argument_bytes": arg,
            "output_bytes": out,
            "temp_bytes": tmp,
            "donated_bytes": donated,
            "peak_bytes": peak,
        }
    except Exception:
        return None


def compiled_flops(fn, *args) -> Optional[float]:
    """FLOPs of the jitted computation, from XLA cost analysis."""
    try:
        cost = cost_analysis_of(_compile_aot(fn, *args))
    except Exception:
        return None
    return None if cost is None else cost["flops"]


def compiled_memory(fn, *args) -> Optional[Dict[str, float]]:
    """Peak/argument/output memory of the compiled computation (bytes).
    Field names kept compatible with the original utils.profiling shim
    consumers (bench.py): temp/argument/output``_bytes``."""
    try:
        mem = memory_analysis_of(_compile_aot(fn, *args))
    except Exception:
        return None
    return None if mem is None else {
        "temp_bytes": mem["temp_bytes"],
        "argument_bytes": mem["argument_bytes"],
        "output_bytes": mem["output_bytes"],
    }


# ---------------------------------------------------------------------------
# profiles
# ---------------------------------------------------------------------------

def _tree_size(value: Any) -> int:
    """Leaf count of a nested dict/list/tuple pytree (no jax import)."""
    if isinstance(value, dict):
        return sum(_tree_size(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_tree_size(v) for v in value)
    return 1


def shape_signature(args: tuple, kwargs: dict) -> str:
    """Static shape/dtype signature over array-like arguments — the facts
    the jit cache keys on for them (non-arrays are skipped, mirroring the
    watchdog's default key)."""
    parts: List[str] = []

    def leaf_sig(prefix: str, value: Any) -> None:
        shape = getattr(value, "shape", None)
        if shape is not None and hasattr(value, "dtype"):
            parts.append(f"{prefix}{str(value.dtype)}[{','.join(map(str, shape))}]")
            return
        # pytrees (param dicts): summarize as LEAF count so two models of
        # equal batch shapes but different depths do not collide silently
        if isinstance(value, dict):
            parts.append(f"{prefix}tree{{{_tree_size(value)}}}")

    for a in args:
        leaf_sig("", a)
    for name in sorted(kwargs):
        leaf_sig(f"{name}=", kwargs[name])
    return ";".join(parts)


def capture_profile(fn, *args, full: bool = True, **kwargs) -> Dict[str, Any]:
    """One compile profile of ``fn(*args, **kwargs)``: jaxpr fingerprint
    always; cost/memory analysis when ``full`` (one extra AOT compile).
    Every section is best-effort — a profile must never take a run down —
    but a totally untraceable function raises (callers decide)."""
    profile: Dict[str, Any] = {
        "sig": shape_signature(args, kwargs),
        "jaxpr": jaxpr_fingerprint(fn, *args, **kwargs),
    }
    if full:
        try:
            compiled = _compile_aot(fn, *args, **kwargs)
        except Exception as e:
            profile["compile_error"] = f"{type(e).__name__}: {e}"
            return profile
        profile["cost"] = cost_analysis_of(compiled)
        profile["memory"] = memory_analysis_of(compiled)
    return profile


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class NullLedger:
    """``GIGAPATH_OBS=0`` twin: absorbs every call, creates nothing."""

    path: Optional[str] = None

    def capture(self, name: str, fn, *args, **kwargs):
        return None

    def capture_for_key(self, name: str, key, fn, *args, **kwargs):
        return None

    capture_full = capture_fingerprint = capture

    def adopt_compiled(self, name: str, key, compiled, fn, *args, **kwargs):
        return None

    def write(self, path: Optional[str] = None):
        return None

    @property
    def entries(self) -> Dict[str, dict]:
        return {}


class PerfLedger(NullLedger):
    """Folds a run's compile profiles into one canonical ledger JSON.

    Entries are keyed ``name|shape-signature`` and written sorted with a
    fixed field order, so two ledgers of the same code + shapes are
    byte-comparable. The file is (re)written after every capture — like
    the run JSONL, the artifact exists the moment the run dies.
    """

    def __init__(self, runlog=None, path: Optional[str] = None,
                 meta: Optional[dict] = None, autowrite: bool = True):
        self.runlog = runlog
        if path is None and runlog is not None and getattr(runlog, "path", None):
            base = os.path.dirname(os.path.abspath(runlog.path))
            path = os.path.join(base, f"{runlog.run_id}.ledger.json")
        self.path = path
        # autowrite=False defers the file to an explicit write() — bench
        # uses it so a failed run cannot overwrite the last good ledger
        # with a partial one (its failure JSON points at the old file)
        self.autowrite = autowrite
        self._entries: Dict[str, dict] = {}
        self._full_named: set = set()  # names that already got a full profile
        self.meta = dict(meta or {})

    @property
    def entries(self) -> Dict[str, dict]:
        return self._entries

    def capture(self, name: str, fn, *args, **kwargs) -> Optional[dict]:
        """Profile ``fn`` under ``name`` unless this (name, signature) is
        already ledgered. Full (cost+memory) for the first signature per
        name, fingerprint-only afterwards; force with ``self.capture_full``.
        Returns the entry (or the existing one), None on capture failure."""
        return self._capture(name, fn, args, kwargs,
                             full=name not in self._full_named)

    def capture_full(self, name: str, fn, *args, **kwargs) -> Optional[dict]:
        return self._capture(name, fn, args, kwargs, full=True)

    def capture_fingerprint(self, name: str, fn, *args, **kwargs) -> Optional[dict]:
        """Jaxpr fingerprint only — one extra trace, never a compile
        (golden generation uses this for interpret-mode pallas programs
        whose CPU compile is slow but whose eqn counts are the signal)."""
        return self._capture(name, fn, args, kwargs, full=False)

    def capture_for_key(self, name: str, key, fn, *args, **kwargs) -> Optional[dict]:
        """Like :meth:`capture`, tagging the entry/event with the
        watchdog's bucket key so compile events and compile_profile
        events join without re-deriving the key<->signature mapping."""
        from gigapath_tpu.obs.runlog import _key_str

        return self._capture(name, fn, args, kwargs,
                             full=name not in self._full_named,
                             extra={"key": _key_str(key)})

    def adopt_compiled(self, name: str, key, compiled, fn,
                       *args, **kwargs) -> Optional[dict]:
        """Ledger an ALREADY-compiled AOT executable.

        The documented cost model of :meth:`capture` pays one extra AOT
        compile per full profile; callers that hold the compiled object
        already (the serving stack's per-bucket executables,
        :mod:`gigapath_tpu.serve.aot`) get cost/memory analysis straight
        off it for free — the only added work is the fingerprint's one
        extra trace. ``args``/``kwargs`` may be ``jax.ShapeDtypeStruct``s
        (they only feed the trace and the shape signature). Every
        (name, signature) is a FULL profile here, since full costs
        nothing. Failures are contained like every other capture."""
        from gigapath_tpu.obs.runlog import _key_str

        sig = shape_signature(args, kwargs)
        entry_key = f"{name}|{sig}"
        existing = self._entries.get(entry_key)
        if existing is not None and "cost" in existing:
            return existing
        try:
            profile: Dict[str, Any] = {
                "sig": sig,
                "jaxpr": jaxpr_fingerprint(fn, *args, **kwargs),
                "cost": cost_analysis_of(compiled),
                "memory": memory_analysis_of(compiled),
            }
        except Exception as e:
            if self.runlog is not None:
                self.runlog.event(
                    "compile_profile", name=name, sig=sig,
                    error=f"{type(e).__name__}: {e}",
                )
            return None
        self._full_named.add(name)
        extra = {"key": _key_str(key)}
        entry = {"name": name, **extra, **profile}
        self._entries[entry_key] = entry
        if self.runlog is not None:
            self.runlog.event("compile_profile", name=name, **extra, **profile)
        if self.autowrite:
            try:
                self.write()
            except Exception as e:  # the artifact must never take a run down
                if self.runlog is not None:
                    self.runlog.error("ledger.write", e)
        return entry

    def _capture(self, name, fn, args, kwargs, *, full,
                 extra: Optional[dict] = None) -> Optional[dict]:
        sig = shape_signature(args, kwargs)
        key = f"{name}|{sig}"
        existing = self._entries.get(key)
        if existing is not None:
            # a full request upgrades a fingerprint-only entry (the
            # documented capture_full override); anything else dedups
            if not full or "cost" in existing or "compile_error" in existing:
                return existing
        try:
            profile = capture_profile(fn, *args, full=full, **kwargs)
        except Exception as e:
            if self.runlog is not None:
                self.runlog.event(
                    "compile_profile", name=name, sig=sig,
                    error=f"{type(e).__name__}: {e}",
                )
            return None
        if full and "compile_error" not in profile:
            self._full_named.add(name)
        entry = {"name": name, **(extra or {}), **profile}
        self._entries[key] = entry
        if self.runlog is not None:
            self.runlog.event("compile_profile", name=name, **(extra or {}),
                              **profile)
        if self.autowrite:
            try:
                self.write()
            except Exception as e:  # the artifact must never take a run down
                if self.runlog is not None:
                    self.runlog.error("ledger.write", e)
        return entry

    def as_dict(self) -> dict:
        doc = {"v": LEDGER_SCHEMA_VERSION}
        doc.update(self.meta)
        if self.runlog is not None and getattr(self.runlog, "run_id", None):
            doc.setdefault("run", self.runlog.run_id)
        doc["entries"] = {k: self._entries[k] for k in sorted(self._entries)}
        return doc

    def write(self, path: Optional[str] = None) -> Optional[str]:
        path = path or self.path
        if path is None:
            return None
        write_ledger(self.as_dict(), path)
        return path


def write_ledger(doc: dict, path: str) -> str:
    """Canonical serialization shared by PerfLedger and the golden
    regenerator: sorted keys, indent 1, trailing newline. allow_nan=False
    enforces the no-NaN invariant loudly — a NaN would serialize as a
    non-RFC token and blind ledger_diff."""
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True, allow_nan=False)
        f.write("\n")
    return path


def get_ledger(runlog, path: Optional[str] = None,
               meta: Optional[dict] = None):
    """Ledger for a run: a real :class:`PerfLedger` when the runlog
    records to a file, a :class:`NullLedger` under ``GIGAPATH_OBS=0``
    (NullRunLog). Mirrors how every other obs component keys off the
    runlog, so the one ``get_run_log`` env read stays the only gate."""
    if runlog is None or getattr(runlog, "path", None) is None:
        return NullLedger()
    return PerfLedger(runlog, path=path, meta=meta)
