"""Runtime lock-order sanitizer: gigarace's dynamic twin.

The static analyzer (:mod:`tools.gigarace`) proves properties of the
lock-acquisition ORDER it can see in the AST; this module observes the
orders that actually happen. Every lock in the library is constructed
through the factories here —

    self._lock = make_lock("gigapath_tpu.obs.runlog.RunLog._lock")
    self._cond = make_condition("gigapath_tpu.serve.queue.RequestQueue._cond")

— with the lock's CANONICAL name (the same ``pkg.mod.Cls._attr`` string
the static model derives) passed as a literal, so the two sides speak
identical identities and ``python -m tools.gigarace --validate`` can
assert that every edge observed at runtime is an edge the static graph
predicted.

Gating (the obs-off discipline): ``GIGAPATH_LOCKTRACE`` is read ONCE,
host-side, at import. Off — the default — every factory returns the
plain ``threading`` primitive: no wrapper object, no per-acquire
bookkeeping, no extra files, and nothing jax-visible (the sanitizer is
pure host threading, so traced-program HLO is byte-identical either
way; ``tests/test_locktrace.py`` pins the off path). On
(``GIGAPATH_LOCKTRACE=1``) each primitive is wrapped and the process
accumulates, per thread, the stack of held locks, and globally:

- the acquisition-order edge set: on every acquire, one edge from each
  DISTINCT currently-held lock to the new one (exactly the static
  model's edge rule);
- violations: acquiring a non-reentrant lock an instance of which this
  thread already holds (self-deadlock — recorded BEFORE the attempt so
  the artifact survives the hang), and an order inversion (edge A->B
  observed when B->A was already recorded: a 2-cycle no static-clean
  tree may produce);
- contention counts (a non-blocking try precedes every blocking
  acquire; failure of the try is one contention event) and per-lock
  hold-time samples for the ``== locks ==`` report section.

Artifacts: ``GIGAPATH_LOCKTRACE_OUT=<path>`` (read once, host-side)
appends one JSON line ``{"kind": "locktrace", ...}`` at process exit;
:func:`attach_locktrace` registers a runlog closer that lands the same
payload as a ``locktrace`` event in the run JSONL, where
``scripts/obs_report.py`` renders it. Both shapes are what
``tools.gigarace --validate`` consumes.

Signal safety: the aggregate state is guarded by an internal (never
traced) lock taken with a short try-acquire — a traced acquisition from
a signal handler (``pending_from_signal``) must never block on state
the interrupted thread holds; on contention the observation is dropped,
never the caller's acquire.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from time import monotonic
from typing import Dict, List, Optional, Tuple

# host-side gates, read once at import (GL001/GL007 discipline)
_ENABLED = os.environ.get("GIGAPATH_LOCKTRACE", "") == "1"
_OUT_PATH = os.environ.get("GIGAPATH_LOCKTRACE_OUT", "") or None

_MAX_HOLD_SAMPLES = 65536   # per lock; count/total stay exact past it


def enabled() -> bool:
    return _ENABLED


class _LockTraceState:
    """Process-global aggregates + per-thread held stacks."""

    def __init__(self):
        self.lock = threading.Lock()   # internal, never traced
        self.names: set = set()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.violations: List[str] = []
        self.contention: Dict[str, int] = {}
        self.hold_samples: Dict[str, List[float]] = {}
        self.hold_counts: Dict[str, int] = {}
        self.hold_totals: Dict[str, float] = {}
        self.tls = threading.local()

    # -- per-thread stack of (name, instance id, t_acquired) -------------
    def _stack(self) -> List[Tuple[str, int, float]]:
        stack = getattr(self.tls, "stack", None)
        if stack is None:
            stack = []
            self.tls.stack = stack
        return stack

    # -- recording hooks (called by the wrappers) -------------------------
    def note_name(self, name: str) -> None:
        if not self.lock.acquire(timeout=0.5):
            return
        try:
            self.names.add(name)
        finally:
            self.lock.release()

    def note_contention(self, name: str) -> None:
        if not self.lock.acquire(timeout=0.5):
            return
        try:
            self.contention[name] = self.contention.get(name, 0) + 1
        finally:
            self.lock.release()

    def pre_acquire(
        self, name: str, inst: int, kind: str, bounded: bool = False,
    ) -> None:
        """Self-deadlock check, BEFORE the acquire attempt: if this
        thread already holds this very instance and it is not reentrant,
        an INDEFINITE acquire will hang — get the violation into the
        record first so the artifact explains the hang. A ``bounded``
        attempt (``blocking=False`` or a finite timeout) on a held lock
        is NOT a violation: it self-resolves by failing, which is
        exactly the sanctioned ``*_from_signal`` try-acquire degradation
        (the handler may run ON the thread that holds the lock)."""
        if kind == "rlock" or bounded:
            return
        if any(i == inst for _, i, _ in self._stack()):
            self._violate(
                f"re-acquire of non-reentrant '{name}' already held by "
                "this thread: self-deadlock")

    def on_acquired(self, name: str, inst: int, kind: str) -> None:
        stack = self._stack()
        reentrant = any(i == inst for _, i, _ in stack)
        if not reentrant:
            held = {n for n, _, _ in stack if n != name}
            if held and not self.lock.acquire(timeout=0.5):
                held = set()   # drop the observation, never the caller
            elif held:
                try:
                    for h in sorted(held):
                        if (name, h) in self.edges:
                            self._violate_locked(
                                f"order inversion: {h} -> {name} here "
                                f"but {name} -> {h} observed earlier")
                        key = (h, name)
                        self.edges[key] = self.edges.get(key, 0) + 1
                finally:
                    self.lock.release()
        stack.append((name, inst, monotonic()))

    def on_release(self, name: str, inst: int) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == inst:
                held_s = monotonic() - stack[i][2]
                del stack[i]
                self._note_hold(name, held_s)
                return
        self._violate(f"release of '{name}' not held by this thread")

    def _note_hold(self, name: str, seconds: float) -> None:
        if not self.lock.acquire(timeout=0.5):
            return
        try:
            self.hold_counts[name] = self.hold_counts.get(name, 0) + 1
            self.hold_totals[name] = self.hold_totals.get(name, 0.0) + seconds
            samples = self.hold_samples.setdefault(name, [])
            if len(samples) < _MAX_HOLD_SAMPLES:
                samples.append(seconds)
        finally:
            self.lock.release()

    def _violate(self, msg: str) -> None:
        if not self.lock.acquire(timeout=0.5):
            return
        try:
            self._violate_locked(msg)
        finally:
            self.lock.release()

    def _violate_locked(self, msg: str) -> None:
        if msg not in self.violations:
            self.violations.append(msg)

    # -- reporting ---------------------------------------------------------
    def summary(self) -> dict:
        with self.lock:
            holds = {}
            for name in sorted(self.hold_counts):
                samples = sorted(self.hold_samples.get(name, ()))
                n = self.hold_counts[name]
                holds[name] = {
                    "count": n,
                    "total_ms": round(self.hold_totals[name] * 1e3, 3),
                    "p50_ms": _pct_ms(samples, 0.50),
                    "p99_ms": _pct_ms(samples, 0.99),
                }
            return {
                "kind": "locktrace",
                "locks": sorted(self.names),
                "edges": sorted([a, b] for (a, b) in self.edges),
                "edge_counts": {
                    f"{a} -> {b}": c
                    for (a, b), c in sorted(self.edges.items())
                },
                "violations": list(self.violations),
                "contention": dict(sorted(self.contention.items())),
                "holds": holds,
            }

    def reset(self) -> None:
        with self.lock:
            self.names.clear()
            self.edges.clear()
            self.violations.clear()
            self.contention.clear()
            self.hold_samples.clear()
            self.hold_counts.clear()
            self.hold_totals.clear()


def _pct_ms(sorted_samples: List[float], q: float) -> float:
    if not sorted_samples:
        return 0.0
    idx = min(len(sorted_samples) - 1,
              max(0, int(round(q * (len(sorted_samples) - 1)))))
    return round(sorted_samples[idx] * 1e3, 3)


class _TracedLock:
    """threading.Lock/RLock twin that reports to the global state."""

    def __init__(self, name: str, inner, kind: str):
        self._name = name
        self._inner = inner
        self._kind = kind

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _STATE.pre_acquire(self._name, id(self._inner), self._kind,
                           bounded=(not blocking) or timeout >= 0)
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            _STATE.note_contention(self._name)
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
        _STATE.on_acquired(self._name, id(self._inner), self._kind)
        return True

    def release(self):
        _STATE.on_release(self._name, id(self._inner))
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedLock {self._name!r} kind={self._kind}>"


class _TracedCondition:
    """threading.Condition twin; ``wait`` re-reports the re-acquire."""

    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        _STATE.pre_acquire(self._name, id(self._inner), "condition",
                           bounded=(not blocking) or timeout >= 0)
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            _STATE.note_contention(self._name)
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
        _STATE.on_acquired(self._name, id(self._inner), "condition")
        return True

    def release(self):
        _STATE.on_release(self._name, id(self._inner))
        self._inner.release()

    def wait(self, timeout: Optional[float] = None):
        # the inner wait releases and re-acquires the underlying lock:
        # mirror both transitions so hold times stop at the park and the
        # re-acquire records fresh order edges
        _STATE.on_release(self._name, id(self._inner))
        try:
            return self._inner.wait(timeout)
        finally:
            _STATE.on_acquired(self._name, id(self._inner), "condition")

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _STATE.on_release(self._name, id(self._inner))
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _STATE.on_acquired(self._name, id(self._inner), "condition")

    def notify(self, n: int = 1):
        return self._inner.notify(n)

    def notify_all(self):
        return self._inner.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TracedCondition {self._name!r}>"


# ---------------------------------------------------------------------------
# the factories: the library's ONLY lock constructors
# ---------------------------------------------------------------------------

def make_lock(name: str):
    """A ``threading.Lock`` (plain when tracing is off) with a canonical
    name matching the static model's derivation for its declaration."""
    if not _ENABLED:
        return threading.Lock()
    _STATE.note_name(name)
    return _TracedLock(name, threading.Lock(), "lock")


def make_rlock(name: str):
    if not _ENABLED:
        return threading.RLock()
    _STATE.note_name(name)
    return _TracedLock(name, threading.RLock(), "rlock")


def make_condition(name: str, lock=None):
    if not _ENABLED:
        return threading.Condition(lock)
    _STATE.note_name(name)
    inner = threading.Condition(getattr(lock, "_inner", lock))
    return _TracedCondition(name, inner)


# ---------------------------------------------------------------------------
# reporting surface
# ---------------------------------------------------------------------------

def summary() -> Optional[dict]:
    """The current aggregate payload, or None when tracing is off."""
    if not _ENABLED:
        return None
    return _STATE.summary()


def reset() -> None:
    """Test hook: clear every aggregate (per-thread stacks excluded —
    callers reset between scenarios with no locks held)."""
    if _ENABLED:
        _STATE.reset()


def dump(path: str) -> None:
    """Append the summary as one JSON line (the --validate input)."""
    if not _ENABLED:
        return
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(_STATE.summary(), sort_keys=True) + "\n")


def attach_locktrace(runlog) -> None:
    """Land the summary as a ``locktrace`` event when the run closes
    (called by ``get_run_log`` for recording runs; no-op when off)."""
    if not _ENABLED:
        return

    def _close() -> None:
        payload = _STATE.summary()
        payload.pop("kind", None)
        runlog.event("locktrace", **payload)

    runlog.add_closer(_close)


def _dump_at_exit() -> None:
    if _OUT_PATH:
        dump(_OUT_PATH)


_STATE = _LockTraceState() if _ENABLED else None

if _ENABLED and _OUT_PATH:
    atexit.register(_dump_at_exit)
