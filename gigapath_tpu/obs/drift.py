"""Embedding drift sentinel: the model-health half of the obs bus.

A quant-tier regression, a corrupt checkpoint, or a shifted tile
population serves garbage embeddings at a perfect p99 — nothing in the
system-side bus can see it. This module watches the *distribution* of
served slide embeddings:

- :class:`EmbeddingSketch` — a mergeable streaming summary per
  embedding dimension (count / mean / M2, the Welford-Chan moments —
  the ONE sanctioned home of running-moment accumulators, gigalint
  GL023) plus a coarse fixed-edge histogram of embedding norms for
  quantile/tail questions. ``merge`` is the Chan parallel fold, so
  per-process sketches combine into a fleet sketch; ``save``/``load``
  persist baseline artifacts with the resilient-checkpoint manifest
  discipline (``.tmp-*`` staging + per-file sha256 ``manifest.json`` +
  atomic rename — corruption is a loud :class:`CorruptDriftArtifact`,
  never silently-wrong baselines).
- :func:`drift_scores` — current-vs-baseline: standardized mean shift
  (mean over dims of |Δmean|/σ_baseline), cosine distance between the
  mean embeddings, and tail mass (fraction of current norms above the
  baseline's q99).
- :class:`DriftSentinel` — the online monitor: every served embedding
  folds into the current sketch; at a cadence the scores are computed,
  exported as :mod:`gigapath_tpu.obs.metrics` gauges, and — TRANSITION-
  EDGED, the SloTracker discipline — a ``drift`` event fires on each
  entry into / exit from the alarming state. The anomaly engine's
  ``embedding_drift`` detector turns the alarming transition into the
  usual reactions (flight dump + armed profiler capture, cooldown);
  terminal status events are marked ``final`` and never fire it.

All host-side, numpy-only (no jax import — a baseline must load on a
workstation far from any chip); deterministic update order makes
restart-resume bit-exact (pinned by ``tests/test_model_health.py``).
Env knobs (``GIGAPATH_DRIFT_EVERY`` / ``GIGAPATH_DRIFT_THRESHOLD`` /
``GIGAPATH_DRIFT_MIN_COUNT`` / ``GIGAPATH_DRIFT_PEEK_EVERY``) are read
ONCE at sentinel construction — driver start, host-side (GL001-clean).
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import shutil
from typing import Dict, Optional

import numpy as np

DRIFT_SCHEMA_VERSION = 1

_MANIFEST = "manifest.json"
_SKETCH_FILE = "sketch.npz"


class CorruptDriftArtifact(ValueError):
    """A drift baseline failed manifest verification (missing file,
    digest mismatch, malformed metadata). Loud by design — restoring a
    rotted baseline would turn every healthy run into an alarm (or
    every drifted run into silence)."""


def _sha256_file(path: str) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


def cosine(a, b, eps: float = 1e-12) -> float:
    """Cosine similarity of two vectors (0.0 when either is ~zero)."""
    a = np.asarray(a, np.float64).reshape(-1)
    b = np.asarray(b, np.float64).reshape(-1)
    na, nb = float(np.linalg.norm(a)), float(np.linalg.norm(b))
    if na < eps or nb < eps:
        return 0.0
    return float(np.dot(a, b) / (na * nb))


class EmbeddingSketch:
    """Mergeable streaming summary of an embedding population.

    Per-dimension Welford moments (count, mean, M2 — variance without a
    second pass) plus a fixed-edge norm histogram: ``bins`` equal-width
    buckets over ``[0, hi)`` and one overflow bucket. Fixed edges make
    two sketches mergeable bucket-wise (the metrics-histogram rule: a
    merge across two ladders would be a silent lie); ``hi`` defaults to
    ``4 * sqrt(dim)``, generous for unit-ish-scale embedding entries.
    """

    def __init__(self, dim: int, *, bins: int = 64,
                 hi: Optional[float] = None):
        if int(dim) < 1:
            raise ValueError(f"dim must be >= 1, got {dim}")
        if int(bins) < 1:
            raise ValueError(f"bins must be >= 1, got {bins}")
        self.dim = int(dim)
        self.bins = int(bins)
        self.hi = float(hi) if hi is not None else 4.0 * math.sqrt(self.dim)
        if self.hi <= 0:
            raise ValueError(f"hi must be > 0, got {self.hi}")
        self.count = 0
        self.mean = np.zeros(self.dim, np.float64)
        self.m2 = np.zeros(self.dim, np.float64)
        # bins equal-width norm buckets over [0, hi) + one overflow
        self.hist = np.zeros(self.bins + 1, np.int64)

    # -- streaming update (Welford) ---------------------------------------
    def update(self, vec) -> None:
        """Fold one embedding. Deterministic given arrival order — the
        restart-resume bit-exactness contract rides on this."""
        vec = np.asarray(vec, np.float64).reshape(-1)
        if vec.shape[0] != self.dim:
            raise ValueError(
                f"sketch dim {self.dim} cannot fold a {vec.shape[0]}-dim "
                f"embedding"
            )
        self.count += 1
        delta = vec - self.mean
        self.mean = self.mean + delta / self.count
        self.m2 = self.m2 + delta * (vec - self.mean)
        norm = float(np.linalg.norm(vec))
        idx = int(norm / self.hi * self.bins)
        self.hist[min(max(idx, 0), self.bins)] += 1

    # -- parallel fold (Chan) ---------------------------------------------
    def merge(self, other: "EmbeddingSketch") -> "EmbeddingSketch":
        """Chan's parallel-moments fold; returns a NEW sketch. Geometry
        (dim/bins/hi) must match — merging mismatched sketches would be
        the mismatched-bucket-ladder lie the metrics layer refuses."""
        if (self.dim, self.bins) != (other.dim, other.bins) or \
                not math.isclose(self.hi, other.hi):
            raise ValueError(
                f"cannot merge sketches with mismatched geometry "
                f"(dim {self.dim}/{other.dim}, bins {self.bins}/"
                f"{other.bins}, hi {self.hi:g}/{other.hi:g})"
            )
        out = EmbeddingSketch(self.dim, bins=self.bins, hi=self.hi)
        n = self.count + other.count
        out.count = n
        if n == 0:
            return out
        delta = other.mean - self.mean
        out.mean = self.mean + delta * (other.count / n)
        out.m2 = self.m2 + other.m2 + \
            delta * delta * (self.count * other.count / n)
        out.hist = self.hist + other.hist
        return out

    # -- derived stats ----------------------------------------------------
    def std(self) -> np.ndarray:
        """Per-dimension standard deviation (zeros below 2 samples)."""
        if self.count < 2:
            return np.zeros(self.dim, np.float64)
        return np.sqrt(self.m2 / self.count)

    def _edge(self, i: int) -> float:
        return self.hi * i / self.bins

    def quantile(self, q: float) -> float:
        """Nearest-rank norm quantile off the histogram: the containing
        bucket's UPPER edge (conservative, the histogram_quantile rule);
        ``inf`` for the overflow bucket, NaN when empty."""
        if self.count == 0:
            return float("nan")
        rank = min(self.count - 1, max(0, int(round(q * (self.count - 1)))))
        seen = 0
        for i in range(self.bins + 1):
            seen += int(self.hist[i])
            if rank < seen:
                return self._edge(i + 1) if i < self.bins else float("inf")
        return float("inf")  # unreachable

    def mass_above(self, v: float) -> float:
        """Fraction of folded norms in buckets wholly above ``v`` —
        conservative (under-counts a straddling bucket, never over)."""
        if self.count == 0 or not math.isfinite(v):
            return 0.0
        mass = 0
        for i in range(self.bins + 1):
            lo = self._edge(i) if i < self.bins else self.hi
            if lo >= v:
                mass += int(self.hist[i])
        return mass / self.count

    # -- persistence (manifest discipline) --------------------------------
    def save(self, path: str) -> str:
        """Atomic verified save into directory ``path``: arrays in
        ``sketch.npz``, metadata + per-file sha256 in ``manifest.json``,
        staged in ``.tmp-*`` and renamed into place — a SIGKILL
        mid-write leaves a stale tmp dir, never a half-written
        baseline."""
        path = os.path.abspath(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, _SKETCH_FILE), mean=self.mean,
                 m2=self.m2, hist=self.hist)
        manifest = {
            "v": DRIFT_SCHEMA_VERSION,
            "dim": self.dim, "bins": self.bins, "hi": self.hi,
            "count": self.count,
            "files": {_SKETCH_FILE: _sha256_file(
                os.path.join(tmp, _SKETCH_FILE))},
        }
        with open(os.path.join(tmp, _MANIFEST), "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, sort_keys=True)
        if os.path.exists(path):
            old = f"{path}.old-{os.getpid()}"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
            os.rename(tmp, path)
            shutil.rmtree(old)
        else:
            os.rename(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "EmbeddingSketch":
        """Verified restore: manifest re-hashed, geometry re-checked —
        any mismatch is a :class:`CorruptDriftArtifact`."""
        manifest_path = os.path.join(path, _MANIFEST)
        try:
            with open(manifest_path, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, ValueError) as e:
            raise CorruptDriftArtifact(
                f"drift baseline {path}: unreadable manifest "
                f"({type(e).__name__}: {e})"
            )
        files = manifest.get("files")
        if not isinstance(files, dict) or _SKETCH_FILE not in files:
            raise CorruptDriftArtifact(
                f"drift baseline {path}: manifest lists no {_SKETCH_FILE}"
            )
        for name, digest in files.items():
            full = os.path.join(path, name)
            if not os.path.isfile(full):
                raise CorruptDriftArtifact(
                    f"drift baseline {path}: missing file {name}"
                )
            actual = _sha256_file(full)
            if actual != digest:
                raise CorruptDriftArtifact(
                    f"drift baseline {path}: sha256 mismatch for {name} "
                    f"(manifest {digest[:12]}..., file {actual[:12]}...)"
                )
        try:
            with np.load(os.path.join(path, _SKETCH_FILE)) as npz:
                mean = np.asarray(npz["mean"], np.float64)
                m2 = np.asarray(npz["m2"], np.float64)
                hist = np.asarray(npz["hist"], np.int64)
            out = cls(int(manifest["dim"]), bins=int(manifest["bins"]),
                      hi=float(manifest["hi"]))
            out.count = int(manifest["count"])
        except (KeyError, ValueError, TypeError) as e:
            raise CorruptDriftArtifact(
                f"drift baseline {path}: malformed payload "
                f"({type(e).__name__}: {e})"
            )
        if mean.shape != (out.dim,) or m2.shape != (out.dim,) or \
                hist.shape != (out.bins + 1,) or \
                out.count != int(hist.sum()):
            raise CorruptDriftArtifact(
                f"drift baseline {path}: geometry/count mismatch between "
                f"manifest and payload"
            )
        out.mean, out.m2, out.hist = mean, m2, hist
        return out


def drift_scores(current: EmbeddingSketch, baseline: EmbeddingSketch,
                 eps: float = 1e-6) -> Dict[str, float]:
    """Current-vs-baseline drift scores (all down-good):

    - ``mean_shift``  — mean over dims of |Δmean| / σ_baseline (the
      standardized shift; ``eps`` floors degenerate dims);
    - ``cosine_dist`` — 1 − cos(mean_current, mean_baseline);
    - ``tail_mass``   — fraction of current norms above the baseline's
      q99 (the per-channel-absmax outlier discipline, continuous)."""
    std = baseline.std()
    mean_shift = float(
        np.mean(np.abs(current.mean - baseline.mean) / (std + eps))
    )
    # fp rounding can put cos() a hair above 1.0; clamp so identical
    # means score exactly 0.0 (not -0.0) in reports and trend points
    cos_dist = max(0.0, 1.0 - cosine(current.mean, baseline.mean))
    tail = current.mass_above(baseline.quantile(0.99))
    return {
        "mean_shift": round(mean_shift, 6),
        "cosine_dist": round(cos_dist, 6),
        "tail_mass": round(tail, 6),
    }


def stream_peek_every() -> int:
    """``GIGAPATH_DRIFT_PEEK_EVERY`` snapshot: peek the streaming
    session every N folded chunks for the anytime-confidence surface
    (0 = off, the default — a peek is a real readout pass). Host-side,
    read once at submitter/consumer construction (GL001)."""
    from gigapath_tpu.obs.runlog import env_number

    return max(int(env_number("GIGAPATH_DRIFT_PEEK_EVERY", 0)), 0)


class DriftSentinel:
    """Online drift monitor over served embeddings (see module
    docstring). ``every``/``threshold``/``min_count`` default to the
    ``GIGAPATH_DRIFT_*`` env knobs, snapshotted here at construction.
    """

    def __init__(self, baseline: EmbeddingSketch, runlog=None, *,
                 metrics=None, every: Optional[int] = None,
                 threshold: Optional[float] = None,
                 min_count: Optional[int] = None,
                 name: str = "serve.drift"):
        from gigapath_tpu.obs.runlog import env_number

        self.baseline = baseline
        self.current = EmbeddingSketch(baseline.dim, bins=baseline.bins,
                                       hi=baseline.hi)
        self.runlog = runlog
        self.metrics = metrics
        self.name = name
        self.every = int(every if every is not None
                         else env_number("GIGAPATH_DRIFT_EVERY", 4))
        self.threshold = float(
            threshold if threshold is not None
            else env_number("GIGAPATH_DRIFT_THRESHOLD", 4.0)
        )
        self.min_count = int(min_count if min_count is not None
                             else env_number("GIGAPATH_DRIFT_MIN_COUNT", 4))
        self.alarming = False
        self.transitions = 0
        self.scores: Optional[Dict[str, float]] = None

    def observe(self, embedding) -> Optional[dict]:
        """Fold one served embedding; at the cadence, score and —
        on an alarming-state TRANSITION — emit the ``drift`` event the
        anomaly engine's ``embedding_drift`` detector reacts to.
        Returns the emitted record on a transition, else None."""
        self.current.update(embedding)
        n = self.current.count
        if self.every <= 0 or n < self.min_count or n % self.every:
            return None
        return self._score_and_edge()

    def _score_and_edge(self) -> Optional[dict]:
        scores = drift_scores(self.current, self.baseline)
        self.scores = scores
        if self.metrics is not None:
            for key, val in scores.items():
                self.metrics.gauge(f"{self.name}.{key}").set(val)
        alarming_now = scores["mean_shift"] > self.threshold
        if alarming_now == self.alarming:
            return None
        self.alarming = alarming_now
        if alarming_now:
            self.transitions += 1
        if self.runlog is None:
            return None
        return self.runlog.event(
            "drift", name=self.name, alarming=alarming_now,
            threshold=self.threshold, count=self.current.count,
            baseline_count=self.baseline.count, **scores,
        )

    def status(self) -> dict:
        return dict(
            name=self.name, alarming=self.alarming,
            threshold=self.threshold, count=self.current.count,
            baseline_count=self.baseline.count,
            transitions=self.transitions,
            **(self.scores or {}),
        )

    def emit_status(self, reason: str = "final") -> None:
        """Terminal ``drift`` status event (marked ``final`` — the
        detector only reacts to transitions, the SloTracker rule)."""
        if self.runlog is None:
            return
        if self.current.count and self.scores is None:
            self.scores = drift_scores(self.current, self.baseline)
        self.runlog.event("drift", reason=reason, final=True,
                          **self.status())


__all__ = [
    "CorruptDriftArtifact",
    "DRIFT_SCHEMA_VERSION",
    "DriftSentinel",
    "EmbeddingSketch",
    "cosine",
    "drift_scores",
    "stream_peek_every",
]
