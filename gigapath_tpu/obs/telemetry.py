"""In-graph scalar telemetry: computed INSIDE the jitted step.

These helpers run at trace time and stay on-device: they add a handful
of reductions to the step's XLA program and return 0-d arrays as extra
step outputs. The host converts them to floats only at its existing
sync points (the finetune loop's 20-iteration print / epoch end), so
telemetry costs no extra device round-trips and — because the helpers
neither read the environment nor branch on values — no retraces
(pinned by the compile-count parity test in tests/test_obs.py).

``collect_moe_metadata`` (utils/profiling.py) remains the host-side
flattener for sown MoE gating stats; :func:`moe_scalars` is its
in-graph twin that keeps the leaves as arrays so they can ride a jitted
step's outputs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def tree_norm(tree) -> jnp.ndarray:
    """Global L2 norm over a pytree, accumulated in fp32 (bf16 squares of
    ~1e-2 grads underflow)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    )


def step_scalars(
    loss: Optional[jnp.ndarray] = None,
    grads=None,
    params=None,
    **extras,
) -> Dict[str, jnp.ndarray]:
    """The standard per-step scalar set: loss, grad-norm, param-norm, plus
    any caller extras (already-scalar arrays). Returned values are 0-d
    DEVICE arrays — thread them out of the jitted step and hand them to
    ``RunLog.step`` only at a host sync point."""
    out: Dict[str, jnp.ndarray] = {}
    if loss is not None:
        out["loss"] = loss.astype(jnp.float32)
    if grads is not None:
        out["grad_norm"] = tree_norm(grads)
    if params is not None:
        out["param_norm"] = tree_norm(params)
    out.update(extras)
    return out


def moe_scalars(intermediates: Dict[str, Any]) -> Dict[str, jnp.ndarray]:
    """In-graph twin of ``collect_moe_metadata``: the same
    ``layer_path/metric`` key space (one shared flattening —
    ``iter_moe_metadata``), but leaves stay DEVICE arrays so MoE gating
    telemetry (entropy, unused experts, balance fractions) can ride a
    jitted step's outputs. Inside a jitted MoE step::

        _, mods = model.apply(..., mutable=["intermediates"])
        tel = {**step_scalars(loss=loss, grads=grads),
               **moe_scalars(mods["intermediates"])}
    """
    from gigapath_tpu.utils.profiling import iter_moe_metadata

    return {
        key: jnp.asarray(leaf)
        for key, leaf in iter_moe_metadata(intermediates)
    }
